"""Socket-backed replicas: the Replica protocol over HTTP.

:class:`RemoteReplica` is the client half of the remote serving plane
(docs/SERVING.md § Remote replicas & autoscaling): it satisfies the
exact surface :class:`~.router.ReplicaRouter` routes through —
``submit`` / ``resume_handoff`` / ``health`` / ``load`` /
``heartbeat_age`` / ``drain`` / ``stop`` — by speaking to a replica
worker process (serve/worker.py, spawnable via ``python -m
deepspeed_tpu.inference.v2.serve.worker``) over its HTTP API:

  * ``submit`` → ``POST /generate`` with W3C ``traceparent`` (+
    ``baggage``) request headers, parsed as a streaming-NDJSON
    :class:`RemoteStream` (the TokenStream surface; closing the client
    write side cancels the remote request and frees its KV);
  * ``health`` / ``load`` / ``heartbeat_age`` → ``GET /healthz``
    snapshots, cached between :meth:`refresh` polls so the router's
    per-submit dead-replica check never pays a blocking probe;
  * ``drain`` / ``stop`` → ``POST /drain`` / ``POST /stop`` lifecycle
    endpoints;
  * ``resume_handoff`` → ``POST /handoff``, streaming the chunked KV
    payload as length-prefixed frames (serve/handoff.py wire format)
    that the worker applies BETWEEN its decode steps — the transfer
    overlaps the remote replica's running batch — then reading the
    decode token stream back on the same connection;
  * ``metrics_text`` / ``fetch_spans`` → ``GET /metrics`` and
    ``GET /debug/spans``, so federated ``/metrics`` and the stitched
    fleet timeline keep working when replicas leave the process
    (remote span clocks are rebased onto this process's
    ``perf_counter`` via the worker's wall-clock anchor).

Everything is stdlib asyncio — no HTTP client dependency — and every
connection is ``Connection: close``, matching serve/api.py's protocol.
"""

import asyncio
import json
import time
from typing import List, Optional

from ....telemetry import context as trace_context
from .admission import OverloadedError
from .frontend import DeadlineExceeded, RequestFailed

# ---------------------------------------------------------------------------
# /handoff frame protocol: after the request headers, the client streams
# [1-byte type][4-byte big-endian length][payload] frames —
#   C  one chunk of a chunked KV handoff (serve/handoff.py chunk .npz)
#   B  one whole legacy blocking payload (handoff.serialize bytes)
#   P  terminal JSON params frame (decode parameters + rng state);
#      the worker commits the restore and streams NDJSON tokens back
# ---------------------------------------------------------------------------
FRAME_CHUNK = b"C"
FRAME_BLOCKING = b"B"
FRAME_PARAMS = b"P"
_MAX_FRAME_BYTES = 256 * 1024 * 1024


def write_frame(writer: asyncio.StreamWriter, kind: bytes,
                payload: bytes) -> None:
    writer.write(kind + len(payload).to_bytes(4, "big") + payload)


async def read_frame(reader: asyncio.StreamReader):
    """Returns ``(kind, payload)``; raises
    :class:`asyncio.IncompleteReadError` on EOF mid-frame (the
    mid-transfer-abort signal the worker handles)."""
    head = await reader.readexactly(5)
    kind, n = head[:1], int.from_bytes(head[1:], "big")
    if n > _MAX_FRAME_BYTES:
        raise ValueError(f"handoff frame too large ({n} bytes)")
    return kind, await reader.readexactly(n)


# ---------------------------------------------------------------------------
# minimal HTTP/1.1 client for the Connection: close API
# ---------------------------------------------------------------------------
async def _open_request(host: str, port: int, method: str, target: str,
                        headers: Optional[dict] = None, body: bytes = b"",
                        timeout: float = 5.0):
    """Send one request and parse the response head; returns
    ``(status_code, resp_headers, reader, writer)`` with the body left
    on ``reader`` (the streaming endpoints keep reading it)."""
    reader, writer = await asyncio.wait_for(
        asyncio.open_connection(host, port), timeout)
    lines = [f"{method} {target} HTTP/1.1", f"Host: {host}:{port}",
             "Connection: close", f"Content-Length: {len(body)}"]
    for k, v in (headers or {}).items():
        lines.append(f"{k}: {v}")
    writer.write(("\r\n".join(lines) + "\r\n\r\n").encode() + body)
    await writer.drain()
    status_line = await asyncio.wait_for(reader.readline(), timeout)
    if not status_line:
        raise ConnectionError(f"empty response from {host}:{port}")
    parts = status_line.decode("latin-1").split(None, 2)
    code = int(parts[1])
    resp_headers = {}
    while True:
        line = await reader.readline()
        if line in (b"\r\n", b"\n", b""):
            break
        name, _, value = line.decode("latin-1").partition(":")
        resp_headers[name.strip().lower()] = value.strip()
    return code, resp_headers, reader, writer


async def _request_json(host: str, port: int, method: str, target: str,
                        body: Optional[dict] = None, timeout: float = 5.0):
    """One-shot JSON request/response; returns ``(code, obj)``."""
    payload = json.dumps(body).encode() if body is not None else b""
    code, _, reader, writer = await _open_request(
        host, port, method, target,
        headers={"Content-Type": "application/json"} if body else None,
        body=payload, timeout=timeout)
    try:
        data = await asyncio.wait_for(reader.read(), timeout)
    finally:
        writer.close()
    try:
        return code, json.loads(data.decode() or "null")
    except json.JSONDecodeError:
        return code, None


def _trace_headers() -> dict:
    """The W3C trace headers for the current bound context — every hop
    a RemoteReplica makes carries the request's ONE trace identity."""
    ctx = trace_context.current()
    if ctx is None:
        return {}
    out = {"traceparent": ctx.to_traceparent()}
    if ctx.baggage:
        out["baggage"] = ctx.to_baggage_header()
    return out


class RemoteStream:
    """Async token stream over one remote NDJSON response — the
    TokenStream surface (iterate / ``cancel()`` / ``drain()`` /
    ``.tokens`` / ``.status`` / ``.reason`` / ``.uid``). ``uid`` is the
    REMOTE runtime's uid, filled in by the tail summary line."""

    def __init__(self, reader: asyncio.StreamReader,
                 writer: asyncio.StreamWriter):
        self._reader = reader
        self._writer = writer
        self._ended = False
        self.uid: Optional[int] = None
        self.status = "active"
        self.reason: Optional[str] = None
        self.trace_id: Optional[str] = None
        self.tokens: List[int] = []

    def __aiter__(self) -> "RemoteStream":
        return self

    async def __anext__(self) -> int:
        if self._ended:
            raise StopAsyncIteration
        while True:
            try:
                line = await self._reader.readline()
            except (ConnectionResetError, BrokenPipeError, OSError) as e:
                self._finish("error", f"connection lost: {e}")
                raise RequestFailed(f"remote stream: {self.reason}")
            if not line:
                self._finish(self.status if self._ended else "error",
                             "connection closed mid-stream")
                raise RequestFailed(f"remote stream: {self.reason}")
            line = line.strip()
            if not line:
                continue
            obj = json.loads(line)
            if "token" in obj:
                tok = int(obj["token"])
                self.tokens.append(tok)
                return tok
            # tail summary line
            self.uid = obj.get("uid")
            self.trace_id = obj.get("trace_id")
            self._finish(obj.get("status", "completed"),
                         obj.get("detail"))
            if self.status == "expired":
                raise DeadlineExceeded("remote request: deadline "
                                       "exceeded")
            if self.status == "error":
                raise RequestFailed(f"remote request: {self.reason}")
            raise StopAsyncIteration

    def _finish(self, status: str, reason: Optional[str]) -> None:
        self._ended = True
        self.status, self.reason = status, reason
        try:
            self._writer.close()
        except Exception:
            pass

    async def cancel(self) -> None:
        """Close the client write side — the worker reads the hangup
        (serve/api.py's EOF protocol) and cancels the request, freeing
        its KV blocks on the remote pool."""
        if not self._ended:
            self._finish("cancelled", None)

    async def aclose(self) -> None:
        await self.cancel()

    async def drain(self) -> List[int]:
        async for _ in self:
            pass
        return self.tokens


class RemoteReplica:
    """A serving replica living in another process, addressed by
    ``host:port`` — the Replica protocol over the worker HTTP API.

    ``state`` stays router-owned exactly like the in-process
    :class:`~.replica.Replica`. Health/load/heartbeat signals come from
    cached ``GET /healthz`` snapshots refreshed by :meth:`refresh`
    (the router polls it from ``check_replicas``); a refresh that
    cannot reach the worker marks the replica not-alive, which the
    router's dead-replica detector treats like a dead loop thread."""

    registry = None          # metrics federate via /metrics text instead

    def __init__(self, name: str, host: str, port: int, *,
                 probe_timeout_s: float = 5.0,
                 probe_interval_s: float = 0.25, clock=time.monotonic):
        self.name = name
        self.host = host
        self.port = int(port)
        self.state = "up"
        self.started = False
        self.probe_timeout_s = probe_timeout_s
        self.probe_interval_s = probe_interval_s
        self.clock = clock
        self._health: dict = {"name": name, "state": "unknown"}
        self._reachable = False
        self._last_probe = -1.0
        self._last_metrics: Optional[str] = None
        self.block_size: Optional[int] = None
        self.max_seq_len: Optional[int] = None

    # -- lifecycle ------------------------------------------------------
    async def start(self) -> "RemoteReplica":
        await self.refresh(force=True)
        if not self._reachable:
            raise ConnectionError(
                f"remote replica {self.name}: no worker reachable at "
                f"{self.host}:{self.port}")
        self.started = True
        return self

    async def drain(self) -> None:
        """Graceful: the worker rejects new submits immediately and
        finishes everything admitted before returning."""
        code, _ = await _request_json(
            self.host, self.port, "POST", "/drain",
            timeout=max(self.probe_timeout_s, 60.0))
        if code != 200:
            raise RuntimeError(
                f"remote replica {self.name}: drain returned {code}")

    async def stop(self) -> None:
        """Hard stop: in-flight requests are cancelled, then the worker
        process exits. Unreachable workers are treated as already
        stopped (the autoscaler kills what it cannot drain)."""
        try:
            await _request_json(self.host, self.port, "POST", "/stop",
                                timeout=self.probe_timeout_s)
        except (OSError, ConnectionError, asyncio.TimeoutError):
            pass

    async def kill(self) -> None:
        await self.stop()

    def reap(self) -> None:
        """Dead-replica cleanup: nothing to reclaim client-side — the
        router re-dispatches its own queued records; the worker (if it
        ever recovers) is told to halt on the next lifecycle call."""

    # -- router signals -------------------------------------------------
    async def refresh(self, force: bool = False) -> None:
        """Re-poll ``GET /healthz`` (rate-limited to
        ``probe_interval_s`` unless forced) — the ONE source for this
        replica's health/load/heartbeat signals between polls."""
        now = self.clock()
        if not force and self._last_probe >= 0 \
                and now - self._last_probe < self.probe_interval_s:
            return
        self._last_probe = now
        try:
            code, obj = await _request_json(
                self.host, self.port, "GET", "/healthz",
                timeout=self.probe_timeout_s)
            self._reachable = code == 200 and isinstance(obj, dict)
            if self._reachable:
                self._health = obj
                if obj.get("block_size") is not None:
                    self.block_size = int(obj["block_size"])
                if obj.get("max_seq_len") is not None:
                    self.max_seq_len = int(obj["max_seq_len"])
        except (OSError, ConnectionError, asyncio.TimeoutError,
                ValueError):
            self._reachable = False

    def alive(self) -> bool:
        return self._reachable and bool(self._health.get("loop_alive",
                                                         False))

    def heartbeat_age(self) -> Optional[float]:
        age = self._health.get("heartbeat_age_s")
        return float(age) if age is not None else None

    def load(self) -> float:
        return float(self._health.get("load", 0.0))

    def health(self) -> dict:
        return {**self._health, "name": self.name, "state": self.state,
                "remote": f"{self.host}:{self.port}",
                "reachable": self._reachable}

    # -- submission -----------------------------------------------------
    async def submit(self, prompt, max_new_tokens: int,
                     **kw) -> RemoteStream:
        body = {"prompt": [int(t) for t in prompt],
                "max_new_tokens": int(max_new_tokens)}
        body.update({k: v for k, v in kw.items() if v is not None})
        payload = json.dumps(body).encode()
        code, headers, reader, writer = await _open_request(
            self.host, self.port, "POST", "/generate",
            headers={"Content-Type": "application/json",
                     **_trace_headers()},
            body=payload, timeout=self.probe_timeout_s)
        if code == 429:
            data = await reader.read()
            writer.close()
            try:
                obj = json.loads(data.decode() or "{}")
            except json.JSONDecodeError:
                obj = {}
            raise OverloadedError(
                obj.get("reason", "overloaded"),
                obj.get("detail", f"remote replica {self.name} shed"),
                retry_after_s=obj.get("retry_after_s"))
        if code != 200:
            data = await reader.read()
            writer.close()
            raise RequestFailed(
                f"remote replica {self.name}: /generate returned "
                f"{code}: {data[:200]!r}")
        return RemoteStream(reader, writer)

    # -- handoff (disaggregated decode side) ----------------------------
    async def resume_handoff(self, payloads: List[bytes], *, chunked:
                             bool, prompt, generated, max_new_tokens:
                             int, eos_token_id=None, temperature=0.0,
                             top_p=1.0, top_k=0, rng_state=None,
                             deadline_s=None) -> RemoteStream:
        """Stream a KV handoff to the worker and return the remote
        decode token stream. Chunked payloads go as one frame each —
        the worker applies frame i between its decode steps while
        frame i+1 is still in flight, so the transfer overlaps the
        remote replica's running batch."""
        # the worker answers only after the terminal params frame, so
        # the request head and every frame go out BEFORE any response
        # read (an _open_request-style head-first read would deadlock)
        reader, writer = await asyncio.wait_for(
            asyncio.open_connection(self.host, self.port),
            self.probe_timeout_s)
        lines = ["POST /handoff HTTP/1.1",
                 f"Host: {self.host}:{self.port}",
                 "Connection: close", "Content-Length: 0"]
        for k, v in _trace_headers().items():
            lines.append(f"{k}: {v}")
        writer.write(("\r\n".join(lines) + "\r\n\r\n").encode())
        transfer_err: Optional[Exception] = None
        try:
            kind = FRAME_CHUNK if chunked else FRAME_BLOCKING
            for p in payloads:
                write_frame(writer, kind, p)
                # drain between frames: the worker ingests at its own
                # pace, so backpressure (not buffering) paces the wire
                await writer.drain()
            params = {
                "prompt": [int(t) for t in prompt],
                "generated": [int(t) for t in generated],
                "max_new_tokens": int(max_new_tokens),
                "eos_token_id": eos_token_id,
                "temperature": temperature, "top_p": top_p,
                "top_k": top_k, "rng_state": rng_state,
                "deadline_s": deadline_s,
            }
            write_frame(writer, FRAME_PARAMS, json.dumps(params).encode())
            await writer.drain()
        except (ConnectionResetError, BrokenPipeError, OSError) as e:
            # a mid-transfer write failure usually means the worker
            # REJECTED the handoff (draining/overload verdict written,
            # then socket closed) while frames were still in flight —
            # fall through and try to read that verdict, so the router
            # can re-route instead of failing the request; only when no
            # verdict is readable is this a transfer failure
            transfer_err = e
        # now the response: status line + headers, then the verdict
        # NDJSON line, then the token stream
        try:
            status_line = await reader.readline()
        except (ConnectionResetError, BrokenPipeError, OSError):
            status_line = b""
        if not status_line:
            writer.close()
            detail = (f"transfer failed: {transfer_err}" if transfer_err
                      else "closed without a response")
            raise RequestFailed(
                f"remote replica {self.name}: handoff {detail}")
        code = int(status_line.decode("latin-1").split(None, 2)[1])
        while True:
            hline = await reader.readline()
            if hline in (b"\r\n", b"\n", b""):
                break
        if code != 200:
            data = await reader.read()
            writer.close()
            if code == 429:
                try:
                    obj = json.loads(data.decode() or "{}")
                except json.JSONDecodeError:
                    obj = {}
                raise OverloadedError(
                    obj.get("reason", "overloaded"),
                    obj.get("detail", "remote handoff shed"),
                    retry_after_s=obj.get("retry_after_s"))
            raise RequestFailed(
                f"remote replica {self.name}: /handoff returned {code}")
        line = await reader.readline()
        try:
            verdict = json.loads(line.decode() or "{}")
        except json.JSONDecodeError:
            verdict = {}
        if not verdict.get("ok"):
            writer.close()
            reason = verdict.get("reason", "error")
            if reason == "draining":
                raise OverloadedError(
                    "draining", verdict.get("detail", "remote handoff "
                                            "rejected: draining"),
                    retry_after_s=verdict.get("retry_after_s"))
            raise RequestFailed(
                f"remote handoff rejected: "
                f"{verdict.get('detail', repr(line[:200]))}")
        return RemoteStream(reader, writer)

    # -- fleet observability --------------------------------------------
    def metrics_text(self) -> Optional[str]:
        """Last-fetched Prometheus exposition (refreshed by
        :meth:`fetch_metrics`; the router's monitor keeps it current)."""
        return self._last_metrics

    async def fetch_metrics(self) -> Optional[str]:
        try:
            code, _, reader, writer = await _open_request(
                self.host, self.port, "GET", "/metrics",
                timeout=self.probe_timeout_s)
            data = await reader.read()
            writer.close()
            if code == 200:
                self._last_metrics = data.decode()
        except (OSError, ConnectionError, asyncio.TimeoutError):
            pass
        return self._last_metrics

    async def fetch_spans(self) -> List[dict]:
        """The worker's span ring, rebased onto THIS process's
        ``perf_counter`` clock through the worker's wall-clock anchor —
        what :meth:`~.router.ReplicaRouter.fleet_timeline` stitches."""
        try:
            code, obj = await _request_json(
                self.host, self.port, "GET", "/debug/spans",
                timeout=self.probe_timeout_s)
        except (OSError, ConnectionError, asyncio.TimeoutError):
            return []
        if code != 200 or not isinstance(obj, dict):
            return []
        # remote perf_counter -> wall clock -> local perf_counter
        offset = ((obj.get("wall_now", 0.0) - obj.get("perf_now", 0.0))
                  - (time.time() - time.perf_counter()))
        spans = []
        for s in obj.get("spans", []):
            s = dict(s)
            s["start"] = s.get("start", 0.0) + offset
            s.setdefault("lane", self.name)
            spans.append(s)
        return spans
