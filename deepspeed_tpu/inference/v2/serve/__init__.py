"""Async serving runtime over the SplitFuse scheduler.

The layer between clients and the model loop (the reference ships it as
DeepSpeed-MII persistent deployments over the FastGen engine):

  frontend.py  — asyncio ServingEngine: async submit() -> token stream,
                 per-request deadlines, cancellation that releases KV
  admission.py — bounded pending queue, token-budget load shedding,
                 weighted-fair scheduling across tenants
  loop.py      — background thread continuously draining the SplitFuse
                 scheduler (continuous batching) with graceful drain
  api.py       — dependency-free HTTP endpoint: streaming /generate,
                 /healthz, /metrics (Prometheus text from the registry);
                 serves a single engine or the routed front tier
  router.py    — prefix-affinity replica router: spreads traffic over N
                 engine replicas, backoff-aware overload re-routing,
                 drain/failover lifecycle, optional prefill/decode
                 disaggregation
  replica.py   — the units behind the router: full serving replicas and
                 dedicated prefill workers
  handoff.py   — paged-KV export/serialize/restore between replicas
                 (the disaggregation transport; parity-pinned), plus the
                 chunked streaming protocol that overlaps transfer with
                 the decode replica's running batch
  remote.py    — RemoteReplica: the Replica protocol over a socket
                 (HTTP client shim onto a worker process)
  worker.py    — the replica worker process behind RemoteReplica
                 (python -m deepspeed_tpu.inference.v2.serve.worker)
  autoscaler.py— spawn/drain replicas off the router's load, shed,
                 SLO-burn and heartbeat signals; spawn failures are
                 counted and quarantined, never propagated
  resilience.py— RetryPolicy (backoff + jitter under one shared
                 deadline budget) and the per-replica CircuitBreaker
                 (suspected vs dead) behind the remote plane
  faults.py    — deterministic, scriptable fault injection over the
                 remote transport (the chaos harness behind the chaos
                 tests and load_bench --chaos)

See docs/SERVING.md ("Async serving runtime", "Routing tier" and
"Remote replicas & autoscaling") for the architecture and protocols.
"""

from . import handoff  # noqa: F401
from . import weights  # noqa: F401
from .admission import (AdmissionConfig, AdmissionController,  # noqa: F401
                        OverloadedError)
from .faults import FaultPlane, FaultSpec  # noqa: F401
from .resilience import (BreakerConfig, CircuitBreaker,  # noqa: F401
                         RetryConfig, RetryPolicy)
from .frontend import (DeadlineExceeded, RequestFailed,  # noqa: F401
                       ServingConfig, ServingEngine, TokenStream)
from .loop import ServingLoop  # noqa: F401
from .api import ServingAPI  # noqa: F401
from .replica import PrefillReplica, Replica, build_replicas  # noqa: F401
from .router import (ReplicaRouter, RoutedStream,  # noqa: F401
                     RouterConfig)
from .remote import RemoteReplica, RemoteStream  # noqa: F401
from .worker import (ReplicaWorker, WorkerAPI,  # noqa: F401
                     WorkerSpawnError, spawn_worker)
from .autoscaler import Autoscaler, AutoscalerConfig  # noqa: F401

__all__ = [
    "AdmissionConfig", "AdmissionController", "OverloadedError",
    "DeadlineExceeded", "RequestFailed", "ServingConfig", "ServingEngine",
    "TokenStream", "ServingLoop", "ServingAPI",
    "PrefillReplica", "Replica", "build_replicas",
    "ReplicaRouter", "RoutedStream", "RouterConfig",
    "RemoteReplica", "RemoteStream", "ReplicaWorker", "WorkerAPI",
    "WorkerSpawnError", "spawn_worker",
    "Autoscaler", "AutoscalerConfig", "handoff", "weights",
    "FaultPlane", "FaultSpec",
    "RetryConfig", "RetryPolicy", "BreakerConfig", "CircuitBreaker",
]
