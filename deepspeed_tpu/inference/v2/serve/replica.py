"""Engine replicas behind the serving router.

Two replica kinds (docs/SERVING.md § Routing tier):

  * :class:`Replica` — a full serving unit: one
    :class:`~.frontend.ServingEngine` (admission + continuous-batching
    loop) over one engine. The router dispatches streaming requests to
    it, reads its load/heartbeat signals, drains it without dropping
    in-flight streams, and declares it dead when its stall-watchdog
    heartbeat expires.
  * :class:`PrefillReplica` — a dedicated prefill worker for the
    disaggregated mode: it runs whole-prompt prefill on its own engine,
    samples the request's FIRST token with the request's own rng (the
    colocated first-token path, so streams stay bit-identical), exports
    the sequence's KV for handoff (serve/handoff.py) and immediately
    flushes — it never decodes, so its pool only ever holds prompts in
    flight.

Replicas here are in-process (each owns its engine; chip-free on CPU).
The router only touches the surface defined by these classes —
``submit`` / ``resume_handoff`` / ``health`` / ``load`` /
``heartbeat_age`` / ``refresh`` / ``drain`` / ``stop`` / ``reap`` /
``kill`` / ``block_size`` — and serve/remote.py's
:class:`~.remote.RemoteReplica` implements the same surface over a
worker process's HTTP API, so socket-backed replicas slot in behind
the identical router.
"""

import asyncio
import contextlib
import itertools
import threading
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ....telemetry import trace
from ....telemetry.registry import scoped_registry
from . import handoff
from .frontend import ServingConfig, ServingEngine


class Replica:
    """One in-process serving replica: name + engine + serving runtime.

    ``state`` is router-owned: 'up' (routable) | 'draining' (finishing
    in-flight work, no new routes) | 'drained' (clean exit) | 'dead'
    (heartbeat expired or loop thread gone).

    ``registry``: optional per-replica
    :class:`~....telemetry.MetricsRegistry` — the serving stack
    (scheduler, admission, loop, diagnostics) is then BUILT inside a
    ``scoped_registry`` block so its series land there instead of the
    process default, and the router's ``/metrics`` federates every
    replica registry under a ``replica`` label. The engine was
    constructed earlier, so engine-level series stay process-global."""

    def __init__(self, name: str, engine,
                 config: Optional[ServingConfig] = None, bridge=None,
                 registry=None):
        self.name = name
        self.engine = engine
        self.registry = registry
        with (scoped_registry(registry) if registry is not None
              else contextlib.nullcontext()):
            self.serving = ServingEngine(engine, config, bridge=bridge,
                                         lane=name)
        self.state = "up"
        self.started = False

    # -- lifecycle ------------------------------------------------------
    async def start(self) -> "Replica":
        await self.serving.start()
        self.started = True
        return self

    async def drain(self) -> None:
        """Graceful: new submits are rejected immediately, everything
        already admitted (including mid-stream decodes) finishes."""
        await self.serving.stop(drain=True)

    async def stop(self) -> None:
        """Hard stop: in-flight requests are cancelled (KV released).
        Idempotent — the autoscaler's drain-then-stop calls it after a
        drain already stopped the runtime."""
        if self.serving._stopped and not self.serving.loop_runner.running:
            return
        await self.serving.stop(drain=False)

    def reap(self) -> None:
        """Dead-replica cleanup (the router declared this replica
        dead): empty the admission queue so a later recovery cannot
        also run the re-enqueued work, tell the loop to halt, stop the
        watchdog thread, and close the spill tier so the dead replica
        leaks no host RAM or disk scratch (the router adopts the disk
        namespace into a survivor BEFORE reaping, so resurrection sees
        the files first)."""
        try:
            self.serving.admission.reclaim_pending()
            self.serving.loop_runner.request_stop()
            self.serving.diagnostics.close()
        except Exception:
            pass
        try:
            spill = getattr(self.engine, "spill", None)
            if spill is not None:
                spill.close()
        except Exception:
            pass

    async def kill(self) -> None:
        """Best-effort terminate a dead replica's loop thread: an
        unwedged loop exits on the halt command; a truly stuck one
        stays a daemon thread."""
        try:
            self.serving.loop_runner.request_stop()
            await asyncio.to_thread(self.serving.loop_runner.join, 2.0)
        except Exception:
            pass

    async def refresh(self, force: bool = False) -> None:
        """In-process signals are always fresh (the remote counterpart
        re-polls /healthz here)."""

    # -- router signals -------------------------------------------------
    def alive(self) -> bool:
        """The loop thread is running (False once drained/stopped, or
        if the thread died)."""
        return self.serving.loop_runner.running

    def heartbeat_age(self) -> Optional[float]:
        """Seconds the loop has been stuck mid-step (None when idle or
        the stall watchdog is disabled) — the dead-replica signal."""
        return self.serving.heartbeat_age()

    def load(self) -> float:
        """Routing load signal: queued future work plus in-flight
        requests (the admission/token-budget signals the router
        rebalances on)."""
        return (self.serving.admission.queued_tokens()
                + self.serving.scheduler.inflight())

    def health(self) -> dict:
        return {"name": self.name, "state": self.state,
                **self.serving.health()}

    # -- spill-aware placement (ragged/spill.py; router placement) ------
    def spill_summary(self):
        """Live :class:`~..ragged.spill.SpillSummary` of this replica's
        spilled digests (None without a spill tier). In-process
        replicas answer from the tier directly — always fresh; the
        remote counterpart decodes its cached /healthz document."""
        spill = getattr(self.engine, "spill", None)
        return spill.digest_summary() if spill is not None else None

    def spill_namespace(self) -> Optional[str]:
        """Disk-tier namespace under the shared kv_spill_dir (None
        without a disk tier) — what a survivor adopts when this
        replica dies."""
        spill = getattr(self.engine, "spill", None)
        if spill is None or not spill.root_dir:
            return None
        return spill.namespace

    def spill_probe(self, digests) -> Optional[int]:
        """EXACT count of ``digests`` present in this replica's spill
        tier — the router's bloom-false-positive detector. Remote
        replicas return None (only the bloom is visible without a
        round trip)."""
        spill = getattr(self.engine, "spill", None)
        if spill is None:
            return None
        return sum(1 for d in digests if spill.has(d))

    async def adopt_spill(self, namespace: str) -> int:
        """Adopt a dead peer's disk-tier spill namespace into this
        replica's tier (session resurrection). Returns entries
        adopted; 0 without a spill tier."""
        spill = getattr(self.engine, "spill", None)
        if spill is None:
            return 0
        return await asyncio.to_thread(spill.adopt_namespace, namespace)

    @property
    def block_size(self) -> int:
        return int(self.engine.state_manager.block_size)

    @property
    def max_seq_len(self) -> int:
        return int(self.engine.state_manager.config.max_seq_len)

    @property
    def diagnostics(self):
        return self.serving.diagnostics

    def metrics_text(self) -> Optional[str]:
        """In-process replicas federate via their registries (None =
        the router reads ``self.registry`` directly)."""
        return None

    # -- live weights (serve/weights.py; blue/green rollout) ------------
    @property
    def weight_version(self) -> int:
        return self.serving.weight_version

    async def apply_weights(self, payloads: Sequence[bytes]) -> int:
        """Stage + commit a weight payload on this replica (the router's
        in-process push transport; remote replicas stream the same
        payload over ``POST /weights``)."""
        return await self.serving.apply_weights(payloads)

    # -- traffic --------------------------------------------------------
    async def submit(self, prompt: Sequence[int], max_new_tokens: int,
                     **kw):
        return await self.serving.submit(prompt, max_new_tokens, **kw)

    async def resume_handoff(self, payloads: Sequence[bytes], *,
                             chunked: bool, prompt: Sequence[int],
                             generated: Sequence[int],
                             max_new_tokens: int, eos_token_id=None,
                             temperature: float = 0.0,
                             top_p: float = 1.0, top_k: int = 0,
                             rng_state=None, deadline_s=None):
        """Adopt a handed-off request from its serialized payloads —
        the ONE handoff entry point the router uses for both transports
        (``chunked=False``: one blocking ``handoff.serialize`` buffer;
        ``chunked=True``: ``[header, kv-chunk...]``, each chunk applied
        between this replica's scheduler steps)."""
        kw = dict(max_new_tokens=max_new_tokens,
                  eos_token_id=eos_token_id, temperature=temperature,
                  top_p=top_p, top_k=top_k, rng_state=rng_state,
                  deadline_s=deadline_s)
        if not chunked:
            pack = await asyncio.to_thread(handoff.deserialize,
                                           payloads[0])
            return await self.serving.resume(pack, prompt=prompt,
                                             generated=generated, **kw)
        handle = await self.serving.begin_handoff(payloads[0])
        try:
            for chunk in payloads[1:]:
                await handle.feed(chunk)
            return await handle.commit(prompt=prompt,
                                       generated=generated, **kw)
        except BaseException:
            await handle.abort()
            raise


class PrefillReplica:
    """Dedicated prefill worker (disaggregated mode).

    The engine is not thread-safe, so one lock serializes prefills; the
    async wrapper runs them in a worker thread to keep the event loop
    (and every live token stream) unblocked."""

    def __init__(self, name: str, engine):
        self.name = name
        self.engine = engine
        self.state = "up"
        self._lock = threading.Lock()
        self._uids = itertools.count(1)
        from ....telemetry import get_registry
        reg = get_registry()
        self._m_prefills = reg.counter(
            "router_prefill_requests_total",
            "requests prefilled on dedicated prefill replicas",
            labelnames=("replica",))

    async def prefill(self, prompt: Sequence[int], max_new_tokens: int, *,
                      eos_token_id: Optional[int] = None,
                      temperature: float = 0.0, top_p: float = 1.0,
                      top_k: int = 0, seed: Optional[int] = None,
                      trace_ctx=None, chunk_blocks: int = 0
                      ) -> Tuple[int, Optional[List[bytes]],
                                 Optional[dict], bool]:
        return await asyncio.to_thread(
            self.prefill_sync, prompt, max_new_tokens,
            eos_token_id=eos_token_id, temperature=temperature,
            top_p=top_p, top_k=top_k, seed=seed, trace_ctx=trace_ctx,
            chunk_blocks=chunk_blocks)

    def prefill_sync(self, prompt: Sequence[int], max_new_tokens: int, *,
                     eos_token_id: Optional[int] = None,
                     temperature: float = 0.0, top_p: float = 1.0,
                     top_k: int = 0, seed: Optional[int] = None,
                     trace_ctx=None, chunk_blocks: int = 0
                     ) -> Tuple[int, Optional[List[bytes]],
                                Optional[dict], bool]:
        """Run one whole-prompt prefill and hand the sequence off.

        Returns ``(first_token, payloads, rng_state, finished)`` —
        ``payloads`` is the serialized KV handoff (None when the
        request already finished at its first token: eos, or a 1-token
        budget): ``[serialize(pack)]`` when ``chunk_blocks == 0`` (the
        blocking transport) or the chunked wire form ``[header,
        kv-chunk...]`` with ``chunk_blocks`` KV blocks per chunk;
        ``rng_state`` is the request rng AFTER the first draw, so the
        decode side continues the exact sampling stream.

        Parity: the first token is ``host_sample`` over the prompt's
        last-token logits with a fresh per-request rng — precisely what
        the colocated scheduler's final-prompt-chunk path computes —
        and chunked-vs-whole prefill is bit-identical (pinned by the
        serving-runtime parity tests), so the handed-off KV matches the
        colocated cache bit-for-bit."""
        from ..sampling import host_sample
        with self._lock:
            # asyncio.to_thread runs this on a pooled worker thread:
            # name its fleet lane for the duration so the engine's
            # prefill span lands in THIS replica's timeline row
            prev_lane = trace.current_lane()
            trace.set_lane(self.name)
            try:
                uid = next(self._uids)
                if trace_ctx is not None:
                    self.engine.bind_trace(uid, trace_ctx.trace_id)
                logits = self.engine.put(
                    [uid], [np.asarray(list(prompt), np.int64)])
                rng = np.random.default_rng(seed)
                tok = int(host_sample(np.asarray(logits[0]), rng,
                                      temperature, top_p, top_k))
                finished = (max_new_tokens <= 1
                            or (eos_token_id is not None
                                and tok == eos_token_id))
                payloads = None
                rng_state = None
                if not finished:
                    pack = handoff.export_sequence(self.engine, uid,
                                                   trace_ctx=trace_ctx)
                    if chunk_blocks > 0:
                        payloads = handoff.chunk_pack(pack, chunk_blocks)
                    else:
                        payloads = [handoff.serialize(pack)]
                    rng_state = rng.bit_generator.state
                self.engine.flush(uid)
                self._m_prefills.labels(replica=self.name).inc()
                return tok, payloads, rng_state, finished
            finally:
                trace.set_lane(prev_lane)

    def health(self) -> dict:
        sm = self.engine.state_manager
        return {"name": self.name, "state": self.state, "role": "prefill",
                "free_blocks": sm.free_blocks(),
                "tracked_sequences": sm.tracked_sequences(),
                "weight_version": self.weight_version}

    @property
    def weight_version(self) -> int:
        return int(getattr(self.engine, "weight_version", 0))

    async def apply_weights(self, payloads: Sequence[bytes]) -> int:
        """Swap this prefill worker's params (no serving loop — the
        engine lock serializes against in-flight prefills, so a prompt
        is never half-prefilled across versions)."""
        from . import weights as serve_weights

        def swap() -> int:
            with self._lock:
                return serve_weights.apply_payload(self.engine, payloads)
        return await asyncio.to_thread(swap)


def build_replicas(engines: Sequence, config: Optional[ServingConfig]
                   = None, name_prefix: str = "replica",
                   own_registries: bool = False) -> List[Replica]:
    """Wrap N engines as named replicas sharing one serving config
    template (each replica gets its OWN config instance — admission
    state is per replica). ``own_registries=True`` gives every replica
    its own :class:`MetricsRegistry` (the federation unit the router's
    ``/metrics`` labels per replica)."""
    import copy

    from ....telemetry.registry import MetricsRegistry
    return [Replica(f"{name_prefix}{i}", eng,
                    copy.deepcopy(config) if config is not None else None,
                    registry=MetricsRegistry() if own_registries else None)
            for i, eng in enumerate(engines)]
