"""Autoscaler loop over the replica router.

Scales the serving fleet off the signals the router already collects
(docs/SERVING.md § Remote replicas & autoscaling): sustained shed /
re-route pressure or a burning fleet SLO scales UP (a factory spawns a
new replica — in-process, or a worker subprocess wrapped in a
:class:`~.remote.RemoteReplica` — and the router's dynamic membership
adds it to the ring); a sustained idle fleet scales DOWN by
drain-then-stop (in-flight streams finish, new traffic diverts, then
the replica stops — a worker process exits); dead replicas (heartbeat
expiry, loop exit) are replaced up to ``min_replicas``.

The decision cadence is :meth:`Autoscaler.tick` — pure and
deterministic given the router state, so tests drive it directly; the
background :meth:`run` task just calls it on ``interval_s``. Every
action is counted (``router_autoscale_{up,down}_total``), the tick
cost is histogrammed (``router_autoscale_tick_seconds`` — the perf
gate pins it next to ``router_dispatch_ns_per_request``), and each
action records a ``router_autoscale`` span in the router lane so fleet
timelines show scaling next to the traffic that caused it.
"""

import asyncio
import itertools
import time
from dataclasses import dataclass
from typing import Awaitable, Callable, Optional

from ....telemetry import trace

_ROUTER_LANE = "router"


@dataclass
class AutoscalerConfig:
    min_replicas: int = 1
    max_replicas: int = 4
    # consecutive pressure ticks (shed/re-route events, burning fleet
    # SLO, or mean up-replica load above load_high) before scaling up
    scale_up_after_ticks: int = 2
    # consecutive fully-idle ticks (zero load, zero shed) before
    # scaling down
    scale_down_after_ticks: int = 5
    # mean load per up replica that counts as pressure even without
    # sheds (queued tokens + in-flight requests, the router's load
    # signal)
    load_high: float = 64.0
    # minimum seconds between scale actions (replacing dead capacity
    # below min_replicas ignores the cooldown)
    cooldown_s: float = 2.0
    # background run() cadence
    interval_s: float = 0.5
    replace_dead: bool = True
    # spawn-failure quarantine: after a factory/spawn failure the
    # autoscaler backs off exponentially (base * 2^(failures-1), capped)
    # before trying to spawn again — even for dead-capacity
    # replacement, so a broken factory cannot hot-loop
    spawn_backoff_s: float = 1.0
    spawn_backoff_max_s: float = 30.0


class Autoscaler:
    """Spawn/drain replicas off the router's load, shed, SLO-burn and
    heartbeat signals.

    ``factory``: ``async (name) -> replica`` building a NOT-yet-added
    replica — an in-process :class:`~.replica.Replica` or a
    :class:`~.remote.RemoteReplica` over a freshly spawned worker
    process. The autoscaler adds it to the router (which starts it)."""

    def __init__(self, router,
                 factory: Callable[[str], Awaitable],
                 config: Optional[AutoscalerConfig] = None,
                 clock=time.monotonic, name_prefix: str = "auto"):
        self.router = router
        self.factory = factory
        self.config = config or AutoscalerConfig()
        if self.config.min_replicas < 1:
            raise ValueError("min_replicas must be >= 1")
        if self.config.max_replicas < self.config.min_replicas:
            raise ValueError("max_replicas must be >= min_replicas")
        self.clock = clock
        self.name_prefix = name_prefix
        self._ids = itertools.count(len(router.replicas))
        self._pressure_ticks = 0
        self._idle_ticks = 0
        self._last_events = self._event_count()
        self._last_action_t: Optional[float] = None
        self._spawning = False
        self._spawn_failures = 0
        self._spawn_quarantine_until: Optional[float] = None
        self._last_spawn_error: Optional[str] = None
        self._task: Optional[asyncio.Task] = None
        from ....telemetry import get_registry
        reg = get_registry()
        self._m_up = reg.counter(
            "router_autoscale_up_total",
            "replicas spawned by the autoscaler",
            labelnames=("reason",))
        self._m_down = reg.counter(
            "router_autoscale_down_total",
            "replicas drained and stopped by the autoscaler")
        self._m_replicas = reg.gauge(
            "router_autoscale_replicas",
            "up replicas as last seen by the autoscaler")
        self._m_tick = reg.histogram(
            "router_autoscale_tick_seconds",
            "autoscaler decision-loop cost per tick (excl. spawn/drain "
            "awaits)", unit="s",
            buckets=(1e-6, 1e-5, 1e-4, 1e-3, 1e-2, 0.1))
        self._m_spawn_fail = reg.counter(
            "router_autoscale_spawn_failures_total",
            "factory/spawn failures caught by the autoscaler (the "
            "failure is recorded in last_decision and the spawner "
            "quarantined; it never escapes tick())")

    # -- signals --------------------------------------------------------
    def _event_count(self) -> float:
        """Cumulative overload events at the router: sheds (every
        routable replica rejected) plus re-routes (one replica rejected,
        another absorbed) — the pressure signal."""
        from ....telemetry import get_registry
        reg = get_registry()
        total = 0.0
        for name in ("router_shed_total", "router_reroutes_total"):
            fam = reg.get(name)
            if fam is not None:
                total += sum(s.value for _, s in fam.series())
        return total

    def _slo_burning(self) -> bool:
        slo = getattr(self.router, "fleet_slo", None)
        return bool(slo is not None and slo.burning())

    # -- one decision round ---------------------------------------------
    async def tick(self) -> dict:
        """Observe, update streaks, take at most one scale action.
        Returns the decision record (/statusz + tests read it)."""
        t0 = time.perf_counter()
        await self.router.check_replicas()
        cfg = self.config
        # reap corpses: check_replicas already re-enqueued a dead
        # replica's requests, so keeping it in the member list would
        # only grow the hash ring / health rollups / metric series
        # forever under worker churn
        for r in [r for r in self.router.replicas
                  if r.state in ("dead", "drained")]:
            try:
                self.router.remove_replica(r.name)
            except (KeyError, RuntimeError):
                pass
        up = [r for r in self.router.replicas if r.state == "up"]
        loads = [r.load() for r in up]
        events = self._event_count()
        shed_delta = events - self._last_events
        self._last_events = events
        burning = self._slo_burning()
        mean_load = (sum(loads) / len(up)) if up else float("inf")
        pressure = (shed_delta > 0 or burning
                    or mean_load > cfg.load_high)
        idle = not pressure and sum(loads) == 0 and shed_delta == 0
        if pressure:
            self._pressure_ticks += 1
            self._idle_ticks = 0
        elif idle:
            self._idle_ticks += 1
            self._pressure_ticks = 0
        else:
            self._pressure_ticks = 0
            self._idle_ticks = 0
        self._m_replicas.set(len(up))
        decision = {
            "up_replicas": len(up), "mean_load": round(mean_load, 3)
            if up else None, "shed_delta": shed_delta,
            "slo_burning": burning,
            "pressure_ticks": self._pressure_ticks,
            "idle_ticks": self._idle_ticks, "action": "none",
        }
        self._m_tick.observe(time.perf_counter() - t0)

        now = self.clock()
        cooled = (self._last_action_t is None
                  or now - self._last_action_t >= cfg.cooldown_s)
        # spawn quarantine: a failed factory backs the SPAWNER off (not
        # just the cooldown), and dead-capacity replacement respects it
        # too — plus the breaker state: suspected replicas still count
        # as up capacity, so suspicion never triggers a replacement
        spawn_ok = (self._spawn_quarantine_until is None
                    or now >= self._spawn_quarantine_until)
        decision["spawn_quarantine_s"] = (
            round(max(self._spawn_quarantine_until - now, 0.0), 3)
            if self._spawn_quarantine_until is not None else 0.0)
        if (cfg.replace_dead and len(up) < cfg.min_replicas
                and spawn_ok and not self._spawning):
            decision["action"] = await self._scale_up("replace_dead")
        elif (self._pressure_ticks >= cfg.scale_up_after_ticks
                and len(up) < cfg.max_replicas and cooled and spawn_ok
                and not self._spawning):
            decision["action"] = await self._scale_up("pressure")
            self._pressure_ticks = 0
        elif (self._idle_ticks >= cfg.scale_down_after_ticks
                and len(up) > cfg.min_replicas and cooled):
            decision["action"] = await self._scale_down(up, loads)
            self._idle_ticks = 0
        if decision["action"].startswith("up_failed:"):
            decision["spawn_error"] = self._last_spawn_error
            decision["spawn_quarantine_s"] = round(
                max(self._spawn_quarantine_until - self.clock(), 0.0), 3)
        self.last_decision = decision
        return decision

    def _spawn_call(self, name: str):
        """Call the factory, passing the fleet's current target weight
        version when the factory accepts it — a scale-up after a push
        must join at the LIVE version, not the boot checkpoint (the
        router's ``sync_weights_on_add`` then verifies/pushes either
        way)."""
        import inspect
        target = getattr(self.router, "target_weight_version", None)
        try:
            sig = inspect.signature(self.factory)
            accepts = ("weight_version" in sig.parameters
                       or any(p.kind is inspect.Parameter.VAR_KEYWORD
                              for p in sig.parameters.values()))
        except (TypeError, ValueError):
            accepts = False
        if accepts:
            return self.factory(name, weight_version=target)
        return self.factory(name)

    async def _scale_up(self, reason: str) -> str:
        name = f"{self.name_prefix}{next(self._ids)}"
        t0 = time.perf_counter()
        self._spawning = True
        try:
            replica = await self._spawn_call(name)
            await self.router.add_replica(replica)
        except Exception as e:
            # a spawn failure must never escape tick(): count it,
            # record it, quarantine the spawner with exponential
            # backoff, and STILL advance the cooldown clock so the
            # decision cadence stays honest
            self._spawn_failures += 1
            self._m_spawn_fail.inc()
            backoff = min(
                self.config.spawn_backoff_s
                * 2 ** (self._spawn_failures - 1),
                self.config.spawn_backoff_max_s)
            self._spawn_quarantine_until = self.clock() + backoff
            self._last_action_t = self.clock()
            self._last_spawn_error = f"{type(e).__name__}: {e}"
            trace.record("router_autoscale", t0,
                         time.perf_counter() - t0, lane=_ROUTER_LANE,
                         action="up_failed", replica=name,
                         reason=reason, error=self._last_spawn_error,
                         backoff_s=round(backoff, 3))
            return f"up_failed:{name}"
        finally:
            self._spawning = False
        self._spawn_failures = 0
        self._spawn_quarantine_until = None
        self._last_action_t = self.clock()
        self._m_up.labels(reason=reason).inc()
        trace.record("router_autoscale", t0, time.perf_counter() - t0,
                     lane=_ROUTER_LANE, action="up", replica=name,
                     reason=reason)
        return f"up:{name}"

    async def _scale_down(self, up, loads) -> str:
        # drain the least-loaded up replica (ties: newest name last so
        # the original fixed fleet is preferred to stay)
        name = min(zip(loads, (r.name for r in up)))[1]
        replica = self.router._by_name[name]
        t0 = time.perf_counter()
        try:
            await self.router.drain_replica(name)
            await replica.stop()    # a worker process exits here
        except Exception:
            # the worker died mid-drain: a replica stuck in 'draining'
            # would never be declared dead NOR reaped — mark it dead so
            # membership cleanup still happens
            replica.state = "dead"
            try:
                replica.reap()
            except Exception:
                pass
        self.router.remove_replica(name)
        self._last_action_t = self.clock()
        self._m_down.inc()
        trace.record("router_autoscale", t0, time.perf_counter() - t0,
                     lane=_ROUTER_LANE, action="down", replica=name)
        return f"down:{name}"

    # -- background loop ------------------------------------------------
    def start(self) -> "Autoscaler":
        if self._task is None:
            self._task = asyncio.ensure_future(self.run())
        return self

    async def run(self) -> None:
        while True:
            await asyncio.sleep(self.config.interval_s)
            try:
                await self.tick()
            except asyncio.CancelledError:
                raise
            except Exception:    # scaling must never kill the router
                pass

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
