"""Replica worker process: one ServingEngine behind the HTTP API.

``python -m deepspeed_tpu.inference.v2.serve.worker`` hosts ONE
in-process :class:`~.replica.Replica` (engine + serving runtime) behind
the serve/api.py surface plus the worker-only endpoints the remote
serving plane needs (docs/SERVING.md § Remote replicas & autoscaling):

  * ``POST /generate`` / ``GET /healthz`` / ``GET /metrics`` /
    ``GET /statusz`` / ``GET /debug/timeline`` /
    ``POST /debug/postmortem`` — unchanged from :class:`~.api.ServingAPI`
    (``/healthz`` carries the replica-level ``load`` /
    ``heartbeat_age_s`` / ``block_size`` fields the router's
    RemoteReplica maps its signals from);
  * ``POST /drain`` — graceful drain: new submits shed immediately,
    admitted work finishes, then the response returns (the process
    stays up so the autoscaler can drain-then-stop);
  * ``POST /stop`` — hard stop: in-flight requests cancel and the
    process exits;
  * ``POST /handoff`` — chunked streaming KV ingest
    (serve/remote.py frame protocol): each ``C`` frame is applied to
    the pool BETWEEN decode steps as it arrives — the transfer overlaps
    this replica's running batch — then the terminal ``P`` frame
    commits the restore and the decode token stream flows back on the
    same connection. EOF before ``P`` aborts the restore and frees the
    partially-filled blocks.
  * ``GET /debug/spans`` — the raw span ring plus a
    ``perf_counter``/wall-clock anchor, so a router in another process
    can rebase and stitch this replica's lane into the fleet timeline.

On start the worker prints ONE ready line — ``DS_TPU_WORKER_READY
{"name", "host", "port", "pid", "block_size"}`` — to stdout (scan for
the prefix: engine-build logging precedes it), which spawners (an
autoscaler subprocess factory, the slow spawn smoke test) parse to
address it.
"""

import argparse
import asyncio
import json
import os
import sys
import time
from typing import Optional, Tuple

from ....telemetry import context as trace_context
from .api import ServingAPI, _json_response, _response_head
from .frontend import ServingConfig
from .remote import (FRAME_BLOCKING, FRAME_CHUNK, FRAME_PARAMS,
                     read_frame)

# the tiny deterministic model the tests/gate/spawn-smoke use: params
# init from PRNGKey(0) is bit-reproducible across processes, so a
# remote worker built from the same spec serves bit-identical streams
TINY_SPEC = {
    "model": {"vocab_size": 128, "hidden_size": 64,
              "intermediate_size": 128, "num_layers": 2, "num_heads": 4,
              "num_kv_heads": 2, "max_seq_len": 256, "remat": False,
              "use_flash": False},
    "state_manager": {"max_tracked_sequences": 8, "max_seq_len": 256,
                      "num_blocks": 65, "block_size": 16,
                      "max_ragged_batch_size": 512},
    "engine": {"dtype": "float32", "prefill_bucket": 16},
    "serving": {"token_budget": 64, "chunk": 16},
}


def build_engine(spec: dict):
    """Engine from a worker spec dict (the ``--spec`` JSON layout)."""
    import jax
    import jax.numpy as jnp

    from ....models import TransformerConfig, TransformerLM
    from .. import InferenceEngineV2, RaggedInferenceEngineConfig
    from ..config_v2 import DSStateManagerConfig
    model = TransformerLM(TransformerConfig(**spec["model"]))
    params = jax.tree.map(
        lambda x: x.astype(jnp.float32),
        model.init_params(jax.random.PRNGKey(spec.get("seed", 0))))
    return InferenceEngineV2(
        model, RaggedInferenceEngineConfig(
            state_manager=DSStateManagerConfig(**spec["state_manager"]),
            **spec.get("engine", {})), params=params)


class WorkerAPI(ServingAPI):
    """ServingAPI over one local Replica, plus the worker lifecycle and
    handoff-ingest endpoints."""

    def __init__(self, replica, host: str = "127.0.0.1", port: int = 0):
        super().__init__(replica, host=host, port=port)
        self.replica = replica
        self.stopped = asyncio.Event()

    async def _route_extra(self, method: str, target: str, query: str,
                           headers, body, reader, writer) -> bool:
        if method == "POST" and target == "/drain":
            await self.replica.drain()
            _json_response(writer, "200 OK", {"status": "drained",
                                              "name": self.replica.name})
            return True
        if method == "POST" and target == "/stop":
            _json_response(writer, "200 OK", {"status": "stopping",
                                              "name": self.replica.name})
            # respond first, then stop: the caller's request must not
            # hang on the runtime it is killing
            asyncio.ensure_future(self._stop_replica())
            return True
        if method == "POST" and target == "/handoff":
            await self._handoff(reader, writer, headers)
            return True
        if method == "GET" and target == "/debug/spans":
            from ....telemetry import trace
            spans = json.loads(json.dumps(trace.export(), default=str))
            _json_response(writer, "200 OK",
                           {"spans": spans,
                            "perf_now": time.perf_counter(),
                            "wall_now": time.time()})
            return True
        return False

    async def _stop_replica(self) -> None:
        try:
            await self.replica.stop()
        finally:
            self.stopped.set()

    async def _handoff(self, reader, writer, headers) -> None:
        """Chunked KV ingest (module docstring): apply frames as they
        arrive, commit on the params frame, stream tokens back."""
        upstream = trace_context.from_headers(headers or {})
        ctx = (upstream.child() if upstream is not None
               else trace_context.new_context())
        handle = None
        blocking_payload = None
        params = None

        async def fail(reason: str, detail: str,
                       retry_after_s=None) -> None:
            writer.write(_response_head("200 OK",
                                        "application/x-ndjson"))
            writer.write(json.dumps(
                {"ok": False, "reason": reason, "detail": detail,
                 "retry_after_s": retry_after_s}).encode() + b"\n")
            # drain the client's in-flight frames before the connection
            # closes: an unread receive buffer would RST the socket and
            # can discard the verdict the client needs to re-route
            try:
                await asyncio.wait_for(writer.drain(), 5.0)
                await asyncio.wait_for(reader.read(), 5.0)
            except (OSError, asyncio.TimeoutError, ConnectionError):
                pass

        from .admission import OverloadedError
        try:
            with trace_context.use(ctx):
                while True:
                    try:
                        kind, payload = await read_frame(reader)
                    except (asyncio.IncompleteReadError,
                            ConnectionResetError):
                        # client hung up mid-transfer: abort the restore
                        # so the partially-filled blocks free
                        if handle is not None:
                            await handle.abort()
                        return
                    if kind == FRAME_BLOCKING:
                        blocking_payload = payload
                    elif kind == FRAME_CHUNK:
                        if handle is None:
                            handle = await self.replica.serving \
                                .begin_handoff(payload)
                        else:
                            await handle.feed(payload)
                    elif kind == FRAME_PARAMS:
                        params = json.loads(payload.decode())
                        break
                    else:
                        if handle is not None:
                            await handle.abort()
                        await fail("protocol",
                                   f"unknown frame {kind!r}")
                        return
                kw = dict(
                    prompt=params["prompt"],
                    generated=params["generated"],
                    max_new_tokens=params["max_new_tokens"],
                    eos_token_id=params.get("eos_token_id"),
                    temperature=params.get("temperature", 0.0),
                    top_p=params.get("top_p", 1.0),
                    top_k=params.get("top_k", 0),
                    rng_state=_rng_state_from_wire(
                        params.get("rng_state")),
                    deadline_s=params.get("deadline_s"))
                if handle is not None:
                    stream = await handle.commit(**kw)
                elif blocking_payload is not None:
                    from . import handoff as handoff_mod
                    pack = await asyncio.to_thread(
                        handoff_mod.deserialize, blocking_payload)
                    stream = await self.replica.serving.resume(
                        pack, **kw)
                else:
                    await fail("protocol",
                               "no handoff payload before params")
                    return
        except OverloadedError as e:
            if handle is not None:
                await handle.abort()
            await fail(e.reason, str(e), retry_after_s=e.retry_after_s)
            return
        except Exception as e:
            if handle is not None:
                await handle.abort()
            await fail("error", f"{type(e).__name__}: {e}")
            return
        writer.write(_response_head(
            "200 OK", "application/x-ndjson",
            {"traceparent": ctx.to_traceparent()}))
        writer.write(json.dumps({"ok": True}).encode() + b"\n")
        await self._stream_tokens(reader, writer, stream, ctx)


def _rng_state_from_wire(state):
    """numpy bit-generator state dicts ride JSON losslessly (Python
    ints are arbitrary precision); nested lists that were tuples on
    export are accepted by numpy's setter as-is."""
    return state


class ReplicaWorker:
    """One replica + its WorkerAPI, runnable in-process (the loopback
    tests and the perf gate) or as the __main__ process."""

    def __init__(self, engine, serving_config: Optional[ServingConfig]
                 = None, name: str = "worker0",
                 host: str = "127.0.0.1", port: int = 0):
        from .replica import Replica
        self.replica = Replica(name, engine, serving_config)
        self.api = WorkerAPI(self.replica, host=host, port=port)

    async def start(self) -> Tuple[str, int]:
        await self.replica.start()
        return await self.api.start()

    async def stop(self) -> None:
        try:
            if self.replica.serving.loop_runner.running:
                await self.replica.stop()
        finally:
            await self.api.stop()

    async def run_until_stopped(self) -> None:
        await self.api.stopped.wait()
        await self.api.stop()


def _serving_config(spec: dict) -> ServingConfig:
    kw = dict(spec.get("serving", {}))
    admission = kw.pop("admission", None)
    cfg = ServingConfig(**kw)
    if admission:
        from .admission import AdmissionConfig
        cfg.admission = AdmissionConfig(**admission)
    return cfg


READY_PREFIX = "DS_TPU_WORKER_READY "


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="deepspeed_tpu serving replica worker")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0,
                   help="0 picks a free port (printed on stdout)")
    p.add_argument("--name", default=f"worker-{os.getpid()}")
    p.add_argument("--spec", default=None,
                   help="JSON file with model/state_manager/engine/"
                        "serving sections (default: the tiny "
                        "deterministic preset)")
    p.add_argument("--jax-platform", default=None,
                   help="force a jax platform (e.g. 'cpu' for the "
                        "chip-free smoke; default: whatever jax picks)")
    p.add_argument("--compile-cache", default=None,
                   help="persistent XLA compilation cache dir "
                        "(default: $DS_TPU_COMPILE_CACHE if set)")
    args = p.parse_args(argv)
    import jax
    if args.jax_platform:
        jax.config.update("jax_platforms", args.jax_platform)
    cache = args.compile_cache or os.environ.get("DS_TPU_COMPILE_CACHE")
    if cache:
        os.makedirs(cache, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          0.5)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    if args.spec:
        with open(args.spec) as fh:
            spec = json.load(fh)
    else:
        spec = TINY_SPEC

    async def run() -> None:
        worker = ReplicaWorker(build_engine(spec),
                               _serving_config(spec), name=args.name,
                               host=args.host, port=args.port)
        host, port = await worker.start()
        print(READY_PREFIX + json.dumps(
            {"name": args.name, "host": host, "port": port,
             "pid": os.getpid(),
             "block_size": spec["state_manager"]["block_size"]}),
            flush=True)
        await worker.run_until_stopped()

    asyncio.run(run())
    return 0


if __name__ == "__main__":
    sys.exit(main())
