"""Replica worker process: one ServingEngine behind the HTTP API.

``python -m deepspeed_tpu.inference.v2.serve.worker`` hosts ONE
in-process :class:`~.replica.Replica` (engine + serving runtime) behind
the serve/api.py surface plus the worker-only endpoints the remote
serving plane needs (docs/SERVING.md § Remote replicas & autoscaling):

  * ``POST /generate`` / ``GET /healthz`` / ``GET /metrics`` /
    ``GET /statusz`` / ``GET /debug/timeline`` /
    ``POST /debug/postmortem`` — unchanged from :class:`~.api.ServingAPI`
    (``/healthz`` carries the replica-level ``load`` /
    ``heartbeat_age_s`` / ``block_size`` fields the router's
    RemoteReplica maps its signals from);
  * ``POST /drain`` — graceful drain: new submits shed immediately,
    admitted work finishes, then the response returns (the process
    stays up so the autoscaler can drain-then-stop);
  * ``POST /stop`` — hard stop: in-flight requests cancel and the
    process exits;
  * ``POST /handoff`` — chunked streaming KV ingest
    (serve/remote.py frame protocol): each ``C`` frame is applied to
    the pool BETWEEN decode steps as it arrives — the transfer overlaps
    this replica's running batch — then the terminal ``P`` frame
    commits the restore and the decode token stream flows back on the
    same connection. EOF before ``P`` aborts the restore and frees the
    partially-filled blocks.
  * ``GET /debug/spans`` — the raw span ring plus a
    ``perf_counter``/wall-clock anchor, so a router in another process
    can rebase and stitch this replica's lane into the fleet timeline.
  * ``GET /resume?uid=N&offset=K`` — MID-STREAM RECONNECT (ISSUE 14):
    every streamed request keeps a bounded per-uid token log; a client
    whose connection dropped re-attaches here and the worker replays
    the log from ``offset`` (dedup by position — the stream stays
    bit-identical) then keeps streaming live. A bare connection loss
    does NOT cancel the request: the worker holds it resumable for
    ``resume_linger_s`` (the KV is still intact — dropping it would
    amplify a network blip into request loss); only an EXPLICIT client
    cancel (one cancel byte before close, serve/remote.py) or linger
    expiry frees the KV. A request cancelled by linger expiry answers
    later resumes with a typed error, never a silently-truncated
    "completed" stream.

On start the worker prints ONE ready line — ``DS_TPU_WORKER_READY
{"name", "host", "port", "pid", "block_size"}`` — to stdout (scan for
the prefix: engine-build logging precedes it), which spawners (an
autoscaler subprocess factory, the slow spawn smoke test) parse to
address it; :func:`spawn_worker` wraps the whole handshake — spawn,
wait for the ready line under an explicit timeout, and surface the
captured stderr when the worker dies before it.
"""

import argparse
import asyncio
import json
import os
import sys
import time
from collections import OrderedDict
from typing import List, Optional, Tuple

from ....telemetry import context as trace_context
from .api import UID_HEADER, ServingAPI, _json_response, _response_head
from .frontend import ServingConfig
from .remote import (FRAME_BLOCKING, FRAME_CHUNK, FRAME_PARAMS,
                     read_frame)

# the tiny deterministic model the tests/gate/spawn-smoke use: params
# init from PRNGKey(0) is bit-reproducible across processes, so a
# remote worker built from the same spec serves bit-identical streams
TINY_SPEC = {
    "model": {"vocab_size": 128, "hidden_size": 64,
              "intermediate_size": 128, "num_layers": 2, "num_heads": 4,
              "num_kv_heads": 2, "max_seq_len": 256, "remat": False,
              "use_flash": False},
    "state_manager": {"max_tracked_sequences": 8, "max_seq_len": 256,
                      "num_blocks": 65, "block_size": 16,
                      "max_ragged_batch_size": 512},
    "engine": {"dtype": "float32", "prefill_bucket": 16},
    "serving": {"token_budget": 64, "chunk": 16},
}


def build_engine(spec: dict):
    """Engine from a worker spec dict (the ``--spec`` JSON layout)."""
    import jax
    import jax.numpy as jnp

    from ....models import TransformerConfig, TransformerLM
    from .. import InferenceEngineV2, RaggedInferenceEngineConfig
    from ..config_v2 import DSStateManagerConfig
    model = TransformerLM(TransformerConfig(**spec["model"]))
    params = jax.tree.map(
        lambda x: x.astype(jnp.float32),
        model.init_params(jax.random.PRNGKey(spec.get("seed", 0))))
    return InferenceEngineV2(
        model, RaggedInferenceEngineConfig(
            state_manager=DSStateManagerConfig(**spec["state_manager"]),
            **spec.get("engine", {})), params=params)


class _StreamRecord:
    """One resumable request: the live TokenStream, its bounded token
    log (``base`` = offset of ``tokens[0]`` once the front is trimmed),
    and the attachment/linger state. All state lives on the worker's
    one event loop — no locking."""

    def __init__(self, uid: int, stream, ctx, log_limit: int):
        self.uid = uid
        self.stream = stream
        self.ctx = ctx
        self.log_limit = log_limit
        self.tokens: List[int] = []
        self.base = 0
        self.status: Optional[str] = None
        self.detail: Optional[str] = None
        self.done = False
        self.event = asyncio.Event()
        self.attached = 0
        self.linger = None           # pending call_later handle
        self.linger_expired = False
        self.client_cancelled = False
        self.task: Optional[asyncio.Task] = None

    @property
    def end(self) -> int:
        return self.base + len(self.tokens)


class WorkerAPI(ServingAPI):
    """ServingAPI over one local Replica, plus the worker lifecycle,
    handoff-ingest and mid-stream-resume endpoints (module
    docstring)."""

    def __init__(self, replica, host: str = "127.0.0.1", port: int = 0,
                 *, resume_linger_s: float = 2.0,
                 token_log_limit: int = 4096, resume_records: int = 256,
                 auth_token: Optional[str] = None):
        super().__init__(replica, host=host, port=port,
                         auth_token=auth_token)
        self.replica = replica
        self.stopped = asyncio.Event()
        self.resume_linger_s = resume_linger_s
        self.token_log_limit = token_log_limit
        self.resume_records = resume_records
        self._records: "OrderedDict[int, _StreamRecord]" = OrderedDict()
        from ....telemetry import get_registry
        self._m_resume = get_registry().counter(
            "worker_resume_requests_total",
            "GET /resume reconnect attempts answered by this worker",
            labelnames=("outcome",))

    async def _route_extra(self, method: str, target: str, query: str,
                           headers, body, reader, writer) -> bool:
        if method == "GET" and target == "/resume":
            await self._resume_route(query, reader, writer)
            return True
        if method == "POST" and target == "/drain":
            await self.replica.drain()
            _json_response(writer, "200 OK", {"status": "drained",
                                              "name": self.replica.name})
            return True
        if method == "POST" and target == "/stop":
            _json_response(writer, "200 OK", {"status": "stopping",
                                              "name": self.replica.name})
            # respond first, then stop: the caller's request must not
            # hang on the runtime it is killing
            asyncio.ensure_future(self._stop_replica())
            return True
        if method == "POST" and target == "/handoff":
            await self._handoff(reader, writer, headers)
            return True
        if method == "POST" and target == "/weights":
            await self._weights(reader, writer)
            return True
        if method == "GET" and target == "/debug/spans":
            from ....telemetry import trace
            spans = json.loads(json.dumps(trace.export(), default=str))
            _json_response(writer, "200 OK",
                           {"spans": spans,
                            "perf_now": time.perf_counter(),
                            "wall_now": time.time()})
            return True
        if method == "POST" and target == "/spill/adopt":
            await self._spill_adopt(body, writer)
            return True
        return False

    async def _spill_adopt(self, body: bytes, writer) -> None:
        """Adopt a dead peer's disk-tier spill namespace (router session
        resurrection over a shared ``kv_spill_dir``). Answers with the
        adopted-entry count and the post-adoption /healthz summary so
        the caller's placement view updates without waiting a probe."""
        try:
            obj = json.loads(body.decode("utf-8")) if body else {}
            ns = obj["namespace"]
            if not isinstance(ns, str) or not ns:
                raise ValueError("namespace must be a non-empty string")
        except (ValueError, KeyError, UnicodeDecodeError) as e:
            _json_response(writer, "400 Bad Request",
                           {"error": "bad_request",
                            "detail": f"{type(e).__name__}: {e}"})
            return
        try:
            adopted = await self.replica.adopt_spill(ns)
        except Exception as e:  # adoption failure degrades to recompute
            _json_response(writer, "200 OK",
                           {"adopted": 0, "name": self.replica.name,
                            "detail": f"{type(e).__name__}: {e}"})
            return
        doc = self.replica.serving.spill_summary_doc()
        _json_response(writer, "200 OK",
                       {"adopted": adopted, "name": self.replica.name,
                        "kv_spill": doc})

    async def _stop_replica(self) -> None:
        try:
            await self.replica.stop()
        finally:
            self.stopped.set()

    # -- resumable streaming (mid-stream reconnect) ---------------------
    async def _stream_tokens(self, reader, writer, stream, ctx) -> None:
        """Worker override of the streaming pump: tokens flow through a
        bounded per-uid log so a dropped connection can re-attach at
        its offset (``GET /resume``) instead of killing the request."""
        rec = self._track(stream, ctx)
        await self._serve_record(reader, writer, rec, offset=0)

    def _track(self, stream, ctx) -> _StreamRecord:
        rec = _StreamRecord(stream.uid, stream, ctx,
                            self.token_log_limit)
        self._records[stream.uid] = rec
        rec.task = asyncio.ensure_future(self._pump_record(rec))
        # bounded registry: evict finished, detached records oldest
        # first (live or attached ones are never evicted)
        while len(self._records) > self.resume_records:
            for uid, r in list(self._records.items()):
                if r.done and r.attached == 0:
                    del self._records[uid]
                    break
            else:
                break
        return rec

    async def _pump_record(self, rec: _StreamRecord) -> None:
        from .frontend import DeadlineExceeded, RequestFailed
        try:
            async for tok in rec.stream:
                rec.tokens.append(int(tok))
                if len(rec.tokens) > rec.log_limit:
                    drop = len(rec.tokens) - rec.log_limit
                    del rec.tokens[:drop]
                    rec.base += drop
                rec.event.set()
            status = rec.stream.status
            detail = getattr(rec.stream, "reason", None)
        except DeadlineExceeded:
            status, detail = "expired", "deadline exceeded"
        except RequestFailed as e:
            status, detail = "error", str(e)
        except Exception as e:       # never strand a waiting client
            status, detail = "error", f"{type(e).__name__}: {e}"
        if status == "cancelled" and not rec.client_cancelled:
            # the CLIENT did not ask for this: linger expiry or a
            # server-side hard stop truncated the request — surface it
            # TYPED, never as a silently-truncated end-of-stream
            status = "error"
            detail = (f"resume window expired ({self.resume_linger_s}s "
                      f"with no client attached); request cancelled"
                      if rec.linger_expired else
                      "request cancelled by the server (hard stop)")
        rec.status, rec.detail = status, detail
        rec.done = True
        rec.event.set()

    async def _serve_record(self, reader, writer, rec: _StreamRecord,
                            offset: int) -> None:
        """Pump one connection from the record: replay the log from
        ``offset``, then follow live until the request ends (tail
        summary) or the client detaches (hangup -> linger window)."""
        rec.attached += 1
        if rec.linger is not None:
            rec.linger.cancel()
            rec.linger = None
        hangup = asyncio.ensure_future(reader.read(1))
        pos = offset
        detached = False
        try:
            while True:
                if pos < rec.base:
                    # the bounded log trimmed past this connection's
                    # position (a slow client fell behind generation):
                    # fail TYPED — serving rec.tokens[negative] would
                    # be silent stream corruption
                    writer.write(json.dumps(
                        {"done": True, "status": "error",
                         "uid": rec.uid,
                         "detail": f"client fell behind the bounded "
                                   f"token log (position {pos} < "
                                   f"retained base {rec.base})"}
                        ).encode() + b"\n")
                    await writer.drain()
                    return
                while pos < rec.end:
                    writer.write(json.dumps(
                        {"token": rec.tokens[pos - rec.base]}).encode()
                        + b"\n")
                    pos += 1
                await writer.drain()
                if rec.done:
                    break
                if hangup.done():
                    break
                rec.event.clear()
                if pos < rec.end or rec.done:
                    continue     # raced a new token past the clear
                waiter = asyncio.ensure_future(rec.event.wait())
                done, _ = await asyncio.wait(
                    {waiter, hangup},
                    return_when=asyncio.FIRST_COMPLETED)
                if hangup in done and waiter not in done:
                    waiter.cancel()
                    break
            if hangup.done() and not rec.done:
                data = (hangup.result()
                        if not hangup.cancelled() else b"")
                if data:
                    # explicit client cancel (serve/remote.py writes a
                    # cancel byte): free the KV NOW, no linger
                    rec.client_cancelled = True
                    await rec.stream.cancel()
                else:
                    detached = True   # bare loss: hold resumable
                return
            tail = {"done": True, "status": rec.status, "uid": rec.uid,
                    "n": rec.end, "tokens": list(rec.tokens),
                    "trace_id": (rec.ctx.trace_id
                                 if rec.ctx is not None else None)}
            if rec.base:
                tail["token_base"] = rec.base
            if rec.detail:
                tail["detail"] = rec.detail
            writer.write(json.dumps(tail).encode() + b"\n")
            await writer.drain()
        except (ConnectionResetError, BrokenPipeError, OSError):
            detached = True
        finally:
            hangup.cancel()
            rec.attached -= 1
            if detached and not rec.done and rec.attached == 0:
                self._arm_linger(rec)

    def _arm_linger(self, rec: _StreamRecord) -> None:
        loop = asyncio.get_event_loop()
        rec.linger = loop.call_later(
            self.resume_linger_s,
            lambda: asyncio.ensure_future(self._linger_expire(rec)))

    async def _linger_expire(self, rec: _StreamRecord) -> None:
        rec.linger = None
        if rec.done or rec.attached > 0:
            return
        rec.linger_expired = True
        await rec.stream.cancel()

    async def _resume_route(self, query: str, reader, writer) -> None:
        from urllib.parse import parse_qs
        q = parse_qs(query)
        try:
            uid = int(q["uid"][0])
            offset = int(q.get("offset", ["0"])[0])
        except (KeyError, ValueError, IndexError):
            self._m_resume.labels(outcome="bad_request").inc()
            _json_response(writer, "400 Bad Request",
                           {"error": "bad_request",
                            "detail": "resume needs integer uid= and "
                                      "offset= parameters"})
            return
        rec = self._records.get(uid)
        if rec is None:
            self._m_resume.labels(outcome="unknown_uid").inc()
            _json_response(writer, "410 Gone",
                           {"error": "unknown_uid",
                            "detail": f"no resumable stream for uid "
                                      f"{uid} (finished long ago, "
                                      f"evicted, or never existed)"})
            return
        if offset < rec.base or offset > rec.end:
            self._m_resume.labels(outcome="bad_offset").inc()
            _json_response(writer, "416 Range Not Satisfiable",
                           {"error": "bad_offset",
                            "detail": f"offset {offset} outside the "
                                      f"retained log "
                                      f"[{rec.base}, {rec.end}]"})
            return
        self._m_resume.labels(outcome="ok").inc()
        extra = {UID_HEADER: str(uid)}
        if rec.ctx is not None:
            extra["traceparent"] = rec.ctx.to_traceparent()
        writer.write(_response_head("200 OK", "application/x-ndjson",
                                    extra))
        await self._serve_record(reader, writer, rec, offset)

    async def _weights(self, reader, writer) -> None:
        """Chunked weight ingest (blue/green hot-swap, serve/weights.py):
        ``C`` frames carry the payload (header first), the terminal
        ``P`` frame commits — chunks stage host-side while the running
        batch keeps stepping, then ONE atomic swap lands between decode
        steps. EOF before ``P`` aborts the staged update (the live
        params are untouched, so retransmit is idempotent)."""
        from .admission import OverloadedError

        async def fail(status: str, obj: dict) -> None:
            _json_response(writer, status, obj)
            # drain in-flight client frames before the close so the
            # verdict is not lost to a socket RST (same discipline as
            # the handoff ingest)
            try:
                await asyncio.wait_for(writer.drain(), 5.0)
                await asyncio.wait_for(reader.read(), 5.0)
            except (OSError, asyncio.TimeoutError, ConnectionError):
                pass

        update = None
        try:
            while True:
                try:
                    kind, payload = await read_frame(reader)
                except (asyncio.IncompleteReadError,
                        ConnectionResetError):
                    if update is not None:
                        await update.abort()
                    return
                if kind == FRAME_CHUNK:
                    if update is None:
                        update = await self.replica.serving \
                            .begin_weight_update(payload)
                    else:
                        await update.feed(payload)
                elif kind == FRAME_PARAMS:
                    break
                else:
                    if update is not None:
                        await update.abort()
                    await fail("400 Bad Request",
                               {"ok": False, "reason": "protocol",
                                "detail": f"unknown frame {kind!r}"})
                    return
            if update is None:
                await fail("400 Bad Request",
                           {"ok": False, "reason": "protocol",
                            "detail": "no weight chunks before the "
                                      "commit frame"})
                return
            version = await update.commit()
        except OverloadedError as e:
            await fail("429 Too Many Requests",
                       {"ok": False, "reason": e.reason,
                        "detail": str(e),
                        "retry_after_s": e.retry_after_s})
            return
        except Exception as e:
            if update is not None:
                await update.abort()
            await fail("400 Bad Request",
                       {"ok": False, "reason": "error",
                        "detail": f"{type(e).__name__}: {e}"})
            return
        _json_response(writer, "200 OK",
                       {"ok": True, "version": version,
                        "name": self.replica.name})

    async def _handoff(self, reader, writer, headers) -> None:
        """Chunked KV ingest (module docstring): apply frames as they
        arrive, commit on the params frame, stream tokens back."""
        upstream = trace_context.from_headers(headers or {})
        ctx = (upstream.child() if upstream is not None
               else trace_context.new_context())
        handle = None
        blocking_payload = None
        params = None

        async def fail(reason: str, detail: str,
                       retry_after_s=None) -> None:
            writer.write(_response_head("200 OK",
                                        "application/x-ndjson"))
            writer.write(json.dumps(
                {"ok": False, "reason": reason, "detail": detail,
                 "retry_after_s": retry_after_s}).encode() + b"\n")
            # drain the client's in-flight frames before the connection
            # closes: an unread receive buffer would RST the socket and
            # can discard the verdict the client needs to re-route
            try:
                await asyncio.wait_for(writer.drain(), 5.0)
                await asyncio.wait_for(reader.read(), 5.0)
            except (OSError, asyncio.TimeoutError, ConnectionError):
                pass

        from .admission import OverloadedError
        try:
            with trace_context.use(ctx):
                while True:
                    try:
                        kind, payload = await read_frame(reader)
                    except (asyncio.IncompleteReadError,
                            ConnectionResetError):
                        # client hung up mid-transfer: abort the restore
                        # so the partially-filled blocks free
                        if handle is not None:
                            await handle.abort()
                        return
                    if kind == FRAME_BLOCKING:
                        blocking_payload = payload
                    elif kind == FRAME_CHUNK:
                        if handle is None:
                            handle = await self.replica.serving \
                                .begin_handoff(payload)
                        else:
                            await handle.feed(payload)
                    elif kind == FRAME_PARAMS:
                        params = json.loads(payload.decode())
                        break
                    else:
                        if handle is not None:
                            await handle.abort()
                        await fail("protocol",
                                   f"unknown frame {kind!r}")
                        return
                kw = dict(
                    prompt=params["prompt"],
                    generated=params["generated"],
                    max_new_tokens=params["max_new_tokens"],
                    eos_token_id=params.get("eos_token_id"),
                    temperature=params.get("temperature", 0.0),
                    top_p=params.get("top_p", 1.0),
                    top_k=params.get("top_k", 0),
                    rng_state=_rng_state_from_wire(
                        params.get("rng_state")),
                    deadline_s=params.get("deadline_s"))
                if handle is not None:
                    stream = await handle.commit(**kw)
                elif blocking_payload is not None:
                    from . import handoff as handoff_mod
                    pack = await asyncio.to_thread(
                        handoff_mod.deserialize, blocking_payload)
                    stream = await self.replica.serving.resume(
                        pack, **kw)
                else:
                    await fail("protocol",
                               "no handoff payload before params")
                    return
        except OverloadedError as e:
            if handle is not None:
                await handle.abort()
            await fail(e.reason, str(e), retry_after_s=e.retry_after_s)
            return
        except Exception as e:
            if handle is not None:
                await handle.abort()
            await fail("error", f"{type(e).__name__}: {e}")
            return
        head = {"traceparent": ctx.to_traceparent()}
        if getattr(stream, "uid", None) is not None:
            head[UID_HEADER] = str(stream.uid)
        writer.write(_response_head(
            "200 OK", "application/x-ndjson", head))
        writer.write(json.dumps({"ok": True}).encode() + b"\n")
        await self._stream_tokens(reader, writer, stream, ctx)


def _rng_state_from_wire(state):
    """numpy bit-generator state dicts ride JSON losslessly (Python
    ints are arbitrary precision); nested lists that were tuples on
    export are accepted by numpy's setter as-is."""
    return state


class ReplicaWorker:
    """One replica + its WorkerAPI, runnable in-process (the loopback
    tests and the perf gate) or as the __main__ process."""

    def __init__(self, engine, serving_config: Optional[ServingConfig]
                 = None, name: str = "worker0",
                 host: str = "127.0.0.1", port: int = 0, **api_kw):
        from .replica import Replica
        self.replica = Replica(name, engine, serving_config)
        self.api = WorkerAPI(self.replica, host=host, port=port,
                             **api_kw)

    async def start(self) -> Tuple[str, int]:
        await self.replica.start()
        return await self.api.start()

    async def stop(self) -> None:
        try:
            if self.replica.serving.loop_runner.running:
                await self.replica.stop()
        finally:
            await self.api.stop()

    async def run_until_stopped(self) -> None:
        await self.api.stopped.wait()
        await self.api.stop()


def _serving_config(spec: dict) -> ServingConfig:
    kw = dict(spec.get("serving", {}))
    admission = kw.pop("admission", None)
    cfg = ServingConfig(**kw)
    if admission:
        from .admission import AdmissionConfig
        cfg.admission = AdmissionConfig(**admission)
    return cfg


READY_PREFIX = "DS_TPU_WORKER_READY "


class WorkerSpawnError(RuntimeError):
    """A spawned worker process never completed the ready handshake —
    it died first (the message carries its exit code and stderr tail)
    or the timeout expired."""


def spawn_worker(extra_args: Optional[List[str]] = None, *,
                 timeout_s: float = 60.0, env: Optional[dict] = None,
                 cmd: Optional[List[str]] = None):
    """Spawn a worker subprocess and wait for its ``DS_TPU_WORKER_READY``
    line under an explicit deadline.

    Returns ``(proc, info)`` — the live ``subprocess.Popen`` (stdout
    still open for the caller) and the parsed ready-line dict. Raises
    :class:`WorkerSpawnError` when the process exits before the
    handshake (the captured stderr tail rides the message, so "no chip
    / bad spec / import error" is diagnosable from the exception) or
    when the deadline passes (the stuck process is killed first).

    ``cmd`` overrides the full command line (tests); the default is
    ``python -m deepspeed_tpu.inference.v2.serve.worker`` plus
    ``extra_args``."""
    import collections
    import subprocess
    import threading

    if cmd is None:
        cmd = [sys.executable, "-m",
               "deepspeed_tpu.inference.v2.serve.worker"]
        cmd += list(extra_args or [])
    proc = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE, env=env, text=True)
    box = {}
    # stderr must be DRAINED for the worker's whole life (jax/absl
    # engine-build logging goes there; an unread PIPE would block the
    # worker once the buffer fills — before OR after the handshake).
    # A bounded tail is kept for spawn-failure diagnostics.
    stderr_tail: "collections.deque" = collections.deque(maxlen=400)

    def drain_stderr():
        for line in proc.stderr:
            stderr_tail.append(line)

    drainer = threading.Thread(target=drain_stderr, daemon=True)
    drainer.start()
    proc.stderr_tail = stderr_tail   # callers can inspect it later

    def scan():
        for line in proc.stdout:      # logging precedes the ready line
            if line.startswith(READY_PREFIX):
                box["info"] = json.loads(line[len(READY_PREFIX):])
                return

    t = threading.Thread(target=scan, daemon=True)
    t.start()
    t.join(timeout_s)

    def tail() -> str:
        drainer.join(2.0)     # let the drainer flush the final lines
        return "".join(stderr_tail)[-2000:]

    if "info" in box:
        return proc, box["info"]
    if proc.poll() is None:          # still running, never handshook
        proc.kill()
        proc.wait(timeout=10)
        raise WorkerSpawnError(
            f"worker spawn timed out after {timeout_s}s without a "
            f"{READY_PREFIX.strip()} line (killed); stderr tail:\n"
            f"{tail()}")
    proc.wait(timeout=10)
    raise WorkerSpawnError(
        f"worker exited with code {proc.returncode} before the "
        f"{READY_PREFIX.strip()} handshake; stderr tail:\n{tail()}")


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="deepspeed_tpu serving replica worker")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0,
                   help="0 picks a free port (printed on stdout)")
    p.add_argument("--name", default=f"worker-{os.getpid()}")
    p.add_argument("--spec", default=None,
                   help="JSON file with model/state_manager/engine/"
                        "serving sections (default: the tiny "
                        "deterministic preset)")
    p.add_argument("--jax-platform", default=None,
                   help="force a jax platform (e.g. 'cpu' for the "
                        "chip-free smoke; default: whatever jax picks)")
    p.add_argument("--compile-cache", default=None,
                   help="persistent XLA compilation cache dir "
                        "(default: $DS_TPU_COMPILE_CACHE if set)")
    p.add_argument("--resume-linger-s", type=float, default=2.0,
                   help="seconds a request stays resumable (KV held) "
                        "after a bare client connection loss before it "
                        "is cancelled")
    p.add_argument("--token-log-limit", type=int, default=4096,
                   help="per-request resume token-log bound (oldest "
                        "tokens trim first; a resume below the trim "
                        "point is refused typed)")
    p.add_argument("--auth-token", default=None,
                   help="shared-secret worker auth: every request must "
                        "carry it in the x-ds-tpu-auth header (401 "
                        "otherwise); default: $DS_TPU_WORKER_AUTH if "
                        "set, else open")
    args = p.parse_args(argv)
    import jax
    if args.jax_platform:
        jax.config.update("jax_platforms", args.jax_platform)
    cache = args.compile_cache or os.environ.get("DS_TPU_COMPILE_CACHE")
    if cache:
        os.makedirs(cache, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          0.5)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    if args.spec:
        with open(args.spec) as fh:
            spec = json.load(fh)
    else:
        spec = TINY_SPEC

    from .api import AUTH_ENV
    auth_token = args.auth_token or os.environ.get(AUTH_ENV) or None

    async def run() -> None:
        worker = ReplicaWorker(build_engine(spec),
                               _serving_config(spec), name=args.name,
                               host=args.host, port=args.port,
                               resume_linger_s=args.resume_linger_s,
                               token_log_limit=args.token_log_limit,
                               auth_token=auth_token)
        host, port = await worker.start()
        print(READY_PREFIX + json.dumps(
            {"name": args.name, "host": host, "port": port,
             "pid": os.getpid(),
             "block_size": spec["state_manager"]["block_size"]}),
            flush=True)
        await worker.run_until_stopped()

    asyncio.run(run())
    return 0


if __name__ == "__main__":
    sys.exit(main())
