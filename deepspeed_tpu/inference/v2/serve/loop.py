"""Continuous-batching background runner over the SplitFuse scheduler.

One dedicated thread owns the scheduler (and through it the engine —
neither is thread-safe): it applies queued commands (request
registration, cancellation, drain), expires deadlines, admits pending
requests from the :class:`AdmissionController` into the scheduler, and
runs composed engine steps. New requests join IN-FLIGHT batches between
steps — FastGen's continuous batching — rather than waiting for the
current batch to finish.

All cross-thread traffic goes one way: the asyncio side posts callables
onto the command deque and wakes the loop; the loop pushes tokens back
through each entry's (thread-safe) callbacks. Every scheduler/engine
touch happens on the loop thread.
"""

import asyncio
import heapq
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional


class ServingLoop:
    """Drains ``scheduler`` continuously; admits from ``admission``.

    Entries are the frontend's request records (duck-typed): they carry
    the scheduler submit() parameters plus ``deadline_t`` (absolute clock
    time or None), ``state`` ('pending' | 'inflight' | 'done'), and the
    thread-safe callbacks ``on_token(token, finished)`` and
    ``on_end(status, reason)``."""

    def __init__(self, scheduler, admission, *,
                 max_inflight: Optional[int] = None,
                 idle_wait_s: float = 0.002, clock=time.perf_counter,
                 bridge=None, diagnostics=None,
                 lane: Optional[str] = None, adapter=None):
        self.scheduler = scheduler
        self.admission = admission
        # fleet lane name (telemetry/trace.py set_lane): the loop thread
        # names its spans' lane once at start, so N in-process replica
        # loops sharing one trace ring stay distinguishable and the
        # stitched fleet timeline gives each its own process row
        self.lane = lane
        # optional TelemetryBridge: final-flushed (close()) when the loop
        # exits, so a drain's last partial flush interval isn't dropped
        self.bridge = bridge
        # optional ServingDiagnostics (frontend.py): the loop beats the
        # stall watchdog around every scheduler step, ticks the SLO
        # burn-rate monitor at ~1 Hz, and runs the KV-leak check when it
        # drains — the loop thread is the only place that sees all three
        # moments
        self.diagnostics = diagnostics
        # optional SLO-driven online adapter (autotuning/online.py):
        # ticked right after the SLO monitor so it reads a fresh burn
        # verdict, on this thread (the only one allowed to swap the
        # engine's fused decode program)
        self.adapter = adapter
        self._last_slo_tick = 0.0
        sm = scheduler.engine.state_manager.config
        # cap on requests inside the scheduler at once; the admission
        # queue (bounded) holds the rest
        self.max_inflight = max_inflight or sm.max_tracked_sequences
        self.idle_wait_s = idle_wait_s
        self.clock = clock
        self._cmds: deque = deque()      # callables run on the loop thread
        # set just before the loop's FINAL command drain: commands
        # posted after it may never run (run_on_loop fails fast on it)
        self._cmds_closed = False
        self._wake = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._stop = False
        self._draining = False
        self._entries: Dict[int, object] = {}   # uid -> entry (not done)
        self._deadlines: List = []              # heap of (deadline_t, uid)
        self._just_finished: List = []          # entries finished in step()
        self._dead: List[int] = []              # uids whose on_token raised
        # chunked streaming KV handoffs in flight (serve/handoff.py
        # ChunkedRestore, keyed by destination uid): each chunk applies
        # between scheduler steps, so the transfer overlaps the running
        # batch; drain waits for them and hard-stop aborts them
        self._restores: Dict[int, object] = {}
        # scheduler steps completed since start — the overlap evidence
        # the chunked-handoff tests and perf gate read
        self.steps_done = 0
        # weight updates currently STAGING host-side (frontend.py
        # WeightUpdate): staging never blocks the loop — steps taken
        # while >= 1 update stages are the publish/decode overlap the
        # perf gate's weight_publish_decode_stall_fraction pins at 0
        self.weight_staging = 0
        from ....telemetry import get_registry
        reg = get_registry()
        self._m_expired = reg.counter(
            "serving_deadline_expired_total",
            "requests cancelled because their deadline passed")
        self._m_chunks = reg.counter(
            "handoff_chunks_total",
            "chunked-handoff KV chunks applied to this runtime's pool")
        self._m_chunk_bytes = reg.counter(
            "handoff_chunk_bytes_total",
            "serialized chunked-handoff bytes applied")
        self._m_chunk_apply = reg.histogram(
            "handoff_chunk_apply_seconds",
            "per-chunk integrity check + scatter time on the loop "
            "thread", unit="s",
            buckets=(1e-5, 1e-4, 1e-3, 1e-2, 0.1, 1.0))
        self._m_chunk_aborts = reg.counter(
            "handoff_chunk_aborts_total",
            "chunked handoffs aborted mid-transfer (client hangup, "
            "integrity failure, drain)")
        self._m_chunk_inflight = reg.gauge(
            "handoff_chunk_inflight",
            "chunked handoffs currently streaming into this runtime")
        self._m_overlap_steps = reg.counter(
            "handoff_chunk_overlap_steps_total",
            "scheduler steps completed while >=1 chunked handoff was "
            "in flight (the transfer/compute overlap the protocol "
            "buys)")
        self._m_weight_overlap_steps = reg.counter(
            "weight_update_overlap_steps_total",
            "scheduler steps completed while >=1 weight update was "
            "staging (publication overlaps decode; only the final "
            "atomic swap lands between steps)")

    # -- cross-thread surface (any thread) ------------------------------
    def post(self, fn: Callable[[], None]) -> None:
        self._cmds.append(fn)
        self.wake()

    def wake(self) -> None:
        self._wake.set()

    def register(self, entry) -> None:
        """Track an admitted entry (deadline enforcement starts here)."""
        self.post(lambda: self._register(entry))

    def request_cancel(self, uid: int, status: str = "cancelled") -> None:
        self.post(lambda: self._cancel(uid, status))

    def resume(self, entry, pack, *, generated, rng_state=None) -> None:
        """Adopt a handed-off request (serve/handoff.py): restore the
        KV pack into the engine and insert the entry directly into the
        scheduler's running set, both on the loop thread."""
        self.post(lambda: self._resume(entry, pack, generated, rng_state))

    def run_on_loop(self, fn: Callable[[], object]) -> "asyncio.Future":
        """Run ``fn`` on the loop thread and resolve an asyncio future
        with its result (or exception) — the chunked-handoff surface's
        ack channel. Must be called from a running event loop."""
        aio = asyncio.get_running_loop()
        fut: asyncio.Future = aio.create_future()

        def done(result, exc) -> None:
            if fut.done():
                return
            if exc is not None:
                fut.set_exception(exc)
            else:
                fut.set_result(result)

        def wrapped() -> None:
            try:
                result = fn()
            except BaseException as e:   # noqa: BLE001 — forwarded
                result, exc = None, e
            else:
                exc = None
            try:
                aio.call_soon_threadsafe(done, result, exc)
            except RuntimeError:
                # the client's event loop is gone (closed between post
                # and execution): drop the ack — it must not kill the
                # serving-loop thread mid-drain
                pass

        self.post(wrapped)
        if self._cmds_closed or not self.running:
            # a dead (or exiting: _cmds_closed set before the final
            # drain) loop never processes this command — fail fast
            # instead of awaiting forever (wrapped() may still run via
            # the final drain; done() is idempotent either way)
            done(None, RuntimeError("serving loop is not running"))
        return fut

    def request_drain(self) -> None:
        """Graceful drain: admission closes immediately (new submits get
        an explicit rejection); everything already admitted finishes,
        then the thread exits."""
        self.admission.close()
        self.post(self._mark_draining)

    def request_stop(self) -> None:
        """Hard stop: in-flight and pending requests are cancelled (KV
        released) and their streams ended, then the thread exits."""
        self.admission.close()

        def _halt():
            self._stop = True
        self.post(_halt)

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(target=self._run,
                                        name="ds-tpu-serving-loop",
                                        daemon=True)
        self._thread.start()

    def join(self, timeout: Optional[float] = None) -> None:
        if self._thread is not None:
            self._thread.join(timeout)

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    @property
    def draining(self) -> bool:
        return self._draining

    # -- loop thread ----------------------------------------------------
    def _mark_draining(self) -> None:
        self._draining = True

    def _register(self, entry) -> None:
        if entry.state == "done":
            # the entry was popped from admission and ran to completion
            # before this command arrived (register is posted after
            # try_admit); inserting it now would strand a permanently
            # done entry in _entries and wedge graceful drain
            return
        self._entries[entry.uid] = entry
        if entry.deadline_t is not None:
            heapq.heappush(self._deadlines, (entry.deadline_t, entry.uid))

    def _end(self, entry, status: str, reason: Optional[str] = None) -> None:
        entry.state = "done"
        self._entries.pop(entry.uid, None)
        try:
            entry.on_end(status, reason)
        except Exception:
            # a dead client (e.g. its asyncio loop is gone) must not
            # take the serving loop down; the entry is done either way
            pass

    def _resume(self, entry, pack, generated, rng_state) -> None:
        from . import handoff
        try:
            handoff.restore_sequence(self.scheduler.engine, pack,
                                     uid=entry.uid)
        except Exception as e:
            self._end(entry, "error",
                      f"handoff restore failed: {type(e).__name__}: {e}")
            return
        self._adopt(entry, generated, rng_state)

    def _adopt(self, entry, generated, rng_state) -> None:
        """Insert an entry whose KV is already in the pool into the
        scheduler's running set (shared by the blocking and chunked
        handoff paths)."""
        try:
            self.scheduler.resume(
                entry.uid, entry.prompt, generated,
                entry.max_new_tokens, eos_token_id=entry.eos_token_id,
                temperature=entry.temperature, top_p=entry.top_p,
                top_k=entry.top_k, rng_state=rng_state,
                on_token=self._make_on_token(entry),
                trace_ctx=getattr(entry, "trace_ctx", None))
        except Exception as e:
            self.scheduler.engine.flush(entry.uid)
            self._end(entry, "error", f"{type(e).__name__}: {e}")
            return
        entry.state = "inflight"
        self._entries[entry.uid] = entry
        if entry.deadline_t is not None:
            heapq.heappush(self._deadlines, (entry.deadline_t, entry.uid))

    # -- chunked streaming handoff (loop thread; serve/handoff.py) ------
    def begin_restore(self, uid: int, header) -> None:
        """Adopt the destination blocks for a streaming handoff
        (raises through run_on_loop's future on layout mismatch /
        pool exhaustion)."""
        from . import handoff
        if self._stop or self._draining:
            raise RuntimeError("serving loop is draining")
        restore = handoff.ChunkedRestore(self.scheduler.engine, uid,
                                         header)
        restore.begin()
        self._restores[uid] = restore
        self._m_chunk_inflight.set(len(self._restores))

    def apply_restore(self, uid: int, chunk, nbytes: int) -> None:
        restore = self._restores.get(uid)
        if restore is None:
            raise ValueError(f"no chunked handoff in flight for uid "
                             f"{uid}")
        t0 = time.perf_counter()
        try:
            restore.apply(chunk)
        except Exception:
            # integrity/protocol failure: free the partial blocks NOW —
            # the client learns from the raised ack either way
            self._abort_restore(uid)
            raise
        self._m_chunks.inc()
        self._m_chunk_bytes.inc(nbytes)
        self._m_chunk_apply.observe(time.perf_counter() - t0)

    def commit_restore(self, entry, generated, rng_state) -> None:
        restore = self._restores.get(entry.uid)
        if restore is None:
            raise ValueError(f"no chunked handoff in flight for uid "
                             f"{entry.uid}")
        try:
            restore.commit_check()
        except Exception:
            self._abort_restore(entry.uid)
            raise
        del self._restores[entry.uid]
        self._m_chunk_inflight.set(len(self._restores))
        self._adopt(entry, generated, rng_state)

    def _abort_restore(self, uid: int) -> None:
        restore = self._restores.pop(uid, None)
        if restore is not None:
            restore.abort()
            self._m_chunk_aborts.inc()
            self._m_chunk_inflight.set(len(self._restores))

    def _cancel(self, uid: int, status: str) -> None:
        entry = self._entries.get(uid)
        if entry is None or entry.state == "done":
            return
        if entry.state == "pending":
            self.admission.remove(uid)
        else:
            self.scheduler.cancel(uid)     # releases the KV blocks
            self.scheduler.release(uid)
        if status == "expired":
            self._m_expired.inc()
        self._end(entry, status)

    def _run_cmds(self) -> None:
        while self._cmds:
            self._cmds.popleft()()

    def _expire_deadlines(self) -> None:
        now = self.clock()
        while self._deadlines and self._deadlines[0][0] <= now:
            _, uid = heapq.heappop(self._deadlines)
            entry = self._entries.get(uid)
            if entry is not None and entry.state != "done":
                self._cancel(uid, "expired")

    def _make_on_token(self, entry):
        def cb(uid, tok, finished):
            try:
                entry.on_token(tok, finished)
            except Exception:
                # this fires INSIDE scheduler.step(): letting one
                # client's dead callback propagate would reach
                # _step_error and fail EVERY in-flight request. Mark
                # just this entry for cancellation after the step.
                if not finished and entry.uid not in self._dead:
                    self._dead.append(entry.uid)
            if finished:
                self._just_finished.append(entry)
        return cb

    def _cancel_dead(self) -> None:
        for uid in self._dead:
            self._cancel(uid, "error")
        self._dead.clear()

    def _admit_ready(self) -> None:
        while self.scheduler.inflight() < self.max_inflight:
            entry = self.admission.pop()
            if entry is None:
                return
            if entry.state == "done":     # raced a cancel; already ended
                continue
            try:
                self.scheduler.submit(
                    entry.uid, entry.prompt, entry.max_new_tokens,
                    eos_token_id=entry.eos_token_id,
                    temperature=entry.temperature, top_p=entry.top_p,
                    top_k=entry.top_k, seed=entry.seed,
                    on_token=self._make_on_token(entry),
                    trace_ctx=getattr(entry, "trace_ctx", None),
                    adapter=getattr(entry, "adapter", None))
            except Exception as e:   # e.g. prompt exceeds max_seq_len
                self._end(entry, "error", f"{type(e).__name__}: {e}")
                continue
            entry.state = "inflight"

    def _flush_finished(self) -> None:
        for entry in self._just_finished:
            self.scheduler.release(entry.uid)
            if entry.state != "done":
                self._end(entry, "completed")
        self._just_finished.clear()

    def _step_error(self, e: BaseException) -> None:
        # a step-time failure cannot be attributed to one request here;
        # fail every in-flight request loudly rather than wedging the loop
        failed = [en for en in self._entries.values()
                  if en.state == "inflight"]
        for entry in failed:
            self.scheduler.cancel(entry.uid)
            self.scheduler.release(entry.uid)
            self._end(entry, "error", f"{type(e).__name__}: {e}")
        if self.diagnostics is not None and failed:
            from ....telemetry import anomaly, postmortem
            anomaly.report(
                "serving_step_error",
                f"scheduler.step() raised {type(e).__name__}: {e}; "
                f"{len(failed)} in-flight request(s) failed",
                error=f"{type(e).__name__}: {e}",
                failed_uids=[en.uid for en in failed])
            if self.diagnostics.config.postmortem_on_anomaly:
                postmortem.maybe_write_bundle(
                    "serving_step_error", config=self.diagnostics.config)

    # -- diagnostics hooks (loop thread) --------------------------------
    def _diag_step(self, fn):
        """Run one scheduler step inside the stall-watchdog heartbeat
        window and tick the SLO monitor at most once a second."""
        diag = self.diagnostics
        if diag is None:
            return fn()
        if diag.stall is not None:
            diag.stall.set_active("serving_loop", True)
        try:
            return fn()
        finally:
            if diag.stall is not None:
                diag.stall.beat("serving_loop")
            self._diag_tick()

    def _diag_tick(self) -> None:
        diag = self.diagnostics
        if diag is not None and diag.slo is not None:
            now = time.monotonic()
            if now - self._last_slo_tick >= 1.0:
                self._last_slo_tick = now
                try:
                    diag.slo.tick()
                except Exception:   # monitoring must never stall serving
                    pass
        if self.adapter is not None:
            try:
                self.adapter.tick()
            except Exception:       # adaptation must never stall serving
                pass

    def _diag_drain(self) -> None:
        """KV-pool reconciliation at drain: every allocated block must be
        owned by a still-inflight request or the prefix cache."""
        diag = self.diagnostics
        if diag is None or diag.leak is None:
            return
        try:
            if diag.stall is not None:
                diag.stall.set_active("serving_loop", False)
            diag.leak.check_at_drain(
                self.scheduler.engine.state_manager,
                inflight_uids=self.scheduler.known_uids())
        except Exception:
            pass

    def _abort_remaining(self) -> None:
        for uid in list(self._restores):
            self._abort_restore(uid)     # free partially-streamed KV
        for entry in list(self._entries.values()):
            self._cancel(entry.uid, "cancelled")
        while (entry := self.admission.pop()) is not None:
            if entry.state != "done":
                self._end(entry, "cancelled")

    def _run(self) -> None:
        if self.lane is not None:
            from ....telemetry import trace
            trace.set_lane(self.lane)
        while not self._stop:
            self._run_cmds()
            if self._stop:
                break
            self._expire_deadlines()
            self._admit_ready()
            if self.scheduler.pending():
                try:
                    self._diag_step(self.scheduler.step)
                except Exception as e:
                    self._step_error(e)
                self.steps_done += 1
                if self._restores:
                    # a chunked handoff is streaming in AND the batch
                    # kept stepping — the overlap the protocol buys
                    self._m_overlap_steps.inc()
                if self.weight_staging:
                    self._m_weight_overlap_steps.inc()
                self._cancel_dead()
                self._flush_finished()
                continue
            if (self.diagnostics is not None
                    and self.diagnostics.stall is not None):
                # idle is silence, not a stall
                self.diagnostics.stall.set_active("serving_loop", False)
            # an idle loop must still tick the SLO monitor, or the burn
            # gauges (and a latched slo_burn alert) freeze at their
            # last busy-time values after traffic stops
            self._diag_tick()
            if (self._draining and not self._entries
                    and not self._restores
                    and self.admission.empty() and not self._cmds):
                break
            # idle: block until woken (every external command calls
            # wake()), or until the nearest registered deadline so
            # queued requests still expire. With the SLO monitor
            # attached the wait is additionally capped at its ~1 Hz
            # tick cadence (burn windows must keep decaying after
            # traffic stops); otherwise never a fixed-rate poll
            if self._deadlines:
                timeout = max(self._deadlines[0][0] - self.clock(),
                              self.idle_wait_s)
            else:
                timeout = None
            if (self.diagnostics is not None
                    and self.diagnostics.slo is not None):
                timeout = 1.0 if timeout is None else min(timeout, 1.0)
            self._wake.wait(timeout)
            self._wake.clear()
        self._cmds_closed = True
        self._run_cmds()
        self._abort_remaining()
        self._diag_drain()
        spill = getattr(self.scheduler.engine, "spill", None)
        if spill is not None:
            # drain/stop semantics for the cold tier: a stopped replica
            # must not leak host RAM or disk scratch; its spilled
            # conversations recompute wherever they land next
            spill.close()
        if self.bridge is not None:
            try:  # drain/stop must end cleanly even if a backend throws
                self.bridge.close()
            except Exception:
                pass
