"""Paged-KV handoff between engine replicas.

The disaggregated serving path (docs/SERVING.md § Routing tier) runs a
request's prefill on a dedicated prefill replica, then moves the
sequence to a decode replica: the prefill side **exports** the
sequence's KV blocks plus a descriptor, the bytes travel (in-process
today, a wire tomorrow — the payload is a real serialized buffer either
way so the path is honest about its cost), and the decode side
**restores** them into its own pool under freshly allocated block ids.
Because KV content is copied bit-for-bit and the descriptor recreates
the exact scheduler state a colocated request has after its final
prompt chunk, handed-off token streams are bit-identical to colocated
serving — parity-pinned by tests/unit/inference/test_router.py.

Payload layout (``serialize``): one ``.npz`` buffer holding a JSON
descriptor (uid, seen_tokens, block count/size, fed-token log) and one
array per KV-pool leaf — ``[num_layers, n_blocks, ...]``, the
sequence's blocks gathered along the pool's block axis. The int8
``kv_quant`` pool hands off the same way (its per-(block, kv-head)
scale leaves — ``[L, n_blocks, kvh]`` — are just more pool leaves;
restore overwrites the destination blocks' scales, so the int8 content
pairs with its exact scales and the roundtrip is bit-exact — pinned by
tests/unit/inference/test_kv_quant_serving.py).

Gather/scatter shapes are bucketed (pow2 over the block count, padded
with the null block) so repeated handoffs of different-length
sequences reuse compiled programs instead of respecializing per
length; pad rows carry zeros and land in the null block, which no
attention read ever sees (reads are masked by position).

**Chunked streaming protocol** (ISSUE 12): :func:`export_chunks`
splits the same payload into one HEADER chunk (the descriptor plus the
chunk manifest: ranges and per-chunk CRCs) and N per-page-range KV
chunks, each an independent ``.npz`` buffer. The decode side drives a
:class:`ChunkedRestore`: ``begin`` adopts the blocks, ``apply``
scatters ONE range (CRC-checked, idempotent on retransmit — the
resumability unit), ``commit_check`` verifies every range arrived, and
``abort`` frees the partially-filled blocks WITHOUT registering their
content in the prefix index (a partial block must never be reused as a
cached prefix). Because each ``apply`` is one small scatter executed
between the serving loop's scheduler steps, the transfer overlaps the
decode replica's running batch instead of stalling it — the
``handoff_chunk_*`` metrics and the perf gate's
``handoff_decode_stall_fraction`` pin that overlap.
"""

import io
import json
import zlib
from functools import partial
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ....utils.bucketing import pow2_bucket
from ..ragged.blocked_allocator import NULL_BLOCK

_DESCRIPTOR_KEY = "__descriptor__"


@jax.jit
def _gather_blocks(leaf, idx):
    return leaf[:, idx]


@partial(jax.jit, donate_argnums=(0,))
def _scatter_blocks(leaf, idx, data):
    # pad rows all target the null block with identical (zero) data, so
    # the duplicate-index scatter stays deterministic
    return leaf.at[:, idx].set(data)


def export_sequence(engine, uid: int, trace_ctx=None) -> Dict:
    """Snapshot ``uid``'s KV blocks and descriptor from ``engine`` into
    a host-side pack (plain numpy + ints). The sequence stays live on
    the source engine; callers flush it once the handoff is accepted.

    ``trace_ctx`` (telemetry/context.py) rides the descriptor as a wire
    payload, so the decode side CONTINUES the prefill side's
    distributed trace — the trace id must cross the process boundary
    inside the handoff itself for remote replicas, not alongside it."""
    sm = engine.state_manager
    seq = sm.seqs.get(uid)
    if seq is None:
        raise ValueError(f"cannot export uid {uid}: unknown sequence")
    blocks = [int(b) for b in seq.blocks]
    nb = len(blocks)
    bucket = pow2_bucket(max(nb, 1), sm.max_blocks_per_seq)
    idx = np.full(bucket, NULL_BLOCK, np.int32)
    idx[:nb] = blocks
    kv = {key: np.asarray(_gather_blocks(leaf, jnp.asarray(idx)))[:, :nb]
          for key, leaf in engine.kv_cache.items()}
    pack = {
        "uid": int(uid),
        "seen_tokens": int(seq.seen_tokens),
        "n_blocks": nb,
        "block_size": int(sm.block_size),
        "token_log": [int(t) for t in seq.token_log],
        "kv": kv,
    }
    if trace_ctx is not None:
        pack["trace"] = trace_ctx.to_wire()
    return pack


def serialize(pack: Dict) -> bytes:
    """Pack -> one self-describing ``.npz`` buffer (the wire format)."""
    descriptor = {k: pack[k] for k in
                  ("uid", "seen_tokens", "n_blocks", "block_size",
                   "token_log", "trace") if k in pack}
    kv_wire = {}
    kv_dtypes = {}
    for key, arr in pack["kv"].items():
        arr = np.ascontiguousarray(arr)
        kv_dtypes[key] = arr.dtype.name
        if arr.dtype.kind == "V":
            # numpy cannot round-trip ml_dtypes leaves (bfloat16, fp8)
            # through .npz — np.load hands back an opaque void dtype —
            # so ship the raw bytes and view them back on the far side
            arr = arr.view(np.uint8)
        kv_wire[f"kv_{key}"] = arr
    descriptor["kv_dtypes"] = kv_dtypes
    bio = io.BytesIO()
    np.savez(bio,
             **{_DESCRIPTOR_KEY: np.frombuffer(
                 json.dumps(descriptor).encode(), np.uint8)},
             **kv_wire)
    return bio.getvalue()


def _wire_dtype(name: str) -> np.dtype:
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes
        return np.dtype(getattr(ml_dtypes, name))


def deserialize(buf: bytes) -> Dict:
    with np.load(io.BytesIO(buf)) as z:
        pack = json.loads(bytes(z[_DESCRIPTOR_KEY]).decode())
        dtypes = pack.pop("kv_dtypes", {})
        kv = {}
        for name in z.files:
            if not name.startswith("kv_"):
                continue
            key, arr = name[3:], z[name]
            want = dtypes.get(key)
            if want and arr.dtype.name != want:
                arr = arr.view(_wire_dtype(want))
            kv[key] = arr
        pack["kv"] = kv
    return pack


# ---------------------------------------------------------------------------
# chunked streaming protocol (module docstring)
# ---------------------------------------------------------------------------
def _leaf_wire_bytes(arr: np.ndarray) -> bytes:
    arr = np.ascontiguousarray(arr)
    return (arr.view(np.uint8) if arr.dtype.kind == "V" else arr) \
        .tobytes()


def _chunk_crc(kv: Dict[str, np.ndarray]) -> int:
    crc = 0
    for key in sorted(kv):
        crc = zlib.crc32(_leaf_wire_bytes(kv[key]), crc)
    return crc


def _npz_chunk(descriptor: Dict, kv: Dict[str, np.ndarray]) -> bytes:
    """One self-describing chunk buffer (same ml_dtypes raw-bytes trick
    as :func:`serialize`)."""
    kv_wire, kv_dtypes = {}, {}
    for key, arr in kv.items():
        arr = np.ascontiguousarray(arr)
        kv_dtypes[key] = arr.dtype.name
        if arr.dtype.kind == "V":
            arr = arr.view(np.uint8)
        kv_wire[f"kv_{key}"] = arr
    descriptor = dict(descriptor, kv_dtypes=kv_dtypes)
    bio = io.BytesIO()
    np.savez(bio,
             **{_DESCRIPTOR_KEY: np.frombuffer(
                 json.dumps(descriptor).encode(), np.uint8)},
             **kv_wire)
    return bio.getvalue()


def parse_chunk(buf: bytes) -> Dict:
    """Chunk buffer -> ``{"descriptor": ..., "kv": {...}}`` with wire
    dtypes restored."""
    with np.load(io.BytesIO(buf)) as z:
        descriptor = json.loads(bytes(z[_DESCRIPTOR_KEY]).decode())
        dtypes = descriptor.pop("kv_dtypes", {})
        kv = {}
        for name in z.files:
            if not name.startswith("kv_"):
                continue
            key, arr = name[3:], z[name]
            want = dtypes.get(key)
            if want and arr.dtype.name != want:
                arr = arr.view(_wire_dtype(want))
            kv[key] = arr
    return {"descriptor": descriptor, "kv": kv}


def chunk_pack(pack: Dict, chunk_blocks: int) -> List[bytes]:
    """Split one exported pack into ``[header, kv-chunk...]`` buffers:
    the header carries the descriptor plus the chunk manifest (ranges +
    CRCs), each KV chunk one ``chunk_blocks``-wide block range."""
    chunk_blocks = max(1, int(chunk_blocks))
    nb = int(pack["n_blocks"])
    ranges = [(i, min(i + chunk_blocks, nb))
              for i in range(0, nb, chunk_blocks)]
    chunks: List[bytes] = []
    crcs: List[int] = []
    for seq, (i, j) in enumerate(ranges):
        kv = {key: np.ascontiguousarray(arr[:, i:j])
              for key, arr in pack["kv"].items()}
        crc = _chunk_crc(kv)
        crcs.append(crc)
        chunks.append(_npz_chunk(
            {"kind": "kv", "uid": int(pack["uid"]), "seq": seq,
             "block_start": i, "block_end": j, "crc32": crc}, kv))
    header = {k: pack[k] for k in
              ("uid", "seen_tokens", "n_blocks", "block_size",
               "token_log", "trace") if k in pack}
    header.update({
        "kind": "header", "chunk_blocks": chunk_blocks,
        "n_chunks": len(ranges),
        "chunk_ranges": [[i, j] for i, j in ranges],
        "chunk_crcs": crcs,
        "leaves": sorted(pack["kv"]),
        "leaf_dtypes": {k: np.ascontiguousarray(v).dtype.name
                        for k, v in pack["kv"].items()},
    })
    return [_npz_chunk(header, {})] + chunks


def export_chunks(engine, uid: int, chunk_blocks: int = 4,
                  trace_ctx=None) -> List[bytes]:
    """Snapshot ``uid``'s KV and serialize it as the chunked wire form
    (``[header, kv-chunk...]``) — the streaming counterpart of
    ``serialize(export_sequence(...))``."""
    return chunk_pack(export_sequence(engine, uid, trace_ctx=trace_ctx),
                      chunk_blocks)


def parse_header(buf: bytes) -> Dict:
    chunk = parse_chunk(buf)
    d = chunk["descriptor"]
    if d.get("kind") != "header":
        raise ValueError(
            f"chunked handoff must start with the header chunk "
            f"(got kind={d.get('kind')!r})")
    return d


class ChunkedRestore:
    """Decode-side state machine for one streaming handoff.

    All methods run on the serving-loop thread (they touch the engine).
    ``apply`` is idempotent per chunk sequence number — a retransmitted
    chunk re-scatters identical content — which is what makes the
    transfer resumable over a flaky wire."""

    def __init__(self, engine, uid: int, header: Dict):
        self.engine = engine
        self.uid = int(uid)
        self.header = header
        self.received: set = set()
        self._begun = False
        self._done = False

    def begin(self) -> None:
        """Validate the layout and adopt the destination blocks."""
        sm = self.engine.state_manager
        h = self.header
        if sm.block_size != h["block_size"]:
            raise ValueError(
                f"handoff block-size mismatch: payload has "
                f"{h['block_size']}, target pool has {sm.block_size} "
                f"(disaggregated replicas must share the KV layout)")
        if set(h["leaves"]) != set(self.engine.kv_cache):
            raise ValueError(
                f"handoff pool-leaf mismatch: payload has "
                f"{sorted(h['leaves'])}, target pool has "
                f"{sorted(self.engine.kv_cache)} (kv_quant must match)")
        self.seq = sm.adopt_sequence(self.uid, int(h["n_blocks"]),
                                     h["seen_tokens"], h["token_log"])
        self._begun = True

    def apply(self, chunk: Dict) -> None:
        """Integrity-check and scatter ONE block-range chunk."""
        d = chunk["descriptor"]
        if d.get("kind") != "kv":
            raise ValueError(f"expected a kv chunk, got "
                             f"{d.get('kind')!r}")
        seq_no = int(d["seq"])
        if not 0 <= seq_no < self.header["n_chunks"]:
            raise ValueError(f"chunk seq {seq_no} outside the header's "
                             f"{self.header['n_chunks']} chunks")
        i, j = int(d["block_start"]), int(d["block_end"])
        if [i, j] != list(self.header["chunk_ranges"][seq_no]):
            raise ValueError(
                f"chunk {seq_no} range [{i},{j}) disagrees with the "
                f"header manifest "
                f"{self.header['chunk_ranges'][seq_no]}")
        crc = _chunk_crc(chunk["kv"])
        if crc != int(d["crc32"]) \
                or crc != int(self.header["chunk_crcs"][seq_no]):
            raise ValueError(
                f"chunk {seq_no} failed its crc32 integrity check "
                f"(corrupted in transfer)")
        if set(chunk["kv"]) != set(self.engine.kv_cache):
            raise ValueError("chunk leaf set disagrees with the pool")
        blocks = self.seq.blocks[i:j]
        nb = len(blocks)
        bucket = pow2_bucket(max(nb, 1),
                             self.engine.state_manager.max_blocks_per_seq)
        idx = np.full(bucket, NULL_BLOCK, np.int32)
        idx[:nb] = blocks
        for key in list(self.engine.kv_cache):
            leaf = self.engine.kv_cache[key]
            data = np.zeros((leaf.shape[0], bucket) + leaf.shape[2:],
                            np.asarray(chunk["kv"][key]).dtype)
            data[:, :nb] = chunk["kv"][key]
            self.engine.kv_cache[key] = _scatter_blocks(
                leaf, jnp.asarray(idx), jnp.asarray(data, leaf.dtype))
        self.received.add(seq_no)

    def missing(self) -> List[int]:
        return [s for s in range(int(self.header["n_chunks"]))
                if s not in self.received]

    def commit_check(self) -> None:
        gaps = self.missing()
        if gaps:
            raise ValueError(
                f"handoff incomplete: missing chunks {gaps} of "
                f"{self.header['n_chunks']}")
        self._done = True

    def abort(self) -> None:
        """Free the adopted blocks. The token log is cleared FIRST so
        flush cannot register partially-filled blocks in the prefix
        index (a later request must never reuse garbage as a cached
        prefix)."""
        if self._begun and not self._done:
            sm = self.engine.state_manager
            seq = sm.seqs.get(self.uid)
            if seq is not None:
                seq.token_log = []
                sm.flush_sequence(self.uid)
        self._done = True


def restore_sequence(engine, pack: Dict, uid: int) -> None:
    """Install the handed-off sequence into ``engine`` as ``uid``:
    allocate fresh blocks, scatter the KV content into them, and adopt
    a descriptor in exactly the state the decode paths expect."""
    sm = engine.state_manager
    if sm.block_size != pack["block_size"]:
        raise ValueError(
            f"handoff block-size mismatch: payload has "
            f"{pack['block_size']}, target pool has {sm.block_size} "
            f"(disaggregated replicas must share the KV layout)")
    if set(pack["kv"]) != set(engine.kv_cache):
        raise ValueError(
            f"handoff pool-leaf mismatch: payload has "
            f"{sorted(pack['kv'])}, target pool has "
            f"{sorted(engine.kv_cache)} (kv_quant must match)")
    nb = int(pack["n_blocks"])
    seq = sm.adopt_sequence(uid, nb, pack["seen_tokens"],
                            pack["token_log"])
    try:
        bucket = pow2_bucket(max(nb, 1), sm.max_blocks_per_seq)
        idx = np.full(bucket, NULL_BLOCK, np.int32)
        idx[:nb] = seq.blocks
        for key in list(engine.kv_cache):
            leaf = engine.kv_cache[key]
            data = np.zeros((leaf.shape[0], bucket) + leaf.shape[2:],
                            np.asarray(pack["kv"][key]).dtype)
            data[:, :nb] = pack["kv"][key]
            engine.kv_cache[key] = _scatter_blocks(
                leaf, jnp.asarray(idx), jnp.asarray(data, leaf.dtype))
    except Exception:
        sm.flush_sequence(uid)   # do not leak the adopted blocks
        raise
