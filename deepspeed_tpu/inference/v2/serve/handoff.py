"""Paged-KV handoff between engine replicas.

The disaggregated serving path (docs/SERVING.md § Routing tier) runs a
request's prefill on a dedicated prefill replica, then moves the
sequence to a decode replica: the prefill side **exports** the
sequence's KV blocks plus a descriptor, the bytes travel (in-process
today, a wire tomorrow — the payload is a real serialized buffer either
way so the path is honest about its cost), and the decode side
**restores** them into its own pool under freshly allocated block ids.
Because KV content is copied bit-for-bit and the descriptor recreates
the exact scheduler state a colocated request has after its final
prompt chunk, handed-off token streams are bit-identical to colocated
serving — parity-pinned by tests/unit/inference/test_router.py.

Payload layout (``serialize``): one ``.npz`` buffer holding a JSON
descriptor (uid, seen_tokens, block count/size, fed-token log) and one
array per KV-pool leaf — ``[num_layers, n_blocks, ...]``, the
sequence's blocks gathered along the pool's block axis. The int8
``kv_quant`` pool hands off the same way (its per-(block, kv-head)
scale leaves — ``[L, n_blocks, kvh]`` — are just more pool leaves;
restore overwrites the destination blocks' scales, so the int8 content
pairs with its exact scales and the roundtrip is bit-exact — pinned by
tests/unit/inference/test_kv_quant_serving.py).

Gather/scatter shapes are bucketed (pow2 over the block count, padded
with the null block) so repeated handoffs of different-length
sequences reuse compiled programs instead of respecializing per
length; pad rows carry zeros and land in the null block, which no
attention read ever sees (reads are masked by position).
"""

import io
import json
from functools import partial
from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np

from ....utils.bucketing import pow2_bucket
from ..ragged.blocked_allocator import NULL_BLOCK

_DESCRIPTOR_KEY = "__descriptor__"


@jax.jit
def _gather_blocks(leaf, idx):
    return leaf[:, idx]


@partial(jax.jit, donate_argnums=(0,))
def _scatter_blocks(leaf, idx, data):
    # pad rows all target the null block with identical (zero) data, so
    # the duplicate-index scatter stays deterministic
    return leaf.at[:, idx].set(data)


def export_sequence(engine, uid: int, trace_ctx=None) -> Dict:
    """Snapshot ``uid``'s KV blocks and descriptor from ``engine`` into
    a host-side pack (plain numpy + ints). The sequence stays live on
    the source engine; callers flush it once the handoff is accepted.

    ``trace_ctx`` (telemetry/context.py) rides the descriptor as a wire
    payload, so the decode side CONTINUES the prefill side's
    distributed trace — the trace id must cross the process boundary
    inside the handoff itself for remote replicas, not alongside it."""
    sm = engine.state_manager
    seq = sm.seqs.get(uid)
    if seq is None:
        raise ValueError(f"cannot export uid {uid}: unknown sequence")
    blocks = [int(b) for b in seq.blocks]
    nb = len(blocks)
    bucket = pow2_bucket(max(nb, 1), sm.max_blocks_per_seq)
    idx = np.full(bucket, NULL_BLOCK, np.int32)
    idx[:nb] = blocks
    kv = {key: np.asarray(_gather_blocks(leaf, jnp.asarray(idx)))[:, :nb]
          for key, leaf in engine.kv_cache.items()}
    pack = {
        "uid": int(uid),
        "seen_tokens": int(seq.seen_tokens),
        "n_blocks": nb,
        "block_size": int(sm.block_size),
        "token_log": [int(t) for t in seq.token_log],
        "kv": kv,
    }
    if trace_ctx is not None:
        pack["trace"] = trace_ctx.to_wire()
    return pack


def serialize(pack: Dict) -> bytes:
    """Pack -> one self-describing ``.npz`` buffer (the wire format)."""
    descriptor = {k: pack[k] for k in
                  ("uid", "seen_tokens", "n_blocks", "block_size",
                   "token_log", "trace") if k in pack}
    kv_wire = {}
    kv_dtypes = {}
    for key, arr in pack["kv"].items():
        arr = np.ascontiguousarray(arr)
        kv_dtypes[key] = arr.dtype.name
        if arr.dtype.kind == "V":
            # numpy cannot round-trip ml_dtypes leaves (bfloat16, fp8)
            # through .npz — np.load hands back an opaque void dtype —
            # so ship the raw bytes and view them back on the far side
            arr = arr.view(np.uint8)
        kv_wire[f"kv_{key}"] = arr
    descriptor["kv_dtypes"] = kv_dtypes
    bio = io.BytesIO()
    np.savez(bio,
             **{_DESCRIPTOR_KEY: np.frombuffer(
                 json.dumps(descriptor).encode(), np.uint8)},
             **kv_wire)
    return bio.getvalue()


def _wire_dtype(name: str) -> np.dtype:
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes
        return np.dtype(getattr(ml_dtypes, name))


def deserialize(buf: bytes) -> Dict:
    with np.load(io.BytesIO(buf)) as z:
        pack = json.loads(bytes(z[_DESCRIPTOR_KEY]).decode())
        dtypes = pack.pop("kv_dtypes", {})
        kv = {}
        for name in z.files:
            if not name.startswith("kv_"):
                continue
            key, arr = name[3:], z[name]
            want = dtypes.get(key)
            if want and arr.dtype.name != want:
                arr = arr.view(_wire_dtype(want))
            kv[key] = arr
        pack["kv"] = kv
    return pack


def restore_sequence(engine, pack: Dict, uid: int) -> None:
    """Install the handed-off sequence into ``engine`` as ``uid``:
    allocate fresh blocks, scatter the KV content into them, and adopt
    a descriptor in exactly the state the decode paths expect."""
    sm = engine.state_manager
    if sm.block_size != pack["block_size"]:
        raise ValueError(
            f"handoff block-size mismatch: payload has "
            f"{pack['block_size']}, target pool has {sm.block_size} "
            f"(disaggregated replicas must share the KV layout)")
    if set(pack["kv"]) != set(engine.kv_cache):
        raise ValueError(
            f"handoff pool-leaf mismatch: payload has "
            f"{sorted(pack['kv'])}, target pool has "
            f"{sorted(engine.kv_cache)} (kv_quant must match)")
    nb = int(pack["n_blocks"])
    seq = sm.adopt_sequence(uid, nb, pack["seen_tokens"],
                            pack["token_log"])
    try:
        bucket = pow2_bucket(max(nb, 1), sm.max_blocks_per_seq)
        idx = np.full(bucket, NULL_BLOCK, np.int32)
        idx[:nb] = seq.blocks
        for key in list(engine.kv_cache):
            leaf = engine.kv_cache[key]
            data = np.zeros((leaf.shape[0], bucket) + leaf.shape[2:],
                            np.asarray(pack["kv"][key]).dtype)
            data[:, :nb] = pack["kv"][key]
            engine.kv_cache[key] = _scatter_blocks(
                leaf, jnp.asarray(idx), jnp.asarray(data, leaf.dtype))
    except Exception:
        sm.flush_sequence(uid)   # do not leak the adopted blocks
        raise
