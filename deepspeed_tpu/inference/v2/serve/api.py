"""Dependency-free HTTP surface for the serving runtime.

Built on stdlib ``asyncio.start_server`` — no web framework. Endpoints:

  * ``POST /generate`` — JSON body ``{"prompt": [ids], "max_new_tokens":
    n, ...}``; the response streams one NDJSON line per generated token
    (``{"token": t}``) followed by a final summary line (``{"done":
    true, "status": ..., "n": k, "tokens": [...]}``). The connection is
    ``Connection: close`` — the stream's end IS the close. A client that
    disconnects mid-stream cancels its request (KV blocks released).
    Protocol note: EOF on the client->server direction is the hangup
    signal (a TCP FIN is all a close gives us), so clients must keep
    their write side open until the stream ends — ``shutdown(SHUT_WR)``
    after the request body reads as a disconnect and cancels the work.
  * ``GET /healthz`` — JSON runtime health (status, queue depth,
    in-flight count).
  * ``GET /metrics`` — Prometheus text exposition rendered from the
    telemetry registry (queue depth, admission rejections, TTFT/TPOT
    histograms, ... — see docs/TELEMETRY.md).
  * ``GET /debug/timeline[?uid=N][&trace=ID]`` — the telemetry span
    ring buffer as Chrome-trace-event JSON (load in chrome://tracing or
    Perfetto); ``uid`` filters to one request's lifeline (queue ->
    prefill -> decode windows -> finish), ``trace`` to one distributed
    trace id. In routed mode the body is the STITCHED fleet timeline —
    one process row per lane (router + each replica) — so
    ``?trace=<id>`` shows a single request's dispatch -> prefill ->
    handoff -> decode hops across the fleet. See docs/PROFILING.md.
  * ``GET /statusz[?format=json]`` — one-call forensics snapshot:
    runtime health plus the recompile-watchdog rollup, the
    device-memory report, recent anomaly verdicts, and SLO state
    (p50/p95/p99 TTFT/TPOT from histogram quantiles plus the fast/slow
    burn rates). The document is JSON either way; ``format=json`` is
    the explicit machine-readable contract (other values are a 400, so
    a dashboard typo cannot silently read the wrong shape).
  * ``POST /debug/postmortem`` — write a post-mortem bundle (metrics
    snapshot, timeline, memory report, compiler fingerprint, last-N
    flight-recorder events, anomaly verdicts) and return its path
    (docs/SERVING.md § Post-mortem bundles).

Overload maps to ``429`` with the admission reason and a ``Retry-After``
header carrying the admission layer's backoff hint; malformed requests
to ``400``; unknown routes to ``404``.

Routed frontend mode: constructed over a
:class:`~.router.ReplicaRouter` instead of a single
:class:`~.frontend.ServingEngine`, the same endpoints serve an N-replica
deployment — ``/generate`` streams through the router's placement
(prefix affinity, overload re-routing, failover), ``/statusz`` gains
``router`` + per-replica ``replicas`` sections, ``/debug/timeline``
serves the stitched fleet trace and ``/metrics`` federates per-replica
registries under a ``replica`` label. The two are duck-compatible
(``submit`` / ``health``); nothing else changes.

Distributed tracing (telemetry/context.py): ``POST /generate`` honors
the W3C ``traceparent`` (+ ``baggage``) request headers — the request's
spans on every hop continue the CALLER's trace — or mints a root
context when absent. The response echoes ``traceparent`` (the request's
trace id, the server's span id) and the final NDJSON line carries
``trace_id``, so clients can fetch ``/debug/timeline?trace=<id>``.
"""

import asyncio
import json
from typing import Optional, Tuple

from .admission import OverloadedError
from .frontend import DeadlineExceeded, RequestFailed

_MAX_HEADER_BYTES = 64 * 1024
_MAX_BODY_BYTES = 8 * 1024 * 1024

# response header carrying the runtime uid at stream start — the one
# name the resume protocol hangs on (serve/remote.py reads it, the
# worker's /resume and /handoff responses echo it)
UID_HEADER = "x-ds-tpu-uid"

# shared-secret auth header (worker API): when the server is built with
# an auth token (worker --auth-token / $DS_TPU_WORKER_AUTH), EVERY
# request must carry it — a mismatch is a typed 401, never a silent
# accept. RemoteReplica sends it on every hop, /weights and /resume
# included.
AUTH_HEADER = "x-ds-tpu-auth"
AUTH_ENV = "DS_TPU_WORKER_AUTH"


async def _read_request(reader: asyncio.StreamReader):
    request_line = await reader.readline()
    if not request_line:
        raise ConnectionError("empty request")
    parts = request_line.decode("latin-1").split()
    if len(parts) < 2:
        raise ValueError("malformed request line")
    method, target = parts[0].upper(), parts[1]
    headers = {}
    total = 0
    while True:
        line = await reader.readline()
        total += len(line)
        if total > _MAX_HEADER_BYTES:
            raise ValueError("headers too large")
        if line in (b"\r\n", b"\n", b""):
            break
        name, _, value = line.decode("latin-1").partition(":")
        headers[name.strip().lower()] = value.strip()
    length = int(headers.get("content-length", 0) or 0)
    if length > _MAX_BODY_BYTES:
        raise ValueError("body too large")
    body = await reader.readexactly(length) if length else b""
    return method, target, headers, body


def _response_head(status: str, content_type: str,
                   extra_headers: Optional[dict] = None) -> bytes:
    lines = [f"HTTP/1.1 {status}",
             f"Content-Type: {content_type}",
             "Connection: close"]
    for name, value in (extra_headers or {}).items():
        lines.append(f"{name}: {value}")
    return ("\r\n".join(lines) + "\r\n\r\n").encode()


def _json_response(writer: asyncio.StreamWriter, status: str, obj,
                   extra_headers: Optional[dict] = None) -> None:
    writer.write(_response_head(status, "application/json", extra_headers)
                 + json.dumps(obj).encode() + b"\n")


class ServingAPI:
    """In-process HTTP server over a :class:`ServingEngine` — or, in
    routed frontend mode, over a :class:`~.router.ReplicaRouter`
    (anything with the ``submit``/``health`` surface)."""

    def __init__(self, serving, host: str = "127.0.0.1",
                 port: int = 0, registry=None,
                 auth_token: Optional[str] = None):
        self.serving = serving
        self.host = host
        self.port = port
        # shared-secret auth (AUTH_HEADER): None = open (the in-process
        # default); a token makes every route require the header
        self.auth_token = auth_token
        if registry is None:
            from ....telemetry import get_registry
            registry = get_registry()
        self.registry = registry
        self._m_auth_failures = registry.counter(
            "serving_auth_failures_total",
            "requests rejected 401 for a missing or wrong "
            "x-ds-tpu-auth shared secret")
        self._server: Optional[asyncio.AbstractServer] = None

    async def start(self) -> Tuple[str, int]:
        self._server = await asyncio.start_server(self._handle, self.host,
                                                  self.port)
        self.port = self._server.sockets[0].getsockname()[1]
        return self.host, self.port

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None

    # ------------------------------------------------------------------
    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        try:
            try:
                method, target, headers, body = await _read_request(reader)
            except ConnectionError:
                return
            except (ValueError, asyncio.IncompleteReadError):
                _json_response(writer, "400 Bad Request",
                               {"error": "malformed request"})
                return
            target, _, query = target.partition("?")
            if self.auth_token is not None and \
                    headers.get(AUTH_HEADER) != self.auth_token:
                self._m_auth_failures.inc()
                _json_response(
                    writer, "401 Unauthorized",
                    {"error": "unauthorized",
                     "detail": f"missing or wrong {AUTH_HEADER} header "
                               f"(this worker requires the shared "
                               f"secret)"})
                if method == "POST" and not body:
                    # frame-streaming routes (/weights, /handoff) send
                    # their payload AFTER the head: drain it so the
                    # close cannot RST away the typed 401 and turn a
                    # non-retryable auth failure into a retried
                    # transport error
                    try:
                        await asyncio.wait_for(writer.drain(), 5.0)
                        await asyncio.wait_for(reader.read(), 5.0)
                    except (OSError, asyncio.TimeoutError,
                            ConnectionError):
                        pass
            elif method == "GET" and target == "/healthz":
                _json_response(writer, "200 OK", self.serving.health())
            elif method == "GET" and target == "/metrics":
                # routed frontend mode: federate per-replica registries
                # under a `replica` label (falls back to the plain
                # process-default exposition when replicas share it).
                # Remote replicas make federation async (their series
                # arrive over HTTP) — prefer the async form when the
                # router exposes one.
                fed = (getattr(self.serving, "federated_metrics_async",
                               None)
                       or getattr(self.serving, "federated_metrics",
                                  None))
                if fed is None:
                    text = self.registry.render_prometheus()
                else:
                    text = fed()
                    if asyncio.iscoroutine(text):
                        text = await text
                writer.write(_response_head(
                    "200 OK", "text/plain; version=0.0.4; charset=utf-8")
                    + text.encode())
            elif method == "GET" and target == "/debug/timeline":
                await self._timeline(writer, query)
            elif method == "GET" and target == "/statusz":
                self._statusz_response(writer, query)
            elif method == "POST" and target == "/debug/postmortem":
                await self._postmortem(writer)
            elif method == "POST" and target == "/generate":
                await self._generate(reader, writer, body, headers)
            elif await self._route_extra(method, target, query, headers,
                                         body, reader, writer):
                pass
            else:
                _json_response(writer, "404 Not Found",
                               {"error": f"no route {method} {target}"})
        except (ConnectionResetError, BrokenPipeError):
            pass
        finally:
            try:
                if writer.can_write_eof():
                    writer.write_eof()
            except (OSError, RuntimeError):
                pass
            writer.close()

    async def _route_extra(self, method: str, target: str, query: str,
                           headers, body, reader, writer) -> bool:
        """Subclass hook for extra endpoints (the replica worker's
        lifecycle + handoff routes, serve/worker.py); returns True when
        the request was handled."""
        return False

    async def _timeline(self, writer, query: str) -> None:
        """Chrome-trace JSON of the span ring buffer (``?uid=N`` filters
        to one request's correlated spans, ``?trace=ID`` to one
        distributed trace). Routed mode serves the STITCHED fleet form
        — a process row per lane — via the router's
        :meth:`~.router.ReplicaRouter.fleet_timeline`."""
        from urllib.parse import parse_qs

        from ....telemetry import timeline
        from ....telemetry import trace as ds_trace
        params = parse_qs(query)
        trace_id = params.get("trace", [None])[0]
        fleet = getattr(self.serving, "fleet_timeline", None)
        if fleet is not None:
            if params.get("uid"):
                _json_response(
                    writer, "400 Bad Request",
                    {"error": "routed timeline filters by ?trace=<id> "
                              "(uids are per replica, not fleet-wide)"})
                return
            doc = fleet(trace_id=trace_id)
            if asyncio.iscoroutine(doc):
                # remote replicas: their span rings arrive over HTTP
                doc = await doc
            _json_response(writer, "200 OK", doc)
            return
        spans = ds_trace.export()
        try:
            uid = params.get("uid")
            if uid:
                spans = timeline.request_spans(int(uid[0]), spans)
        except (TypeError, ValueError):
            _json_response(writer, "400 Bad Request",
                           {"error": "uid must be an integer"})
            return
        if trace_id:
            spans = timeline.trace_spans(trace_id, spans)
        _json_response(writer, "200 OK", timeline.to_chrome_trace(spans))

    def _statusz_response(self, writer, query: str) -> None:
        """``/statusz`` with the explicit ``?format=json`` contract:
        the document is JSON either way, but an unknown format is a 400
        instead of a silently-ignored parameter."""
        from urllib.parse import parse_qs
        fmt = parse_qs(query).get("format", ["json"])[0]
        if fmt != "json":
            _json_response(writer, "400 Bad Request",
                           {"error": f"unsupported format {fmt!r} "
                                     f"(only 'json')"})
            return
        _json_response(writer, "200 OK", self._statusz())

    def _statusz(self) -> dict:
        import math

        from ....telemetry import anomaly as ds_anomaly
        from ....telemetry import memory as ds_memory
        from ....telemetry import watchdog
        from ....runtime import tunables
        from ....telemetry.recorder import get_recorder
        out = {
            "health": self.serving.health(),
            "compile": {"programs": watchdog.summary(),
                        "steady_state": watchdog.is_steady(),
                        "recent_events": len(watchdog.events())},
            "memory": ds_memory.oom_report(),
            "metric_families": len(self.registry.families()),
            "recorder": get_recorder().stats(),
            "anomalies": {"recent": ds_anomaly.recent(16)},
            # every registered perf knob: effective value + provenance
            # (default|config|tuned|online) — runtime/tunables.py
            "tunables": tunables.statusz_section(),
        }
        if hasattr(self.serving, "replica_statusz"):
            # routed frontend mode: the "serving engine" is a
            # ReplicaRouter — aggregate the per-replica rollups and the
            # router's own placement state into the same document
            out["router"] = self.serving.router_statusz()
            out["replicas"] = self.serving.replica_statusz()
        diag = getattr(self.serving, "diagnostics", None)
        if diag is not None and diag.slo is not None:
            def clean(d):
                return {k: (None if isinstance(v, float)
                            and not math.isfinite(v) else v)
                        for k, v in d.items()}
            out["slo"] = {
                "quantiles": {s: clean(q) for s, q
                              in diag.slo.quantiles().items()},
                "burn": diag.slo.tick(),
            }
        return out

    async def _postmortem(self, writer) -> None:
        import json as _json
        import os

        from ....telemetry import postmortem as ds_postmortem
        diag = getattr(self.serving, "diagnostics", None)
        cfg = diag.config if diag is not None else None

        def collect():
            # bundle writing is disk I/O exactly when the server is in
            # trouble — keep it off the event-loop thread so live
            # /generate streams don't stall behind it
            path = ds_postmortem.write_bundle("http_request", config=cfg)
            with open(os.path.join(path, "manifest.json")) as fh:
                return path, _json.load(fh)

        try:
            path, manifest = await asyncio.to_thread(collect)
            _json_response(writer, "200 OK",
                           {"path": path, "manifest": manifest})
        except Exception as e:
            _json_response(writer, "500 Internal Server Error",
                           {"error": f"{type(e).__name__}: {e}"})

    async def _generate(self, reader, writer, body: bytes,
                        headers: Optional[dict] = None) -> None:
        from ....telemetry import context as trace_context
        # coerce every field up front: an unchecked value (e.g.
        # temperature="hot") would only blow up inside scheduler.step(),
        # where _step_error fails EVERY in-flight request
        try:
            payload = json.loads(body or b"{}")
            prompt = [int(t) for t in payload["prompt"]]
            max_new = int(payload.get("max_new_tokens", 64))
            kw = {}
            for name, cast in (("eos_token_id", int), ("top_k", int),
                               ("seed", int), ("temperature", float),
                               ("top_p", float), ("weight", float),
                               ("deadline_s", float), ("tenant", str)):
                if payload.get(name) is not None:
                    kw[name] = cast(payload[name])
        except (KeyError, TypeError, ValueError, json.JSONDecodeError):
            _json_response(writer, "400 Bad Request",
                           {"error": "body must be JSON with a 'prompt' "
                                     "list of token ids (and numeric "
                                     "sampling/deadline fields)"})
            return
        # distributed tracing: continue the caller's W3C traceparent
        # (+ baggage) headers, or mint the root HERE — binding before
        # submit means both the single-engine frontend and the router
        # continue ONE identity, and the API layer can echo it back.
        # child() keeps the caller's trace id but mints THIS server's
        # span id, so the echoed traceparent never hands the caller its
        # own span back (a client parenting follow-ups on it would
        # self-parent)
        upstream = trace_context.from_headers(headers or {})
        ctx = (upstream.child() if upstream is not None
               else trace_context.new_context())
        try:
            with trace_context.use(ctx):
                stream = await self.serving.submit(prompt, max_new, **kw)
        except OverloadedError as e:
            # Retry-After carries the machine-readable backoff hint the
            # admission layer attached (integer seconds, ceil'd — the
            # HTTP header grammar is delta-seconds); the JSON body keeps
            # the precise float for clients that parse it
            import math
            retry = getattr(e, "retry_after_s", None)
            headers = ({"Retry-After": str(max(1, math.ceil(retry)))}
                       if retry is not None else None)
            _json_response(writer, "429 Too Many Requests",
                           {"error": "overloaded", "reason": e.reason,
                            "retry_after_s": retry,
                            "detail": str(e)}, extra_headers=headers)
            return
        except ValueError as e:
            _json_response(writer, "400 Bad Request", {"error": str(e)})
            return

        # the runtime uid rides a response header so a client knows what
        # to resume (worker GET /resume) BEFORE the tail line arrives
        extra = {"traceparent": ctx.to_traceparent()}
        if getattr(stream, "uid", None) is not None:
            extra[UID_HEADER] = str(stream.uid)
        writer.write(_response_head(
            "200 OK", "application/x-ndjson", extra))
        await self._stream_tokens(reader, writer, stream, ctx)

    async def _stream_tokens(self, reader, writer, stream, ctx) -> None:
        """Pump one token stream as NDJSON lines + the tail summary
        (shared by /generate and the worker's /handoff response).
        With Connection: close the client sends nothing more; read()
        completing means it hung up — cancel so the KV blocks free."""
        hangup = asyncio.ensure_future(reader.read(1))
        status, detail = "completed", None
        try:
            while True:
                nxt = asyncio.ensure_future(stream.__anext__())
                done, _ = await asyncio.wait(
                    {nxt, hangup}, return_when=asyncio.FIRST_COMPLETED)
                if hangup in done and nxt not in done:
                    nxt.cancel()
                    await stream.cancel()
                    return
                try:
                    tok = nxt.result()
                except StopAsyncIteration:
                    status = stream.status
                    break
                except DeadlineExceeded:
                    status, detail = "expired", "deadline exceeded"
                    break
                except RequestFailed as e:
                    status, detail = "error", str(e)
                    break
                writer.write(json.dumps({"token": tok}).encode() + b"\n")
                await writer.drain()
            tail = {"done": True, "status": status, "uid": stream.uid,
                    "n": len(stream.tokens), "tokens": stream.tokens,
                    "trace_id": ctx.trace_id}
            if detail:
                tail["detail"] = detail
            writer.write(json.dumps(tail).encode() + b"\n")
            await writer.drain()
        except (ConnectionResetError, BrokenPipeError):
            await stream.cancel()
        finally:
            hangup.cancel()
