"""Asyncio front end of the serving runtime.

:class:`ServingEngine` decouples clients from the model loop: ``await
submit(...)`` admission-checks the request (raising
:class:`~.admission.OverloadedError` under overload — explicit
backpressure, never an unbounded queue) and returns a
:class:`TokenStream`, an async iterator that yields tokens as the
background :class:`~.loop.ServingLoop` emits them. Cancelling a stream —
``cancel()``, ``aclose()`` (e.g. via ``contextlib.aclosing``), or as a
garbage-collection safety net when the stream is dropped — releases the
request's KV blocks back to the pool mid-decode. A bare ``break`` out of
``async for`` does NOT call ``aclose()`` on a plain async iterator:
callers abandoning a stream early should ``await stream.cancel()`` (the
GC net is best-effort and its timing is the collector's). Per-request
deadlines cancel overdue work wherever it is (pending or mid-decode).

Tokens are byte-identical to the direct scheduler path: the runtime
changes WHEN work runs, never what it computes.
"""

import asyncio
import itertools
import time
from dataclasses import dataclass, field
from typing import List, Optional, Sequence

from ....autotuning.online import OnlineAdapter, OnlineAdapterConfig
from ....telemetry import context as trace_context
from ....telemetry.anomaly import (DiagnosticsConfig, KVLeakDetector,
                                   SLOBurnRateMonitor, StallWatchdog)
from ....telemetry.recorder import get_recorder
from ..scheduler import DynamicSplitFuseScheduler
from .admission import AdmissionConfig, AdmissionController
from .loop import ServingLoop


class DeadlineExceeded(Exception):
    """The request's deadline passed before it finished; its KV blocks
    were released and no further tokens will arrive."""


class RequestFailed(RuntimeError):
    """The model loop could not run the request (e.g. the prompt exceeds
    max_seq_len, or a step-time engine failure)."""


@dataclass
class ServingConfig:
    token_budget: Optional[int] = None      # scheduler step budget
    chunk: Optional[int] = None             # prefill chunk size
    max_inflight: Optional[int] = None      # requests inside the scheduler
    idle_wait_s: float = 0.002
    # 'auto' | 'on' | 'off': override the engine's ragged unified-step
    # dispatch (config_v2.ragged_attention) for this serving runtime —
    # 'off' is the rollback knob to the stitched prefill/decode
    # families; None leaves the engine's own setting alone
    ragged_attention: Optional[str] = None
    admission: AdmissionConfig = field(default_factory=AdmissionConfig)
    # active observability: flight-recorder budget, SLO burn-rate
    # monitoring, stall watchdog, KV-leak check at drain (telemetry/
    # anomaly.py; docs/TELEMETRY.md § Anomaly detectors)
    diagnostics: DiagnosticsConfig = field(
        default_factory=DiagnosticsConfig)
    # SLO-driven online adaptation of the registry's online=True knobs
    # (decode window, admission token budget) between scheduler steps —
    # autotuning/online.py; None disables it
    autotune: Optional["OnlineAdapterConfig"] = None


class ServingDiagnostics:
    """The serving runtime's active-observability bundle: the SLO
    burn-rate monitor the loop ticks, the stall watchdog it beats, and
    the KV-leak detector it runs at drain. ``None`` members mean the
    feature is disabled; the loop checks for that."""

    def __init__(self, config: DiagnosticsConfig):
        self.config = config
        self.slo: Optional[SLOBurnRateMonitor] = None
        self.stall: Optional[StallWatchdog] = None
        self.leak: Optional[KVLeakDetector] = None
        if not config.enabled:
            return
        get_recorder().set_budget(config.recorder_max_bytes)
        self.slo = SLOBurnRateMonitor(config)
        self.leak = KVLeakDetector(config)
        if config.stall_enabled:
            self.stall = StallWatchdog(config).start()
            self.stall.register("serving_loop")

    def close(self) -> None:
        if self.stall is not None:
            self.stall.stop()


@dataclass
class _Entry:
    """The loop-side request record (see ServingLoop's duck-type)."""
    uid: int
    prompt: List[int]
    max_new_tokens: int
    eos_token_id: Optional[int]
    temperature: float
    top_p: float
    top_k: int
    seed: Optional[int]
    tenant: str
    weight: Optional[float]
    deadline_t: Optional[float]
    on_token: object = None
    on_end: object = None
    state: str = "pending"
    # LoRA adapter NAME this request is served through (None = base):
    # rides into scheduler.submit and the admission fairness key
    adapter: Optional[str] = None
    # distributed TraceContext (telemetry/context.py), captured on the
    # asyncio side: the serving-loop thread does not share the asyncio
    # contextvar context, so the entry carries it across that boundary
    trace_ctx: object = None


class TokenStream:
    """Async iterator over one request's generated tokens.

    Ends (StopAsyncIteration) when the request completes or is
    cancelled; raises :class:`DeadlineExceeded` on deadline expiry and
    :class:`RequestFailed` on model-loop errors. ``status`` is one of
    'active' | 'completed' | 'cancelled' | 'expired' | 'error'."""

    def __init__(self, serving: "ServingEngine", uid: int,
                 aio_loop: asyncio.AbstractEventLoop):
        self._serving = serving
        self._aio = aio_loop
        self._q: asyncio.Queue = asyncio.Queue()
        self._ended = False
        self.uid = uid
        self.status = "active"
        self.reason: Optional[str] = None
        self.tokens: List[int] = []

    # called from the serving-loop thread
    def _push_token(self, tok: int, finished: bool) -> None:
        self._aio.call_soon_threadsafe(self._q.put_nowait, ("tok", tok))

    def _push_end(self, status: str, reason: Optional[str]) -> None:
        self._aio.call_soon_threadsafe(self._q.put_nowait,
                                       ("end", status, reason))

    # -- async iterator -------------------------------------------------
    def __aiter__(self) -> "TokenStream":
        return self

    async def __anext__(self) -> int:
        if self._ended:
            raise StopAsyncIteration
        item = await self._q.get()
        if item[0] == "tok":
            self.tokens.append(item[1])
            return item[1]
        self._ended = True
        self.status, self.reason = item[1], item[2]
        if self.status == "expired":
            raise DeadlineExceeded(
                f"request {self.uid}: deadline exceeded")
        if self.status == "error":
            raise RequestFailed(
                f"request {self.uid}: {self.reason}")
        raise StopAsyncIteration    # completed or cancelled

    async def cancel(self) -> None:
        """Abort the request: its KV blocks return to the pool and the
        stream ends (status 'cancelled'); no further tokens arrive."""
        self._serving._loop_runner.request_cancel(self.uid)

    async def aclose(self) -> None:
        if not self._ended and self.status == "active":
            await self.cancel()

    def __del__(self):
        # best-effort net for dropped streams: without it an abandoned
        # request decodes to max_new_tokens holding its KV blocks.
        # request_cancel only touches a thread-safe deque + Event, so it
        # is safe from a finalizer; a finished uid makes it a no-op.
        if self.status == "active":
            try:
                self._serving._loop_runner.request_cancel(self.uid)
            except Exception:
                pass

    async def drain(self) -> List[int]:
        """Collect every remaining token; returns all tokens so far."""
        async for _ in self:
            pass
        return self.tokens


class ServingEngine:
    """Async serving runtime: frontend -> admission -> loop -> scheduler.

    Usage::

        serving = ServingEngine(engine, ServingConfig(token_budget=128))
        await serving.start()
        stream = await serving.submit(prompt_ids, max_new_tokens=64)
        async for tok in stream:
            ...
        await serving.stop()          # graceful drain
    """

    def __init__(self, engine, config: Optional[ServingConfig] = None,
                 clock=time.perf_counter, bridge=None,
                 lane: Optional[str] = None):
        """``bridge``: optional :class:`~...telemetry.TelemetryBridge`;
        the loop final-flushes (``close()``) it on drain/stop so the last
        partial flush interval reaches the monitor backends.

        ``lane``: fleet lane name for the serving loop's spans (the
        replica name under a router; see telemetry/trace.py
        ``set_lane``) — the stitched fleet timeline groups spans into
        one process row per lane."""
        self.config = config or ServingConfig()
        self.clock = clock
        if self.config.ragged_attention is not None:
            engine.set_ragged_mode(self.config.ragged_attention)
        self.scheduler = DynamicSplitFuseScheduler(
            engine, token_budget=self.config.token_budget,
            chunk=self.config.chunk, clock=clock)
        self.admission = AdmissionController(self.config.admission)
        self.diagnostics = ServingDiagnostics(self.config.diagnostics)
        # SLO-driven online adapter (autotuning/online.py): ticked by the
        # loop thread between scheduler steps — the only thread allowed
        # to swap the engine's fused decode program
        self.adapter: Optional[OnlineAdapter] = None
        if (self.config.autotune is not None
                and self.config.autotune.enabled):
            self.adapter = OnlineAdapter(
                engine, admission=self.admission,
                slo=self.diagnostics.slo, config=self.config.autotune)
        self._loop_runner = ServingLoop(
            self.scheduler, self.admission,
            max_inflight=self.config.max_inflight,
            idle_wait_s=self.config.idle_wait_s, clock=clock,
            bridge=bridge, diagnostics=self.diagnostics, lane=lane,
            adapter=self.adapter)
        self._uids = itertools.count(1)
        self._stopped = False

    @property
    def loop_runner(self) -> ServingLoop:
        return self._loop_runner

    # -- lifecycle ------------------------------------------------------
    async def start(self) -> "ServingEngine":
        if self._stopped:
            raise RuntimeError("serving engine already stopped")
        self._loop_runner.start()
        return self

    async def stop(self, drain: bool = True,
                   timeout: Optional[float] = None) -> None:
        """Shut the runtime down. ``drain=True`` (graceful): new submits
        are rejected immediately, everything already admitted finishes.
        ``drain=False``: in-flight requests are cancelled (KV released)
        and their streams end with status 'cancelled'."""
        self._stopped = True
        if drain:
            self._loop_runner.request_drain()
        else:
            self._loop_runner.request_stop()
        if not self._loop_runner.running:
            # never started: end anything parked in the queues
            self._loop_runner.start()
        await asyncio.to_thread(self._loop_runner.join, timeout)
        self.diagnostics.close()

    async def __aenter__(self) -> "ServingEngine":
        return await self.start()

    async def __aexit__(self, *exc) -> None:
        await self.stop(drain=exc == (None, None, None))

    # -- submission -----------------------------------------------------
    async def submit(self, prompt: Sequence[int], max_new_tokens: int, *,
                     eos_token_id: Optional[int] = None,
                     temperature: float = 0.0, top_p: float = 1.0,
                     top_k: int = 0, seed: Optional[int] = None,
                     tenant: str = "default",
                     weight: Optional[float] = None,
                     deadline_s: Optional[float] = None,
                     adapter: Optional[str] = None) -> TokenStream:
        """Admit a request and return its token stream.

        Raises :class:`~.admission.OverloadedError` when the runtime is
        overloaded (bounded queue full / token budget exceeded /
        draining) — callers retry with backoff or surface 429.
        ``deadline_s`` is a wall-clock budget from now; overdue requests
        are cancelled wherever they are and the stream raises
        :class:`DeadlineExceeded`. ``adapter`` names a loaded LoRA
        adapter to serve the request through (None = base model); it
        scopes admission fairness within the tenant and the engine's
        per-row adapter gather."""
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        uid = next(self._uids)
        # distributed tracing: continue the caller's context (bound by
        # the HTTP layer from a traceparent header, or by the router at
        # dispatch) or mint a fresh root — every request has ONE trace
        # identity from here to its last decode token
        ctx = trace_context.get_or_new()
        stream = TokenStream(self, uid, asyncio.get_running_loop())
        entry = _Entry(
            uid=uid, prompt=list(map(int, prompt)),
            max_new_tokens=int(max_new_tokens),
            eos_token_id=eos_token_id, temperature=temperature,
            top_p=top_p, top_k=top_k, seed=seed, tenant=tenant,
            weight=weight,
            deadline_t=(self.clock() + deadline_s
                        if deadline_s is not None else None),
            on_token=stream._push_token, on_end=stream._push_end,
            trace_ctx=ctx, adapter=adapter)
        self.admission.try_admit(entry)     # raises OverloadedError
        self._loop_runner.register(entry)
        return stream

    # -- handoff (prefill/decode disaggregation; serve/handoff.py) ------
    async def resume(self, pack, *, prompt: Sequence[int],
                     generated: Sequence[int], max_new_tokens: int,
                     eos_token_id: Optional[int] = None,
                     temperature: float = 0.0, top_p: float = 1.0,
                     top_k: int = 0, rng_state=None,
                     deadline_s: Optional[float] = None,
                     trace_ctx=None) -> TokenStream:
        """Adopt a handed-off request: restore the KV ``pack`` exported
        by a prefill replica and continue decoding it here. The stream
        yields only the tokens decoded on THIS runtime — the caller
        already streamed ``generated`` (at least the prefill's first
        token). Restore and scheduler adoption run on the loop thread
        (the engine is not thread-safe); a restore failure ends the
        stream with status 'error'.

        ``trace_ctx`` continues the request's distributed trace across
        the handoff; when omitted, the pack's wire payload (embedded by
        the prefill side — serve/handoff.py) or the caller's bound
        context is used, so the decode hop lands in the SAME trace as
        router dispatch and prefill.

        Resumed requests bypass the admission queue — there is no
        pending phase to queue through; the ROUTER is the admission
        point for disaggregated traffic and picks the decode replica by
        its load signals before prefill ever runs."""
        if self._stopped or self.admission.closed:
            from .admission import OverloadedError
            raise OverloadedError(
                "draining", "serving runtime is draining; not accepting "
                "handoffs",
                retry_after_s=self.config.admission.retry_after_s)
        uid = next(self._uids)
        stream = TokenStream(self, uid, asyncio.get_running_loop())
        entry = _Entry(
            uid=uid, prompt=list(map(int, prompt)),
            max_new_tokens=int(max_new_tokens),
            eos_token_id=eos_token_id, temperature=temperature,
            top_p=top_p, top_k=top_k, seed=None, tenant="handoff",
            weight=None,
            deadline_t=(self.clock() + deadline_s
                        if deadline_s is not None else None),
            on_token=stream._push_token, on_end=stream._push_end,
            state="inflight",
            trace_ctx=(trace_ctx if trace_ctx is not None
                       else trace_context.from_wire(pack.get("trace"))
                       or trace_context.current()))
        self._loop_runner.resume(entry, pack,
                                 generated=list(map(int, generated)),
                                 rng_state=rng_state)
        return stream

    async def begin_handoff(self, header_chunk: bytes) -> "ChunkedHandoff":
        """Open a chunked streaming handoff (serve/handoff.py chunk
        protocol): parse the header chunk, adopt the destination blocks
        on the loop thread, and return the feed/commit/abort handle.
        Each fed chunk applies BETWEEN scheduler steps, so the transfer
        overlaps this runtime's running batch. Raises
        :class:`~.admission.OverloadedError` while draining (the
        caller re-routes, like ``resume``)."""
        from . import handoff as handoff_mod
        if self._stopped or self.admission.closed:
            from .admission import OverloadedError
            raise OverloadedError(
                "draining", "serving runtime is draining; not accepting "
                "handoffs",
                retry_after_s=self.config.admission.retry_after_s)
        header = await asyncio.to_thread(handoff_mod.parse_header,
                                         header_chunk)
        uid = next(self._uids)
        await self._loop_runner.run_on_loop(
            lambda: self._loop_runner.begin_restore(uid, header))
        return ChunkedHandoff(self, uid, header)

    # -- live weight update (serve/weights.py; blue/green hot-swap) -----
    async def begin_weight_update(self, header_chunk: bytes
                                  ) -> "WeightUpdate":
        """Open a chunked weight update: chunks stage HOST-SIDE (CRC-
        checked, off the loop thread — the running batch keeps
        stepping), then ``commit`` applies ONE atomic param swap
        between scheduler steps. A stream therefore never sees tokens
        from two weight versions unless it spans the commit — which the
        router's blue/green rollout prevents by draining a replica's
        routed streams before pushing (serve/router.py)."""
        from . import weights as serve_weights
        if self._stopped or self.admission.closed:
            from .admission import OverloadedError
            raise OverloadedError(
                "draining", "serving runtime is draining; not accepting "
                "weight updates",
                retry_after_s=self.config.admission.retry_after_s)
        header = await asyncio.to_thread(
            serve_weights.parse_weights_header, header_chunk)
        return WeightUpdate(self, serve_weights.WeightStager(header))

    async def apply_weights(self, payloads: Sequence[bytes]) -> int:
        """Stage + commit a complete weight payload; returns the
        installed version."""
        update = await self.begin_weight_update(payloads[0])
        try:
            for chunk in payloads[1:]:
                await update.feed(chunk)
            return await update.commit()
        except BaseException:
            await update.abort()
            raise

    @property
    def weight_version(self) -> int:
        return int(getattr(self.scheduler.engine, "weight_version", 0))

    # -- introspection --------------------------------------------------
    def heartbeat_age(self) -> Optional[float]:
        """Seconds since the serving loop's last stall-watchdog
        heartbeat while mid-step, or None when idle / watchdog off.
        The replica router's dead-replica detector reads this."""
        stall = self.diagnostics.stall
        if stall is None:
            return None
        return stall.heartbeat_age("serving_loop")

    def health(self) -> dict:
        age = self.heartbeat_age()
        return {
            "status": ("draining" if (self.admission.closed
                                      or self._stopped) else "ok"),
            "queue_depth": self.admission.depth(),
            "queued_tokens": self.admission.queued_tokens(),
            "inflight": self.scheduler.inflight(),
            "loop_alive": self._loop_runner.running,
            # the replica-surface signals a remote router shim maps
            # from one /healthz poll (serve/remote.py)
            "load": (self.admission.queued_tokens()
                     + self.scheduler.inflight()),
            "heartbeat_age_s": age,
            "block_size": int(
                self.scheduler.engine.state_manager.block_size),
            "max_seq_len": int(
                self.scheduler.engine.state_manager.config.max_seq_len),
            # blue/green rollout signal (serve/weights.py): the router
            # converges the fleet onto one target version off this field
            "weight_version": self.weight_version,
            # spill-aware placement signal (ragged/spill.py): the bloom
            # summary of this replica's spilled digests rides every
            # heartbeat, so the router can place a returning
            # conversation where its cold KV actually lives
            "kv_spill": self.spill_summary_doc(),
        }

    def spill_summary_doc(self) -> Optional[dict]:
        """Serialized spill-tier digest summary, or None when the
        engine runs without a spill tier."""
        spill = getattr(self.scheduler.engine, "spill", None)
        if spill is None:
            return None
        return spill.digest_summary().to_doc()


class ChunkedHandoff:
    """Client handle for one streaming handoff into a
    :class:`ServingEngine` (``begin_handoff``): ``feed`` each KV chunk
    (awaiting the ack paces the wire and lets scheduler steps
    interleave), then ``commit`` with the decode parameters to get the
    token stream — or ``abort`` to free the partially-streamed blocks."""

    def __init__(self, serving: ServingEngine, uid: int, header: dict):
        self._serving = serving
        self.uid = uid
        self.header = header
        self._open = True

    async def feed(self, chunk: bytes) -> None:
        from . import handoff as handoff_mod
        parsed = await asyncio.to_thread(handoff_mod.parse_chunk, chunk)
        loop = self._serving._loop_runner
        try:
            await loop.run_on_loop(
                lambda: loop.apply_restore(self.uid, parsed, len(chunk)))
        except asyncio.CancelledError:
            # the AWAIT was cancelled, not the apply — the loop-side
            # restore may still be live, so the handle stays open and
            # abort()/__del__ can free it (closing here would leak the
            # blocks and wedge graceful drain)
            raise
        except BaseException:
            # the loop already freed the blocks on an apply failure
            self._open = False
            raise

    async def commit(self, *, prompt: Sequence[int],
                     generated: Sequence[int], max_new_tokens: int,
                     eos_token_id: Optional[int] = None,
                     temperature: float = 0.0, top_p: float = 1.0,
                     top_k: int = 0, rng_state=None,
                     deadline_s: Optional[float] = None,
                     trace_ctx=None) -> TokenStream:
        """Verify every chunk arrived and resume decoding here — the
        chunked counterpart of :meth:`ServingEngine.resume` (same
        parameters, same bit-identical-to-colocated contract)."""
        serving = self._serving
        stream = TokenStream(serving, self.uid,
                             asyncio.get_running_loop())
        entry = _Entry(
            uid=self.uid, prompt=list(map(int, prompt)),
            max_new_tokens=int(max_new_tokens),
            eos_token_id=eos_token_id, temperature=temperature,
            top_p=top_p, top_k=top_k, seed=None, tenant="handoff",
            weight=None,
            deadline_t=(serving.clock() + deadline_s
                        if deadline_s is not None else None),
            on_token=stream._push_token, on_end=stream._push_end,
            state="inflight",
            trace_ctx=(trace_ctx if trace_ctx is not None
                       else trace_context.current()
                       or trace_context.from_wire(
                           self.header.get("trace"))))
        loop = self._serving._loop_runner
        try:
            await loop.run_on_loop(
                lambda: loop.commit_restore(
                    entry, list(map(int, generated)), rng_state))
        except asyncio.CancelledError:
            # await cancelled mid-commit: leave the handle open so
            # abort() can still free an uncommitted restore (abort is
            # a no-op if the loop-side commit did run)
            raise
        except BaseException:
            self._open = False   # loop-side commit failed: already
            raise                # aborted there
        self._open = False
        return stream

    async def abort(self) -> None:
        if not self._open:
            return
        self._open = False
        loop = self._serving._loop_runner
        try:
            await loop.run_on_loop(
                lambda: loop._abort_restore(self.uid))
        except Exception:
            pass

    def __del__(self):
        # GC net: a dropped handle must not wedge drain holding blocks
        # (_abort_restore only touches loop-thread state via post())
        if self._open:
            try:
                self._serving._loop_runner.post(
                    lambda: self._serving._loop_runner._abort_restore(
                        self.uid))
            except Exception:
                pass


class WeightUpdate:
    """Client handle for one staged weight update into a
    :class:`ServingEngine` (``begin_weight_update``): ``feed`` each
    payload chunk (host-side staging + CRC — the loop keeps stepping
    its batch), then ``commit`` applies the atomic swap between
    scheduler steps; ``abort`` drops the staged leaves without touching
    the live params."""

    def __init__(self, serving: ServingEngine, stager):
        self._serving = serving
        self._stager = stager
        self._open = True
        self._t0 = time.perf_counter()
        loop = serving._loop_runner
        loop.weight_staging += 1
        from ....telemetry import get_registry
        self._m_seconds = get_registry().histogram(
            "serving_weight_update_seconds",
            "weight update begin -> committed swap (staging overlaps "
            "the running batch; only the final swap touches the loop)",
            unit="s", buckets=(1e-3, 1e-2, 0.1, 1.0, 10.0, 60.0))

    @property
    def version(self) -> int:
        return int(self._stager.version)

    async def feed(self, chunk: bytes) -> None:
        if not self._open:
            raise RuntimeError("weight update already closed")
        try:
            await asyncio.to_thread(self._stager.feed, chunk)
        except BaseException:
            await self.abort()
            raise

    async def commit(self) -> int:
        """Verify every chunk arrived and swap the live params between
        scheduler steps. Returns the installed version."""
        from . import weights as serve_weights
        if not self._open:
            raise RuntimeError("weight update already closed")
        stager = self._stager
        stager.commit_check()
        loop = self._serving._loop_runner

        engine = loop.scheduler.engine
        # host-side half off the loop thread: for DELTA payloads this
        # validates the base version and reconstructs base +
        # dequant(delta) (typed failure on stale base, live params
        # untouched); full payloads pass through
        try:
            flat = await asyncio.to_thread(
                serve_weights.prepare_stager, engine, stager)
        except BaseException:
            await self.abort()
            raise

        def swap() -> int:
            # full/delta -> donated-buffer param swap; adapter ->
            # bank-slot load_adapter (weights.install_stager routes)
            return serve_weights.install_stager(engine, stager, flat)
        try:
            version = await loop.run_on_loop(swap)
        finally:
            self._close()
        self._m_seconds.observe(time.perf_counter() - self._t0)
        return version

    async def abort(self) -> None:
        self._close()

    def _close(self) -> None:
        if self._open:
            self._open = False
            self._stager.leaves = {}
            self._serving._loop_runner.weight_staging -= 1

    def __del__(self):
        if self._open:
            try:
                self._close()
            except Exception:
                pass
