"""Admission control and backpressure for the serving runtime.

Overload policy (the reference's MII deployments sit behind an RPC
queue; here the policy is explicit and observable):

  * **bounded pending queue** — at most ``max_pending`` requests wait for
    the model loop; the queue never grows without bound,
  * **token-budget load shedding** — each request costs
    ``len(prompt) + max_new_tokens`` tokens of future work; when the
    queued cost would exceed ``max_queued_tokens`` the request is shed
    at the door (an explicit :class:`OverloadedError`, never a silent
    stall),
  * **weighted-fair scheduling** — pending requests drain in virtual-
    finish-time order across tenants (start-time fair queuing weighted
    by tenant weight, cost measured in tokens), so one chatty tenant
    cannot starve the rest.

Thread-safety: ``try_admit`` runs on the asyncio thread, ``pop`` on the
serving-loop thread — every public method takes the controller lock.
"""

import threading
from collections import deque
from dataclasses import dataclass, field
from typing import Deque, Dict, List, Optional

from ....telemetry import recorder as flight


class OverloadedError(RuntimeError):
    """Explicit admission rejection (HTTP surfaces map it to 429).

    ``reason`` is one of ``queue_full`` / ``token_budget`` / ``draining``
    — the same labels the rejection counter uses. ``retry_after_s`` is a
    machine-readable backoff hint: the HTTP surface emits it as a
    ``Retry-After`` header and the replica router uses it to take the
    rejecting replica out of rotation for exactly that long
    (backoff-aware re-routing) instead of hammering it."""

    def __init__(self, reason: str, message: str,
                 retry_after_s: Optional[float] = None):
        super().__init__(message)
        self.reason = reason
        self.retry_after_s = retry_after_s


@dataclass
class AdmissionConfig:
    max_pending: int = 128            # bounded pending queue
    # cap on queued future work, in tokens (prompt + max_new per
    # request); None disables token-budget shedding
    max_queued_tokens: Optional[int] = None
    # per-tenant weights for fair scheduling; tenants not listed get 1.0
    tenant_weights: Dict[str, float] = field(default_factory=dict)
    # backoff hint attached to every rejection (OverloadedError
    # .retry_after_s / HTTP Retry-After): how long a shed client should
    # wait before retrying THIS runtime
    retry_after_s: float = 0.5

    def __post_init__(self):
        # the shed threshold is a registered tunable: a bad value fails
        # naming the registry entry + documented range, and /statusz
        # shows the effective value with its provenance
        if self.max_queued_tokens is not None:
            from ....runtime import tunables
            tunables.check("serving.max_queued_tokens",
                           self.max_queued_tokens,
                           label="max_queued_tokens")
            tunables.observe("serving.max_queued_tokens",
                             self.max_queued_tokens, "config")


def request_cost(entry) -> int:
    """Future-work cost of a request in tokens (admission currency)."""
    return len(entry.prompt) + max(int(entry.max_new_tokens), 1)


class AdmissionController:
    """Bounded, tenant-fair pending queue between submit() and the loop."""

    def __init__(self, config: Optional[AdmissionConfig] = None):
        self.config = config or AdmissionConfig()
        self._lock = threading.Lock()
        self._queues: Dict[str, Deque] = {}
        # start-time fair queuing state: virtual time advances to the
        # finish tag of each popped request; a tenant head's finish tag
        # is max(vtime, tenant's last finish) + cost / weight
        self._vtime = 0.0
        self._last_finish: Dict[str, float] = {}
        self._head_finish: Dict[str, float] = {}
        self._depth = 0
        self._tokens = 0
        self._closed = False
        self._init_telemetry()

    def _init_telemetry(self):
        from ....telemetry import get_registry
        reg = get_registry()
        self._m_depth = reg.gauge(
            "serving_admission_queue_depth",
            "requests waiting in the admission queue")
        self._m_tokens = reg.gauge(
            "serving_admission_queued_tokens",
            "queued future work in tokens (prompt + max_new)")
        self._m_admitted = reg.counter(
            "serving_admission_admitted_total", "requests admitted")
        self._m_rejected = reg.counter(
            "serving_admission_rejections_total",
            "requests shed at admission", labelnames=("reason",))

    def _update_gauges(self):
        self._m_depth.set(self._depth)
        self._m_tokens.set(self._tokens)

    def _weight(self, entry) -> float:
        if entry.weight is not None:
            return max(float(entry.weight), 1e-6)
        return max(self.config.tenant_weights.get(entry.tenant, 1.0), 1e-6)

    @staticmethod
    def _lane(entry) -> str:
        """Fairness-queue key: tenant, sub-divided by LoRA adapter. A
        tenant hammering one adapter then cannot starve its OWN other
        adapters either — each (tenant, adapter) pair drains in
        virtual-finish-time order like a tenant of its own (weights
        still come from the tenant, via ``_weight``). ``|`` cannot
        appear ambiguously: it is appended only when an adapter is
        set."""
        adapter = getattr(entry, "adapter", None)
        return f"{entry.tenant}|{adapter}" if adapter else entry.tenant

    def _reject(self, reason: str, message: str):
        self._m_rejected.labels(reason=reason).inc()
        flight.record("shed", reason=reason, depth=self._depth,
                      queued_tokens=self._tokens)
        raise OverloadedError(reason, message,
                              retry_after_s=self.config.retry_after_s)

    # ------------------------------------------------------------------
    def try_admit(self, entry) -> None:
        """Admit ``entry`` into the pending queue or raise
        :class:`OverloadedError` (the explicit backpressure signal)."""
        cost = request_cost(entry)
        with self._lock:
            if self._closed:
                self._reject("draining",
                             "serving runtime is draining; not accepting "
                             "new requests")
            if self._depth >= self.config.max_pending:
                self._reject(
                    "queue_full",
                    f"admission queue full ({self.config.max_pending} "
                    f"pending); retry later")
            budget = self.config.max_queued_tokens
            if budget is not None and self._tokens + cost > budget:
                self._reject(
                    "token_budget",
                    f"queued token budget exceeded ({self._tokens} "
                    f"queued + {cost} requested > {budget}); shed")
            t = self._lane(entry)
            q = self._queues.setdefault(t, deque())
            if not q:
                self._head_finish[t] = (max(self._vtime,
                                            self._last_finish.get(t, 0.0))
                                        + cost / self._weight(entry))
            q.append(entry)
            self._depth += 1
            self._tokens += cost
            self._m_admitted.inc()
            flight.record("admit", uid=entry.uid, tenant=entry.tenant,
                          adapter=getattr(entry, "adapter", None),
                          cost_tokens=cost, depth=self._depth)
            self._update_gauges()

    def pop(self):
        """Next request in weighted-fair order, or None if empty."""
        with self._lock:
            best_t, best_f = None, None
            for t, q in self._queues.items():
                if q and (best_f is None or self._head_finish[t] < best_f):
                    best_t, best_f = t, self._head_finish[t]
            if best_t is None:
                return None
            return self._pop_locked(best_t)

    def _pop_locked(self, tenant: str):
        q = self._queues[tenant]
        entry = q.popleft()
        self._vtime = self._head_finish[tenant]
        self._last_finish[tenant] = self._head_finish[tenant]
        if q:
            head = q[0]
            self._head_finish[tenant] = (
                self._last_finish[tenant]
                + request_cost(head) / self._weight(head))
        else:
            self._drop_tenant(tenant)
        self._depth -= 1
        self._tokens -= request_cost(entry)
        self._update_gauges()
        return entry

    def _drop_tenant(self, tenant: str) -> None:
        """Forget an idle tenant's fairness state. Tenant names are
        client-controlled (the HTTP surface passes them verbatim), so
        keeping every tenant ever seen would grow these dicts without
        bound and make pop()'s head scan O(tenants-ever). Equivalent for
        fairness: once a tenant's last pop advanced vtime to its finish
        tag, max(vtime, last_finish) == vtime for it from then on."""
        self._queues.pop(tenant, None)
        self._head_finish.pop(tenant, None)
        self._last_finish.pop(tenant, None)

    def remove(self, uid: int) -> bool:
        """Drop a still-pending request (cancellation / deadline expiry
        before it reached the model loop)."""
        with self._lock:
            for t, q in self._queues.items():
                for entry in q:
                    if entry.uid == uid:
                        was_head = q[0] is entry
                        q.remove(entry)
                        self._depth -= 1
                        self._tokens -= request_cost(entry)
                        if not q:
                            self._drop_tenant(t)
                        elif was_head:
                            head = q[0]
                            self._head_finish[t] = (
                                max(self._vtime,
                                    self._last_finish.get(t, 0.0))
                                + request_cost(head) / self._weight(head))
                        self._update_gauges()
                        return True
        return False

    def reclaim_pending(self) -> List:
        """Empty the pending queues and return the reclaimed entries —
        the dead-replica failover path (serve/router.py): when a
        replica's heartbeat expires, its queued (not-yet-prefilled)
        requests are pulled back here and re-enqueued on survivors.
        Entries are marked ``done`` under the lock so a loop thread that
        later recovers cannot ALSO run them (it skips done entries at
        admit time)."""
        with self._lock:
            out: List = []
            for tenant in list(self._queues):
                q = self._queues[tenant]
                while q:
                    entry = q.popleft()
                    entry.state = "done"
                    out.append(entry)
                self._drop_tenant(tenant)
            self._depth = 0
            self._tokens = 0
            self._update_gauges()
            return out

    def set_max_queued_tokens(self, budget: Optional[int], *,
                              source: str = "online") -> Optional[int]:
        """Retarget the queued-token shed threshold at runtime (the
        online adapter's actuation path — autotuning/online.py).
        ``try_admit`` reads the config under the lock on every call, so
        the new budget applies to the next admission decision. ``None``
        disables token-budget shedding (the config default)."""
        from ....runtime import tunables
        if budget is not None:
            budget = tunables.check("serving.max_queued_tokens", budget,
                                    label="max_queued_tokens")
        with self._lock:
            old = self.config.max_queued_tokens
            self.config.max_queued_tokens = budget
        if budget != old:
            tunables.observe("serving.max_queued_tokens", budget, source)
            flight.record("tunable_set", name="serving.max_queued_tokens",
                          value=budget, source=source)
        return budget

    # ------------------------------------------------------------------
    def close(self) -> None:
        """Stop admitting (graceful drain): subsequent try_admit raises
        OverloadedError(reason='draining'); queued requests still pop."""
        with self._lock:
            self._closed = True

    @property
    def closed(self) -> bool:
        return self._closed

    def depth(self) -> int:
        return self._depth

    def queued_tokens(self) -> int:
        return self._tokens

    def empty(self) -> bool:
        return self._depth == 0
