"""Prefix-affinity replica router — the multi-host serving front tier.

One :class:`ReplicaRouter` spreads streaming requests across N engine
replicas (serve/replica.py; the production topology the TPU-vs-GPU
serving study treats as baseline — PAPERS.md arXiv:2605.25645):

  * **Prefix-affinity placement** — an incoming prompt is chain-hashed
    with the replicas' KV block size (`ragged_manager.prefix_digest`,
    the exact digests the per-replica prefix caches key on) and routed
    to the replica that last served the longest matching digest, so
    shared-prefix traffic (system prompts, few-shot preambles,
    multi-turn conversations) lands where its KV blocks already are.
    Affinity is recorded at DISPATCH time, so concurrent same-prefix
    requests converge on one replica before the first even finishes.
    No match falls back to a consistent-hash ring (stable under replica
    death: only the dead node's keys move).
  * **Backoff-aware rebalancing** — a replica that sheds
    (:class:`~.admission.OverloadedError`) is taken out of rotation for
    its ``retry_after_s`` hint and the request re-routes to the
    next-best (least-loaded) replica; only when EVERY routable replica
    is overloaded does the router itself shed, with the soonest
    retry hint attached.
  * **Lifecycle** — ``drain_replica()`` finishes a replica's in-flight
    streams while new traffic diverts to survivors;
    ``check_replicas()`` (run at submit time and by the background
    monitor) classifies every replica through a per-replica circuit
    breaker (serve/resilience.py): probe timeouts/resets make it
    SUSPECTED — out of rotation, mid-stream requests keep streaming —
    while a refused dial (process exit), an exhausted breaker, a dead
    loop thread or an expired stall-watchdog heartbeat make it DEAD:
    its queued (not-yet-prefilled) requests re-enqueue on survivors
    and a request that already streamed tokens fails explicitly (its
    KV lives only on the dead replica). One slow ``/healthz`` probe is
    never a death verdict.
  * **Disaggregation** (``RouterConfig.disaggregated``) — dedicated
    prefill replicas run whole-prompt prefill and hand the paged KV
    blocks off to a decode replica (serve/handoff.py); token streams
    stay bit-identical to colocated serving.

The router is asyncio-side only: it owns no engine and touches replicas
exclusively through their thread-safe serving frontends, so N
in-process replicas (N loop threads) serve concurrently under one
event loop — and the same surface maps onto subprocess or multi-host
replicas.
"""

import asyncio
import bisect
import hashlib
import itertools
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

import numpy as np

from ....telemetry import context as trace_context
from ....telemetry import trace
from ....telemetry.anomaly import DiagnosticsConfig, SLOBurnRateMonitor
from ..ragged.ragged_manager import prefix_digest
from .admission import OverloadedError
from .frontend import DeadlineExceeded, RequestFailed
from .replica import PrefillReplica, Replica
from .resilience import BreakerConfig, CircuitBreaker

# transport-level dispatch failures the router re-routes (typed server
# verdicts — OverloadedError, RequestFailed — are handled separately)
_DISPATCH_CONN_ERRORS = (OSError, ConnectionError, asyncio.TimeoutError,
                         asyncio.IncompleteReadError, TimeoutError)

_ROUTER_LANE = "router"


def _relabel_exposition(text: str, label: str, value: str) -> str:
    """Inject ``label="value"`` into every sample line of a Prometheus
    text exposition fetched from a remote replica, so its series
    federate next to the local registries' (comment lines are dropped —
    the local render already emitted TYPE/HELP for shared families, and
    duplicating them would violate the exactly-once contract)."""
    esc = value.replace("\\", r"\\").replace('"', r'\"')
    out = []
    for line in text.splitlines():
        if not line.strip() or line.startswith("#"):
            continue
        brace, space = line.find("{"), line.find(" ")
        if brace != -1 and (space == -1 or brace < space):
            # labeled sample: label values may contain spaces, so split
            # on the braces (the value after '}' never contains one)
            close = line.rfind("}")
            out.append(f'{line[:brace]}{{{label}="{esc}",'
                       f'{line[brace + 1:close]}}}{line[close + 1:]}')
        else:
            name, _, rest = line.partition(" ")
            out.append(f'{name}{{{label}="{esc}"}} {rest}')
    return "\n".join(out) + ("\n" if out else "")


@dataclass
class RouterConfig:
    # 'affinity' — prefix-digest affinity with consistent-hash fallback
    # (the default); 'hash' — consistent hash only; 'round_robin' — the
    # random-placement baseline the perf gate pins affinity against
    placement: str = "affinity"
    # digest -> replica map bound (LRU): memory ceiling for the
    # affinity index, NOT correctness — evicted digests just fall back
    # to the hash ring
    affinity_max_entries: int = 8192
    # spill-aware placement: when no replica holds a request's prefix
    # HOT (affinity miss at every depth), prefer a replica whose
    # advertised spill-tier bloom summary claims the prefix digests —
    # restoring spilled KV beats recomputing it. A bloom false positive
    # degrades silently to recompute on the chosen replica (counted,
    # never a typed failure). Only consulted under placement='affinity'.
    spill_placement: bool = True
    # session resurrection: when a replica dies, a least-loaded survivor
    # adopts the dead replica's disk spill namespace (shared
    # kv_spill_dir) BEFORE the reap sweeps it, so re-enqueued requests
    # whose prefixes were spilled restore on the failover target instead
    # of recomputing from token zero. No shared directory -> no-op.
    resurrection: bool = True
    # dead-replica detection: loop stuck mid-step longer than this (as
    # reported by the stall-watchdog heartbeat) or a dead loop thread
    heartbeat_timeout_s: float = 10.0
    # background monitor cadence (0 disables; check_replicas() also
    # runs inline on every submit)
    monitor_interval_s: float = 1.0
    # backoff for a shedding replica when its rejection carries no
    # retry_after_s hint
    default_backoff_s: float = 0.25
    # prefill/decode disaggregation: prompts prefill on dedicated
    # prefill replicas, KV hands off to a decode replica
    disaggregated: bool = False
    # KV blocks per chunk of the streaming handoff (serve/handoff.py
    # chunk protocol): each chunk applies between the decode replica's
    # scheduler steps, so the transfer overlaps its running batch.
    # 0 = the legacy blocking whole-sequence transport.
    handoff_chunk_blocks: int = 4
    # consistent-hash ring points per replica
    ring_points: int = 32
    # blue/green weight push (push_weights): how long a stale replica
    # may take to finish its in-flight routed streams before the push
    # fails typed (streams complete on their ORIGINAL version — the
    # swap waits for them, never flips a stream mid-decode)
    weight_push_drain_timeout_s: float = 30.0
    # a replica added after a push (autoscaler scale-up) receives the
    # cached target payload before taking traffic, so scale-ups join
    # the fleet at the LIVE version instead of their boot checkpoint
    sync_weights_on_add: bool = True
    # per-replica circuit breaker (serve/resilience.py): probe failures
    # OPEN it (the replica is SUSPECTED — routed around, mid-stream
    # requests keep streaming), half-open probes retest it, exhaustion
    # (max_open_cycles failed retests) or a refused dial (process exit)
    # is the DEAD verdict that triggers failover + re-enqueue
    breaker: BreakerConfig = field(default_factory=BreakerConfig)
    # fleet-level diagnostics (telemetry/anomaly.py): the router runs an
    # SLO burn monitor over the AGGREGATED replica histograms
    # (fleet_slo_burn_rate gauges / fleet_slo_burn verdicts) and — when
    # postmortem_on_anomaly — answers any replica's anomaly verdict
    # with ONE fleet post-mortem bundle (postmortem.write_fleet_bundle)
    diagnostics: DiagnosticsConfig = field(
        default_factory=DiagnosticsConfig)


class RoutedStream:
    """Async token stream over a routed request (the TokenStream
    surface: iterate, ``cancel()``, ``drain()``, ``.tokens`` /
    ``.status`` / ``.uid``), decoupled from any one replica so the
    router can re-dispatch a queued request when its replica dies.
    ``replica`` names where the request is (currently) running."""

    def __init__(self, router: "ReplicaRouter", uid: int):
        self._router = router
        self._q: asyncio.Queue = asyncio.Queue()
        self._ended = False
        self.uid = uid
        self.replica: Optional[str] = None
        self.status = "active"
        self.reason: Optional[str] = None
        self.tokens: List[int] = []
        # tokens PUSHED by the router (>= len(tokens), which counts only
        # what the client consumed): the failover safety check — a
        # request is only re-runnable elsewhere while nothing was
        # emitted, consumed or not
        self.pushed = 0

    # router-side (event loop)
    def _push_token(self, tok: int) -> None:
        self.pushed += 1
        self._q.put_nowait(("tok", int(tok)))

    def _push_end(self, status: str, reason: Optional[str]) -> None:
        if not self._ended:
            self._q.put_nowait(("end", status, reason))

    # -- async iterator -------------------------------------------------
    def __aiter__(self) -> "RoutedStream":
        return self

    async def __anext__(self) -> int:
        if self._ended:
            raise StopAsyncIteration
        item = await self._q.get()
        if item[0] == "tok":
            self.tokens.append(item[1])
            return item[1]
        self._ended = True
        self.status, self.reason = item[1], item[2]
        if self.status == "expired":
            raise DeadlineExceeded(
                f"request {self.uid}: deadline exceeded")
        if self.status == "error":
            raise RequestFailed(f"request {self.uid}: {self.reason}")
        raise StopAsyncIteration

    async def cancel(self) -> None:
        await self._router.cancel(self.uid)

    async def aclose(self) -> None:
        if not self._ended and self.status == "active":
            await self.cancel()

    async def drain(self) -> List[int]:
        async for _ in self:
            pass
        return self.tokens


class _RoutedRequest:
    """Router-side request record: everything needed to (re)dispatch."""

    def __init__(self, uid: int, prompt: List[int], max_new_tokens: int,
                 kw: dict, deadline_t: Optional[float],
                 stream: RoutedStream, ctx=None):
        self.uid = uid
        self.prompt = prompt
        self.max_new_tokens = max_new_tokens
        self.kw = kw                 # submit() keywords sans deadline_s
        self.deadline_t = deadline_t  # absolute, router clock
        self.stream = stream
        self.ctx = ctx               # distributed TraceContext
        self.replica: Optional[str] = None
        self.inner = None            # the replica-side TokenStream
        self.pump: Optional[asyncio.Task] = None
        self.handed_off = False      # disaggregated: KV moved already

    def trace_attr(self) -> dict:
        return ({"trace_id": self.ctx.trace_id}
                if self.ctx is not None else {})


class _HashRing:
    """Consistent hashing over replica names: each node owns K points on
    a ring; a key routes to the next point clockwise whose node is
    allowed. Node removal moves only the removed node's keys."""

    def __init__(self, names: Sequence[str], points: int):
        self._ring: List[tuple] = sorted(
            (self._h(f"{name}#{i}".encode()), name)
            for name in names for i in range(points))
        self._hashes = [h for h, _ in self._ring]

    @staticmethod
    def _h(key: bytes) -> int:
        return int.from_bytes(hashlib.sha1(key).digest()[:8], "big")

    def pick(self, key: bytes, allowed) -> Optional[str]:
        if not self._ring:
            return None
        start = bisect.bisect_left(self._hashes, self._h(key))
        for off in range(len(self._ring)):
            name = self._ring[(start + off) % len(self._ring)][1]
            if name in allowed:
                return name
        return None


class ReplicaRouter:
    """Front tier over N serving replicas (module docstring).

    Duck-compatible with :class:`~.frontend.ServingEngine` where the
    HTTP surface needs it (``submit`` / ``health``), so
    :class:`~.api.ServingAPI` serves routed traffic unchanged — the
    routed frontend mode."""

    def __init__(self, replicas: Sequence[Replica],
                 config: Optional[RouterConfig] = None,
                 prefill_replicas: Sequence[PrefillReplica] = (),
                 clock=time.monotonic):
        if not replicas:
            raise ValueError("router needs at least one replica")
        if config is None:
            config = RouterConfig()
        if config.placement not in ("affinity", "hash", "round_robin"):
            raise ValueError(
                f"placement must be 'affinity', 'hash' or 'round_robin' "
                f"(got {config.placement!r})")
        if config.disaggregated and not prefill_replicas:
            raise ValueError(
                "disaggregated mode needs at least one prefill replica")
        self.config = config
        self.clock = clock
        self.replicas: List[Replica] = list(replicas)
        self.prefill_replicas: List[PrefillReplica] = list(prefill_replicas)
        self._by_name = {r.name: r for r in self.replicas}
        if len(self._by_name) != len(self.replicas):
            raise ValueError("replica names must be unique")
        # every replica must share the KV block geometry: prefix digests
        # (and disaggregated handoffs) are keyed on it. Remote replicas
        # report their block size only after start()'s first /healthz
        # probe (None here) — start() re-verifies them.
        sizes = {r.block_size for r in self.replicas
                 if r.block_size is not None}
        for p in self.prefill_replicas:
            sizes.add(p.engine.state_manager.block_size)
        if len(sizes) > 1:
            raise ValueError(
                f"replicas disagree on KV block size ({sorted(sizes)}); "
                f"prefix affinity and handoff require one layout")
        self.block_size = sizes.pop() if sizes else None
        self._ring = _HashRing([r.name for r in self.replicas],
                               config.ring_points)
        self._affinity: "OrderedDict[bytes, str]" = OrderedDict()
        self._backoff_until: Dict[str, float] = {}
        # resilience state (remote replicas): per-replica breaker, the
        # suspected set (out of rotation, streams kept), and the last
        # probe_seq consumed so each probe feeds the breaker ONCE
        self._breakers: Dict[str, CircuitBreaker] = {}
        self._suspected: Dict[str, str] = {}     # name -> reason
        self._probe_seen: Dict[str, int] = {}
        self._rr = itertools.count()          # round-robin cursors
        self._rr_prefill = itertools.count()
        # blue/green weight state (push_weights): the fleet's target
        # version, the cached payload newcomers sync from, and the set
        # of replicas currently draining for their swap (out of
        # rotation, streams finishing on their original version)
        self.target_weight_version: Optional[int] = None
        self._weight_payloads: Optional[List[bytes]] = None
        # adapter payloads cached by NAME for scale-up sync (a newcomer
        # must hold every live adapter before it can take adapter
        # traffic); latest push per name wins (hot redeploy)
        self._adapter_payloads: Dict[str, List[bytes]] = {}
        self._updating: set = set()
        self._uids = itertools.count(1)
        self._requests: Dict[int, _RoutedRequest] = {}
        self._monitor: Optional[asyncio.Task] = None
        self._stopped = False
        self._init_telemetry()
        # fleet SLO burn monitor: burns over the replica registries'
        # aggregated TTFT/TPOT histograms (one registry per replica
        # when Replica(registry=...) is used; otherwise the shared
        # process registry already aggregates the fleet). Distinct
        # gauge/verdict names so per-replica monitors never collide.
        regs = [r.registry for r in self.replicas
                if getattr(r, "registry", None) is not None]
        self.fleet_slo: Optional[SLOBurnRateMonitor] = None
        if config.diagnostics.enabled:
            self.fleet_slo = SLOBurnRateMonitor(
                config.diagnostics, registries=regs or None,
                gauge_name="fleet_slo_burn_rate",
                verdict_kind="fleet_slo_burn")
        # fleet post-mortem trigger state: per KIND, the wall clock of
        # the newest anomaly verdict whose bundle attempt ran (a failed
        # write must leave its verdicts un-consumed for the next tick)
        self._fleet_pm_start = time.time()
        self._fleet_pm_seen: Dict[str, float] = {}
        self._last_fleet_bundle: Optional[str] = None
        self._fleet_bundle_paths: set = set()

    def _init_telemetry(self):
        from ....telemetry import get_registry
        reg = get_registry()
        self._m_replicas = reg.gauge(
            "router_replicas", "replicas registered with the router")
        self._m_requests = reg.counter(
            "router_requests_total",
            "requests dispatched to a replica", labelnames=("replica",))
        self._m_aff_hits = reg.counter(
            "router_affinity_hits_total",
            "requests placed by prefix-digest affinity")
        self._m_aff_miss = reg.counter(
            "router_affinity_fallback_total",
            "requests placed by the consistent-hash ring / round robin "
            "(no affinity match)")
        # spill-aware placement + session resurrection (ragged/spill.py
        # bloom summaries advertised over /healthz)
        self._m_spill_hits = reg.counter(
            "router_spill_placement_hits_total",
            "requests placed onto a replica whose spill-tier bloom "
            "summary claims the prompt's prefix digests (restore "
            "preferred over recompute)")
        self._m_spill_fp = reg.counter(
            "router_spill_placement_false_positives_total",
            "spill placements where none of the bloom-claimed digests "
            "actually existed in the tier (the replica silently "
            "recomputes; exact check, in-process replicas only)")
        self._m_spill_restored = reg.counter(
            "router_spill_placement_restored_blocks_total",
            "KV blocks a spill placement expects to restore instead of "
            "recompute (exact for in-process replicas, bloom-claimed "
            "for remote)")
        self._m_resurrections = reg.counter(
            "router_session_resurrections_total",
            "dead replicas whose disk spill namespace a survivor "
            "adopted (shared kv_spill_dir)")
        self._m_resurrected = reg.counter(
            "router_resurrected_requests_total",
            "re-enqueued requests whose prefix digests survived into "
            "the adopter's spill tier (restore instead of full "
            "recompute on the failover target)")
        self._m_reroutes = reg.counter(
            "router_reroutes_total",
            "requests re-routed off an overloaded replica",
            labelnames=("reason",))
        self._m_shed = reg.counter(
            "router_shed_total",
            "requests shed by the router (every routable replica "
            "overloaded)")
        self._m_requeued = reg.counter(
            "router_requeued_total",
            "queued requests re-enqueued onto survivors after their "
            "replica died")
        self._m_dead = reg.counter(
            "router_dead_replicas_total",
            "replicas declared dead (heartbeat expiry / loop exit)")
        self._m_drains = reg.counter(
            "router_drains_total", "replica drains initiated")
        self._m_state = reg.gauge(
            "router_replica_state",
            "per-replica lifecycle state (1 up, 0.5 draining, 0 "
            "drained, -1 dead)", labelnames=("replica",))
        self._m_dispatch = reg.histogram(
            "router_dispatch_seconds",
            "routing decision time (digest + placement, excl. the "
            "replica submit)", unit="s",
            buckets=(1e-6, 1e-5, 1e-4, 1e-3, 1e-2, 0.1))
        self._m_handoffs = reg.counter(
            "router_handoffs_total",
            "prefill->decode KV handoffs completed")
        self._m_handoff_bytes = reg.counter(
            "router_handoff_bytes_total",
            "serialized KV handoff payload bytes moved")
        # ONE source for the per-replica heartbeat signal: /statusz,
        # check_replicas() and dashboards all read this gauge (fed by
        # StallWatchdog.heartbeat_age via replica_heartbeat_age())
        self._m_heartbeat = reg.gauge(
            "router_replica_heartbeat_age_seconds",
            "seconds each replica's serving loop has been stuck "
            "mid-step (0 when idle / healthy; the dead-replica "
            "detector fires past heartbeat_timeout_s)",
            labelnames=("replica",))
        # labeled series resolved once: replica_heartbeat_age() runs on
        # the per-request dispatch path (check_replicas -> _is_dead),
        # which must not pay a registry-lock labels() lookup per probe
        self._hb_series = {r.name: self._m_heartbeat.labels(replica=r.name)
                           for r in self.replicas}
        self._m_fleet_bundles = reg.counter(
            "router_fleet_postmortems_total",
            "fleet post-mortem bundles written in answer to a replica "
            "anomaly verdict")
        # resilience signals: suspected (out of rotation, streams kept)
        # is DISTINCT from dead (failover) — the breaker's whole point
        self._m_suspected = reg.gauge(
            "router_replica_suspected",
            "1 while the replica is suspected (probe timeouts / open "
            "breaker): routed around but NOT failed over",
            labelnames=("replica",))
        self._m_suspects = reg.counter(
            "router_suspects_total",
            "replicas taken out of rotation as suspected (probe "
            "timeout / reset / breaker open)")
        self._m_breaker_state = reg.gauge(
            "router_breaker_state",
            "per-replica circuit-breaker state (0 closed, 0.5 "
            "half-open, 1 open)", labelnames=("replica",))
        self._m_breaker_opens = reg.counter(
            "router_breaker_open_total",
            "circuit-breaker open transitions (a replica entered "
            "suspicion)")
        # blue/green weight push (serve/weights.py)
        self._m_weight_pushes = reg.counter(
            "router_weight_pushes_total",
            "per-replica weight pushes completed by the blue/green "
            "rollout", labelnames=("replica",))
        self._m_weight_push_bytes = reg.counter(
            "router_weight_push_bytes_total",
            "serialized weight-payload bytes pushed to replicas",
            unit="bytes")
        self._m_weight_push_time = reg.histogram(
            "router_weight_push_seconds",
            "whole-fleet push_weights wall time (drain stale streams + "
            "transfer + swap, per rollout)", unit="s",
            buckets=(1e-2, 0.1, 1.0, 10.0, 60.0, 600.0))
        self._m_weight_push_failures = reg.counter(
            "router_weight_push_failures_total",
            "per-replica weight pushes that failed (replica still "
            "stale; the rollout raises typed when it stays up)")
        self._m_target_version = reg.gauge(
            "router_target_weight_version",
            "the fleet's target weight version (0 until the first "
            "push)")
        # delta negotiation (serve/weights.py § delta payloads)
        self._m_delta_pushes = reg.counter(
            "router_weight_delta_pushes_total",
            "per-replica pushes that shipped the quantized DELTA "
            "payload (replica advertised the delta's base version)")
        self._m_delta_fallbacks = reg.counter(
            "router_weight_delta_fallbacks_total",
            "delta pushes that failed typed (stale base, no retained "
            "base, corrupt chunk) and fell back to the full payload")
        self._m_replica_version = reg.gauge(
            "router_replica_weight_version",
            "per-replica live weight version as last advertised "
            "(healthz/heartbeat) or installed by a push",
            labelnames=("replica",))
        self._wv_series: Dict[str, object] = {}
        self._m_replicas.set(len(self.replicas))
        for r in self.replicas:
            self._m_state.labels(replica=r.name).set(1)

    # -- lifecycle ------------------------------------------------------
    def _check_block_size(self, replica) -> None:
        bs = replica.block_size
        if bs is None:
            raise ValueError(
                f"replica {replica.name} reports no KV block size "
                f"(remote replica not started?)")
        if self.block_size is None:
            self.block_size = int(bs)
        elif int(bs) != self.block_size:
            raise ValueError(
                f"replica {replica.name} has KV block size {bs}, the "
                f"fleet uses {self.block_size}; prefix affinity and "
                f"handoff require one layout")
        # disaggregated mode pre-checks KV-slot need against the
        # PREFILL side's max_seq_len before burning prefill flops — a
        # decode replica with a smaller pool would defeat that check
        # after the work was already done, so require one geometry
        msl = getattr(replica, "max_seq_len", None)
        if self.prefill_replicas and msl is not None:
            want = self.prefill_replicas[0].engine.state_manager \
                .config.max_seq_len
            if int(msl) != int(want):
                raise ValueError(
                    f"replica {replica.name} has max_seq_len {msl}, "
                    f"the prefill replicas use {want}; disaggregated "
                    f"replicas must share the KV geometry")

    async def start(self) -> "ReplicaRouter":
        for r in self.replicas:
            await r.start()
            self._check_block_size(r)
        if self.config.monitor_interval_s > 0:
            self._monitor = asyncio.ensure_future(self._monitor_loop())
        return self

    # -- dynamic membership (the autoscaler's surface) ------------------
    def _rebuild_ring(self) -> None:
        """Rebuild the consistent-hash ring from the current member
        names. Point hashes are deterministic per name, so surviving
        replicas keep their ring positions — only keys owned by a
        removed (or claimed by an added) node remap."""
        self._ring = _HashRing([r.name for r in self.replicas],
                               self.config.ring_points)

    async def add_replica(self, replica, start: bool = True) -> None:
        """Grow the fleet: start the replica (unless already started),
        verify the shared KV layout, and rebuild the ring so it takes
        traffic immediately."""
        if self._stopped:
            raise RuntimeError("router is stopped")
        if replica.name in self._by_name:
            raise ValueError(f"replica name {replica.name!r} already "
                             f"registered")
        if start and not replica.started:
            await replica.start()
        self._check_block_size(replica)
        # scale-ups join at the LIVE version: push the cached target
        # payload BEFORE the replica enters the ring, so it never
        # serves a request from its boot checkpoint after a push
        if (self.config.sync_weights_on_add
                and self._weight_payloads is not None
                and self.target_weight_version is not None
                and self._replica_weight_version(replica)
                != self.target_weight_version):
            try:
                await self._push_to_replica(
                    replica, self._weight_payloads,
                    sum(len(p) for p in self._weight_payloads))
            except BaseException:
                # the replica was already STARTED above: stop it before
                # propagating, or a failed sync leaks a live worker the
                # autoscaler only counts as a spawn failure
                try:
                    await replica.stop()
                except Exception:
                    pass
                raise
        # newcomers also sync every live ADAPTER before taking traffic
        # (bank-slot installs; weight_version untouched)
        if self.config.sync_weights_on_add and self._adapter_payloads:
            try:
                for pl in self._adapter_payloads.values():
                    await self._push_to_replica(
                        replica, pl, sum(len(p) for p in pl))
            except BaseException:
                try:
                    await replica.stop()
                except Exception:
                    pass
                raise
        self.replicas.append(replica)
        self._by_name[replica.name] = replica
        self._rebuild_ring()
        self._m_replicas.set(len(self.replicas))
        self._m_state.labels(replica=replica.name).set(1)
        trace.record("router_membership", time.perf_counter(), 0.0,
                     lane=_ROUTER_LANE, action="add",
                     replica=replica.name)

    def remove_replica(self, name: str) -> None:
        """Shrink the fleet: pure membership removal — the replica must
        already be drained or dead (``drain_replica`` first; the
        autoscaler's drain-then-stop does). Ring and affinity entries
        remap; in-flight failover bookkeeping is untouched (a dead
        replica's requests were already re-enqueued by
        ``check_replicas``)."""
        replica = self._by_name.get(name)
        if replica is None:
            raise KeyError(f"no replica named {name!r}")
        if replica.state == "up":
            raise RuntimeError(
                f"replica {name} is still 'up': drain it (or let the "
                f"death check reap it) before removing")
        del self._by_name[name]
        self.replicas = [r for r in self.replicas if r.name != name]
        self._rebuild_ring()
        # affinity remap: purge the removed replica's digests so a
        # future same-name replica never inherits stale residency claims
        for digest in [d for d, n in self._affinity.items() if n == name]:
            del self._affinity[digest]
        self._backoff_until.pop(name, None)
        self._hb_series.pop(name, None)
        self._wv_series.pop(name, None)
        self._updating.discard(name)
        self._breakers.pop(name, None)
        self._probe_seen.pop(name, None)
        if name in self._suspected:
            del self._suspected[name]
            self._m_suspected.labels(replica=name).set(0)
        self._m_replicas.set(len(self.replicas))
        trace.record("router_membership", time.perf_counter(), 0.0,
                     lane=_ROUTER_LANE, action="remove", replica=name)

    async def stop(self, drain: bool = True) -> None:
        self._stopped = True
        if self._monitor is not None:
            self._monitor.cancel()
            try:
                await self._monitor
            except asyncio.CancelledError:
                pass
            self._monitor = None
        for r in self.replicas:
            if r.state in ("up", "draining") and r.started:
                try:
                    if drain:
                        await r.drain()
                    else:
                        await r.stop()
                except Exception:
                    pass
                r.state = "drained"
                self._m_state.labels(replica=r.name).set(0)
            elif r.state == "dead" and r.started:
                # best-effort: an unwedged dead loop exits on the halt
                # command; a truly stuck one stays a daemon thread
                try:
                    await r.kill()
                except Exception:
                    pass
        for rec in list(self._requests.values()):
            self._finish(rec, "cancelled", None)

    async def __aenter__(self) -> "ReplicaRouter":
        return await self.start()

    async def __aexit__(self, *exc) -> None:
        await self.stop(drain=exc == (None, None, None))

    async def _monitor_loop(self) -> None:
        while True:
            await asyncio.sleep(self.config.monitor_interval_s)
            try:
                await self.check_replicas()
            except Exception:       # monitoring must never kill routing
                pass
            try:
                if self.fleet_slo is not None:
                    self.fleet_slo.tick()
            except Exception:
                pass
            try:
                await self._maybe_fleet_postmortem()
            except Exception:
                pass

    async def _maybe_fleet_postmortem(self) -> None:
        """Answer any NEW anomaly verdict (raised by any replica's
        detectors — they share the process ledger — or the fleet SLO
        monitor) with one fleet bundle: every replica's evidence plus
        the router's routing state under a cross-replica manifest.
        Per-kind rate-limited like single-process bundles."""
        if not self.config.diagnostics.postmortem_on_anomaly:
            return
        from ....telemetry import anomaly as ds_anomaly
        from ....telemetry import postmortem as ds_postmortem
        # one bundle attempt per DISTINCT fresh kind: collapsing to the
        # newest verdict would let a chatty kind suppress the others at
        # the trigger level — the very failure the per-kind rate limit
        # exists to prevent. The watermark advances per kind and only
        # AFTER its attempt ran, so a failed write (disk full) leaves
        # the incident's verdicts fresh for the next monitor tick.
        by_kind: Dict[str, float] = {}
        for v in ds_anomaly.recent():
            kind, wall = v.get("kind"), v.get("wall", 0.0)
            if wall > self._fleet_pm_seen.get(kind, self._fleet_pm_start):
                by_kind[kind] = max(by_kind.get(kind, 0.0), wall)
        for kind, wall in by_kind.items():
            # bundle writing is disk I/O at exactly the wrong moment —
            # keep it off the event loop so live streams never stall
            # behind it
            path = await asyncio.to_thread(
                ds_postmortem.maybe_write_fleet_bundle, kind, self,
                self.config.diagnostics)
            self._fleet_pm_seen[kind] = wall
            if path is not None and path not in self._fleet_bundle_paths:
                # rate-limited calls return the previous bundle's path —
                # only a NEW directory counts as a bundle written
                self._fleet_bundle_paths.add(path)
                self._last_fleet_bundle = path
                self._m_fleet_bundles.inc()

    # -- placement ------------------------------------------------------
    @staticmethod
    def _replica_weight_version(replica) -> Optional[int]:
        v = getattr(replica, "weight_version", None)
        return int(v) if v is not None else None

    def _note_weight_version(self, replica) -> None:
        v = self._replica_weight_version(replica)
        if v is None:
            return
        series = self._wv_series.get(replica.name)
        if series is None:
            series = self._m_replica_version.labels(
                replica=replica.name)
            self._wv_series[replica.name] = series
        series.set(v)

    def _routable(self) -> List[Replica]:
        now = self.clock()
        base = [r for r in self.replicas
                if r.state == "up"
                and r.name not in self._suspected
                and r.name not in self._updating
                and self._backoff_until.get(r.name, 0.0) <= now]
        # blue/green invariant: once ANY routable replica serves the
        # target version, new dispatches land only on target-version
        # replicas — stale ones keep their in-flight streams (their
        # pumps are untouched) and drain toward their own swap
        if self.target_weight_version is not None:
            at_target = [r for r in base
                         if self._replica_weight_version(r)
                         == self.target_weight_version]
            if at_target:
                return at_target
        return base

    def _record_affinity(self, digests: List[bytes], name: str) -> None:
        for d in digests:
            self._affinity[d] = name
            self._affinity.move_to_end(d)
        while len(self._affinity) > self.config.affinity_max_entries:
            self._affinity.popitem(last=False)

    def pick_replica(self, prompt: Sequence[int],
                     adapter: Optional[str] = None) -> tuple:
        """Placement decision only (no dispatch): returns
        ``(replica_name, digests, via)`` where ``via`` is 'affinity' |
        'spill' | 'hash' | 'round_robin'. ``adapter`` scopes the
        placement key the same way it scopes the replica-side prefix
        cache (the digests ARE the replica's cache keys): the same
        prompt under different adapters lands wherever each adapter's
        KV actually lives. 'spill' means no replica holds the prefix
        HOT at that depth but one's advertised spill-tier bloom claims
        it — restoring spilled KV beats recomputing it (a bloom false
        positive silently recomputes). Exposed for the perf gate's
        dispatch-overhead probe."""
        routable = self._routable()
        if not routable:
            return None, [], "none"
        names = {r.name for r in routable}
        digests: List[bytes] = []
        if self.config.placement == "affinity":
            digests = prefix_digest(np.asarray(list(prompt), np.int64),
                                    self.block_size, adapter=adapter)
            summaries = []
            if self.config.spill_placement and digests:
                for r in routable:
                    fn = getattr(r, "spill_summary", None)
                    s = fn() if fn is not None else None
                    if s is not None and s.entries:
                        summaries.append((r, s))
            # longest matching digest wins: the deepest shared prefix.
            # At equal depth hot KV (affinity) beats spilled KV (the
            # restore costs a host->device scatter the hot block
            # doesn't); a DEEPER spill claim beats a shallower affinity
            # entry because the walk is deepest-first over depths.
            for d in reversed(digests):
                name = self._affinity.get(d)
                if name is not None and name in names:
                    return name, digests, "affinity"
                if summaries:
                    claimants = [r for (r, s) in summaries if s.claims(d)]
                    if claimants:
                        best = min(claimants, key=lambda r: r.load())
                        return best.name, digests, "spill"
        if self.config.placement == "round_robin":
            name = routable[next(self._rr) % len(routable)].name
            return name, digests, "round_robin"
        key = np.asarray(list(prompt), np.int64).tobytes()
        if adapter:
            key = adapter.encode("utf-8") + b"\x00" + key
        return self._ring.pick(key, names), digests, "hash"

    def _candidates(self, first: str) -> List[Replica]:
        """The chosen replica, then every other routable one least-
        loaded first (the overload re-route order)."""
        rest = sorted((r for r in self._routable() if r.name != first),
                      key=lambda r: r.load())
        head = [self._by_name[first]] if first in {
            r.name for r in self._routable()} else []
        return head + rest

    # -- submission -----------------------------------------------------
    async def submit(self, prompt: Sequence[int], max_new_tokens: int,
                     **kw) -> RoutedStream:
        """Route and dispatch one streaming request (the ServingEngine
        submit surface). Raises :class:`OverloadedError` — with the
        soonest per-replica ``retry_after_s`` hint — only when every
        routable replica sheds."""
        if self._stopped:
            raise OverloadedError("draining", "router is stopped")
        await self.check_replicas()
        uid = next(self._uids)
        # one trace identity from router dispatch to the last decode
        # token: continue the HTTP layer's bound context (traceparent
        # header) or mint the root here — the router IS the fleet entry
        ctx = trace_context.get_or_new()
        stream = RoutedStream(self, uid)
        deadline_s = kw.pop("deadline_s", None)
        rec = _RoutedRequest(
            uid, list(map(int, prompt)), int(max_new_tokens), dict(kw),
            self.clock() + deadline_s if deadline_s is not None else None,
            stream, ctx=ctx)
        # register BEFORE dispatching: a request that finishes inside
        # dispatch (finished-at-prefill, handoff error) must find its
        # record to pop, or it would linger in _requests forever
        self._requests[uid] = rec
        try:
            if self.config.disaggregated:
                await self._dispatch_disaggregated(rec)
            else:
                await self._dispatch(rec)
        except BaseException:
            self._requests.pop(uid, None)
            raise
        return stream

    def _pick_for(self, rec: _RoutedRequest):
        t0 = time.perf_counter()
        name, digests, via = self.pick_replica(
            rec.prompt, adapter=rec.kw.get("adapter"))
        self._m_dispatch.observe(time.perf_counter() - t0)
        if name is None:
            self._m_shed.inc()
            raise OverloadedError(
                "no_replicas", "no routable replicas (all dead, "
                "draining or backing off)",
                retry_after_s=self._soonest_backoff())
        if via == "affinity":
            self._m_aff_hits.inc()
        elif via == "spill":
            self._m_aff_miss.inc()
            self._note_spill_placement(name, digests)
        else:
            self._m_aff_miss.inc()
        return name, digests

    def _note_spill_placement(self, name: str, digests) -> None:
        """Account a via='spill' placement: count the hit, the blocks
        it expects to restore, and — where an exact check is possible —
        a bloom false positive (placement gained nothing; the replica
        recomputes silently, which is the designed degradation)."""
        self._m_spill_hits.inc()
        replica = self._by_name.get(name)
        if replica is None:
            return
        summary = replica.spill_summary()
        claimed = ([d for d in digests if summary.claims(d)]
                   if summary is not None else [])
        if not claimed:
            return
        probe = replica.spill_probe(claimed)
        if probe is None:
            # remote replica: no exact digest check over the wire —
            # count the bloom-claimed blocks (documented-optimistic)
            self._m_spill_restored.inc(len(claimed))
        elif probe == 0:
            self._m_spill_fp.inc()
        else:
            self._m_spill_restored.inc(probe)

    def _soonest_backoff(self) -> Optional[float]:
        now = self.clock()
        waits = [t - now for r in self.replicas if r.state == "up"
                 for t in [self._backoff_until.get(r.name, 0.0)]
                 if t > now]
        return round(min(waits), 3) if waits else None

    def _remaining_deadline(self, rec: _RoutedRequest) -> Optional[float]:
        if rec.deadline_t is None:
            return None
        return max(rec.deadline_t - self.clock(), 0.001)

    async def _dispatch(self, rec: _RoutedRequest) -> None:
        """Pick a replica and submit; on shed, back the replica off for
        its retry hint and try the next-best until one admits."""
        t0 = time.perf_counter()
        name, digests = self._pick_for(rec)
        last_err: Optional[OverloadedError] = None
        conn_err: Optional[Exception] = None
        for replica in self._candidates(name):
            try:
                # bind the request's trace context around the replica
                # submit: the replica frontend CONTINUES it (get_or_new
                # reads the contextvar) instead of minting a new root —
                # one trace id from dispatch to the last decode token
                with trace_context.use(rec.ctx):
                    inner = await replica.submit(
                        rec.prompt, rec.max_new_tokens,
                        deadline_s=self._remaining_deadline(rec),
                        **rec.kw)
            except OverloadedError as e:
                last_err = e
                backoff = (e.retry_after_s if e.retry_after_s is not None
                           else self.config.default_backoff_s)
                self._backoff_until[replica.name] = self.clock() + backoff
                self._m_reroutes.labels(reason=e.reason).inc()
                trace.record("router_reroute", time.perf_counter(), 0.0,
                             lane=_ROUTER_LANE, uid=rec.uid,
                             replica=replica.name, reason=e.reason,
                             backoff_s=round(backoff, 3),
                             **rec.trace_attr())
                continue
            except _DISPATCH_CONN_ERRORS as e:
                # transport failure before any token: the prompt is
                # idempotent at zero tokens, so route around — feed the
                # breaker, suspect the replica, try the next candidate
                conn_err = e
                self._note_dispatch_failure(replica)
                self._m_reroutes.labels(reason="connect_error").inc()
                trace.record("router_reroute", time.perf_counter(), 0.0,
                             lane=_ROUTER_LANE, uid=rec.uid,
                             replica=replica.name,
                             reason="connect_error",
                             **rec.trace_attr())
                continue
            self._attach(rec, replica.name, inner, digests)
            trace.record("router_dispatch", t0,
                         time.perf_counter() - t0, lane=_ROUTER_LANE,
                         uid=rec.uid, replica=replica.name,
                         **rec.trace_attr())
            return
        self._m_shed.inc()
        trace.record("router_shed", t0, time.perf_counter() - t0,
                     lane=_ROUTER_LANE, uid=rec.uid,
                     reason=last_err.reason if last_err else
                     ("connect_error" if conn_err else "no_replicas"),
                     **rec.trace_attr())
        if last_err is None and conn_err is not None:
            # every candidate failed at the transport level: a typed
            # dispatch failure, not an overload signal
            raise RequestFailed(
                f"dispatch failed: no replica reachable "
                f"({type(conn_err).__name__}: {conn_err})")
        raise OverloadedError(
            last_err.reason if last_err else "no_replicas",
            f"all routable replicas overloaded: "
            f"{last_err if last_err else 'none routable'}",
            retry_after_s=(last_err.retry_after_s if last_err
                           and last_err.retry_after_s is not None
                           else self._soonest_backoff()))

    async def _dispatch_disaggregated(self, rec: _RoutedRequest) -> None:
        """Prefill on a dedicated prefill replica, then hand the KV off
        to a decode replica picked by the normal placement. The decode
        replica is chosen BEFORE prefill runs (shed-before-compute: an
        unroutable fleet never burns prefill flops)."""
        t0 = time.perf_counter()
        name, digests = self._pick_for(rec)
        # the decode-side KV-slot precheck, before any prefill flops are
        # burned (replicas share one layout — the prefill side's state
        # manager speaks for remote decode replicas too)
        max_seq = self.prefill_replicas[0].engine.state_manager.config \
            .max_seq_len
        need = len(rec.prompt) + max(rec.max_new_tokens - 1, 0)
        if need > max_seq:
            self._finish(
                rec, "error",
                f"RuntimeError: request needs {need} KV slots, over "
                f"max_seq_len={max_seq}; shorten the request")
            return
        pw = self.prefill_replicas[
            next(self._rr_prefill) % len(self.prefill_replicas)]
        # the dispatch span closes at the routing DECISION (decode
        # candidate + prefill worker chosen), before any prefill flops —
        # the first hop of the request's distributed trace
        trace.record("router_dispatch", t0, time.perf_counter() - t0,
                     lane=_ROUTER_LANE, uid=rec.uid, replica=name,
                     prefill_replica=pw.name, disaggregated=True,
                     **rec.trace_attr())
        chunk_blocks = max(int(self.config.handoff_chunk_blocks), 0)
        tok, payloads, rng_state, finished = await pw.prefill(
            rec.prompt, rec.max_new_tokens,
            eos_token_id=rec.kw.get("eos_token_id"),
            temperature=rec.kw.get("temperature", 0.0),
            top_p=rec.kw.get("top_p", 1.0),
            top_k=rec.kw.get("top_k", 0), seed=rec.kw.get("seed"),
            trace_ctx=rec.ctx, chunk_blocks=chunk_blocks)
        rec.stream._push_token(tok)
        if finished:
            # NO affinity recorded: the decode candidate never received
            # this KV (the prefill replica flushed it), and an affinity
            # entry would assert residency that does not exist
            rec.replica = pw.name
            self._finish(rec, "completed", None)
            return
        t_h = time.perf_counter()
        payload_bytes = sum(len(p) for p in payloads)
        last_err: Optional[OverloadedError] = None
        for replica in self._candidates(name):
            try:
                with trace_context.use(rec.ctx):
                    inner = await replica.resume_handoff(
                        payloads, chunked=chunk_blocks > 0,
                        prompt=rec.prompt, generated=[tok],
                        max_new_tokens=rec.max_new_tokens,
                        eos_token_id=rec.kw.get("eos_token_id"),
                        temperature=rec.kw.get("temperature", 0.0),
                        top_p=rec.kw.get("top_p", 1.0),
                        top_k=rec.kw.get("top_k", 0),
                        rng_state=rng_state,
                        deadline_s=self._remaining_deadline(rec))
            except OverloadedError as e:
                last_err = e
                self._backoff_until[replica.name] = self.clock() + (
                    e.retry_after_s if e.retry_after_s is not None
                    else self.config.default_backoff_s)
                self._m_reroutes.labels(reason=e.reason).inc()
                continue
            except _DISPATCH_CONN_ERRORS as e:
                # the chunked protocol is idempotent-retransmit (and
                # the worker aborts partial restores on disconnect), so
                # after the replica's own retries failed the handoff is
                # safe to offer to the next candidate
                self._note_dispatch_failure(replica)
                self._m_reroutes.labels(reason="connect_error").inc()
                trace.record("router_reroute", time.perf_counter(), 0.0,
                             lane=_ROUTER_LANE, uid=rec.uid,
                             replica=replica.name,
                             reason="connect_error",
                             **rec.trace_attr())
                continue
            rec.handed_off = True
            self._m_handoffs.inc()
            self._m_handoff_bytes.inc(payload_bytes)
            # the KV transfer hop: wire (de)serialize -> decode-side
            # restore/adopt, between the prefill span (prefill lane) and
            # the first decode span (decode lane)
            trace.record("router_handoff", t_h,
                         time.perf_counter() - t_h, lane=_ROUTER_LANE,
                         uid=rec.uid, src=pw.name, dst=replica.name,
                         payload_bytes=payload_bytes,
                         chunks=(len(payloads) - 1 if chunk_blocks
                                 else 0), **rec.trace_attr())
            self._attach(rec, replica.name, inner, digests)
            return
        self._m_shed.inc()
        self._finish(rec, "error",
                     f"no decode replica accepted the handoff: "
                     f"{last_err}")

    def _attach(self, rec: _RoutedRequest, name: str, inner,
                digests: List[bytes]) -> None:
        rec.replica = name
        rec.stream.replica = name
        rec.inner = inner
        self._record_affinity(digests, name)
        self._m_requests.labels(replica=name).inc()
        rec.pump = asyncio.ensure_future(self._pump(rec, inner))

    async def _pump(self, rec: _RoutedRequest, inner) -> None:
        """Forward one replica-side stream into the routed stream."""
        try:
            async for tok in inner:
                rec.stream._push_token(tok)
            self._finish(rec, inner.status, inner.reason)
        except DeadlineExceeded:
            self._finish(rec, "expired", "deadline exceeded")
        except RequestFailed as e:
            self._finish(rec, "error", str(e))
        except asyncio.CancelledError:   # failover/cancel detached us
            raise
        except Exception as e:           # never lose a stream silently
            self._finish(rec, "error", f"{type(e).__name__}: {e}")

    def _finish(self, rec: _RoutedRequest, status: str,
                reason: Optional[str]) -> None:
        rec.stream._push_end(status, reason)
        self._requests.pop(rec.uid, None)

    async def cancel(self, uid: int) -> None:
        rec = self._requests.get(uid)
        if rec is None:
            return
        if rec.pump is not None:
            rec.pump.cancel()
        if rec.inner is not None:
            try:
                await rec.inner.cancel()
            except Exception:
                pass
        self._finish(rec, "cancelled", None)

    # -- blue/green weight push (serve/weights.py) ----------------------
    async def push_weights(self, payloads: Sequence[bytes],
                           version: Optional[int] = None,
                           delta: Optional[Sequence[bytes]] = None
                           ) -> int:
        """Converge the fleet onto a new weight version, blue/green:

        1. the payload version becomes the fleet TARGET (``_routable``
           then prefers target-version replicas for every new
           dispatch);
        2. each stale up replica in turn is taken out of rotation, its
           in-flight routed streams finish ON THE OLD VERSION (the
           quiesce wait — a stream never spans a swap), the payload is
           pushed (``POST /weights`` for remote replicas, the staged
           in-process update otherwise) and the replica returns to
           rotation at the target version.

        Zero requests are dropped: new traffic always has the other
        replicas (rolling, one at a time), in-flight streams complete
        where they started, and a replica that cannot be pushed (still
        up, still stale) fails the rollout TYPED. The payload is cached
        so later ``add_replica`` scale-ups join at the live version.
        Returns the target version.

        ``delta`` (or a :class:`~....runtime.hybrid_engine.
        WeightPublication` passed as ``payloads``) enables per-replica
        DELTA NEGOTIATION: a replica whose advertised
        ``weight_version`` equals the delta's ``base_version`` gets the
        quantized delta payload (~4x fewer wire bytes); anyone else —
        and any delta that fails typed (stale base, corrupt chunk) —
        gets the full payload. Only the FULL payload is cached for
        scale-up sync (newcomers hold no base)."""
        from . import weights as serve_weights
        if hasattr(payloads, "full"):   # a WeightPublication
            if delta is None:
                delta = payloads.delta
            payloads = payloads.full
        if serve_weights.is_adapter_payload(payloads):
            # an ADAPTER rode the publish path: same per-replica push,
            # but it installs into a bank slot and leaves the fleet
            # weight-version target untouched
            return await self.push_adapter(payloads)
        if self.config.disaggregated:
            raise NotImplementedError(
                "blue/green weight push over disaggregated fleets is "
                "not supported yet: prefill and decode replicas would "
                "need a coupled swap to keep handed-off streams on one "
                "version")
        if self._stopped:
            raise RuntimeError("router is stopped")
        if version is None:
            version = serve_weights.payload_version(payloads)
        version = int(version)
        t0 = time.perf_counter()
        payloads = list(payloads)
        delta_base: Optional[int] = None
        delta_nbytes = 0
        if delta is not None:
            delta = list(delta)
            if serve_weights.payload_version(delta) != version:
                raise ValueError(
                    f"delta payload version "
                    f"{serve_weights.payload_version(delta)} != full "
                    f"payload version {version}")
            delta_base = serve_weights.delta_base_version(delta)
            delta_nbytes = serve_weights.payload_bytes(delta)
        self.target_weight_version = version
        self._weight_payloads = payloads
        self._m_target_version.set(version)
        nbytes = serve_weights.payload_bytes(payloads)
        failures: List[str] = []
        for replica in list(self.replicas):
            if replica.state != "up":
                continue
            if self._replica_weight_version(replica) == version:
                continue
            if (delta is not None
                    and self._replica_weight_version(replica)
                    == delta_base):
                try:
                    await self._push_to_replica(replica, delta,
                                                delta_nbytes)
                    self._m_delta_pushes.inc()
                    continue
                except Exception as e:
                    # typed delta rejection (stale base, corrupt
                    # chunk, pre-delta worker): fall back to the full
                    # payload for this replica
                    self._m_delta_fallbacks.inc()
                    trace.record(
                        "router_weight_delta_fallback", t0,
                        time.perf_counter() - t0, lane=_ROUTER_LANE,
                        replica=replica.name,
                        error=f"{type(e).__name__}: {e}")
            try:
                await self._push_to_replica(replica, payloads, nbytes)
            except Exception as e:
                self._m_weight_push_failures.inc()
                failures.append(
                    f"{replica.name}: {type(e).__name__}: {e}")
        self._m_weight_push_time.observe(time.perf_counter() - t0)
        trace.record("router_weight_push", t0,
                     time.perf_counter() - t0, lane=_ROUTER_LANE,
                     version=version, payload_bytes=nbytes,
                     failures=len(failures))
        # a failed push only fails the rollout while the replica is
        # still UP and stale — a replica that died mid-push was already
        # failed over by check_replicas and no longer serves anything
        still_stale = [
            r.name for r in self.replicas
            if r.state == "up"
            and self._replica_weight_version(r) != version]
        if still_stale:
            detail = "; ".join(failures) if failures \
                else "no error recorded"
            raise RequestFailed(
                f"weight push to version {version} did not converge: "
                f"replicas {still_stale} still stale ({detail})")
        return version

    async def push_adapter(self, payloads: Sequence[bytes]) -> int:
        """Hot-deploy a LoRA adapter fleet-wide over the SAME
        per-replica push path as blue/green weights (quiesce ->
        ``POST /weights`` / staged in-process update -> ingest), but
        WITHOUT moving the fleet weight-version target: the payload
        installs into a bank slot (``engine.load_adapter``) on each
        replica and ``weight_version`` stays put, so convergence is
        judged by per-replica push success rather than advertised
        version. The payload is cached by adapter NAME so later
        ``add_replica`` scale-ups join holding every live adapter.
        Returns the adapter payload version."""
        from . import weights as serve_weights
        if self._stopped:
            raise RuntimeError("router is stopped")
        header = serve_weights.parse_weights_header(payloads[0])
        if not serve_weights.is_adapter_header(header):
            raise ValueError(
                "push_adapter requires an adapter payload "
                "(payload_kind='adapter'); use push_weights for "
                "full/delta payloads")
        name = str(header["adapter_name"])
        version = int(header["version"])
        payloads = list(payloads)
        nbytes = serve_weights.payload_bytes(payloads)
        t0 = time.perf_counter()
        failures: List[str] = []
        for replica in list(self.replicas):
            if replica.state != "up":
                continue
            try:
                await self._push_to_replica(replica, payloads, nbytes)
            except Exception as e:
                self._m_weight_push_failures.inc()
                failures.append(
                    f"{replica.name}: {type(e).__name__}: {e}")
        self._adapter_payloads[name] = payloads
        trace.record("router_adapter_push", t0,
                     time.perf_counter() - t0, lane=_ROUTER_LANE,
                     adapter=name, version=version,
                     payload_bytes=nbytes, failures=len(failures))
        if failures:
            raise RequestFailed(
                f"adapter {name!r} push did not converge: "
                + "; ".join(failures))
        return version

    async def _push_to_replica(self, replica, payloads: List[bytes],
                               nbytes: int) -> None:
        name = replica.name
        self._updating.add(name)
        t0 = time.perf_counter()
        try:
            await self._quiesce_replica(replica)
            if hasattr(replica, "push_weights"):
                v = await replica.push_weights(payloads)
            else:
                v = await replica.apply_weights(payloads)
        finally:
            self._updating.discard(name)
        self._m_weight_pushes.labels(replica=name).inc()
        self._m_weight_push_bytes.inc(nbytes)
        self._note_weight_version(replica)
        trace.record("router_weight_push_replica", t0,
                     time.perf_counter() - t0, lane=_ROUTER_LANE,
                     replica=name, version=int(v))

    async def _quiesce_replica(self, replica) -> None:
        """Wait for the replica's routed in-flight streams to finish
        (they complete on the version they started on; new dispatches
        already divert — the replica is in ``_updating``)."""
        deadline = (time.monotonic()
                    + self.config.weight_push_drain_timeout_s)
        while True:
            live = [rec for rec in self._requests.values()
                    if rec.replica == replica.name]
            if not live:
                return
            if time.monotonic() > deadline:
                raise RequestFailed(
                    f"replica {replica.name} did not finish its "
                    f"{len(live)} in-flight streams within "
                    f"{self.config.weight_push_drain_timeout_s}s; "
                    f"weight push aborted for it")
            await asyncio.sleep(0.005)

    # -- lifecycle: drain & failover ------------------------------------
    async def drain_replica(self, name: str) -> None:
        """Take ``name`` out of rotation and finish its in-flight
        streams (new traffic diverts immediately; this returns when the
        replica has fully drained)."""
        replica = self._by_name[name]
        if replica.state != "up":
            return
        replica.state = "draining"
        self._m_state.labels(replica=name).set(0.5)
        self._m_drains.inc()
        await replica.drain()
        replica.state = "drained"
        self._m_state.labels(replica=name).set(0)

    def replica_heartbeat_age(self, replica: Replica) -> Optional[float]:
        """THE source for the per-replica heartbeat signal: reads the
        stall watchdog's ``heartbeat_age``, publishes it as the
        ``router_replica_heartbeat_age_seconds`` gauge (0 = idle or
        healthy) and returns it — ``check_replicas()``, ``/statusz``
        and dashboards all read this one probe instead of each asking
        the watchdog themselves."""
        age = replica.heartbeat_age()
        series = self._hb_series.get(replica.name)
        if series is None:       # replica added after _init_telemetry
            series = self._m_heartbeat.labels(replica=replica.name)
            self._hb_series[replica.name] = series
        series.set(age if age is not None else 0.0)
        return age

    def _breaker(self, name: str) -> CircuitBreaker:
        br = self._breakers.get(name)
        if br is None:
            br = CircuitBreaker(self.config.breaker, clock=self.clock)
            self._breakers[name] = br
        return br

    @staticmethod
    def _is_remote(replica) -> bool:
        # the probe-classification surface is the remote marker
        return hasattr(replica, "probe_seq")

    def _suspect(self, name: str, reason: str) -> None:
        if name not in self._suspected:
            self._suspected[name] = reason
            self._m_suspected.labels(replica=name).set(1)
            self._m_suspects.inc()
            trace.record("router_suspect", time.perf_counter(), 0.0,
                         lane=_ROUTER_LANE, replica=name, action="suspect",
                         reason=reason)
        else:
            self._suspected[name] = reason

    def _unsuspect(self, name: str) -> None:
        if name in self._suspected:
            del self._suspected[name]
            self._m_suspected.labels(replica=name).set(0)
            trace.record("router_suspect", time.perf_counter(), 0.0,
                         lane=_ROUTER_LANE, replica=name, action="clear")

    def _note_dispatch_failure(self, replica) -> None:
        """A submit/handoff attempt failed at the transport level:
        feed the breaker (one verdict) and suspect the replica so the
        very next candidate scan routes around it."""
        if not self._is_remote(replica):
            return
        br = self._breaker(replica.name)
        was = br.state
        br.record_failure()
        if br.state == "open" and was != "open":
            self._m_breaker_opens.inc()
        self._sync_breaker_gauge(replica.name)
        self._suspect(replica.name, "connect_error")

    def _sync_breaker_gauge(self, name: str) -> None:
        state = self._breaker(name).state
        self._m_breaker_state.labels(replica=name).set(
            {"closed": 0.0, "half_open": 0.5, "open": 1.0}[state])

    def _verdict(self, replica) -> tuple:
        """Classify one up replica: ``('ok'|'suspected'|'dead',
        reason)``. In-process replicas keep the direct local signals
        (loop exit / heartbeat expiry are reliable, not a network
        blip); remote replicas go through the probe classification +
        circuit breaker so one slow probe suspends routing instead of
        amplifying into a failover."""
        if not self._is_remote(replica):
            if not replica.alive():
                return "dead", "loop_exit"
            age = self.replica_heartbeat_age(replica)
            if age is not None and age > self.config.heartbeat_timeout_s:
                return "dead", "heartbeat_expired"
            return "ok", None
        br = self._breaker(replica.name)
        seq = replica.probe_seq
        fresh = seq != self._probe_seen.get(replica.name)
        self._probe_seen[replica.name] = seq
        status = replica.probe_status
        if not fresh and br.state != "closed":
            # no new probe, breaker not closed (opened by dispatch
            # failures or held open between half-open windows): a STALE
            # 'ok' must not re-admit the replica — only a fresh
            # successful probe closes the breaker
            return "suspected", f"breaker_{br.state}"
        if status == "ok":
            if fresh:
                br.record_success()
                self._sync_breaker_gauge(replica.name)
            if not replica.alive():
                # the worker answered but reports its loop dead
                return "dead", "worker_loop_exit"
            age = self.replica_heartbeat_age(replica)
            if age is not None and age > self.config.heartbeat_timeout_s:
                return "dead", "heartbeat_expired"
            return "ok", None
        if status == "refused":
            # connection refused = nothing listening = process exit
            return "dead", "connection_refused"
        if fresh:
            was = br.state
            br.record_failure()
            if br.state == "open" and was != "open":
                self._m_breaker_opens.inc()
            self._sync_breaker_gauge(replica.name)
        if br.exhausted:
            return "dead", f"breaker_exhausted({status})"
        return "suspected", status

    async def check_replicas(self) -> List[str]:
        """Probe the fleet and classify each up replica: OK (in
        rotation), SUSPECTED (probe timeouts / open breaker — routed
        around, mid-stream requests KEEP streaming) or DEAD (process
        exit / exhausted breaker / local loop death), then fail the
        dead ones over: queued requests with no tokens yet re-dispatch
        onto survivors; requests that already streamed tokens end with
        an explicit error (their KV exists only on the dead replica).
        Returns the names declared dead this call."""
        # remote replicas: re-poll /healthz (rate-limited client-side);
        # an OPEN breaker holds its probes back until its half-open
        # window, so a struggling worker is not hammered
        up = [r for r in self.replicas if r.started and r.state == "up"]
        await asyncio.gather(
            *(r.refresh() for r in up
              if not self._is_remote(r)
              or self._breaker(r.name).allow_probe()),
            return_exceptions=True)
        died = []
        for r in up:
            self._note_weight_version(r)
            verdict, why = self._verdict(r)
            if verdict == "dead":
                died.append(r)
                self._unsuspect(r.name)
            elif verdict == "suspected":
                self._suspect(r.name, why)
            else:
                self._unsuspect(r.name)
        for replica in died:
            t0 = time.perf_counter()
            requeued = failed = resurrected = 0
            replica.state = "dead"
            self._m_state.labels(replica=replica.name).set(-1)
            self._m_dead.inc()
            # session resurrection: a survivor adopts the dead
            # replica's disk spill namespace BEFORE the reap below
            # closes the tier — the adoption moves the files out via
            # atomic rename, so the reap's own-namespace sweep finds
            # nothing to destroy
            adopter = None
            if self.config.resurrection:
                adopter = await self._adopt_spill_from(replica)
            # empty the dead replica's admission queue so a later
            # recovery cannot also run the re-enqueued work, tell its
            # loop to halt (if the thread ever unwedges it cancels
            # everything and exits instead of lingering as a zombie),
            # and stop its watchdog thread
            try:
                replica.reap()
            except Exception:
                pass
            for rec in [rec for rec in self._requests.values()
                        if rec.replica == replica.name]:
                if rec.pump is not None:
                    rec.pump.cancel()
                if rec.stream.pushed == 0 and not rec.handed_off:
                    # queued / not-yet-prefilled: safe to re-run
                    # elsewhere (prompts are idempotent)
                    self._m_requeued.inc()
                    requeued += 1
                    if adopter is not None and \
                            self._resurrects(rec, adopter):
                        self._m_resurrected.inc()
                        resurrected += 1
                    try:
                        await self._dispatch(rec)
                    except (OverloadedError, RequestFailed) as e:
                        self._finish(rec, "error",
                                     f"re-enqueue after replica death "
                                     f"shed: {e}")
                else:
                    failed += 1
                    self._finish(
                        rec, "error",
                        f"replica {replica.name} died mid-stream "
                        f"({rec.stream.pushed} tokens emitted)")
            trace.record("router_failover", t0,
                         time.perf_counter() - t0, lane=_ROUTER_LANE,
                         replica=replica.name, requeued=requeued,
                         failed_mid_stream=failed,
                         resurrected=resurrected)
        return [r.name for r in died]

    async def _adopt_spill_from(self, dead) -> Optional[Replica]:
        """Find the dead replica's disk spill namespace and have the
        least-loaded routable survivor adopt it. Returns the adopter
        (None when the dead replica had no disk tier, no survivor has
        one, or the namespace was empty) — every failure mode degrades
        to plain recompute, never a typed error."""
        try:
            fn = getattr(dead, "spill_namespace", None)
            ns = fn() if fn is not None else None
        except Exception:
            return None
        if not ns:
            return None
        for r in sorted(self._routable(), key=lambda r: r.load()):
            try:
                adopted = await r.adopt_spill(ns)
            except Exception:
                adopted = 0
            if adopted:
                self._m_resurrections.inc()
                return r
            # 0 = this survivor has no disk tier (or the source is
            # already gone): try the next one — adoption is an atomic
            # rename, so at most one survivor can win
        return None

    def _resurrects(self, rec: _RoutedRequest, adopter) -> bool:
        """True when the re-enqueued request's prefix digests survive
        in the adopter's spill tier (recompute avoided). Exact probe
        in-process; bloom-claimed for remote adopters."""
        try:
            digests = prefix_digest(
                np.asarray(rec.prompt, np.int64), self.block_size,
                adapter=rec.kw.get("adapter"))
        except Exception:
            return False
        if not digests:
            return False
        summary = adopter.spill_summary()
        if summary is None:
            return False
        claimed = [d for d in digests if summary.claims(d)]
        if not claimed:
            return False
        probe = adopter.spill_probe(claimed)
        return bool(claimed) if probe is None else probe > 0

    # -- introspection (the ServingAPI surface) -------------------------
    def health(self) -> dict:
        up = [r for r in self.replicas if r.state == "up"]
        healths = {r.name: r.health() for r in self.replicas}
        return {
            "status": "ok" if up and not self._stopped else "draining",
            "replicas": healths,
            "queue_depth": sum(h.get("queue_depth", 0)
                               for h in healths.values()),
            "queued_tokens": sum(h.get("queued_tokens", 0)
                                 for h in healths.values()),
            "inflight": sum(h.get("inflight", 0)
                            for h in healths.values()),
            "routable": [r.name for r in self._routable()],
        }

    def replica_statusz(self) -> dict:
        """Per-replica forensics rollup for the aggregated /statusz."""
        out = {}
        for r in self.replicas:
            # one probe feeds the gauge AND this document (satellite:
            # dashboards, check_replicas and /statusz share the source)
            age = self.replica_heartbeat_age(r)
            out[r.name] = {
                "state": r.state,
                "health": r.health(),
                "load": r.load(),
                "heartbeat_age_s": (round(age, 3)
                                    if age is not None else None),
                "backoff_remaining_s": max(
                    0.0, round(self._backoff_until.get(r.name, 0.0)
                               - self.clock(), 3)),
                "suspected": self._suspected.get(r.name),
                "breaker": (self._breaker(r.name).snapshot()
                            if self._is_remote(r) else None),
            }
        for p in self.prefill_replicas:
            out[p.name] = p.health()
        return out

    def router_statusz(self) -> dict:
        return {
            "placement": self.config.placement,
            "disaggregated": self.config.disaggregated,
            "affinity_entries": len(self._affinity),
            "inflight_routed": len(self._requests),
            "replica_states": {r.name: r.state for r in self.replicas},
            "suspected": dict(self._suspected),
            "last_fleet_bundle": self._last_fleet_bundle,
            # blue/green rollout state: the fleet has converged when
            # every up replica's version equals the target
            "target_weight_version": self.target_weight_version,
            "weight_updating": sorted(self._updating),
            "replica_weight_versions": {
                r.name: self._replica_weight_version(r)
                for r in self.replicas},
        }

    # -- fleet observability surfaces -----------------------------------
    def _remote_replicas(self) -> List:
        return [r for r in self.replicas if hasattr(r, "fetch_spans")]

    def fleet_timeline(self, trace_id: Optional[str] = None):
        """The stitched fleet Chrome trace: one process row per lane —
        the router plus every replica (in-process replicas share the
        ring; spans are lane-tagged; remote replicas' rings are fetched
        over ``GET /debug/spans`` and rebased onto this clock, which
        makes the result a coroutine when any replica is remote).
        ``trace_id`` filters to one request's hops across the whole
        fleet (the router-level ``GET /debug/timeline?trace=<id>``
        body)."""
        from ....telemetry import timeline
        remotes = self._remote_replicas()
        if not remotes:
            return timeline.stitch_fleet(trace_id=trace_id)

        async def stitch():
            rings = {"host": trace.export()}
            spans = await asyncio.gather(
                *(r.fetch_spans() for r in remotes),
                return_exceptions=True)
            for r, s in zip(remotes, spans):
                if isinstance(s, list):
                    rings[r.name] = s
            return timeline.stitch_fleet(rings, trace_id=trace_id)

        return stitch()

    def federated_metrics(self) -> str:
        """The router-level ``/metrics`` exposition: when replicas own
        registries (``Replica(registry=...)``), every replica's series
        is federated under a ``replica`` label next to the router's own
        (process-default) series; with shared registries the process
        default already aggregates the fleet and renders unchanged.
        Remote replicas contribute their LAST-FETCHED exposition
        (``federated_metrics_async`` refreshes before rendering — the
        HTTP layer prefers it)."""
        from ....telemetry import get_registry
        from ....telemetry.registry import render_federated
        own = [(r.name, r.registry) for r in self.replicas
               if getattr(r, "registry", None) is not None]
        if own:
            text = render_federated([("router", get_registry())] + own)
        else:
            text = get_registry().render_prometheus()
        for r in self._remote_replicas():
            remote_text = r.metrics_text()
            if remote_text:
                text += _relabel_exposition(remote_text, "replica",
                                            r.name)
        return text

    async def federated_metrics_async(self) -> str:
        """Fetch fresh expositions from remote replicas, then render
        the federated view."""
        await asyncio.gather(
            *(r.fetch_metrics() for r in self._remote_replicas()),
            return_exceptions=True)
        return self.federated_metrics()
