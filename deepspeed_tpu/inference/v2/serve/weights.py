"""Versioned weight payloads and zero-recompile param hot-swap.

The train->serve seam of the Hybrid Engine (docs/SERVING.md § Blue/green
weight push; docs/TRAINING.md § Hybrid engine): a training engine
publishes its live params as a **versioned, chunked, CRC-checked**
payload — the same frame discipline as the KV handoff (serve/handoff.py)
— and a serving engine ingests it by **donated buffer replacement**:
every new leaf is ``device_put`` onto the OLD leaf's sharding with the
OLD leaf's dtype, so the swapped tree presents the exact executable
signature (shape x dtype x sharding) every compiled serving program was
keyed on. Steady-state recompiles across a swap are zero *by
construction* — and pinned by the recompile watchdog in the perf gate
(``hot_swap_steady_recompiles``) and the parity tests.

Payload layout (``chunk_weight_leaves``): one HEADER chunk carrying the
version, the leaf manifest (names / shapes / dtypes) and per-chunk
CRC32s, then N leaf-group chunks — leaves are packed into size-capped
buckets (``bucket_bytes``) so the publisher gathers and serializes one
bucket at a time instead of materializing the whole model twice. Each
chunk is an independent ``.npz`` buffer (handoff's ``_npz_chunk``), so
retransmit is idempotent and a corrupt chunk fails TYPED at its CRC
without touching the serving params.

Leaves travel as fp32 numpy (the lossless host form of bf16/fp16 train
params — checkpoint/state_checkpoint's ``_fetch`` convention); the
ingest side casts to the serving dtype with the same ``jnp.asarray``
cast a fresh engine applies at init, which is what makes post-swap
streams bit-identical to a fresh engine built from the published
payload (the hot-swap parity pin).

DELTA payloads (docs/SERVING.md § Delta weight push): at RLHF
publish-every-N cadence push bytes are the scaling limit, so
``chunk_weight_deltas`` ships ``current - base`` block-quantized to
int8 with fp32 per-block scales (the PR 9 quantized-wire helpers,
comm/quantized.py) instead of full fp32 leaves — ~4x fewer bytes.
The header grows ``payload_kind="delta"``, ``base_version`` and a
per-chunk manifest; each chunk carries the concatenated int8 values +
scales for its leaf bucket (EQuARX, arXiv:2506.17615 — the publisher
carries error-feedback residuals across pushes, see
hybrid_engine.WeightPublisher). Ingest (``commit_stager``) rebuilds
``base + dequant(delta)`` HOST-SIDE against the fp32 base retained
from the last applied payload, then runs the same donated-buffer swap
— still zero steady-state recompiles. A stale base, version mismatch
or CRC failure raises typed BEFORE any live param is touched (the
router falls back to a full push). Reconstruction is deterministic
numpy fp32, so every replica following the delta chain holds
bit-identical weights — the publisher's error-feedback reference
tracks them exactly. ``quant="off"`` ships changed leaves at full
fp32 (bitwise-unchanged leaves are skipped), making reconstruction
EXACTLY equal to a full push.
"""

import time
from typing import Dict, Iterable, List, Sequence, Tuple

import numpy as np

from .handoff import _chunk_crc, _npz_chunk, parse_chunk

_HEADER_KIND = "weights_header"
_CHUNK_KIND = "weights"

# default leaf-group bucket: bounds how much of the model the publisher
# holds gathered at once (and the per-frame wire unit of a remote push)
DEFAULT_BUCKET_BYTES = 16 << 20


def _metrics():
    from ....telemetry import get_registry
    reg = get_registry()
    return (
        reg.counter("serving_weight_update_chunks_total",
                    "weight-payload chunks staged by serving runtimes"),
        reg.counter("serving_weight_update_bytes_total",
                    "serialized weight-payload bytes staged",
                    unit="bytes"),
    )


def flatten_params(tree) -> Tuple[List[Tuple[str, object]], object]:
    """Flatten a params pytree to ``([(path, leaf)], treedef)`` with the
    checkpoint layer's stable path naming — the one key space the
    publisher, the payload and every ingesting engine share."""
    from ....checkpoint.state_checkpoint import _leaf_paths
    return _leaf_paths(tree)


def fetch_leaf(leaf) -> np.ndarray:
    """Gather one (possibly sharded) leaf to host fp32 numpy — the
    checkpoint layer's lossless wire form (bf16/fp16 upcast)."""
    from ....checkpoint.state_checkpoint import _fetch
    return _fetch(leaf)


def plan_buckets(items: Sequence[Tuple[str, object]],
                 bucket_bytes: int = DEFAULT_BUCKET_BYTES
                 ) -> List[List[str]]:
    """Group leaf names into size-capped publication buckets (fp32 host
    bytes), preserving tree order — the gather/serialize granularity."""
    bucket_bytes = max(int(bucket_bytes), 1)
    buckets: List[List[str]] = []
    cur: List[str] = []
    cur_bytes = 0
    for name, leaf in items:
        nbytes = int(np.prod(getattr(leaf, "shape", ()) or (1,))) * 4
        if cur and cur_bytes + nbytes > bucket_bytes:
            buckets.append(cur)
            cur, cur_bytes = [], 0
        cur.append(name)
        cur_bytes += nbytes
    if cur:
        buckets.append(cur)
    return buckets


def chunk_weight_leaves(groups: Iterable[Dict[str, np.ndarray]],
                        version: int) -> List[bytes]:
    """Serialize host leaf groups into the wire payload
    ``[header, chunk...]``. ``groups`` yields ``{name: fp32 ndarray}``
    dicts (one per publication bucket)."""
    chunks: List[bytes] = []
    crcs: List[int] = []
    chunk_leaves: List[List[str]] = []
    leaf_meta: Dict[str, dict] = {}
    param_count = 0
    for seq, group in enumerate(groups):
        group = {k: np.ascontiguousarray(np.asarray(v, np.float32))
                 for k, v in group.items()}
        crc = _chunk_crc(group)
        crcs.append(crc)
        chunk_leaves.append(sorted(group))
        for name, arr in group.items():
            leaf_meta[name] = {"shape": list(arr.shape)}
            param_count += int(arr.size)
        chunks.append(_npz_chunk(
            {"kind": _CHUNK_KIND, "seq": seq, "crc32": crc,
             "version": int(version)}, group))
    header = _npz_chunk(
        {"kind": _HEADER_KIND, "version": int(version),
         "n_chunks": len(chunks), "chunk_crcs": crcs,
         "chunk_leaves": chunk_leaves, "leaf_meta": leaf_meta,
         "param_count": param_count}, {})
    return [header] + chunks


# ---------------------------------------------------------------------------
# Delta payloads (quantized weight-delta publication)
# ---------------------------------------------------------------------------
# delta quant modes: "int8" (block-quantized values + fp32 block scales,
# the comm/quantized wire form) or "off" (changed leaves at full fp32 —
# reconstruction is bitwise-exact)
DELTA_QUANT_MODES = ("int8", "off")
DEFAULT_DELTA_BLOCK = 2048


def _delta_keys(seq: int) -> Tuple[str, str]:
    """The two kv entries of one int8 delta chunk: concatenated
    quantized values and concatenated fp32 block scales. Seq-suffixed
    so a stager's flat leaf map never collides across chunks."""
    return f"__dq{seq}__", f"__ds{seq}__"


def _dequant_leaf(q_flat: np.ndarray, s_flat: np.ndarray,
                  numel: int) -> np.ndarray:
    """(int8 [nb*block], f32 [nb]) -> flat f32 [numel]. Plain numpy so
    the publisher's error-feedback reference and every ingesting
    replica reconstruct BIT-IDENTICAL values."""
    nb = int(s_flat.shape[0])
    d = q_flat.reshape(nb, -1).astype(np.float32) * \
        s_flat.reshape(nb, 1).astype(np.float32)
    return d.reshape(-1)[:numel]


def chunk_weight_deltas(flat: Dict[str, np.ndarray],
                        base: Dict[str, np.ndarray], version: int,
                        base_version: int, quant: str = "int8",
                        block: int = DEFAULT_DELTA_BLOCK,
                        bucket_bytes: int = DEFAULT_BUCKET_BYTES
                        ) -> Tuple[List[bytes], Dict[str, np.ndarray]]:
    """Serialize ``flat - base`` into a DELTA payload
    ``[header, chunk...]``.

    ``base`` is the receivers' reconstruction of ``base_version`` (the
    publisher's error-feedback reference — it tracks the fleet exactly,
    so the residual the quantizer introduced at version k is folded
    into the k+1 delta automatically). Returns ``(payloads, recon)``
    where ``recon`` is the bit-exact fleet state after this payload is
    applied — the caller's next error-feedback reference."""
    if quant not in DELTA_QUANT_MODES:
        raise ValueError(
            f"delta quant mode must be one of {DELTA_QUANT_MODES} "
            f"(got {quant!r})")
    if set(flat) != set(base):
        raise ValueError(
            "delta publication leaf set changed vs the base version; "
            "publisher and base must share one model structure")
    import jax.numpy as jnp

    from ....comm.quantized import _quantize_wire
    chunks: List[bytes] = []
    crcs: List[int] = []
    chunk_leaves: List[List[str]] = []
    delta_manifest: List[list] = []
    leaf_meta: Dict[str, dict] = {}
    recon: Dict[str, np.ndarray] = {}
    param_count = 0
    items = list(flat.items())
    for seq, names in enumerate(plan_buckets(items, bucket_bytes)):
        manifest: list = []
        if quant == "off":
            kv: Dict[str, np.ndarray] = {}
            for n in names:
                cur = np.ascontiguousarray(np.asarray(flat[n],
                                                      np.float32))
                leaf_meta[n] = {"shape": list(cur.shape)}
                param_count += int(cur.size)
                ref = np.asarray(base[n], np.float32)
                if cur.shape != ref.shape:
                    raise ValueError(
                        f"delta leaf {n!r} shape {cur.shape} != base "
                        f"shape {ref.shape}")
                if np.array_equal(cur, ref):
                    recon[n] = ref     # unchanged: receiver keeps base
                else:
                    kv[n] = cur
                    # recon must not alias the caller's live array (it
                    # becomes the next error-feedback base)
                    recon[n] = np.array(cur, np.float32)
                    manifest.append(n)
        else:
            qk, sk = _delta_keys(seq)
            qs: List[np.ndarray] = []
            ss: List[np.ndarray] = []
            for n in names:
                cur = np.asarray(flat[n], np.float32)
                ref = np.asarray(base[n], np.float32)
                if cur.shape != ref.shape:
                    raise ValueError(
                        f"delta leaf {n!r} shape {cur.shape} != base "
                        f"shape {ref.shape}")
                leaf_meta[n] = {"shape": list(cur.shape)}
                numel = int(cur.size)
                param_count += numel
                d = np.ascontiguousarray(cur - ref).reshape(-1)
                q, s = _quantize_wire(jnp.asarray(d),
                                      max(1, min(int(block),
                                                 max(numel, 1))),
                                      "int8")
                q = np.asarray(q, np.int8)
                s = np.asarray(s, np.float32)
                manifest.append({"name": n, "numel": numel,
                                 "nb": int(q.shape[0]),
                                 "block": int(q.shape[1])})
                recon[n] = (ref.reshape(-1)
                            + _dequant_leaf(q.reshape(-1),
                                            s.reshape(-1), numel)
                            ).astype(np.float32).reshape(cur.shape)
                qs.append(q.reshape(-1))
                ss.append(s.reshape(-1))
            kv = {qk: (np.concatenate(qs) if qs
                       else np.zeros(0, np.int8)),
                  sk: (np.concatenate(ss) if ss
                       else np.zeros(0, np.float32))}
        crc = _chunk_crc(kv)
        crcs.append(crc)
        chunk_leaves.append(sorted(kv))
        delta_manifest.append(manifest)
        chunks.append(_npz_chunk(
            {"kind": _CHUNK_KIND, "seq": seq, "crc32": crc,
             "version": int(version)}, kv))
    header = _npz_chunk(
        {"kind": _HEADER_KIND, "version": int(version),
         "payload_kind": "delta", "base_version": int(base_version),
         "quant": quant, "n_chunks": len(chunks), "chunk_crcs": crcs,
         "chunk_leaves": chunk_leaves,
         "delta_manifest": delta_manifest, "leaf_meta": leaf_meta,
         "param_count": param_count}, {})
    return [header] + chunks, recon


def is_delta_header(header: Dict) -> bool:
    return header.get("payload_kind") == "delta"


def is_delta_payload(payloads: Sequence[bytes]) -> bool:
    return is_delta_header(parse_weights_header(payloads[0]))


def delta_base_version(payloads: Sequence[bytes]) -> int:
    header = parse_weights_header(payloads[0])
    if not is_delta_header(header):
        raise ValueError("not a delta payload (no base_version)")
    return int(header["base_version"])


def reconstruct_delta(header: Dict, staged: Dict[str, np.ndarray],
                      base: Dict[str, np.ndarray]
                      ) -> Dict[str, np.ndarray]:
    """Rebuild the full ``{name: fp32 ndarray}`` map from a staged
    delta payload and the receiver's retained base (``base_version``'s
    fp32 leaves). Pure host math, deterministic — every replica
    applying this payload over the same base holds identical bits."""
    quant = header.get("quant", "int8")
    out = dict(base)
    missing = [n for n in header["leaf_meta"] if n not in base]
    if missing:
        raise ValueError(
            f"delta payload names {len(missing)} leaves absent from "
            f"the retained base (first: {missing[:3]})")
    for seq, manifest in enumerate(header["delta_manifest"]):
        if quant == "off":
            for n in manifest:
                out[n] = np.asarray(staged[n], np.float32)
            continue
        qk, sk = _delta_keys(seq)
        q_flat = np.asarray(staged[qk])
        s_flat = np.asarray(staged[sk], np.float32)
        q_off = s_off = 0
        for ent in manifest:
            n, numel = ent["name"], int(ent["numel"])
            nb, blk = int(ent["nb"]), int(ent["block"])
            ref = np.asarray(base[n], np.float32)
            if int(ref.size) != numel:
                raise ValueError(
                    f"delta leaf {n!r} numel {numel} != base "
                    f"{int(ref.size)}")
            q_seg = q_flat[q_off:q_off + nb * blk]
            s_seg = s_flat[s_off:s_off + nb]
            if q_seg.size != nb * blk or s_seg.size != nb:
                raise ValueError(
                    f"delta chunk {seq} truncated at leaf {n!r}")
            out[n] = (ref.reshape(-1)
                      + _dequant_leaf(q_seg, s_seg, numel)
                      ).astype(np.float32).reshape(ref.shape)
            q_off += nb * blk
            s_off += nb
    return out


# ---------------------------------------------------------------------------
# Adapter payloads (multi-tenant LoRA hot-deploy)
# ---------------------------------------------------------------------------
# A freshly trained LoRA adapter rides the SAME publish path as full /
# delta weight payloads (router.push_weights -> POST /weights ->
# begin_weight_update), so it inherits the chunk CRCs, the retransmit
# idempotence, the fleet blue/green drain and the fault plane for free.
# The header carries ``payload_kind="adapter"`` + the adapter NAME (the
# cross-replica identity the router and prefix cache key on) and the
# scale; each low-rank pair travels as two leaves keyed ``path + "::a"``
# / ``path + "::b"``. Ingest routes to ``engine.load_adapter`` — a
# same-shape bank slot write, no param swap, no recompile — instead of
# ``swap_engine_params``; ``weight_version`` and the retained delta
# base are untouched (the base model did not change).

_ADAPTER_A = "::a"
_ADAPTER_B = "::b"


def chunk_adapter_payload(name: str, adapters: Dict[str, tuple],
                          version: int,
                          scale: float = 1.0) -> List[bytes]:
    """Serialize one LoRA adapter (``{"layers/wq": (a, b), ...}`` —
    the hybrid-engine external-adapter convention) into the weights
    wire ``[header, chunk]``. Adapters are tiny relative to the model,
    so one chunk always suffices."""
    if not str(name):
        raise ValueError("adapter payload requires a non-empty name")
    flat: Dict[str, np.ndarray] = {}
    for path in sorted(adapters):
        a, b = adapters[path]
        flat[path + _ADAPTER_A] = np.ascontiguousarray(
            np.asarray(a, np.float32))
        flat[path + _ADAPTER_B] = np.ascontiguousarray(
            np.asarray(b, np.float32))
    crc = _chunk_crc(flat)
    leaf_meta = {n: {"shape": list(v.shape)} for n, v in flat.items()}
    chunk = _npz_chunk(
        {"kind": _CHUNK_KIND, "seq": 0, "crc32": crc,
         "version": int(version)}, flat)
    header = _npz_chunk(
        {"kind": _HEADER_KIND, "version": int(version),
         "payload_kind": "adapter", "adapter_name": str(name),
         "adapter_scale": float(scale), "n_chunks": 1,
         "chunk_crcs": [crc], "chunk_leaves": [sorted(flat)],
         "leaf_meta": leaf_meta,
         "param_count": sum(int(v.size) for v in flat.values())}, {})
    return [header, chunk]


def is_adapter_header(header: Dict) -> bool:
    return header.get("payload_kind") == "adapter"


def is_adapter_payload(payloads: Sequence[bytes]) -> bool:
    return is_adapter_header(parse_weights_header(payloads[0]))


def adapters_from_flat(flat: Dict[str, np.ndarray]
                       ) -> Dict[str, tuple]:
    """Regroup staged ``path::a`` / ``path::b`` leaves into the
    ``{path: (a, b)}`` map ``engine.load_adapter`` takes. Typed failure
    on an unpaired or unrecognized leaf."""
    adapters: Dict[str, tuple] = {}
    for n in sorted(flat):
        if n.endswith(_ADAPTER_A):
            path = n[:-len(_ADAPTER_A)]
            bk = path + _ADAPTER_B
            if bk not in flat:
                raise ValueError(
                    f"adapter payload leaf {n!r} has no matching "
                    f"{bk!r} (a/b pairs must travel together)")
            adapters[path] = (flat[n], flat[bk])
        elif not n.endswith(_ADAPTER_B):
            raise ValueError(
                f"adapter payload leaf {n!r} is neither "
                f"'{_ADAPTER_A}' nor '{_ADAPTER_B}' suffixed")
    for n in flat:
        if n.endswith(_ADAPTER_B) \
                and n[:-len(_ADAPTER_B)] not in adapters:
            raise ValueError(
                f"adapter payload leaf {n!r} has no matching "
                f"'{_ADAPTER_A}' half")
    return adapters


def parse_weights_header(buf: bytes) -> Dict:
    d = parse_chunk(buf)["descriptor"]
    if d.get("kind") != _HEADER_KIND:
        raise ValueError(
            f"weight payload must start with the header chunk "
            f"(got kind={d.get('kind')!r})")
    return d


def payload_version(payloads: Sequence[bytes]) -> int:
    return int(parse_weights_header(payloads[0])["version"])


def payload_bytes(payloads: Sequence[bytes]) -> int:
    return sum(len(p) for p in payloads)


class WeightStager:
    """Host-side state machine for one incoming weight payload: feed
    each chunk (CRC-checked, idempotent on retransmit), then
    ``commit_check`` + ``flat()`` hand the complete ``{name: ndarray}``
    map to the swap. Staging never touches the engine — the atomic
    swap is the only loop-thread moment."""

    def __init__(self, header: Dict):
        self.header = header
        self.version = int(header["version"])
        self.leaves: Dict[str, np.ndarray] = {}
        self.received: set = set()
        self._m_chunks, self._m_bytes = _metrics()

    def feed(self, chunk_buf: bytes) -> None:
        try:
            chunk = parse_chunk(chunk_buf)
        except Exception as e:
            # a corrupt buffer can die inside np.load (BadZipFile &c.)
            # before the CRC ever runs — surface it as the same typed
            # integrity failure so ingest verdicts stay uniform
            raise ValueError(
                f"weights chunk failed to parse (corrupted in "
                f"transfer): {type(e).__name__}: {e}") from e
        d = chunk["descriptor"]
        if d.get("kind") != _CHUNK_KIND:
            raise ValueError(
                f"expected a weights chunk, got {d.get('kind')!r}")
        seq = int(d["seq"])
        if not 0 <= seq < int(self.header["n_chunks"]):
            raise ValueError(
                f"weights chunk seq {seq} outside the header's "
                f"{self.header['n_chunks']} chunks")
        crc = _chunk_crc(chunk["kv"])
        if crc != int(d["crc32"]) \
                or crc != int(self.header["chunk_crcs"][seq]):
            raise ValueError(
                f"weights chunk {seq} failed its crc32 integrity check "
                f"(corrupted in transfer)")
        if sorted(chunk["kv"]) != list(self.header["chunk_leaves"][seq]):
            raise ValueError(
                f"weights chunk {seq} leaf set disagrees with the "
                f"header manifest")
        self.leaves.update(chunk["kv"])
        self.received.add(seq)
        self._m_chunks.inc()
        self._m_bytes.inc(len(chunk_buf))

    def missing(self) -> List[int]:
        return [s for s in range(int(self.header["n_chunks"]))
                if s not in self.received]

    def commit_check(self) -> None:
        gaps = self.missing()
        if gaps:
            raise ValueError(
                f"weight payload incomplete: missing chunks {gaps} of "
                f"{self.header['n_chunks']}")


def stage_payload(payloads: Sequence[bytes]) -> WeightStager:
    """Parse + CRC-check a complete payload into a ready stager."""
    stager = WeightStager(parse_weights_header(payloads[0]))
    for chunk in payloads[1:]:
        stager.feed(chunk)
    stager.commit_check()
    return stager


def flat_to_tree(template_tree, flat: Dict[str, np.ndarray]):
    """Rebuild a host params pytree shaped like ``template_tree`` from a
    flat ``{path: ndarray}`` map (fresh-engine construction from a
    published payload — the hot-swap parity reference)."""
    import jax
    items, treedef = flatten_params(template_tree)
    leaves = []
    for name, leaf in items:
        if name not in flat:
            raise ValueError(f"weight payload missing leaf {name!r}")
        leaves.append(np.asarray(flat[name], np.float32))
    return jax.tree_util.tree_unflatten(treedef, leaves)


def swap_engine_params(engine, flat: Dict[str, np.ndarray],
                       version: int) -> None:
    """Replace ``engine.params`` (an :class:`InferenceEngineV2`) with
    the published leaves by donated buffer replacement: each new leaf is
    cast to the OLD leaf's dtype and ``device_put`` onto the OLD leaf's
    sharding, so every compiled program's executable signature is
    unchanged — no retrace, no respecialization. Validation happens
    BEFORE any leaf is replaced: a bad payload leaves the engine
    serving its current version."""
    import jax
    import jax.numpy as jnp

    if getattr(engine, "_qmeta", None) is not None:
        raise NotImplementedError(
            "weight hot-swap over quant_bits (WOQ) params is not "
            "supported: the quantized leaf layout does not match the "
            "published dense tree")
    items, treedef = flatten_params(engine.params)
    names = [name for name, _ in items]
    missing = [n for n in names if n not in flat]
    if missing:
        raise ValueError(
            f"weight payload missing {len(missing)} leaves "
            f"(first: {missing[:3]}); publisher and serving engine "
            f"must share one model structure")
    extra = sorted(set(flat) - set(names))
    if extra:
        raise ValueError(
            f"weight payload has {len(extra)} unknown leaves "
            f"(first: {extra[:3]})")
    for name, old in items:
        if tuple(np.shape(flat[name])) != tuple(old.shape):
            raise ValueError(
                f"weight leaf {name!r} shape "
                f"{tuple(np.shape(flat[name]))} != engine shape "
                f"{tuple(old.shape)}")
    t0 = time.perf_counter()
    new_leaves = []
    for name, old in items:
        arr = jnp.asarray(np.asarray(flat[name]), old.dtype)
        # replicate the OLD leaf's placement exactly: the pjit
        # executable cache keys on committed-ness as well as sharding —
        # committing a leaf the engine held uncommitted (a plain jit
        # output on one device) would silently respecialize every
        # program on its next call
        if getattr(old, "committed", True):
            arr = jax.device_put(arr, old.sharding)
        new_leaves.append(arr)
    engine.params = jax.tree_util.tree_unflatten(treedef, new_leaves)
    engine.weight_version = int(version)
    # retain the fp32 flat leaves as the DELTA BASE for the next push:
    # a delta payload reconstructs against exactly these bits (the
    # receiver-side half of the publisher's error-feedback reference).
    # Host cost: one fp32 copy of the model per serving engine.
    set_delta_base(engine, flat)
    engine.note_weight_swap(time.perf_counter() - t0)


def set_delta_base(engine, flat: Dict[str, np.ndarray]) -> None:
    """Record ``flat`` (fp32 host leaves) as the engine's delta base —
    what ``commit_stager`` reconstructs the next delta payload
    against. Called by every ingest path (swap + fresh build)."""
    engine._weight_flat_base = {
        n: np.asarray(a, np.float32) for n, a in flat.items()}


def delta_base_of(engine):
    """The engine's retained ``{name: fp32 ndarray}`` delta base, or
    None when it never ingested a payload (boot-checkpoint engines
    cannot take deltas — the router falls back to a full push)."""
    return getattr(engine, "_weight_flat_base", None)


def prepare_stager(engine, stager: WeightStager
                   ) -> Dict[str, np.ndarray]:
    """The host-side half of ingest: validate + (for deltas)
    reconstruct the full flat leaf map, touching nothing live. Delta
    payloads validate base version + retained base BEFORE any
    reconstruction — a stale base fails typed with the live params
    untouched. Runs off the serving loop thread (heavy host math);
    the returned map goes to ``swap_engine_params`` between scheduler
    steps."""
    header = stager.header
    if is_adapter_header(header):
        # validate pairing off-loop so a malformed payload fails typed
        # BEFORE the loop-thread install; the regrouped map is rebuilt
        # (cheap — adapters are tiny) by install_stager
        adapters_from_flat(stager.leaves)
        return stager.leaves
    if not is_delta_header(header):
        return stager.leaves
    base_version = int(header["base_version"])
    live = int(getattr(engine, "weight_version", 0) or 0)
    base = delta_base_of(engine)
    if live != base_version:
        raise ValueError(
            f"delta payload base_version={base_version} does not "
            f"match the live weight_version={live}; a full push is "
            f"required")
    if base is None:
        raise ValueError(
            "delta payload cannot apply: this engine retains no delta "
            "base (it never ingested a weight payload); a full push "
            "is required")
    return reconstruct_delta(header, stager.leaves, base)


def install_stager(engine, stager: WeightStager,
                   flat: Dict[str, np.ndarray]) -> int:
    """The loop-thread half of ingest: install the prepared leaves into
    the engine. Full/delta payloads run the donated-buffer param swap;
    ADAPTER payloads route to ``engine.load_adapter`` (a bank-slot
    write — ``weight_version`` and the retained delta base stay put,
    the base model did not change). Both the colocated
    ``commit_stager`` and the serving loop's ``WeightUpdate.commit``
    land here, so every payload kind behaves identically on every
    ingest path."""
    if is_adapter_header(stager.header):
        header = stager.header
        engine.load_adapter(
            str(header["adapter_name"]), adapters_from_flat(flat),
            scale=float(header.get("adapter_scale", 1.0)))
        return int(stager.version)
    swap_engine_params(engine, flat, stager.version)
    return int(stager.version)


def commit_stager(engine, stager: WeightStager) -> int:
    """THE ingest choke point: every path that turns a complete stager
    into live params (colocated ``apply_payload``, the serving loop's
    ``WeightUpdate.commit``, the worker ``/weights`` handler above it)
    lands here, so full, delta and adapter payloads behave identically
    everywhere."""
    flat = prepare_stager(engine, stager)
    return install_stager(engine, stager, flat)


def apply_payload(engine, payloads: Sequence[bytes]) -> int:
    """Stage + swap a complete payload (full or delta) into ``engine``
    synchronously (the colocated hybrid path; serving runtimes go
    through :meth:`~.frontend.ServingEngine.begin_weight_update` so the
    swap lands between scheduler steps). Returns the installed
    version."""
    return commit_stager(engine, stage_payload(payloads))
