"""Versioned weight payloads and zero-recompile param hot-swap.

The train->serve seam of the Hybrid Engine (docs/SERVING.md § Blue/green
weight push; docs/TRAINING.md § Hybrid engine): a training engine
publishes its live params as a **versioned, chunked, CRC-checked**
payload — the same frame discipline as the KV handoff (serve/handoff.py)
— and a serving engine ingests it by **donated buffer replacement**:
every new leaf is ``device_put`` onto the OLD leaf's sharding with the
OLD leaf's dtype, so the swapped tree presents the exact executable
signature (shape x dtype x sharding) every compiled serving program was
keyed on. Steady-state recompiles across a swap are zero *by
construction* — and pinned by the recompile watchdog in the perf gate
(``hot_swap_steady_recompiles``) and the parity tests.

Payload layout (``chunk_weight_leaves``): one HEADER chunk carrying the
version, the leaf manifest (names / shapes / dtypes) and per-chunk
CRC32s, then N leaf-group chunks — leaves are packed into size-capped
buckets (``bucket_bytes``) so the publisher gathers and serializes one
bucket at a time instead of materializing the whole model twice. Each
chunk is an independent ``.npz`` buffer (handoff's ``_npz_chunk``), so
retransmit is idempotent and a corrupt chunk fails TYPED at its CRC
without touching the serving params.

Leaves travel as fp32 numpy (the lossless host form of bf16/fp16 train
params — checkpoint/state_checkpoint's ``_fetch`` convention); the
ingest side casts to the serving dtype with the same ``jnp.asarray``
cast a fresh engine applies at init, which is what makes post-swap
streams bit-identical to a fresh engine built from the published
payload (the hot-swap parity pin).
"""

import time
from typing import Dict, Iterable, List, Sequence, Tuple

import numpy as np

from .handoff import _chunk_crc, _npz_chunk, parse_chunk

_HEADER_KIND = "weights_header"
_CHUNK_KIND = "weights"

# default leaf-group bucket: bounds how much of the model the publisher
# holds gathered at once (and the per-frame wire unit of a remote push)
DEFAULT_BUCKET_BYTES = 16 << 20


def _metrics():
    from ....telemetry import get_registry
    reg = get_registry()
    return (
        reg.counter("serving_weight_update_chunks_total",
                    "weight-payload chunks staged by serving runtimes"),
        reg.counter("serving_weight_update_bytes_total",
                    "serialized weight-payload bytes staged",
                    unit="bytes"),
    )


def flatten_params(tree) -> Tuple[List[Tuple[str, object]], object]:
    """Flatten a params pytree to ``([(path, leaf)], treedef)`` with the
    checkpoint layer's stable path naming — the one key space the
    publisher, the payload and every ingesting engine share."""
    from ....checkpoint.state_checkpoint import _leaf_paths
    return _leaf_paths(tree)


def fetch_leaf(leaf) -> np.ndarray:
    """Gather one (possibly sharded) leaf to host fp32 numpy — the
    checkpoint layer's lossless wire form (bf16/fp16 upcast)."""
    from ....checkpoint.state_checkpoint import _fetch
    return _fetch(leaf)


def plan_buckets(items: Sequence[Tuple[str, object]],
                 bucket_bytes: int = DEFAULT_BUCKET_BYTES
                 ) -> List[List[str]]:
    """Group leaf names into size-capped publication buckets (fp32 host
    bytes), preserving tree order — the gather/serialize granularity."""
    bucket_bytes = max(int(bucket_bytes), 1)
    buckets: List[List[str]] = []
    cur: List[str] = []
    cur_bytes = 0
    for name, leaf in items:
        nbytes = int(np.prod(getattr(leaf, "shape", ()) or (1,))) * 4
        if cur and cur_bytes + nbytes > bucket_bytes:
            buckets.append(cur)
            cur, cur_bytes = [], 0
        cur.append(name)
        cur_bytes += nbytes
    if cur:
        buckets.append(cur)
    return buckets


def chunk_weight_leaves(groups: Iterable[Dict[str, np.ndarray]],
                        version: int) -> List[bytes]:
    """Serialize host leaf groups into the wire payload
    ``[header, chunk...]``. ``groups`` yields ``{name: fp32 ndarray}``
    dicts (one per publication bucket)."""
    chunks: List[bytes] = []
    crcs: List[int] = []
    chunk_leaves: List[List[str]] = []
    leaf_meta: Dict[str, dict] = {}
    param_count = 0
    for seq, group in enumerate(groups):
        group = {k: np.ascontiguousarray(np.asarray(v, np.float32))
                 for k, v in group.items()}
        crc = _chunk_crc(group)
        crcs.append(crc)
        chunk_leaves.append(sorted(group))
        for name, arr in group.items():
            leaf_meta[name] = {"shape": list(arr.shape)}
            param_count += int(arr.size)
        chunks.append(_npz_chunk(
            {"kind": _CHUNK_KIND, "seq": seq, "crc32": crc,
             "version": int(version)}, group))
    header = _npz_chunk(
        {"kind": _HEADER_KIND, "version": int(version),
         "n_chunks": len(chunks), "chunk_crcs": crcs,
         "chunk_leaves": chunk_leaves, "leaf_meta": leaf_meta,
         "param_count": param_count}, {})
    return [header] + chunks


def parse_weights_header(buf: bytes) -> Dict:
    d = parse_chunk(buf)["descriptor"]
    if d.get("kind") != _HEADER_KIND:
        raise ValueError(
            f"weight payload must start with the header chunk "
            f"(got kind={d.get('kind')!r})")
    return d


def payload_version(payloads: Sequence[bytes]) -> int:
    return int(parse_weights_header(payloads[0])["version"])


def payload_bytes(payloads: Sequence[bytes]) -> int:
    return sum(len(p) for p in payloads)


class WeightStager:
    """Host-side state machine for one incoming weight payload: feed
    each chunk (CRC-checked, idempotent on retransmit), then
    ``commit_check`` + ``flat()`` hand the complete ``{name: ndarray}``
    map to the swap. Staging never touches the engine — the atomic
    swap is the only loop-thread moment."""

    def __init__(self, header: Dict):
        self.header = header
        self.version = int(header["version"])
        self.leaves: Dict[str, np.ndarray] = {}
        self.received: set = set()
        self._m_chunks, self._m_bytes = _metrics()

    def feed(self, chunk_buf: bytes) -> None:
        try:
            chunk = parse_chunk(chunk_buf)
        except Exception as e:
            # a corrupt buffer can die inside np.load (BadZipFile &c.)
            # before the CRC ever runs — surface it as the same typed
            # integrity failure so ingest verdicts stay uniform
            raise ValueError(
                f"weights chunk failed to parse (corrupted in "
                f"transfer): {type(e).__name__}: {e}") from e
        d = chunk["descriptor"]
        if d.get("kind") != _CHUNK_KIND:
            raise ValueError(
                f"expected a weights chunk, got {d.get('kind')!r}")
        seq = int(d["seq"])
        if not 0 <= seq < int(self.header["n_chunks"]):
            raise ValueError(
                f"weights chunk seq {seq} outside the header's "
                f"{self.header['n_chunks']} chunks")
        crc = _chunk_crc(chunk["kv"])
        if crc != int(d["crc32"]) \
                or crc != int(self.header["chunk_crcs"][seq]):
            raise ValueError(
                f"weights chunk {seq} failed its crc32 integrity check "
                f"(corrupted in transfer)")
        if sorted(chunk["kv"]) != list(self.header["chunk_leaves"][seq]):
            raise ValueError(
                f"weights chunk {seq} leaf set disagrees with the "
                f"header manifest")
        self.leaves.update(chunk["kv"])
        self.received.add(seq)
        self._m_chunks.inc()
        self._m_bytes.inc(len(chunk_buf))

    def missing(self) -> List[int]:
        return [s for s in range(int(self.header["n_chunks"]))
                if s not in self.received]

    def commit_check(self) -> None:
        gaps = self.missing()
        if gaps:
            raise ValueError(
                f"weight payload incomplete: missing chunks {gaps} of "
                f"{self.header['n_chunks']}")


def stage_payload(payloads: Sequence[bytes]) -> WeightStager:
    """Parse + CRC-check a complete payload into a ready stager."""
    stager = WeightStager(parse_weights_header(payloads[0]))
    for chunk in payloads[1:]:
        stager.feed(chunk)
    stager.commit_check()
    return stager


def flat_to_tree(template_tree, flat: Dict[str, np.ndarray]):
    """Rebuild a host params pytree shaped like ``template_tree`` from a
    flat ``{path: ndarray}`` map (fresh-engine construction from a
    published payload — the hot-swap parity reference)."""
    import jax
    items, treedef = flatten_params(template_tree)
    leaves = []
    for name, leaf in items:
        if name not in flat:
            raise ValueError(f"weight payload missing leaf {name!r}")
        leaves.append(np.asarray(flat[name], np.float32))
    return jax.tree_util.tree_unflatten(treedef, leaves)


def swap_engine_params(engine, flat: Dict[str, np.ndarray],
                       version: int) -> None:
    """Replace ``engine.params`` (an :class:`InferenceEngineV2`) with
    the published leaves by donated buffer replacement: each new leaf is
    cast to the OLD leaf's dtype and ``device_put`` onto the OLD leaf's
    sharding, so every compiled program's executable signature is
    unchanged — no retrace, no respecialization. Validation happens
    BEFORE any leaf is replaced: a bad payload leaves the engine
    serving its current version."""
    import jax
    import jax.numpy as jnp

    if getattr(engine, "_qmeta", None) is not None:
        raise NotImplementedError(
            "weight hot-swap over quant_bits (WOQ) params is not "
            "supported: the quantized leaf layout does not match the "
            "published dense tree")
    items, treedef = flatten_params(engine.params)
    names = [name for name, _ in items]
    missing = [n for n in names if n not in flat]
    if missing:
        raise ValueError(
            f"weight payload missing {len(missing)} leaves "
            f"(first: {missing[:3]}); publisher and serving engine "
            f"must share one model structure")
    extra = sorted(set(flat) - set(names))
    if extra:
        raise ValueError(
            f"weight payload has {len(extra)} unknown leaves "
            f"(first: {extra[:3]})")
    for name, old in items:
        if tuple(np.shape(flat[name])) != tuple(old.shape):
            raise ValueError(
                f"weight leaf {name!r} shape "
                f"{tuple(np.shape(flat[name]))} != engine shape "
                f"{tuple(old.shape)}")
    t0 = time.perf_counter()
    new_leaves = []
    for name, old in items:
        arr = jnp.asarray(np.asarray(flat[name]), old.dtype)
        # replicate the OLD leaf's placement exactly: the pjit
        # executable cache keys on committed-ness as well as sharding —
        # committing a leaf the engine held uncommitted (a plain jit
        # output on one device) would silently respecialize every
        # program on its next call
        if getattr(old, "committed", True):
            arr = jax.device_put(arr, old.sharding)
        new_leaves.append(arr)
    engine.params = jax.tree_util.tree_unflatten(treedef, new_leaves)
    engine.weight_version = int(version)
    engine.note_weight_swap(time.perf_counter() - t0)


def apply_payload(engine, payloads: Sequence[bytes]) -> int:
    """Stage + swap a complete payload into ``engine`` synchronously
    (the colocated hybrid path; serving runtimes go through
    :meth:`~.frontend.ServingEngine.begin_weight_update` so the swap
    lands between scheduler steps). Returns the installed version."""
    stager = stage_payload(payloads)
    swap_engine_params(engine, stager.leaves, stager.version)
    return stager.version
