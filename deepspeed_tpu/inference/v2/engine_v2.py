"""Ragged/continuous-batching inference engine (FastGen-style).

Reference: inference/v2/engine_v2.py:26 (InferenceEngineV2): the serving
loop calls ``put(batch_uids, batch_tokens)`` each step with a mix of new
prompts and one next-token per running sequence; the engine returns the
next-token logits for every entry. KV lives in a blocked (paged) pool
managed by DSStateManager; sequences are freed with ``flush``.

TPU-native scheduling: with ragged attention enabled (the default,
``config_v2.ragged_attention``) every put() — mixed prompts,
continuations and decode rows — packs into ONE RaggedBatch and runs as
a single unified compiled program per (token bucket, row bucket)
(``paged_ragged_step`` + ``kernels/ragged_attention.py``), the Ragged
Paged Attention design (PAPERS.md arXiv:2604.15464). The stitched
families remain behind ``ragged_attention="off"``: prompts through
``paged_prefill`` (one compiled program per prompt-length bucket),
multi-token continuations through ONE fused ``paged_continue`` chunk
pass, and running sequences batched into a ``paged_decode`` call padded
to the next power-of-two bucket — the compiled-program cache plays the
role the reference's CUDA graphs + atom builder play. Stitched mixed
puts do the prefills/continuations first, then the fused decode batch.

The decode hot loop itself is fused on device (``decode_window`` > 1):
``paged_decode_window`` runs up to K decode steps per dispatch — cache
write, paged attention, argmax/per-row-keyed sampling, EOS + budget
masking, arithmetic block-table advancement over pre-allocated blocks —
with one [N, K] int32 transfer per window instead of a Python round-trip
per token (docs/SERVING.md, "Fused multi-token decode").
"""

import time
from typing import Dict, Iterable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ...models.transformer import TransformerConfig
from ...telemetry import memory as ds_memory
from ...telemetry import recorder as flight
from ...telemetry import trace, watchdog
from ...utils.bucketing import ceil_bucket, pow2_bucket
from ...utils.logging import log_dist
from .config_v2 import RaggedInferenceEngineConfig
from .paged_model import (init_lora_bank, init_paged_kv_cache,
                          paged_continue, paged_decode, paged_decode_window,
                          paged_prefill, paged_ragged_step,
                          paged_spec_decode_window)
from .ragged import batch as ragged_batch
from .ragged.blocked_allocator import NULL_BLOCK
from .ragged.ragged_manager import DSStateManager

DTYPES = {"float32": jnp.float32, "float16": jnp.float16,
          "bfloat16": jnp.bfloat16}


class DraftModelMismatchError(ValueError):
    """A draft model cannot verify-share with the serving target:
    greedy verification compares raw token ids, so the vocabularies
    must be the SAME id space, and the draft writes its KV through the
    target's block tables, so it must cover the same sequence range."""


class SpecChooser:
    """Routes each speculative request between the two draft sources —
    the host n-gram index (``"ngram"``, prompt-lookup) and the in-window
    draft model (``"draft"``) — by observed accept rate.

    Hysteresis-armed like the online autotuner (autotuning/online.py's
    armed/hold cycle): a switch commits only after the OTHER source's
    accept-rate EMA beats the current one by ``margin`` for ``hold``
    consecutive observations, so one noisy window never flips the
    route. Cold start (no accept history for either source) routes by a
    repetitiveness prior: histories whose trailing n-gram already
    recurs draft well from their own text; everything else goes to the
    draft model."""

    def __init__(self, mode: str = "auto", alpha: float = 0.3,
                 margin: float = 0.05, hold: int = 3):
        self.mode = mode
        self.alpha = float(alpha)
        self.margin = float(margin)
        self.hold = int(hold)
        self.rate: Dict[str, Optional[float]] = {"ngram": None,
                                                 "draft": None}
        self.current = "draft" if mode == "draft" else "ngram"
        self.switches = 0
        self._armed: Optional[str] = None
        self._streak = 0

    def observe(self, mode: str, drafted: int, accepted: int) -> None:
        """Fold one round's (drafted, accepted) counts into ``mode``'s
        accept-rate EMA; may arm or commit a route switch."""
        if drafted <= 0:
            return
        r = min(max(accepted / drafted, 0.0), 1.0)
        prev = self.rate.get(mode)
        self.rate[mode] = (r if prev is None
                           else (1 - self.alpha) * prev + self.alpha * r)
        self._maybe_switch()

    def _maybe_switch(self) -> None:
        if self.mode != "auto":
            return
        other = "draft" if self.current == "ngram" else "ngram"
        ro, rc = self.rate[other], self.rate[self.current]
        if ro is None or rc is None or ro <= rc + self.margin:
            self._armed, self._streak = None, 0
            return
        if self._armed != other:
            self._armed, self._streak = other, 1
        else:
            self._streak += 1
        if self._streak >= self.hold:
            self.current = other
            self.switches += 1
            self._armed, self._streak = None, 0

    def choose(self, has_draft_model: bool, ngram_hit: bool) -> str:
        """Route one incoming request. Pinned modes and a missing draft
        model short-circuit; "auto" returns the hysteresis-settled
        current source once any accept history exists."""
        if self.mode == "ngram" or not has_draft_model:
            return "ngram"
        if self.mode == "draft":
            return "draft"
        if self.rate["ngram"] is None and self.rate["draft"] is None:
            return "ngram" if ngram_hit else "draft"
        return self.current


class InferenceEngineV2:
    def __init__(self, model, config: Optional[RaggedInferenceEngineConfig]
                 = None, params=None):
        if isinstance(config, dict) or config is None:
            config = RaggedInferenceEngineConfig.from_dict(config or {})
        self.config = config
        self.model = model
        cfg: TransformerConfig = model.cfg
        if cfg.moe_num_experts > 0 and config.expert_parallel_size > 1:
            # ep>1 serving routes through the worst-case-capacity einsum
            # dispatch (moe_layer_dropless_ep -> moe_layer), whose gating
            # implements the training top-1/top-2 conventions only. ep=1
            # serving uses the k-generic sorted-token grouped GEMM
            # (dropless_topk_dispatch) with renormalized top-k weights —
            # the Mixtral/Qwen-MoE/DBRX convention — so any k serves.
            assert cfg.moe_top_k <= 2, \
                f"expert-parallel serving is top-1/top-2 only " \
                f"(got moe_top_k={cfg.moe_top_k}); serve top-k>2 at ep=1"
        sm = config.state_manager
        if sm.max_seq_len > cfg.max_seq_len:
            sm.max_seq_len = cfg.max_seq_len
        self.dtype = DTYPES[config.dtype]
        self.block_size = sm.block_size

        from ...parallel.topology import build_topology
        tp = config.tensor_parallel_size
        ep = config.expert_parallel_size
        if ep > 1:
            assert cfg.moe_num_experts > 0, \
                "expert_parallel_size > 1 requires an MoE model"
            assert cfg.moe_num_experts % ep == 0, \
                f"num experts {cfg.moe_num_experts} not divisible by " \
                f"expert_parallel_size {ep}"
        self.topology = build_topology(model=tp, expert=ep,
                                       devices=jax.devices()[:tp * ep])
        self.mesh = self.topology.mesh
        if hasattr(model, "set_topology"):
            model.set_topology(self.topology)
        from jax.sharding import NamedSharding, PartitionSpec as P
        specs = (model.param_partition_specs(self.topology)
                 if hasattr(model, "param_partition_specs") else None)
        self.param_sharding = (jax.tree.map(
            lambda s: NamedSharding(self.mesh, s), specs,
            is_leaf=lambda x: isinstance(x, P)) if specs is not None else None)

        if params is not None:
            cast = jax.jit(lambda p: jax.tree.map(
                lambda x: jnp.asarray(x, self.dtype), p),
                out_shardings=self.param_sharding)
            self.params = cast(params)
        else:
            init = jax.jit(
                lambda r: jax.tree.map(lambda x: x.astype(self.dtype),
                                       model.init_params(r)),
                out_shardings=self.param_sharding)
            self.params = init(jax.random.PRNGKey(config.seed))

        if config.quant_bits:
            # WOQ at rest (v1 machinery, inference/quantization.py):
            # int8/packed-int4 + per-block scales in HBM. paged_model
            # dequantizes non-layer leaves at entry and each scanned
            # layer INSIDE the scan body (per-layer stacked quant), so
            # peak HBM really is the quantized footprint — see
            # QuantizedTensor.stacked. tp/ep shardings are declared
            # against the dense leaf structure: single-device only
            assert tp == 1 and ep == 1, \
                "quant_bits requires tensor_parallel_size == " \
                "expert_parallel_size == 1 (shardings are declared " \
                "against dense leaves)"
            from ..quantization import quantize_params
            self.params, self._qmeta = quantize_params(
                self.params, bits=config.quant_bits)

        self.state_manager = DSStateManager(sm)
        # note: the fresh pool carries no sharding, while every program
        # returns the donated cache with an explicit NamedSharding — so
        # a bucket's FIRST call compiles against a different executable
        # signature than its steady repeats (one respecialization per
        # bucket, for the stitched families too). Warmup should replay
        # the bucket set twice before watchdog.mark_steady(); committing
        # the pool sharded at init was tried and destabilizes unrelated
        # XLA-CPU executables later in the process (see PR 7 notes)
        self.kv_cache = init_paged_kv_cache(cfg, sm.num_blocks,
                                            sm.block_size, self.dtype,
                                            kv_quant=config.kv_quant)
        # cold-block KV spill tier (ragged/spill.py): installed on the
        # state manager so prefix eviction demotes content to host RAM
        # (+ optional disk) and match_prefix restores it between steps
        self.spill = None
        if sm.enable_kv_spill:
            from .ragged.spill import KVSpillTier
            self.spill = KVSpillTier(self, sm)
            self.state_manager.spill = self.spill
        # per-uid consecutive failed-verify counter for speculative
        # decoding; entries are cleared on flush() and at generate() entry
        # so a cold streak never bans a uid across independent calls
        self._spec_miss_streak: Dict[int, int] = {}
        # per-uid incremental n-gram index (ngram_index.py): keeps draft
        # lookup O(ngram) per round instead of re-scanning the history
        # window; same lifecycle as the miss streaks
        self._draft_index: Dict[int, object] = {}
        # per-uid distributed-trace ids (telemetry/context.py): the
        # scheduler binds them at submit/resume so batch-level spans
        # (decode_step/decode_window/ragged_step) carry the trace ids of
        # every request they served; cleared on flush()
        self._uid_traces: Dict[int, str] = {}
        # live-weight version (serve/weights.py hot-swap): 0 = the boot
        # checkpoint; bumped by swap_engine_params. Advertised through
        # /healthz so the router's blue/green rollout can converge a
        # fleet onto one version
        self.weight_version = 0
        # multi-tenant batched LoRA (config_v2.max_lora_adapters): the
        # stacked adapter bank lives on device next to the params; slot
        # 0 holds the all-zero base delta, so rows without an adapter
        # ride the same gathered program bit-exactly (+0.0). The bank is
        # a jit ARGUMENT, not a closure constant, so loading an adapter
        # is a same-shape slot update — no recompile.
        self.lora_bank = None
        self._adapter_slots: Dict[str, int] = {}
        self._uid_adapter: Dict[int, str] = {}
        if config.max_lora_adapters > 0:
            self.lora_bank = init_lora_bank(
                cfg, config.max_lora_adapters + 1, config.lora_rank,
                self.dtype)
        # draft-model speculation (load_draft_model): the draft shares
        # the target's block tables against its OWN paged KV pool, so
        # propose->verify->accept runs entirely inside one jitted window
        # (paged_spec_decode_window); jits cached per (window, spec_k)
        self.draft_model = None
        self.draft_params = None
        self.draft_cache = None
        self._draft_cfg = None
        self._draft_seen: Dict[int, int] = {}
        self._spec_window_jits: Dict[tuple, object] = {}
        self.spec_chooser = SpecChooser(config.spec_mode)
        self._spec_mode_of: Dict[int, str] = {}
        self._spec_switches_seen = 0
        self._init_telemetry()
        # Pallas kernels only at tp=1: a bare pallas_call is not
        # GSPMD-partitionable, so sharded-param (tp>1) serving keeps the
        # jnp paths, which the partitioner splits over the head axis (same
        # gate as the v1 decode kernel, models/transformer.py). kv_quant
        # no longer gates the decode/ragged kernels: the quant kernel
        # variants stream the int8 pages + per-(block, head) scale rows
        # and dequantize in VMEM (kernels/paged_attention.py,
        # kernels/ragged_attention.py), so 2x KV capacity keeps the whole
        # Pallas fast path — fused decode windows and the ragged family
        # included
        use_kernel = (config.use_paged_kernel and tp == 1 and ep == 1
                      and cfg.positional != "alibi")  # kernels carry no
        # alibi bias; the jnp paths add the softmax-invariant row
        topo = self.topology if ep > 1 else None
        # load_draft_model builds jits after __init__; it reuses the
        # same kernel gate and topology the serving programs resolved
        self._use_kernel = use_kernel
        self._topo = topo
        # every compile point below is watchdog-wrapped: the power-of-two
        # bucketing is SUPPOSED to make steady-state serving compile-free,
        # and the watchdog is what proves it (telemetry/watchdog.py)
        # every decode-family jit takes trailing (lb, aid): the LoRA
        # bank and per-row adapter slots. Both are None when the bank is
        # disabled (an empty pytree — same compiled programs as before),
        # and they TRAIL the existing argument lists so every
        # donate_argnums index stays put
        self._decode_jit = watchdog.watch("decode", jax.jit(
            lambda p, t, pos, bt, c, a, lb, aid: paged_decode(
                cfg, p, t, pos, bt, c, a, sm.block_size,
                use_kernel=use_kernel, topo=topo, lora=lb,
                adapter_ids=aid),
            donate_argnums=(4,)))

        def _decode_tok(p, t, pos, bt, c, a, lb, aid):
            # greedy variant for the generate() hot loop: argmax on device
            # so the per-token host transfer is [N] int32, not [N, vocab]
            # (the reference's sampler also runs device-side)
            logits, c = paged_decode(cfg, p, t, pos, bt, c, a,
                                     sm.block_size,
                                     use_kernel=use_kernel,
                                     topo=topo, lora=lb, adapter_ids=aid)
            return jnp.argmax(logits, axis=-1).astype(jnp.int32), c

        self._decode_tok_jit = watchdog.watch(
            "decode_greedy", jax.jit(_decode_tok, donate_argnums=(4,)))

        def _decode_sample(p, t, pos, bt, c, a, rng, seeds, gidx, temp,
                           topp, topk, lb, aid):
            # sampling variant (FastGen temperature/top-p/top-k): the
            # sampler runs device-side too, still an [N] int32 transfer.
            # Per-ROW keys (stable row seed + generated-token index) so
            # the stream matches the fused window path bit-for-bit
            from .sampling import fold_in_rows, sample_tokens_rowwise
            logits, c = paged_decode(cfg, p, t, pos, bt, c, a,
                                     sm.block_size,
                                     use_kernel=use_kernel,
                                     topo=topo, lora=lb, adapter_ids=aid)
            keys = fold_in_rows(rng, seeds, gidx)
            return sample_tokens_rowwise(logits, keys, temp, topp,
                                         topk), c

        self._decode_sample_jit = watchdog.watch(
            "decode_sample", jax.jit(_decode_sample, donate_argnums=(4,)))
        # fused multi-token decode window (the generate()/scheduler hot
        # path when decode_window > 1): K decode steps per dispatch, one
        # [N, K] int32 transfer per window. K is baked into the compiled
        # program; batch rows pad to the same power-of-two buckets as the
        # per-token path, so the compile cache stays one program per
        # (batch bucket, table-width bucket).
        self.decode_window = max(int(config.decode_window), 1)
        self._m_window_size.set(self.decode_window)

        # K is baked into each compiled window program, so runtime
        # adaptation (autotuning/online.py set_decode_window) swaps
        # whole jit OBJECTS from this per-K cache — reusing one jit
        # across K values would silently serve the old-K program (the
        # closure int is not part of jax's cache key). All K values
        # share the watchdog program names, so compile accounting stays
        # one row per path regardless of the ladder.
        self._fused_jit_cache: Dict[int, tuple] = {}

        def _build_fused_pair(K: int):
            greedy = watchdog.watch("decode_window_greedy", jax.jit(
                lambda p, t, pos, bt, c, sl, eos, lb, aid, _K=K:
                paged_decode_window(
                    cfg, p, t, pos, bt, c, sl, eos, sm.block_size,
                    _K, use_kernel=use_kernel,
                    topo=topo, lora=lb, adapter_ids=aid),
                donate_argnums=(4,)))
            sample = watchdog.watch("decode_window_sample", jax.jit(
                lambda p, t, pos, bt, c, sl, eos, rng, seeds, g0, temp, \
                topp, topk, lb, aid, _K=K: paged_decode_window(
                    cfg, p, t, pos, bt, c, sl, eos, sm.block_size,
                    _K, rng=rng, row_seeds=seeds, gen_idx0=g0,
                    temp=temp, topp=topp, topk=topk,
                    use_kernel=use_kernel, topo=topo, lora=lb,
                    adapter_ids=aid),
                donate_argnums=(4,)))
            return greedy, sample

        self._build_fused_pair = _build_fused_pair
        # windows whose programs have actually run (and therefore
        # compiled for the current buckets): the online adapter's
        # steady-state move set
        self._warmed_windows: set = set()
        self._fused_greedy_jit, self._fused_sample_jit = \
            self._fused_pair(self.decode_window)
        self._prefill_jit = watchdog.watch("prefill", jax.jit(
            lambda p, ids, n, c, b, o, lb, aid: paged_prefill(
                cfg, p, ids, n, c, b, o,
                use_kernel=use_kernel, topo=topo, lora=lb,
                adapter_ids=aid),
            donate_argnums=(3,)))
        self._continue_jit = watchdog.watch("continue", jax.jit(
            lambda p, ids, s, n, c, b, o, t, lb, aid: paged_continue(
                cfg, p, ids, s, n, c, b, o, t, sm.block_size, topo=topo,
                lora=lb, adapter_ids=aid),
            donate_argnums=(4,)))
        # ragged unified step (ROADMAP item 1; kernels/ragged_attention.py
        # + ragged/batch.py): every mixed prefill+decode composition runs
        # as ONE program keyed by (token bucket, row bucket, table-width
        # bucket) — put() and the SplitFuse scheduler route here instead
        # of sequencing the prefill/continue/decode families. The ragged
        # kernel shares the decode kernel's gates (no alibi, tp=ep=1;
        # int8 kv_quant pools ride the quant kernel variants); gated-off
        # configs serve through the jnp ragged fallback inside the same
        # unified program.
        self.ragged_enabled = self._resolve_ragged_mode(
            config.ragged_attention)
        self._ragged_jit = watchdog.watch("ragged_step", jax.jit(
            lambda p, ids, rows, pos, ln, wb, wo, bt, li, c, lb, aid:
            paged_ragged_step(
                cfg, p, ids, rows, pos, ln, wb, wo, bt, li, c,
                sm.block_size, use_kernel=use_kernel, topo=topo,
                lora=lb, adapter_ids=aid),
            donate_argnums=(9,)))
        # speculative verification: greedy ids for a static window of
        # fed positions from one fused continuation pass (prompt-lookup
        # decoding); one compiled program per window size
        self._continue_spec_jits: Dict[int, object] = {}

        def _spec_jit(window: int):
            if window not in self._continue_spec_jits:
                self._continue_spec_jits[window] = watchdog.watch(
                    f"spec_verify_w{window}", jax.jit(
                        lambda p, ids, s, n, c, b, o, t, lb, aid:
                        paged_continue(
                            cfg, p, ids, s, n, c, b, o, t, sm.block_size,
                            topo=topo, greedy_window=window, lora=lb,
                            adapter_ids=aid),
                        donate_argnums=(4,)))
            return self._continue_spec_jits[window]

        self._spec_jit = _spec_jit
        if config.kv_quant:
            # the capacity win, as a live gauge: pool bytes the int8
            # layout frees vs the same (num_blocks x block_size) pool at
            # the serving dtype
            unquant = 2 * (cfg.num_layers * sm.num_blocks * sm.block_size
                           * cfg.kv_heads * cfg.head_dim
                           * jnp.dtype(self.dtype).itemsize)
            quant = sum(int(np.prod(v.shape)) * v.dtype.itemsize
                        for v in self.kv_cache.values())
            self._m_kv_quant_saved.set(max(unquant - quant, 0))
        try:  # HBM accounting (telemetry/memory.py): the two big
            # long-lived buffers every decode program references
            ds_memory.record_buffer("kv_pool",
                                    ds_memory.tree_bytes(self.kv_cache))
            ds_memory.record_buffer("params",
                                    ds_memory.tree_bytes(self.params))
        except Exception:  # accounting must never block serving
            pass
        log_dist(
            f"ragged inference engine: blocks={sm.num_blocks}x"
            f"{sm.block_size} max_seqs={sm.max_tracked_sequences} tp={tp}"
            f" ep={ep}",
            ranks=[0])

    # ------------------------------------------------------------------
    # Telemetry (unified registry, telemetry/registry.py)
    # ------------------------------------------------------------------
    def _init_telemetry(self):
        from ...telemetry import get_registry
        reg = get_registry()
        self._m_prefill_tokens = reg.counter(
            "inference_prefill_tokens_total",
            "prompt tokens run through prefill/continuation passes")
        self._m_decode_tokens = reg.counter(
            "inference_decode_tokens_total",
            "tokens produced by batched decode steps")
        self._m_decode_steps = reg.counter(
            "inference_decode_steps_total", "batched decode passes")
        self._m_decode_time = reg.histogram(
            "inference_decode_step_seconds",
            "batched decode pass wall time", unit="s")
        self._m_decode_tput = reg.gauge(
            "inference_decode_tokens_per_s",
            "last decode pass throughput (batch tokens / wall time)")
        self._m_ttft = reg.histogram(
            "inference_ttft_seconds",
            "generate(): time to the first token batch", unit="s")
        self._m_kv_util = reg.gauge(
            "inference_kv_pool_utilization",
            "fraction of usable KV blocks currently allocated")
        self._m_kv_util_peak = reg.gauge(
            "inference_kv_pool_utilization_peak",
            "high-water mark of inference_kv_pool_utilization")
        self._m_tracked = reg.gauge(
            "inference_tracked_sequences", "sequences with live KV state")
        self._m_spec_drafted = reg.counter(
            "inference_spec_drafted_tokens_total",
            "speculative tokens drafted for verification")
        self._m_spec_accepted = reg.counter(
            "inference_spec_accepted_tokens_total",
            "speculative tokens accepted by greedy verification")
        self._m_spec_miss_rounds = reg.counter(
            "inference_spec_miss_rounds_total",
            "speculative rounds whose whole draft was rejected")
        self._m_spec_window_rounds = reg.counter(
            "inference_spec_window_rounds_total",
            "draft-model propose->verify->accept rounds run inside "
            "fused speculative decode windows (per-row, summed on "
            "device)")
        self._m_spec_mode_requests = reg.counter(
            "inference_spec_mode_requests_total",
            "speculative requests routed per speculation source",
            labelnames=("mode",))
        self._m_spec_switches = reg.counter(
            "inference_spec_chooser_switches_total",
            "speculation-source switches committed by the hysteresis "
            "chooser")
        self._m_spec_rate = reg.gauge(
            "inference_spec_accept_rate",
            "EMA accept rate (accepted/drafted) per speculation source",
            labelnames=("mode",))
        self._m_adapter_loads = reg.counter(
            "inference_lora_adapter_loads_total",
            "LoRA adapters (re)loaded into device bank slots")
        self._m_adapters_live = reg.gauge(
            "inference_lora_adapters_live",
            "adapter names currently resident in the device bank")
        self._m_window_size = reg.gauge(
            "inference_decode_window_size",
            "configured fused decode window K (1 = per-token decode)")
        self._m_host_syncs = reg.counter(
            "inference_decode_host_syncs_total",
            "device->host transfers made by the decode loop (one per "
            "per-token step, one per fused multi-step window)")
        self._m_fused_time = reg.histogram(
            "inference_fused_window_seconds",
            "fused multi-step decode window wall time", unit="s")
        self._m_ragged_steps = reg.counter(
            "inference_ragged_steps_total",
            "unified ragged steps run (mixed prefill+decode, one "
            "compiled program per step)")
        self._m_ragged_tokens = reg.counter(
            "inference_ragged_tokens_total",
            "valid tokens run through unified ragged steps")
        self._m_ragged_prefill_rows = reg.counter(
            "inference_ragged_prefill_rows_total",
            "ragged rows carrying prompt/continuation chunks")
        self._m_ragged_decode_rows = reg.counter(
            "inference_ragged_decode_rows_total",
            "ragged rows carrying a single decode token")
        self._m_ragged_time = reg.histogram(
            "inference_ragged_step_seconds",
            "unified ragged step wall time", unit="s")
        self._m_ragged_pad = reg.gauge(
            "inference_ragged_pad_fraction",
            "padding waste of the last ragged step's token bucket")
        self._m_ragged_host_syncs = reg.counter(
            "inference_ragged_host_syncs_total",
            "device->host transfers made by unified ragged steps (one "
            "per step)")
        self._m_kv_quant_saved = reg.gauge(
            "inference_kv_pool_quant_bytes_saved",
            "HBM the int8 KV pool frees vs the same pool at the serving "
            "dtype (0 when kv_quant is off) — the capacity headroom that "
            "admits ~2x concurrent sequences", unit="bytes")
        self._m_weight_swaps = reg.counter(
            "inference_weight_swaps_total",
            "live param hot-swaps applied to this engine (donated "
            "buffer replacement; zero recompiles by construction)")
        self._m_weight_swap_time = reg.histogram(
            "inference_weight_swap_seconds",
            "param hot-swap apply time (device_put of every leaf onto "
            "its existing sharding)", unit="s",
            buckets=(1e-4, 1e-3, 1e-2, 0.1, 1.0, 10.0))
        self._m_weight_version = reg.gauge(
            "serving_weight_version",
            "live weight version this engine serves (0 = the boot "
            "checkpoint; bumped by each hot-swap)")

    def _update_pool_telemetry(self):
        sm = self.state_manager
        usable = max(sm.config.num_blocks - 1, 1)  # block 0 is the null
        util = (usable - sm.free_blocks()) / usable
        self._m_kv_util.set(util)
        # the live gauge reads 0 between requests (flush returns blocks),
        # so pool-pressure tuning needs the high-water mark too
        if util > self._m_kv_util_peak.value:
            self._m_kv_util_peak.set(util)
        self._m_tracked.set(sm.tracked_sequences())

    # ------------------------------------------------------------------
    # Ragged mode (config_v2.ragged_attention: auto | on | off)
    # ------------------------------------------------------------------
    @staticmethod
    def _resolve_ragged_mode(mode: str) -> bool:
        if mode not in ("auto", "on", "off"):
            raise ValueError(
                f"ragged_attention must be 'auto', 'on' or 'off' "
                f"(got {mode!r})")
        # "auto" is on everywhere today: the unified program's jnp
        # fallback covers every config the ragged kernel gates off
        # (tp/ep, alibi), and quantized KV runs the kernel's quant
        # variant — there is no unsupported case
        return mode != "off"

    # ------------------------------------------------------------------
    # Fused decode window K: per-K jit cache + live adaptation
    # ------------------------------------------------------------------
    def _fused_pair(self, window: int):
        if window not in self._fused_jit_cache:
            self._fused_jit_cache[window] = self._build_fused_pair(window)
        return self._fused_jit_cache[window]

    def warmed_decode_windows(self):
        """Window sizes whose decode program has dispatched at least
        once (so its compiled program is cached for the buckets traffic
        actually uses) — the only K values the online adapter may move
        to at steady state."""
        return sorted(self._warmed_windows)

    def set_decode_window(self, window: int, *,
                          source: str = "online") -> int:
        """Switch the fused decode window K at runtime
        (autotuning/online.py actuates here; must be called from the
        thread that owns the engine). Swaps the per-K jit pair, so an
        already-warmed K never recompiles; a brand-new K compiles on
        its next dispatch like any cold program."""
        from ...runtime import tunables
        window = tunables.check("serving.decode_window", window,
                                label="decode_window")
        if window == self.decode_window:
            return window
        self._fused_greedy_jit, self._fused_sample_jit = \
            self._fused_pair(window)
        self.decode_window = window
        self.config.decode_window = window
        self._m_window_size.set(window)
        tunables.observe("serving.decode_window", window, source)
        flight.record("tunable_set", name="serving.decode_window",
                      value=window, source=source)
        return window

    def set_ragged_mode(self, mode: str) -> None:
        """Flip the ragged/stitched dispatch at runtime
        (ServingConfig.ragged_attention routes here). Compiled programs
        for both paths stay cached, so flipping never recompiles."""
        self.ragged_enabled = self._resolve_ragged_mode(mode)
        self.config.ragged_attention = mode

    # ------------------------------------------------------------------
    # Multi-tenant batched LoRA (config_v2.max_lora_adapters)
    # ------------------------------------------------------------------
    def load_adapter(self, name: str, adapters: Dict[str, tuple],
                     scale: float = 1.0) -> int:
        """Install a LoRA adapter into a device bank slot (hot-deploy:
        a same-shape ``.at[:, slot].set`` — no recompile, serving
        continues through the same programs).

        ``adapters`` is the hybrid engine's external-adapter payload
        convention (``runtime/hybrid_engine.py fuse_flat_leaves``):
        ``{"layers/wq": (a, b), "layers/wv": (a, b)}`` with a [L, h, r]
        and b [L, r, out]. ``scale`` folds into b at load time so the
        gathered per-row delta matches the fused-weight definition
        ``_fused_w``: w + scale * (a @ b). Ranks below the bank rank
        zero-pad (extra rank contributes exactly 0); larger ranks are a
        typed error. Re-loading a known name updates its slot in place
        (hot redeploy of a freshly trained adapter). Returns the slot."""
        if self.lora_bank is None:
            raise ValueError(
                "adapter bank disabled: set max_lora_adapters > 0 in "
                "RaggedInferenceEngineConfig")
        from ...models.transformer import lora_target_leaves
        cfg = self.model.cfg
        targets = lora_target_leaves(cfg)
        if set(adapters) != set(targets):
            raise ValueError(
                f"adapter {name!r} leaves {sorted(adapters)} != serving "
                f"targets {sorted(targets)} (q/v projections only)")
        R = self.config.lora_rank
        L = cfg.num_layers
        staged = {}
        for leaf, keys in (("layers/wq", ("qa", "qb")),
                           ("layers/wv", ("va", "vb"))):
            a, b = adapters[leaf]
            a = np.asarray(a, np.float32)
            b = np.asarray(b, np.float32)
            in_dim, out_dim = targets[leaf]
            if (a.ndim != 3 or b.ndim != 3 or a.shape[0] != L
                    or b.shape[0] != L or a.shape[1] != in_dim
                    or b.shape[2] != out_dim or a.shape[2] != b.shape[1]):
                raise ValueError(
                    f"adapter {name!r} leaf {leaf}: got a{a.shape} "
                    f"b{b.shape}, want a[{L},{in_dim},r] "
                    f"b[{L},r,{out_dim}]")
            r = a.shape[2]
            if r > R:
                raise ValueError(
                    f"adapter {name!r} rank {r} exceeds bank rank {R} "
                    f"(config_v2.lora_rank)")
            if r < R:   # zero-pad: the extra rank contributes exactly 0
                a = np.concatenate(
                    [a, np.zeros((L, in_dim, R - r), a.dtype)], axis=2)
                b = np.concatenate(
                    [b, np.zeros((L, R - r, out_dim), b.dtype)], axis=1)
            staged[keys] = (a, b * float(scale))
        slot = self._adapter_slots.get(name)
        if slot is None:
            used = set(self._adapter_slots.values())
            slot = next(
                (s for s in range(1, self.config.max_lora_adapters + 1)
                 if s not in used), None)
            if slot is None:
                raise RuntimeError(
                    f"adapter bank full "
                    f"({self.config.max_lora_adapters} slots); "
                    f"unload_adapter() one or raise max_lora_adapters")
        bank = self.lora_bank
        for (ka, kb), (a, b) in staged.items():
            bank[ka] = bank[ka].at[:, slot].set(jnp.asarray(a, self.dtype))
            bank[kb] = bank[kb].at[:, slot].set(jnp.asarray(b, self.dtype))
        self.lora_bank = bank
        self._adapter_slots[name] = slot
        self._m_adapter_loads.inc()
        self._m_adapters_live.set(len(self._adapter_slots))
        flight.record("adapter_load", name=str(name), slot=int(slot))
        return slot

    def unload_adapter(self, name: str) -> None:
        """Zero the adapter's slot (back to the base no-op delta) and
        free it for reuse; uids still routed to the name fall back to
        the base model."""
        slot = self._adapter_slots.pop(name, None)
        if slot is None:
            return
        bank = self.lora_bank
        for k in bank:
            bank[k] = bank[k].at[:, slot].set(
                jnp.zeros(bank[k].shape[2:], bank[k].dtype))
        self.lora_bank = bank
        self._uid_adapter = {u: n for u, n in self._uid_adapter.items()
                             if n != name}
        self._m_adapters_live.set(len(self._adapter_slots))

    def assign_adapter(self, uid: int, name: Optional[str]) -> int:
        """Route ``uid``'s tokens through a loaded adapter's bank slot
        (None/"" clears to the base slot 0). Typed failure at SUBMIT
        time when the adapter is unknown — not mid-batch on device."""
        uid = int(uid)
        if not name:
            self._uid_adapter.pop(uid, None)
            return 0
        if self.lora_bank is None:
            raise ValueError(
                f"adapter {name!r} requested but the bank is disabled "
                f"(max_lora_adapters=0)")
        slot = self._adapter_slots.get(name)
        if slot is None:
            raise KeyError(
                f"unknown adapter {name!r}: load_adapter() it first "
                f"(loaded: {sorted(self._adapter_slots)})")
        self._uid_adapter[uid] = str(name)
        seq = self.state_manager.seqs.get(uid)
        if seq is not None:
            seq.adapter = str(name)
            seq.adapter_slot = int(slot)
        return slot

    def adapter_of(self, uid: int) -> Optional[str]:
        """The adapter NAME serving ``uid`` (None = base). Names — not
        engine-local slot ints — are the identity prefix digests and
        router affinity key on, so they agree across replicas."""
        return self._uid_adapter.get(int(uid))

    def _adapter_slot_of(self, uid: int) -> int:
        name = self._uid_adapter.get(int(uid))
        if name is None:
            return 0
        return self._adapter_slots.get(name, 0)

    # ------------------------------------------------------------------
    # Schedulability (reference engine_v2.py:135 query / :161 can_schedule)
    # ------------------------------------------------------------------
    def query(self, uid: int) -> Dict[str, int]:
        seq = self.state_manager.seqs.get(uid)
        return {
            "seen_tokens": seq.seen_tokens if seq else 0,
            "free_blocks": self.state_manager.free_blocks(),
            "tracked_sequences": self.state_manager.tracked_sequences(),
            "max_seq_len": self.state_manager.config.max_seq_len,
        }

    def can_schedule(self, uids: Sequence[int],
                     lengths: Sequence[int]) -> bool:
        total_new = 0
        # retained prefix blocks are evictable on demand (ensure_blocks
        # evicts LRU) — counting only free blocks would spuriously
        # reject requests once the index occupies the pool
        free = self.state_manager.reclaimable_blocks()
        for uid, n in zip(uids, lengths):
            if not self.state_manager.can_schedule(uid, n):
                return False
            seq = self.state_manager.seqs.get(uid)
            if seq is not None:
                total_new += seq.blocks_needed(n, self.block_size)
            else:
                total_new += -(-n // self.block_size)
        return total_new <= free and \
            sum(lengths) <= self.state_manager.config.max_ragged_batch_size

    # ------------------------------------------------------------------
    # Bucketing (shared rules: utils/bucketing.py — the same helpers key
    # the RaggedBatch packer, so every layer buckets identically)
    # ------------------------------------------------------------------
    def _bucket(self, n: int) -> int:
        """Prefill chunk-length bucket (multiple of prefill_bucket,
        capped at the max_seq_len bucket)."""
        return ceil_bucket(n, self.config.prefill_bucket,
                           cap=self.state_manager.config.max_seq_len)

    def _prefill(self, uid: int, tokens: np.ndarray) -> np.ndarray:
        sm = self.state_manager
        n = len(tokens)
        seq = sm.ensure_blocks(uid, n)
        start = seq.seen_tokens
        assert start == 0, \
            "prompt continuation for an existing sequence must arrive " \
            "token-by-token (chunked prefill lands with the Pallas kernel)"
        C = self._bucket(n)
        ids = np.zeros((1, C), np.int32)
        ids[0, :n] = tokens
        # chunk position -> (block, slot); padding -> null block
        positions = np.arange(C)
        block_idx = positions // self.block_size
        offs = positions % self.block_size
        table = np.full(C, NULL_BLOCK, np.int32)
        valid = positions < n
        table[valid] = np.asarray(seq.blocks, np.int32)[block_idx[valid]]
        lb = self.lora_bank
        aid = (jnp.asarray(self._adapter_slot_of(uid), jnp.int32)
               if lb is not None else None)
        with trace.span("prefill", uid=int(uid), tokens=int(n),
                        **self._trace_attr(uid)):
            logits, self.kv_cache = self._prefill_jit(
                self.params, jnp.asarray(ids), jnp.asarray(n),
                self.kv_cache, jnp.asarray(table), jnp.asarray(offs),
                lb, aid)
        flight.record("prefill", uid=int(uid), tokens=int(n))
        seq.seen_tokens = n
        if sm.config.enable_prefix_caching:
            seq.token_log.extend(map(int, tokens))
        self._m_prefill_tokens.inc(n)
        self._update_pool_telemetry()
        return np.asarray(logits)

    def _continue(self, uid: int, tokens: np.ndarray,
                  all_logits: int = 0) -> np.ndarray:
        """Multi-token continuation in ONE compiled pass (replaces the
        token-at-a-time decode loop; reference chunked prefill).
        ``all_logits`` > 0 returns greedy ids for that many leading fed
        positions (speculative verification, device-side argmax, [w]
        int32 to host) instead of the last token's [V] logits."""
        sm = self.state_manager
        n = len(tokens)
        seq = sm.ensure_blocks(uid, n)
        start = seq.seen_tokens
        C = self._bucket(n)
        ids = np.zeros((1, C), np.int32)
        ids[0, :n] = tokens
        positions = start + np.arange(C)
        block_idx = positions // self.block_size
        offs = positions % self.block_size
        table = np.full(C, NULL_BLOCK, np.int32)
        valid = np.arange(C) < n
        seq_blocks = np.asarray(seq.blocks, np.int32)
        table[valid] = seq_blocks[block_idx[valid]]
        full_table = sm.block_table_for(uid)
        jit_fn = (self._spec_jit(all_logits) if all_logits
                  else self._continue_jit)
        lb = self.lora_bank
        aid = (jnp.asarray(self._adapter_slot_of(uid), jnp.int32)
               if lb is not None else None)
        with trace.span("continue", uid=int(uid), tokens=int(n),
                        spec=bool(all_logits), **self._trace_attr(uid)):
            logits, self.kv_cache = jit_fn(
                self.params, jnp.asarray(ids), jnp.asarray(start),
                jnp.asarray(n), self.kv_cache, jnp.asarray(table),
                jnp.asarray(offs), jnp.asarray(full_table), lb, aid)
        seq.seen_tokens = start + n
        if sm.config.enable_prefix_caching:
            seq.token_log.extend(map(int, tokens))
        if not all_logits:  # spec-verify feeds count via the spec counters
            self._m_prefill_tokens.inc(n)
        self._update_pool_telemetry()
        return np.asarray(logits)

    # -- speculative decoding (prompt-lookup) ---------------------------
    _SPEC_SCAN_WINDOW = 512   # bound the per-round host scan (the scan
    # is O(window); an unbounded history would make draft lookup
    # quadratic over a long generation)

    @staticmethod
    def _lookup_draft(history: List[int], k: int, ngram: int) -> List[int]:
        """Draft the k tokens that followed the most recent earlier
        occurrence of the history's trailing n-gram (prompt-lookup
        decoding: the sequence's own text is the draft model). Scans at
        most the last _SPEC_SCAN_WINDOW tokens.

        This right-to-left scan is the REFERENCE implementation (O(window
        * ngram) per round); the hot path uses the incremental
        NGramIndex (ngram_index.py, parity-tested against this)."""
        W = InferenceEngineV2._SPEC_SCAN_WINDOW
        base = max(0, len(history) - W)
        win = history[base:]
        for n in range(ngram, 1, -1):
            if len(win) <= n:
                continue
            tail = win[-n:]
            # scan right-to-left for the most recent earlier match
            for i in range(len(win) - n - 1, -1, -1):
                if win[i:i + n] == tail:
                    start = base + i + n
                    draft = history[start:start + k]
                    if draft:
                        return list(draft)
        return []

    def _speculative_step(self, uid: int, cur: int,
                          draft: List[int]) -> List[int]:
        """Feed [cur] + draft through one fused continuation, accept the
        longest greedily-verified draft prefix, roll the cache position
        back over rejected tokens, and return the emitted tokens
        (1 + accepted; the last emitted token is NOT yet in the cache —
        same invariant as the normal decode loop).

        Rollback is a host-side counter reset: attention masks by
        position (ctx_pos <= pos), so the rejected tokens' stale KV
        slots are never attended and the next write overwrites them."""
        sm = self.state_manager
        seq = sm.seqs[uid]
        fed = [int(cur)] + list(map(int, draft))
        start = seq.seen_tokens
        greedy = self._continue(uid, np.asarray(fed, np.int64),
                                all_logits=len(fed))
        emitted = [int(greedy[0])]
        accepted = 0
        for j, d in enumerate(draft):
            if int(d) != emitted[-1]:
                break
            accepted += 1
            emitted.append(int(greedy[j + 1]))
        # rewind over the rejected fed tokens (cur + accepted stay)
        seq.seen_tokens = start + 1 + accepted
        if sm.config.enable_prefix_caching:
            rejected = len(fed) - 1 - accepted
            if rejected:
                del seq.token_log[-rejected:]
        return emitted

    def _speculative_round(self, step_uids, outs, row_of, prompt_lens,
                           live, max_new_tokens, eos_token_id,
                           spec_k, spec_ngram) -> Dict[int, int]:
        """One greedy round with prompt-lookup speculation: per uid,
        draft from its own history and verify in one fused pass. The
        accepted extras append to ``outs`` here (with per-token
        eos/budget checks); the final emitted token becomes the round's
        ``cur`` — the last-token-never-fed invariant the plain loop
        keeps. Sequences without a usable draft fall back to the normal
        batched greedy decode."""
        cur: Dict[int, int] = {}
        plain_uids: List[int] = []
        sm = self.state_manager
        for uid in step_uids:
            row = outs[row_of[uid]]
            remaining = max_new_tokens - (len(row) - prompt_lens[uid])
            # draft length budget: the generation budget, the sequence
            # length limit (1+k fed tokens must fit max_seq_len — the
            # loop's guard only covered 1), and a cold-streak cutoff
            # (natural text with recurring n-grams but divergent
            # continuations would otherwise pay a rejected verify pass
            # every round, slower than plain batched greedy)
            seq_room = sm.config.max_seq_len - sm.seqs[uid].seen_tokens - 1
            k = min(spec_k, remaining - 1, seq_room)
            if k > 0 and self._spec_miss_streak.get(uid, 0) < 3:
                idx = self._draft_index.get(uid)
                if idx is None:
                    from .ngram_index import NGramIndex
                    idx = self._draft_index[uid] = NGramIndex(
                        spec_ngram, self._SPEC_SCAN_WINDOW)
                idx.sync(row)
                draft = idx.draft(k, spec_ngram)
            else:
                draft = []
            if draft and not self.can_schedule([uid], [1 + len(draft)]):
                draft = []
            if not draft:
                plain_uids.append(uid)
                continue
            emitted = self._speculative_step(uid, row[-1], draft)
            self._m_spec_drafted.inc(len(draft))
            self._m_spec_accepted.inc(len(emitted) - 1)
            self.spec_chooser.observe("ngram", len(draft),
                                      len(emitted) - 1)
            if len(emitted) == 1:
                self._m_spec_miss_rounds.inc()
                self._spec_miss_streak[uid] = \
                    self._spec_miss_streak.get(uid, 0) + 1
            else:
                self._spec_miss_streak[uid] = 0
            finished = False
            for tok in emitted[:-1]:
                row.append(tok)
                if ((eos_token_id is not None and tok == eos_token_id)
                        or len(row) - prompt_lens[uid] >= max_new_tokens):
                    finished = True
                    break
            if finished:
                live.discard(uid)
            else:
                cur[uid] = emitted[-1]
        if plain_uids:
            cur.update(self._decode_batch_greedy(
                plain_uids, [outs[row_of[u]][-1] for u in plain_uids]))
        self._observe_spec_rates()
        return cur

    # -- draft-model speculation (in-window propose->verify->accept) ----
    def load_draft_model(self, model, params=None) -> None:
        """Attach a small draft model for in-window speculative
        decoding. The draft shares the TARGET's block tables against its
        own paged KV pool (same num_blocks x block_size geometry), so
        the fused spec window (``paged_spec_decode_window``) needs no
        extra table plumbing and rollback stays free. Raises the typed
        :class:`DraftModelMismatchError` when the draft cannot
        verify-share with the target. ``params`` defaults to a fresh
        init (tests); production passes the trained draft weights."""
        dcfg = model.cfg
        cfg = self.model.cfg
        if dcfg.vocab_size != cfg.vocab_size:
            raise DraftModelMismatchError(
                f"draft vocab_size {dcfg.vocab_size} != target "
                f"{cfg.vocab_size}: greedy verification compares raw "
                f"token ids, so the vocabularies must be the same id "
                f"space")
        sm = self.state_manager
        if dcfg.max_seq_len < sm.config.max_seq_len:
            raise DraftModelMismatchError(
                f"draft max_seq_len {dcfg.max_seq_len} < serving "
                f"max_seq_len {sm.config.max_seq_len}: the draft must "
                f"decode at every position the target serves")
        self.draft_model = model
        self._draft_cfg = dcfg
        if params is not None:
            self.draft_params = jax.jit(lambda p: jax.tree.map(
                lambda x: jnp.asarray(x, self.dtype), p))(params)
        else:
            self.draft_params = jax.jit(lambda p: jax.tree.map(
                lambda x: x.astype(self.dtype), model.init_params(p)))(
                jax.random.PRNGKey(self.config.seed + 1))
        self.draft_cache = init_paged_kv_cache(
            dcfg, sm.config.num_blocks, sm.block_size, self.dtype)
        self._draft_seen.clear()
        self._spec_window_jits.clear()
        # draft catch-up: one fused continuation over the DRAFT pool,
        # replaying history the target built through non-draft paths
        # (prefill, plain decode, n-gram rounds) before a uid's first
        # spec window
        bs = self.block_size
        self._draft_continue_jit = watchdog.watch(
            "draft_catchup", jax.jit(
                lambda p, ids, s, n, c, b, o, t: paged_continue(
                    dcfg, p, ids, s, n, c, b, o, t, bs, topo=None),
                donate_argnums=(4,)))
        try:
            ds_memory.record_buffer(
                "draft_params", ds_memory.tree_bytes(self.draft_params))
            ds_memory.record_buffer(
                "draft_kv_pool", ds_memory.tree_bytes(self.draft_cache))
        except Exception:   # accounting must never block serving
            pass
        log_dist(
            f"draft model attached: layers={dcfg.num_layers} "
            f"hidden={dcfg.hidden_size} (target hidden="
            f"{cfg.hidden_size})", ranks=[0])

    def _spec_window_jit(self, window: int, spec_k: int):
        """Per-(window, spec_k) fused speculative window program — like
        the per-K plain-window cache, both constants are baked into the
        compiled loop, so per-request draft lengths ride a bounded jit
        cache instead of growing it. One watchdog name for all sizes."""
        key = (int(window), int(spec_k))
        if key not in self._spec_window_jits:
            cfg = self.model.cfg
            dcfg = self._draft_cfg
            bs = self.block_size
            uk, topo = self._use_kernel, self._topo
            self._spec_window_jits[key] = watchdog.watch(
                "spec_decode_window", jax.jit(
                    lambda p, dp, t, pos, bt, c, dc, sl, eos, lb, aid,
                    _K=window, _k=spec_k: paged_spec_decode_window(
                        cfg, dcfg, p, dp, t, pos, bt, c, dc, sl, eos,
                        bs, _K, _k, use_kernel=uk, topo=topo,
                        lora=lb, adapter_ids=aid),
                    donate_argnums=(5, 6)))
        return self._spec_window_jits[key]

    def _draft_catchup(self, uid: int, row: List[int]) -> None:
        """Bring the draft KV pool level with the target's cache for
        ``uid``: feed the fed-token suffix the draft has not seen
        (``row[:seen_tokens]`` is exactly the fed history — the last
        emitted token is never fed, the loop invariant). No-op when the
        draft is already level (consecutive spec windows)."""
        sm = self.state_manager
        seq = sm.seqs[uid]
        seen = seq.seen_tokens
        d0 = self._draft_seen.get(uid, 0)
        if d0 >= seen:
            return
        toks = np.asarray(row[d0:seen], np.int64)
        n = len(toks)
        C = self._bucket(n)
        ids = np.zeros((1, C), np.int32)
        ids[0, :n] = toks
        positions = d0 + np.arange(C)
        block_idx = positions // self.block_size
        offs = positions % self.block_size
        table = np.full(C, NULL_BLOCK, np.int32)
        valid = np.arange(C) < n
        seq_blocks = np.asarray(seq.blocks, np.int32)
        table[valid] = seq_blocks[block_idx[valid]]
        full_table = sm.block_table_for(uid)
        with trace.span("draft_catchup", uid=int(uid), tokens=int(n),
                        **self._trace_attr(uid)):
            _, self.draft_cache = self._draft_continue_jit(
                self.draft_params, jnp.asarray(ids), jnp.asarray(d0),
                jnp.asarray(n), self.draft_cache, jnp.asarray(table),
                jnp.asarray(offs), jnp.asarray(full_table))
        self._draft_seen[uid] = seen

    def _observe_spec_rates(self) -> None:
        """Publish the chooser's per-source accept-rate EMAs and any
        newly committed route switches."""
        for mode in ("ngram", "draft"):
            r = self.spec_chooser.rate.get(mode)
            if r is not None:
                self._m_spec_rate.labels(mode=mode).set(r)
        d = self.spec_chooser.switches - self._spec_switches_seen
        if d > 0:
            self._m_spec_switches.inc(d)
            self._spec_switches_seen = self.spec_chooser.switches

    def _spec_window_round(self, step_uids, outs, row_of, prompt_lens,
                           live, max_new_tokens, eos_token_id,
                           spec_k) -> Dict[int, int]:
        """One fused draft-model speculative window per batch:
        propose(k) -> target-verify -> accept-prefix loops ON DEVICE
        (``paged_spec_decode_window``) — speculation adds zero host
        round-trips on top of the window's single [N, K] transfer.
        Rows without the sequence room / KV blocks the widened
        pre-allocation contract needs (``steps_left + spec_k`` writes)
        fall back to the plain batched greedy step."""
        sm = self.state_manager
        K = max(self.decode_window, spec_k + 1)
        spec_uids: List[int] = []
        plain_uids: List[int] = []
        sl: List[int] = []
        for uid in step_uids:
            row = outs[row_of[uid]]
            remaining = max_new_tokens - (len(row) - prompt_lens[uid])
            room = (sm.config.max_seq_len - sm.seqs[uid].seen_tokens
                    - spec_k)
            s = min(K, remaining, room)
            if s < 1 or not self.can_schedule([uid], [s + spec_k]):
                plain_uids.append(uid)
                continue
            spec_uids.append(uid)
            sl.append(s)
        cur: Dict[int, int] = {}
        if plain_uids:
            cur.update(self._decode_batch_greedy(
                plain_uids, [outs[row_of[u]][-1] for u in plain_uids]))
        if not spec_uids:
            return cur
        for uid in spec_uids:
            self._draft_catchup(uid, outs[row_of[uid]])
        tokens = [outs[row_of[u]][-1] for u in spec_uids]
        t0 = time.perf_counter()
        with trace.span("spec_decode_window", batch=len(spec_uids),
                        window=K, spec_k=spec_k,
                        uids=[int(u) for u in spec_uids],
                        **self._trace_attrs(spec_uids)):
            # widened pre-allocation contract: row i may write KV at
            # positions pos..pos+sl[i]+spec_k-1 (the final round's
            # unaccepted tail), so those blocks exist BEFORE dispatch
            N, toks, pos, tables = self._assemble_decode_rows(
                spec_uids, tokens, [s + spec_k for s in sl])
            eos = np.full(N, -1, np.int32)
            eos[:len(spec_uids)] = (
                -1 if eos_token_id is None else int(eos_token_id))
            lb = self.lora_bank
            aid = (self._pad_i32(N, [self._adapter_slot_of(u)
                                     for u in spec_uids])
                   if lb is not None else None)
            out, stats, self.kv_cache, self.draft_cache = \
                self._spec_window_jit(K, spec_k)(
                    self.params, self.draft_params, jnp.asarray(toks),
                    jnp.asarray(pos), jnp.asarray(tables),
                    self.kv_cache, self.draft_cache,
                    self._pad_i32(N, sl), jnp.asarray(eos), lb, aid)
            out = np.asarray(out)   # one transfer for the whole window
            stats = np.asarray(stats)
        self._m_host_syncs.inc()
        dt = time.perf_counter() - t0
        drafted, accepted, miss, rounds = (int(x) for x in stats)
        self._m_spec_drafted.inc(drafted)
        self._m_spec_accepted.inc(accepted)
        self._m_spec_miss_rounds.inc(miss)
        self._m_spec_window_rounds.inc(rounds)
        self.spec_chooser.observe("draft", drafted, accepted)
        self._observe_spec_rates()
        log_tokens = sm.config.enable_prefix_caching
        total = 0
        for i, uid in enumerate(spec_uids):
            out_row = out[i]
            e = int((out_row >= 0).sum())   # emissions are a prefix
            toks_out = [int(t) for t in out_row[:e]]
            seq = sm.seqs[uid]
            seq.seen_tokens += e
            # accepted draft tokens ARE the canonical stream, so the
            # draft cache is level with the target after the window
            self._draft_seen[uid] = seq.seen_tokens
            if log_tokens:
                seq.token_log.extend([int(tokens[i])] + toks_out[:-1])
            total += e
            row = outs[row_of[uid]]
            finished = False
            # all but the last emit are fed/cached already; the host
            # re-applies the eos/budget cuts (defensively — the device
            # enforced them too), same fold-back as the plain window
            for tok in toks_out[:-1]:
                row.append(tok)
                if ((eos_token_id is not None and tok == eos_token_id)
                        or len(row) - prompt_lens[uid] >= max_new_tokens):
                    finished = True
                    break
            if finished or not toks_out:
                live.discard(uid)
            else:
                cur[uid] = toks_out[-1]
        self._m_decode_steps.inc()
        self._m_decode_tokens.inc(total)
        self._m_decode_time.observe(dt)
        self._m_fused_time.observe(dt)
        if dt > 0:
            self._m_decode_tput.set(total / dt)
        flight.record("spec_decode_window", batch=len(spec_uids),
                      tokens=total, window=K, spec_k=spec_k,
                      drafted=drafted, accepted=accepted,
                      dur_s=round(dt, 5))
        self._update_pool_telemetry()
        return cur

    # next power-of-two >= count, capped (one compiled program per
    # bucket keeps the jit-cache size logarithmic in the range); the
    # shared utils/bucketing rule, kept as a static method for the
    # existing call sites
    _pow2_bucket = staticmethod(pow2_bucket)

    def _decode_bucket(self, count: int) -> int:
        """Pad the decode batch to the next power-of-two bucket instead of
        always the tracked-sequence cap (one compiled program per bucket);
        fixes the fixed-cap padding waste (round-2 Weak #6)."""
        return pow2_bucket(
            count, self.state_manager.config.max_tracked_sequences)

    @staticmethod
    def _pad_i32(N: int, vals) -> jnp.ndarray:
        """[N] int32 with ``vals`` in the leading rows, zeros as padding."""
        out = np.zeros(N, np.int32)
        out[:len(vals)] = vals
        return jnp.asarray(out)

    def _assemble_decode_rows(self, uids: List[int], tokens: List[int],
                              new_tokens: List[int]):
        """Shared decode-batch assembly (per-token step AND fused
        window): pad rows to the power-of-two batch bucket, allocate
        each row's blocks for the ``new_tokens[i]`` KV writes it will
        make, and slice tables to the used-page bucket. The decode
        program's cost scales with table width (the BlockSpec-pipelined
        kernel streams EVERY table slot, and the gather fallback
        materializes [N, MB*bs, ...]), so a 128-token sequence in a
        2048-token-wide table would pay 16x the bandwidth."""
        sm = self.state_manager
        N = self._decode_bucket(len(uids))
        MB = sm.max_blocks_per_seq
        toks = np.zeros(N, np.int32)
        pos = np.zeros(N, np.int32)
        tables = np.full((N, MB), NULL_BLOCK, np.int32)
        used_pages = 1
        for i, (uid, tok, k) in enumerate(zip(uids, tokens, new_tokens)):
            seq = sm.ensure_blocks(uid, int(k))
            toks[i] = tok
            pos[i] = seq.seen_tokens
            tables[i] = sm.block_table_for(uid)
            used_pages = max(used_pages, len(seq.blocks))
        tables = tables[:, :self._pow2_bucket(used_pages, MB)]
        return N, toks, pos, tables

    def _build_decode_inputs(self, uids: List[int], tokens: List[int]):
        N, toks, pos, tables = self._assemble_decode_rows(
            uids, tokens, [1] * len(uids))
        active = np.zeros(N, bool)
        active[:len(uids)] = True
        return (jnp.asarray(toks), jnp.asarray(pos), jnp.asarray(tables),
                jnp.asarray(active))

    def _decode_common(self, uids: List[int], tokens: List[int], jit_fn,
                       extract) -> Dict[int, object]:
        sm = self.state_manager
        t0 = time.perf_counter()
        with trace.span("decode_step", batch=len(uids),
                        uids=[int(u) for u in uids],
                        **self._trace_attrs(uids)):
            toks, pos, tables, active = self._build_decode_inputs(uids,
                                                                  tokens)
            lb = self.lora_bank
            aid = (self._pad_i32(active.shape[0],
                                 [self._adapter_slot_of(u) for u in uids])
                   if lb is not None else None)
            vals, self.kv_cache = jit_fn(
                self.params, toks, pos, tables, self.kv_cache, active,
                lb, aid)
            vals = np.asarray(vals)  # blocks: the pass completes here
        self._m_host_syncs.inc()
        dt = time.perf_counter() - t0
        self._m_decode_steps.inc()
        self._m_decode_tokens.inc(len(uids))
        self._m_decode_time.observe(dt)
        if dt > 0:
            self._m_decode_tput.set(len(uids) / dt)
        flight.record("decode_step", batch=len(uids),
                      dur_s=round(dt, 5))
        self._warmed_windows.add(1)   # per-token path == window 1
        log_tokens = sm.config.enable_prefix_caching
        out = {}
        for i, uid in enumerate(uids):
            seq = sm.seqs[uid]
            seq.seen_tokens += 1
            if log_tokens:
                seq.token_log.append(int(tokens[i]))
            out[uid] = extract(vals, i)
        self._update_pool_telemetry()
        return out

    def _decode_batch(self, uids: List[int],
                      tokens: List[int]) -> Dict[int, np.ndarray]:
        return self._decode_common(uids, tokens, self._decode_jit,
                                   lambda v, i: v[i])

    def _decode_batch_greedy(self, uids: List[int],
                             tokens: List[int]) -> Dict[int, int]:
        """Greedy decode step returning next TOKENS (device argmax): the
        generate() hot loop's [N] int transfer instead of [N, vocab]."""
        return self._decode_common(uids, tokens, self._decode_tok_jit,
                                   lambda v, i: int(v[i]))

    def _sampling_arrays(self, N: int, row_seeds: List[int],
                         gen_idx: List[int], temperature: float,
                         top_p: float, top_k: int):
        """Padded per-row sampling inputs shared by the per-token and
        fused-window sampled paths (keeping them one definition is part
        of the bit-identical-streams guarantee)."""
        return (self._pad_i32(N, row_seeds), self._pad_i32(N, gen_idx),
                jnp.full((N,), temperature, jnp.float32),
                jnp.full((N,), top_p, jnp.float32),
                jnp.full((N,), top_k, jnp.int32))

    def _decode_batch_sample(self, uids: List[int], tokens: List[int],
                             rng, row_seeds: List[int],
                             gen_idx: List[int], temperature: float,
                             top_p: float,
                             top_k: int = 0) -> Dict[int, int]:
        """Sampled decode step (device-side temperature/top-p/top-k with
        per-row keys — see sampling.fold_in_rows)."""
        seeds, g0, temp, topp, topk = self._sampling_arrays(
            self._decode_bucket(len(uids)), row_seeds, gen_idx,
            temperature, top_p, top_k)
        return self._decode_common(
            uids, tokens,
            lambda p, t, pos, bt, c, a, lb, aid: self._decode_sample_jit(
                p, t, pos, bt, c, a, rng, seeds, g0, temp, topp, topk,
                lb, aid),
            lambda v, i: int(v[i]))

    # -- fused multi-token decode window --------------------------------
    def _decode_window_common(self, uids: List[int], tokens: List[int],
                              steps_left: List[int], eos_ids: List[int],
                              run) -> Dict[int, List[int]]:
        """Run one fused window and fold the [N, K] result back into
        host state. Returns {uid: emitted tokens} (1..steps_left[i] each;
        the row's last emitted token is never fed/cached — the same
        invariant as the per-token loop)."""
        sm = self.state_manager
        t0 = time.perf_counter()
        with trace.span("decode_window", batch=len(uids),
                        window=self.decode_window,
                        uids=[int(u) for u in uids],
                        **self._trace_attrs(uids)):
            # block pre-allocation contract: every block row i can write
            # during its steps_left[i] steps is allocated HERE, so the
            # device loop never needs the host mid-window (block-table
            # advancement is position arithmetic over a complete table)
            N, toks, pos, tables = self._assemble_decode_rows(
                uids, tokens, steps_left)
            eos = np.full(N, -1, np.int32)
            eos[:len(uids)] = eos_ids
            lb = self.lora_bank
            aid = (self._pad_i32(N, [self._adapter_slot_of(u)
                                     for u in uids])
                   if lb is not None else None)
            out, self.kv_cache = run(
                jnp.asarray(toks), jnp.asarray(pos), jnp.asarray(tables),
                self._pad_i32(N, steps_left), jnp.asarray(eos), lb, aid)
            out = np.asarray(out)   # ONE transfer for the whole window
        self._m_host_syncs.inc()
        dt = time.perf_counter() - t0
        log_tokens = sm.config.enable_prefix_caching
        emitted: Dict[int, List[int]] = {}
        total = 0
        for i, uid in enumerate(uids):
            row = out[i]
            e = int((row >= 0).sum())   # active steps are a prefix
            toks_out = [int(t) for t in row[:e]]
            seq = sm.seqs[uid]
            seq.seen_tokens += e        # e tokens were fed and cached
            if log_tokens:
                # fed tokens: the input token plus all but the last emit
                seq.token_log.extend([int(tokens[i])] + toks_out[:-1])
            emitted[uid] = toks_out
            total += e
        self._m_decode_steps.inc()
        self._m_decode_tokens.inc(total)
        self._m_decode_time.observe(dt)
        self._m_fused_time.observe(dt)
        if dt > 0:
            self._m_decode_tput.set(total / dt)
        flight.record("decode_window", batch=len(uids), tokens=total,
                      window=self.decode_window, dur_s=round(dt, 5))
        self._warmed_windows.add(self.decode_window)
        self._update_pool_telemetry()
        return emitted

    def _decode_window_greedy(self, uids: List[int], tokens: List[int],
                              steps_left: List[int],
                              eos_ids: List[int]) -> Dict[int, List[int]]:
        return self._decode_window_common(
            uids, tokens, steps_left, eos_ids,
            lambda t, pos, bt, sl, eos, lb, aid: self._fused_greedy_jit(
                self.params, t, pos, bt, self.kv_cache, sl, eos, lb, aid))

    def _decode_window_sample(self, uids: List[int], tokens: List[int],
                              steps_left: List[int], eos_ids: List[int],
                              rng, row_seeds: List[int],
                              gen_idx0: List[int], temperature: float,
                              top_p: float,
                              top_k: int = 0) -> Dict[int, List[int]]:
        seeds, g0, temp, topp, topk = self._sampling_arrays(
            self._decode_bucket(len(uids)), row_seeds, gen_idx0,
            temperature, top_p, top_k)
        return self._decode_window_common(
            uids, tokens, steps_left, eos_ids,
            lambda t, pos, bt, sl, eos, lb, aid: self._fused_sample_jit(
                self.params, t, pos, bt, self.kv_cache, sl, eos, rng,
                seeds, g0, temp, topp, topk, lb, aid))

    def _window_steps_left(self, step_uids: List[int],
                           remaining: List[int]) -> List[int]:
        """Per-row step budgets for one window: the generation budget,
        the sequence-length room, and — when the KV pool is too tight for
        the full window everywhere — a halving cap so the window shrinks
        instead of failing (cap 1 is always schedulable: the caller
        already ran the per-token can_schedule guard).

        The halving checks ONLY the KV block pool. can_schedule's other
        term — sum(lengths) <= max_ragged_batch_size — is the put()
        prefill cap (one pass over that many tokens); a window is K
        sequential steps of at most N tokens each, so a large decode
        batch times K must not shrink the window against it."""
        sm = self.state_manager
        K = self.decode_window
        sl = [max(1, min(K, r,
                         sm.config.max_seq_len
                         - sm.seqs[u].seen_tokens))
              for u, r in zip(step_uids, remaining)]

        def blocks_ok(lengths):
            need = sum(sm.seqs[u].blocks_needed(n, self.block_size)
                       for u, n in zip(step_uids, lengths))
            return need <= sm.reclaimable_blocks()

        cap = K
        while cap > 1 and not blocks_ok([min(cap, s) for s in sl]):
            cap //= 2
        return [min(cap, s) for s in sl]

    # -- ragged unified step --------------------------------------------
    def step_ragged(self, batch_uids: Sequence[int],
                    batch_tokens: Sequence[Iterable[int]]) -> np.ndarray:
        """One compiled launch for a MIXED batch: prompt chunks,
        continuations and decode rows pack into a single
        :class:`~.ragged.batch.RaggedBatch` and run through the unified
        ragged program (paged_model.paged_ragged_step) — the dispatch
        put() previously sequenced through the prefill / continue /
        decode program families. Same contract as put(): returns
        [len(batch_uids), vocab] last-token logits per entry."""
        sm = self.state_manager
        entries = [(int(uid), np.atleast_1d(np.asarray(toks, np.int64)))
                   for uid, toks in zip(batch_uids, batch_tokens)]
        if not self.can_schedule([u for u, _ in entries],
                                 [len(t) for _, t in entries]):
            raise RuntimeError(
                "batch not schedulable (KV blocks / sequence budget); "
                "check can_schedule()/query() before put()")
        for i, (uid, toks) in enumerate(entries):
            if not sm.known_seq(uid) and len(toks) > 1:
                # prefix caching: shared full blocks shorten the row to
                # its unseen suffix (same as the stitched put()).
                # Adapter-keyed: a LoRA row's v-projection KV differs
                # from the base model's, so prefixes only share within
                # one adapter identity (the NAME — stable across
                # replicas, unlike engine-local slot ints)
                _, n_reused = sm.match_prefix(
                    uid, toks, adapter=self._uid_adapter.get(int(uid)))
                if n_reused:
                    entries[i] = (uid, toks[n_reused:])
        # classify rows BEFORE packing mutates allocation state: a
        # decode row is one token for a sequence with cached history
        decode_rows = sum(
            1 for uid, toks in entries
            if len(toks) == 1 and sm.known_seq(uid)
            and sm.seqs[uid].seen_tokens > 0)
        if self.lora_bank is not None:
            # stamp each row's adapter identity into its descriptor so
            # the packer carries the per-row bank slots in the ragged
            # layout (and flush-time prefix registration keys on it)
            for uid, _ in entries:
                seq = sm.get_or_create_sequence(uid)
                seq.adapter = self._uid_adapter.get(int(uid))
                seq.adapter_slot = self._adapter_slot_of(uid)
        t0 = time.perf_counter()
        rb = ragged_batch.pack(entries, sm)
        with trace.span("ragged_step", rows=len(entries),
                        tokens=rb.total_tokens,
                        uids=[u for u, _ in entries],
                        **self._trace_attrs(u for u, _ in entries)):
            logits, self.kv_cache = self._ragged_jit(
                self.params, jnp.asarray(rb.ids),
                jnp.asarray(rb.row_ids), jnp.asarray(rb.positions),
                jnp.asarray(rb.lengths), jnp.asarray(rb.write_blocks),
                jnp.asarray(rb.write_offsets),
                jnp.asarray(rb.block_tables),
                jnp.asarray(rb.last_index), self.kv_cache,
                self.lora_bank,
                (jnp.asarray(rb.adapter_slots)
                 if self.lora_bank is not None else None))
            logits = np.asarray(logits)  # blocks: the pass completes here
        dt = time.perf_counter() - t0
        log_tokens = sm.config.enable_prefix_caching
        for uid, toks in entries:
            seq = sm.seqs[uid]
            seq.seen_tokens += len(toks)
            if log_tokens:
                seq.token_log.extend(map(int, toks))
        chunk_tokens = rb.total_tokens - decode_rows
        self._m_ragged_steps.inc()
        self._m_ragged_tokens.inc(rb.total_tokens)
        self._m_ragged_prefill_rows.inc(len(entries) - decode_rows)
        self._m_ragged_decode_rows.inc(decode_rows)
        self._m_ragged_time.observe(dt)
        self._m_ragged_pad.set(rb.pad_fraction)
        self._m_ragged_host_syncs.inc()
        # the family counters stay comparable across ragged/stitched:
        # chunk tokens are prefill work wherever they run
        if chunk_tokens:
            self._m_prefill_tokens.inc(chunk_tokens)
        flight.record("ragged_step", rows=len(entries),
                      tokens=rb.total_tokens, bucket=rb.token_bucket,
                      dur_s=round(dt, 5))
        self._update_pool_telemetry()
        return logits[:len(entries)]

    def put(self, batch_uids: Sequence[int],
            batch_tokens: Sequence[Iterable[int]]) -> np.ndarray:
        """Reference engine_v2.put: returns [len(batch_uids), vocab] logits
        for the last token of each entry. With ragged attention enabled
        (config_v2.ragged_attention) the whole batch runs as ONE unified
        ragged launch; otherwise the stitched dispatch below sequences
        prefills, continuations and the batched decode."""
        if self.ragged_enabled:
            return self.step_ragged(batch_uids, batch_tokens)
        sm = self.state_manager
        entries = [(int(uid), np.atleast_1d(np.asarray(toks, np.int64)))
                   for uid, toks in zip(batch_uids, batch_tokens)]
        if not self.can_schedule([u for u, _ in entries],
                                 [len(t) for _, t in entries]):
            raise RuntimeError(
                "batch not schedulable (KV blocks / sequence budget); "
                "check can_schedule()/query() before put()")
        results: Dict[int, np.ndarray] = {}
        decode_uids: List[int] = []
        decode_toks: List[int] = []
        for i, (uid, toks) in enumerate(entries):
            if not sm.known_seq(uid) and len(toks) > 1:
                # prefix caching: shared full blocks make this uid a
                # KNOWN sequence whose suffix continues below
                # (adapter-keyed — see step_ragged)
                _, n_reused = sm.match_prefix(
                    uid, toks, adapter=self._uid_adapter.get(int(uid)))
                if n_reused:
                    toks = toks[n_reused:]
                    entries[i] = (uid, toks)
            known = sm.known_seq(uid) and sm.seqs[uid].seen_tokens > 0
            if not known and len(toks) >= 1:
                results[uid] = self._prefill(uid, toks)
            elif len(toks) == 1:
                decode_uids.append(uid)
                decode_toks.append(int(toks[0]))
            else:
                # multi-token continuation: one fused chunked pass
                results[uid] = self._continue(uid, toks)
        if decode_uids:
            for chunk_start in range(0, len(decode_uids),
                                     sm.config.max_tracked_sequences):
                chunk_u = decode_uids[chunk_start:chunk_start
                                      + sm.config.max_tracked_sequences]
                chunk_t = decode_toks[chunk_start:chunk_start
                                      + sm.config.max_tracked_sequences]
                results.update(self._decode_batch(chunk_u, chunk_t))
        return np.stack([results[uid] for uid, _ in entries])

    # -- weight hot-swap (serve/weights.py) -----------------------------
    def note_weight_swap(self, seconds: float) -> None:
        """Book-keeping after ``swap_engine_params`` replaced
        ``self.params``: telemetry, flight event, and the params-buffer
        HBM accounting (the swapped tree may differ in dtype bytes only
        if the publisher changed — record the live truth)."""
        self._m_weight_swaps.inc()
        self._m_weight_swap_time.observe(seconds)
        self._m_weight_version.set(self.weight_version)
        flight.record("weight_swap", version=int(self.weight_version),
                      dur_s=round(float(seconds), 5))
        try:
            ds_memory.record_buffer("params",
                                    ds_memory.tree_bytes(self.params))
        except Exception:   # accounting must never block serving
            pass

    def swap_params(self, flat_leaves, version: int) -> None:
        """Install published weight leaves (``{path: fp32 ndarray}``) by
        donated buffer replacement — see serve/weights.py
        ``swap_engine_params`` (this is the method form the serving
        runtime and the hybrid engine call)."""
        from .serve import weights as serve_weights
        serve_weights.swap_engine_params(self, flat_leaves, version)

    # -- distributed tracing (telemetry/context.py) ---------------------
    def bind_trace(self, uid: int, trace_id: str) -> None:
        """Correlate ``uid``'s engine spans with a distributed trace:
        until flush(uid), every span that serves the uid carries the
        trace id (single-request spans as ``trace_id``, batch spans as
        a ``trace_ids`` list) — the stitched fleet timeline selects on
        it (timeline.trace_spans)."""
        self._uid_traces[int(uid)] = str(trace_id)

    def _trace_attr(self, uid: int) -> Dict[str, str]:
        tid = self._uid_traces.get(int(uid))
        return {"trace_id": tid} if tid is not None else {}

    def _trace_attrs(self, uids) -> Dict[str, List[str]]:
        seen: List[str] = []
        for u in uids:
            tid = self._uid_traces.get(int(u))
            if tid is not None and tid not in seen:
                seen.append(tid)
        return {"trace_ids": seen} if seen else {}

    def flush(self, uid: int) -> None:
        """Release a finished sequence's KV blocks (reference flush).
        Also forgets the uid's speculative cold-streak state: uids are
        caller-assigned and commonly reused, and a streak carried across
        independent requests would permanently ban drafting for them."""
        self._spec_miss_streak.pop(uid, None)
        self._draft_index.pop(uid, None)
        self._uid_traces.pop(int(uid), None)
        self._uid_adapter.pop(int(uid), None)
        self._spec_mode_of.pop(int(uid), None)
        self._draft_seen.pop(int(uid), None)
        self.state_manager.flush_sequence(uid)
        self._update_pool_telemetry()

    # ------------------------------------------------------------------
    # Device-memory accounting (telemetry/memory.py; chip-free)
    # ------------------------------------------------------------------
    def memory_report(self, batch: int = 1) -> Dict[str, object]:
        """AOT compile-and-analyze the serving hot-path programs —
        per-token decode, the fused window (when ``decode_window`` > 1)
        and one prefill chunk — at the bucket shapes a ``batch``-row
        step uses, with the FULL block-table width (the worst-case
        program a long sequence pays). Publishes peak/argument/temp
        bytes per program and returns ``{"programs", "buffers", "flops"
        per program}``. Runs chip-free: the compiler is a host library,
        so OOM forensics and the perf gate never need a TPU.

        Analysis compiles are NOT watchdog events — they never run on
        the serving path."""
        sm = self.state_manager
        N = self._decode_bucket(max(int(batch), 1))
        MB = sm.max_blocks_per_seq

        def sds(x):
            return jax.ShapeDtypeStruct(x.shape, x.dtype,
                                        sharding=getattr(x, "sharding",
                                                         None))

        def i32(*shape):
            return jax.ShapeDtypeStruct(shape, jnp.int32)

        params = jax.tree.map(sds, self.params)
        cache = jax.tree.map(sds, self.kv_cache)
        toks, pos, tables = i32(N), i32(N), i32(N, MB)
        # the LoRA bank rides every hot-path program as trailing (bank,
        # adapter-ids) args; None keeps the pre-bank program signatures
        lb = (jax.tree.map(sds, self.lora_bank)
              if self.lora_bank is not None else None)
        aidN = i32(N) if self.lora_bank is not None else None
        aid0 = (jax.ShapeDtypeStruct((), jnp.int32)
                if self.lora_bank is not None else None)
        programs: Dict[str, dict] = {}
        compiled = self._decode_tok_jit.lower(
            params, toks, pos, tables, cache,
            jax.ShapeDtypeStruct((N,), jnp.bool_), lb, aidN).compile()
        programs["decode_greedy"] = ds_memory.record_memory_analysis(
            "decode_greedy", compiled)
        if self.decode_window > 1:
            compiled = self._fused_greedy_jit.lower(
                params, toks, pos, tables, cache, i32(N), i32(N),
                lb, aidN).compile()
            programs["decode_window_greedy"] = \
                ds_memory.record_memory_analysis("decode_window_greedy",
                                                 compiled)
        C = self._bucket(self.config.prefill_bucket)
        compiled = self._prefill_jit.lower(
            params, i32(1, C), jax.ShapeDtypeStruct((), jnp.int32), cache,
            i32(C), i32(C), lb, aid0).compile()
        programs["prefill"] = ds_memory.record_memory_analysis(
            "prefill", compiled)
        if self.ragged_enabled:
            # a representative mixed bucket: one prefill chunk plus a
            # decode row per batch slot, full table width (the
            # worst-case ragged program a long sequence pays). The
            # analyzed bucket geometry rides along in the record so
            # consumers (perf_gate's per-token normalization) read the
            # bucket this analysis actually compiled
            TB = pow2_bucket(self.config.prefill_bucket + N,
                             sm.config.max_ragged_batch_size)
            compiled = self._ragged_jit.lower(
                params, i32(TB), i32(TB), i32(TB), i32(TB), i32(TB),
                i32(TB), i32(N, MB), i32(N), cache, lb, aidN).compile()
            programs["ragged_step"] = dict(
                ds_memory.record_memory_analysis("ragged_step", compiled),
                token_bucket=TB, row_bucket=N)
        return {"programs": programs, "buffers": ds_memory.buffers()}

    # convenience: serve-style generation over the ragged engine
    def generate(self, prompts: Sequence[Sequence[int]], max_new_tokens: int,
                 uids: Optional[Sequence[int]] = None,
                 eos_token_id: Optional[int] = None,
                 temperature: float = 0.0, top_p: float = 1.0,
                 top_k: int = 0, seed: int = 0, speculative: bool = False,
                 spec_k: int = 4, spec_ngram: int = 3,
                 spec_mode: Optional[str] = None,
                 adapter=None) -> List[np.ndarray]:
        """Greedy by default; temperature > 0 samples with nucleus top_p
        (FastGen's sampling surface), deterministic for a given seed.
        ``speculative`` turns on speculative decoding (greedy only):
        per request the chooser routes between prompt-lookup drafting
        (spec_ngram-gram history match + one fused verify pass) and the
        draft MODEL in-window path (propose->verify->accept inside one
        jitted program) when one is loaded — output is IDENTICAL to
        plain greedy either way. ``spec_mode`` overrides the configured
        chooser mode for this call ("auto"/"ngram"/"draft"). ``adapter``
        routes rows through a loaded LoRA adapter: a str applies to all
        rows, a sequence gives one name (or None) per row."""
        uids = list(uids) if uids is not None else list(range(len(prompts)))
        outs: List[List[int]] = [list(map(int, p)) for p in prompts]
        row_of = {uid: i for i, uid in enumerate(uids)}
        sampling = temperature > 0.0
        assert not (speculative and sampling), \
            "speculative decoding is greedy-only (draft verification " \
            "compares argmax)"
        # each generate() call is an independent request batch: spec
        # cold-streaks (and draft indexes) from earlier calls must not
        # leak into this one
        self._spec_miss_streak.clear()
        self._draft_index.clear()
        if adapter is not None:
            names = ([adapter] * len(uids) if isinstance(adapter, str)
                     else list(adapter))
            if len(names) != len(uids):
                raise ValueError(
                    f"adapter list length {len(names)} != batch size "
                    f"{len(uids)}")
            for uid, name in zip(uids, names):
                self.assign_adapter(uid, name)
        if speculative:
            if spec_mode not in (None, "auto", "ngram", "draft"):
                raise ValueError(f"spec_mode must be auto|ngram|draft, "
                                 f"got {spec_mode!r}")
            if spec_mode == "draft" and self.draft_model is None:
                raise ValueError("spec_mode='draft' requires a draft "
                                 "model: call load_draft_model() first")
            from .ngram_index import NGramIndex
            for uid in uids:
                # the request's routing decision is made ONCE, up front:
                # the n-gram index over the prompt is the chooser's
                # cheap repetitiveness prior, the per-mode accept-rate
                # EMAs its learned history
                idx = self._draft_index[uid] = NGramIndex(
                    spec_ngram, self._SPEC_SCAN_WINDOW)
                idx.sync(outs[row_of[uid]])
                if spec_mode in ("ngram", "draft"):
                    mode = spec_mode
                else:
                    mode = self.spec_chooser.choose(
                        self.draft_model is not None,
                        idx.has_candidate(spec_ngram))
                self._spec_mode_of[int(uid)] = mode
                self._m_spec_mode_requests.labels(mode=mode).inc()
        base_rng = jax.random.PRNGKey(seed) if sampling else None
        t_start = time.perf_counter()
        # prompts go through put() (prefill); the continuation loop then
        # stays in token space — argmax/sampler runs on device and only
        # [N] int32s cross to host per step (put()'s [N, vocab] logits
        # are the API for external schedulers, not the hot loop)
        try:
            logits = self.put(uids, prompts)
            self._m_ttft.observe(time.perf_counter() - t_start)
            if sampling:
                from .sampling import fold_in_rows, sample_tokens_rowwise
                # per-row keys (stable row seed + generated-token index):
                # a row's stream depends only on its own draw history,
                # so the per-token and fused-window paths sample the
                # exact same tokens for a given seed
                keys = fold_in_rows(base_rng,
                                    jnp.arange(len(uids), dtype=jnp.int32),
                                    jnp.zeros(len(uids), jnp.int32))
                first = np.asarray(sample_tokens_rowwise(
                    jnp.asarray(logits), keys,
                    jnp.full((len(uids),), temperature, jnp.float32),
                    jnp.full((len(uids),), top_p, jnp.float32),
                    jnp.full((len(uids),), top_k, jnp.int32)))
                cur = {uid: int(t) for uid, t in zip(uids, first)}
            else:
                cur = {uid: int(t) for uid, t in
                       zip(uids, np.argmax(logits, axis=-1))}
            live = set(uids)
            prompt_lens = {uid: len(prompts[row_of[uid]]) for uid in uids}
            row_seed = {uid: i for i, uid in enumerate(uids)}
            window = 1 if speculative else self.decode_window
            while max_new_tokens > 0:   # 0 -> prompt-only rows (no emit)
                step_uids = []
                for uid in uids:
                    if uid not in live:
                        continue
                    tok = cur[uid]
                    row = outs[row_of[uid]]
                    row.append(tok)
                    # per-uid budget (not a step counter): speculative
                    # rounds and fused windows emit several tokens, so
                    # sequences finish at different steps
                    if ((eos_token_id is not None and tok == eos_token_id)
                            or len(row) - prompt_lens[uid]
                            >= max_new_tokens):
                        live.discard(uid)
                    else:
                        step_uids.append(uid)
                if not step_uids:
                    break
                # same guard put() applies: generating past max_seq_len
                # (or a drained block pool) must raise, not silently
                # overrun or crash inside table assembly
                if not self.can_schedule(step_uids, [1] * len(step_uids)):
                    raise RuntimeError(
                        "generation not schedulable: prompt + generated "
                        "tokens exceed max_seq_len or the free KV block "
                        "pool; lower max_new_tokens or raise the limits")
                # every step_uid is already tracked, so the batch can
                # never exceed max_tracked_sequences — one call suffices
                feed = [outs[row_of[u]][-1] for u in step_uids]
                gen_count = [len(outs[row_of[u]]) - prompt_lens[u]
                             for u in step_uids]
                if speculative:
                    # per-request routing: draft-model rows take the
                    # fused in-window path, the rest keep prompt-lookup
                    draft_set = {u for u in step_uids
                                 if self._spec_mode_of.get(int(u))
                                 == "draft"}
                    cur = {}
                    if draft_set:
                        cur.update(self._spec_window_round(
                            [u for u in step_uids if u in draft_set],
                            outs, row_of, prompt_lens, live,
                            max_new_tokens, eos_token_id, spec_k))
                    ngram_uids = [u for u in step_uids
                                  if u not in draft_set]
                    if ngram_uids:
                        cur.update(self._speculative_round(
                            ngram_uids, outs, row_of, prompt_lens, live,
                            max_new_tokens, eos_token_id, spec_k,
                            spec_ngram))
                    continue
                if window > 1:
                    sl = self._window_steps_left(
                        step_uids, [max_new_tokens - g for g in gen_count])
                    eos = -1 if eos_token_id is None else int(eos_token_id)
                    if sampling:
                        em = self._decode_window_sample(
                            step_uids, feed, sl, [eos] * len(step_uids),
                            base_rng, [row_seed[u] for u in step_uids],
                            gen_count, temperature, top_p, top_k)
                    else:
                        em = self._decode_window_greedy(
                            step_uids, feed, sl, [eos] * len(step_uids))
                    cur = {}
                    for uid in step_uids:
                        row = outs[row_of[uid]]
                        toks_out = em[uid]
                        finished = False
                        # all but the last emit are fed/cached already;
                        # the host only re-applies the eos/budget cuts
                        # (defensively — the device enforced them too)
                        for tok in toks_out[:-1]:
                            row.append(tok)
                            if ((eos_token_id is not None
                                 and tok == eos_token_id)
                                    or len(row) - prompt_lens[uid]
                                    >= max_new_tokens):
                                finished = True
                                break
                        if finished:
                            live.discard(uid)
                        else:
                            cur[uid] = toks_out[-1]
                elif sampling:
                    cur = self._decode_batch_sample(
                        step_uids, feed, base_rng,
                        [row_seed[u] for u in step_uids], gen_count,
                        temperature, top_p, top_k)
                else:
                    cur = self._decode_batch_greedy(step_uids, feed)
        finally:
            # flush even on the schedulability raise: a long-lived engine
            # must not leak this call's KV blocks / sequence slots
            for uid in uids:
                self.flush(uid)
        return [np.asarray(o) for o in outs]
