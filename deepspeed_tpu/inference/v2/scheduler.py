"""Dynamic SplitFuse scheduler over the ragged v2 engine.

Reference: DeepSpeed-FastGen's Dynamic SplitFuse strategy
(blogs/deepspeed-fastgen/README.md §3: long prompts are decomposed into
chunks scheduled across forward passes, short prompts composed to fill a
target token budget, and decodes are never stalled behind a long
prefill). The reference implements the policy in the MII serving layer on
top of ``InferenceEngineV2.put``; here it sits directly on the TPU-native
engine (engine_v2.py). With ragged attention enabled (the default,
config_v2.ragged_attention) each composed step is emitted as ONE
:class:`~.ragged.batch.RaggedBatch` — prompt chunks and decode rows run
in a single unified compiled program (kernels/ragged_attention.py), so
the scheduler never trades prefill against decode across dispatches.
With it off, put() sequences the stitched program families: first
prompt chunk -> paged_prefill, later chunks -> the fused paged_continue
pass, single tokens -> the batched paged_decode.

TPU-first consequence of the same "schedule a token budget, not
sequences" insight: every (bucketed) token count is one precompiled XLA
program, so a consistent per-step budget also maximizes compiled-program
reuse — the scheduler is what keeps serving out of the retrace/recompile
tail on TPU, the role CUDA-graph capture plays in the reference.

Usage:
    sched = DynamicSplitFuseScheduler(engine, token_budget=256)
    sched.submit(uid, prompt_tokens, max_new_tokens=64)
    while sched.pending():
        sched.step()
    outs = sched.results()   # {uid: np.ndarray of prompt+generated tokens}
"""

import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from ...telemetry import trace
from ...telemetry import recorder as flight


@dataclass
class _Request:
    uid: int
    prompt: List[int]
    max_new_tokens: int
    eos_token_id: Optional[int]
    submit_t: float
    temperature: float = 0.0         # 0 = greedy
    top_p: float = 1.0
    top_k: int = 0                   # 0 = no rank cutoff
    rng: Optional[np.random.Generator] = None
    prefill_sent: int = 0            # prompt tokens handed to the engine
    generated: List[int] = field(default_factory=list)
    next_token: Optional[int] = None  # pending decode input
    first_token_t: Optional[float] = None
    last_emit_t: Optional[float] = None
    finish_t: Optional[float] = None
    cancelled: bool = False
    # streaming hook (the async serving runtime, serve/): called as
    # on_token(uid, token, finished) from inside step()
    on_token: Optional[Callable[[int, int, bool], None]] = None
    # timeline anchors (telemetry/timeline.py request lifeline). These are
    # ALWAYS perf_counter stamps — submit_t/finish_t follow the
    # scheduler's injectable clock (tests fake it), and a fake timestamp
    # must never leak into the shared trace buffer's time base.
    t_submit_pc: float = 0.0
    t_prefill_pc: Optional[float] = None
    t_first_tok_pc: Optional[float] = None
    # distributed trace id (telemetry/context.py): lifeline spans and
    # flight events carry it so the stitched fleet timeline follows the
    # request across router dispatch / prefill / handoff / decode hops
    trace_id: Optional[str] = None
    # multi-tenant LoRA: adapter NAME serving this request (None = base
    # model); scopes prefix-cache matches and rides the engine's
    # per-row slot gather
    adapter: Optional[str] = None

    def trace_attr(self) -> Dict[str, str]:
        return ({"trace_id": self.trace_id}
                if self.trace_id is not None else {})

    def pick(self, logits_row: np.ndarray) -> int:
        from .sampling import host_sample
        return host_sample(logits_row, self.rng, self.temperature,
                           self.top_p, self.top_k)

    @property
    def prefill_done(self) -> bool:
        return self.prefill_sent >= len(self.prompt)

    @property
    def done(self) -> bool:
        return self.finish_t is not None


class DynamicSplitFuseScheduler:
    """Composes each engine step from (a) every running decode and (b) as
    many prompt-chunk tokens as fit in the remaining token budget —
    FastGen's two behaviors: long prompts split across steps, short
    prompts/chunks fused with generation so forward sizes stay uniform."""

    def __init__(self, engine, token_budget: Optional[int] = None,
                 chunk: Optional[int] = None, clock=time.perf_counter):
        self.engine = engine
        sm = engine.state_manager.config
        self.token_budget = min(token_budget or sm.max_ragged_batch_size,
                                sm.max_ragged_batch_size)
        # chunks align to the prefill bucket so every split hits an
        # already-compiled program size
        self.chunk = chunk or engine.config.prefill_bucket
        self.clock = clock
        self._queue: List[_Request] = []     # waiting for prefill budget
        self._running: List[_Request] = []   # prefill done, decoding
        self._all: Dict[int, _Request] = {}
        self.steps = 0
        self._init_telemetry()

    def _init_telemetry(self):
        from ...telemetry import get_registry
        reg = get_registry()
        self._m_queue = reg.gauge(
            "serving_queue_depth", "requests waiting on prefill budget")
        self._m_running = reg.gauge(
            "serving_running_sequences", "requests decoding")
        self._m_steps = reg.counter(
            "serving_steps_total", "composed engine steps run")
        self._m_step_tokens = reg.histogram(
            "serving_step_tokens", "tokens composed per engine step",
            buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024))
        self._m_submitted = reg.counter(
            "serving_requests_submitted_total", "requests submitted")
        self._m_finished = reg.counter(
            "serving_requests_finished_total", "requests finished")
        self._m_preempted = reg.counter(
            "serving_preemptions_total",
            "partial prefills evicted to free KV blocks")
        self._m_ttft = reg.histogram(
            "serving_ttft_seconds", "submit -> first generated token",
            unit="s")
        self._m_tpot = reg.histogram(
            "serving_tpot_seconds",
            "time per output token (gap between consecutive emitted "
            "tokens of one request)", unit="s")
        self._m_req_time = reg.histogram(
            "serving_request_seconds", "submit -> request finished",
            unit="s")
        self._m_cancelled = reg.counter(
            "serving_requests_cancelled_total",
            "requests cancelled before finishing (KV blocks released)")
        self._m_gen_tokens = reg.counter(
            "serving_generated_tokens_total",
            "tokens generated across finished requests")

    def _update_depth_gauges(self):
        self._m_queue.set(len(self._queue))
        self._m_running.set(len(self._running))

    # ------------------------------------------------------------------
    def submit(self, uid: int, prompt: Sequence[int], max_new_tokens: int,
               eos_token_id: Optional[int] = None,
               temperature: float = 0.0, top_p: float = 1.0,
               top_k: int = 0, seed: Optional[int] = None,
               on_token: Optional[Callable[[int, int, bool], None]]
               = None, trace_ctx=None,
               adapter: Optional[str] = None) -> None:
        """temperature/top_p/seed are PER REQUEST (the MII SamplingParams
        surface): mixed greedy and sampled requests compose into the same
        steps; a SEEDED request's tokens are deterministic (independent
        of batch composition — the rng is per request), an unseeded one
        draws fresh OS entropy. ``on_token(uid, token, finished)`` fires
        for every emitted token (the serve/ streaming hook).
        ``trace_ctx`` (a :class:`~...telemetry.context.TraceContext`)
        correlates the request's lifeline spans — and, via
        ``engine.bind_trace``, the engine's batch spans — with its
        distributed trace. ``adapter`` names a loaded LoRA adapter to
        serve this request through (KeyError if unknown; None = base
        model)."""
        if uid in self._all:
            # results()/metrics() are keyed by uid: admitting a second
            # request under a live key would silently cross their
            # per-request state. Reject loudly (a plain assert vanishes
            # under python -O).
            raise ValueError(
                f"uid {uid} already submitted to this scheduler "
                f"(per-uid results()/metrics() state would be "
                f"corrupted); use a fresh uid, or release(uid) once the "
                f"previous request is finished or cancelled")
        max_seq_len = self.engine.state_manager.config.max_seq_len
        # the final emitted token is never fed back (_emit), so the
        # request writes prompt + max(new-1, 0) KV slots — the same need
        # formula as the drain-path diagnostic below
        need = len(prompt) + max(max_new_tokens - 1, 0)
        if need > max_seq_len:
            # reject up front: admitted, the request would run until the
            # state manager refuses the decode past max_seq_len and the
            # failure would surface as a misleading KV-pool error
            raise RuntimeError(
                f"request uid={uid} cannot be scheduled: "
                f"len(prompt)={len(prompt)} + max_new_tokens="
                f"{max_new_tokens} needs {need} KV slots, over "
                f"max_seq_len={max_seq_len}; shorten the request or "
                f"raise state_manager.max_seq_len")
        req = _Request(uid, list(map(int, prompt)), max_new_tokens,
                       eos_token_id, self.clock(),
                       temperature=temperature, top_p=top_p, top_k=top_k,
                       rng=np.random.default_rng(seed), on_token=on_token,
                       t_submit_pc=time.perf_counter(), adapter=adapter)
        if adapter:
            # resolve the name to a bank slot NOW (KeyError surfaces at
            # submit, not mid-batch) and route every engine pass for
            # this uid through it
            self.engine.assign_adapter(uid, adapter)
        self._bind_trace(req, trace_ctx)
        self._all[uid] = req
        self._queue.append(req)
        self._m_submitted.inc()
        flight.record("request_submit", uid=int(uid),
                      prompt_tokens=len(req.prompt),
                      max_new_tokens=int(max_new_tokens),
                      **req.trace_attr())
        self._update_depth_gauges()

    def resume(self, uid: int, prompt: Sequence[int],
               generated: Sequence[int], max_new_tokens: int,
               eos_token_id: Optional[int] = None,
               temperature: float = 0.0, top_p: float = 1.0,
               top_k: int = 0, rng_state: Optional[dict] = None,
               on_token: Optional[Callable[[int, int, bool], None]]
               = None, trace_ctx=None) -> None:
        """Adopt a request mid-generation (the prefill/decode
        disaggregation path, serve/handoff.py): the engine already holds
        the sequence's KV — restored from a prefill replica — and
        ``generated`` tokens were emitted there (at least the first
        token, whose logits came from the handed-off prefill). The
        request enters the RUNNING set directly, its last generated
        token pending as the next decode input — exactly the state a
        colocated request is in after its final prompt chunk, which is
        what makes handed-off streams bit-identical to colocated ones.

        ``rng_state`` is the numpy bit-generator state captured after
        the prefill side's draws; restoring it keeps SAMPLED streams on
        the colocated token path too. ``on_token`` fires only for
        tokens decoded here — the caller already streamed
        ``generated``."""
        if uid in self._all:
            raise ValueError(
                f"uid {uid} already submitted to this scheduler; "
                f"resume needs a fresh uid")
        sm = self.engine.state_manager
        # same KV-slot precheck submit() enforces: an oversized request
        # must fail HERE, not mid-decode as a misleading pool error
        # that would take every in-flight request on this replica down
        need = len(prompt) + max(int(max_new_tokens) - 1, 0)
        if need > sm.config.max_seq_len:
            raise RuntimeError(
                f"request uid={uid} cannot be resumed: "
                f"len(prompt)={len(prompt)} + max_new_tokens="
                f"{max_new_tokens} needs {need} KV slots, over "
                f"max_seq_len={sm.config.max_seq_len}")
        if not sm.known_seq(uid):
            raise ValueError(
                f"cannot resume uid {uid}: the engine holds no KV for "
                f"it (restore the handoff first)")
        if not generated:
            raise ValueError("resume needs at least the first generated "
                             "token (emitted by the prefill side)")
        if len(generated) >= max_new_tokens or (
                eos_token_id is not None
                and int(generated[-1]) == eos_token_id):
            raise ValueError(
                f"uid {uid} already finished at prefill; nothing to "
                f"resume")
        seen = sm.seqs[uid].seen_tokens
        expect = len(prompt) + len(generated) - 1
        if seen != expect:
            # the last emitted token is never fed back, so the cache
            # must hold exactly prompt + all-but-last generated tokens
            raise ValueError(
                f"handoff state inconsistent for uid {uid}: cache holds "
                f"{seen} tokens, descriptor implies {expect}")
        rng = np.random.default_rng()
        if rng_state is not None:
            rng.bit_generator.state = rng_state
        now = self.clock()
        req = _Request(uid, list(map(int, prompt)), max_new_tokens,
                       eos_token_id, now, temperature=temperature,
                       top_p=top_p, top_k=top_k, rng=rng,
                       on_token=on_token,
                       t_submit_pc=time.perf_counter())
        self._bind_trace(req, trace_ctx)
        req.prefill_sent = len(req.prompt)
        req.generated = list(map(int, generated))
        req.next_token = int(generated[-1])
        req.first_token_t = now        # TTFT was paid on the prefill side
        req.last_emit_t = now
        req.t_prefill_pc = req.t_first_tok_pc = time.perf_counter()
        self._all[uid] = req
        self._running.append(req)
        self._m_submitted.inc()
        flight.record("request_resume", uid=int(uid),
                      prompt_tokens=len(req.prompt),
                      generated=len(req.generated),
                      max_new_tokens=int(max_new_tokens),
                      **req.trace_attr())
        self._update_depth_gauges()

    def _bind_trace(self, req: _Request, trace_ctx) -> None:
        """Record the request's distributed trace id and mirror it into
        the engine's per-uid binding so batch-level engine spans
        (ragged_step / decode_window / ...) carry it too."""
        if trace_ctx is None:
            return
        req.trace_id = str(trace_ctx.trace_id)
        bind = getattr(self.engine, "bind_trace", None)
        if bind is not None:
            bind(req.uid, req.trace_id)

    def pending(self) -> bool:
        return bool(self._queue or self._running)

    def inflight(self) -> int:
        """Requests admitted and not yet finished/cancelled (queued for
        prefill budget + decoding)."""
        return len(self._queue) + len(self._running)

    def known_uids(self) -> List[int]:
        """Every uid the scheduler still tracks (in flight, finished but
        not yet released) — the set the KV-leak detector reconciles the
        block pool against at drain."""
        return list(self._all)

    # ------------------------------------------------------------------
    def cancel(self, uid: int) -> bool:
        """Abort an in-flight request: drop it from the step composition
        and release its KV blocks back to the pool. No further tokens are
        emitted (and no on_token callback fires again). Returns False if
        the uid is unknown, already finished, or already cancelled. The
        request stays recorded (excluded from results()/metrics()) so the
        uid cannot be silently reused; release(uid) forgets it."""
        req = self._all.get(uid)
        if req is None or req.done or req.cancelled:
            return False
        req.cancelled = True
        req.next_token = None
        now_pc = time.perf_counter()
        t0 = req.t_submit_pc or now_pc
        trace.record("request", t0, now_pc - t0, uid=req.uid,
                     tokens=len(req.generated), status="cancelled",
                     **req.trace_attr())
        if req in self._running:
            self._running.remove(req)
        if req in self._queue:
            self._queue.remove(req)
        self.engine.flush(uid)     # frees the blocks; no-op if none held
        self._m_cancelled.inc()
        flight.record("request_cancel", uid=int(uid),
                      tokens=len(req.generated))
        self._update_depth_gauges()
        return True

    def release(self, uid: int) -> None:
        """Forget a finished or cancelled request so its uid can be
        resubmitted (long-lived serving: _all must not grow forever)."""
        req = self._all.get(uid)
        if req is None:
            return
        if not (req.done or req.cancelled):
            raise ValueError(
                f"uid {uid} is still in flight; cancel() it first")
        del self._all[uid]

    # ------------------------------------------------------------------
    def _finish(self, req: _Request) -> None:
        req.finish_t = self.clock()
        now_pc = time.perf_counter()
        start = req.t_first_tok_pc or now_pc
        trace.record("request_decode", start, now_pc - start,
                     uid=req.uid, tokens=len(req.generated),
                     **req.trace_attr())
        t0 = req.t_submit_pc or start
        trace.record("request", t0, now_pc - t0, uid=req.uid,
                     tokens=len(req.generated), status="completed",
                     **req.trace_attr())
        self.engine.flush(req.uid)
        if req in self._running:
            self._running.remove(req)
        self._m_finished.inc()
        self._m_gen_tokens.inc(len(req.generated))
        ttft = (req.first_token_t or req.finish_t) - req.submit_t
        self._m_ttft.observe(ttft)
        self._m_req_time.observe(req.finish_t - req.submit_t)
        flight.record("request_finish", uid=int(req.uid),
                      tokens=len(req.generated),
                      ttft_s=round(ttft, 4),
                      total_s=round(req.finish_t - req.submit_t, 4),
                      **req.trace_attr())
        self._update_depth_gauges()

    def _evict_partial_prefill(self, exclude=()) -> bool:
        """Free the KV blocks of the most recently admitted partial
        prefill (it restarts from token 0 later). The recovery move when
        the pool is exhausted by work that cannot finish."""
        for req in reversed(self._queue):
            if req.prefill_sent > 0 and req.uid not in exclude:
                self.engine.flush(req.uid)
                req.prefill_sent = 0
                self._m_preempted.inc()
                return True
        return False

    def step(self) -> int:
        """One composed engine step; returns the number of tokens run."""
        uids: List[int] = []
        toks: List[List[int]] = []
        decode_reqs: List[_Request] = []
        budget = self.token_budget

        # (a) decodes first: generation is never stalled behind prefill.
        # Round-robin rotation so a budget smaller than the running set
        # starves nobody (the skipped tail leads the next step).
        for req in list(self._running):
            if budget <= 0:
                break
            uids.append(req.uid)
            toks.append([req.next_token])
            decode_reqs.append(req)
            budget -= 1
        if decode_reqs and len(decode_reqs) < len(self._running):
            k = len(decode_reqs)
            self._running = self._running[k:] + self._running[:k]

        # (b) fill the remainder with prompt chunks (FIFO, chunk-aligned;
        # the final or budget-tail chunk may be smaller — bucketed compile
        # sizes absorb fragments)
        sm = self.engine.state_manager
        new_admitted = 0  # can_schedule checks each uid against the
        # CURRENT tracked count; new uids admitted into the same batch
        # must be counted here or put() raises mid-batch
        for req in list(self._queue):
            if budget <= 0:
                break
            if req.prefill_sent == 0:
                if (sm.tracked_sequences() + new_admitted
                        >= sm.config.max_tracked_sequences):
                    break  # sequence slots full: wait for a finish
                # prefix caching must match against the FULL prompt here:
                # put() only ever sees one chunk (<= self.chunk tokens),
                # which would cap reuse at a chunk's worth
                _, n_reused = sm.match_prefix(
                    req.uid, np.asarray(req.prompt, np.int64),
                    adapter=req.adapter)
                if n_reused:
                    # match_prefix registered the uid in sm.seqs, so
                    # tracked_sequences() already counts it — no
                    # new_admitted increment (that compensates only for
                    # sequences created later inside put())
                    req.prefill_sent = n_reused
            left = len(req.prompt) - req.prefill_sent
            take = min(left, budget, max(self.chunk, 1))
            piece = req.prompt[req.prefill_sent:req.prefill_sent + take]
            # whole-batch check: decodes already composed + chunks so far
            # + this piece (a decode crossing a page boundary can itself
            # need a fresh KV block)
            if not self.engine.can_schedule(
                    uids + [req.uid], [len(t) for t in toks] + [take]):
                break  # KV pool full: wait for a running seq to finish
            if req.prefill_sent == 0:
                new_admitted += 1
            if req.t_prefill_pc is None:
                # first prefill chunk composed: the queue phase of the
                # request's timeline lifeline ends here
                req.t_prefill_pc = time.perf_counter()
                trace.record("request_queue", req.t_submit_pc,
                             req.t_prefill_pc - req.t_submit_pc,
                             uid=req.uid, **req.trace_attr())
            uids.append(req.uid)
            toks.append(piece)
            req.prefill_sent += take
            budget -= take

        if uids and not self.engine.can_schedule(
                uids, [len(t) for t in toks]):
            # decodes alone over the pool: free blocks held by a queued
            # partial prefill before declaring the config impossible
            if self._evict_partial_prefill(exclude=set(uids)):
                return 0
            raise RuntimeError(
                "running decodes alone exceed the KV pool; shrink the "
                "admitted set (lower max_tracked_sequences) or add blocks")

        if not uids:
            if self._queue and not self._running:
                # pool dry with nothing draining it (requests exceeding
                # max_seq_len were already rejected at submit). Two cases:
                head = self._queue[0]
                bs = sm.block_size
                # the final emitted token is never fed back (_emit), so a
                # request writes prompt + max(new-1, 0) KV slots total
                total = len(head.prompt) + max(head.max_new_tokens - 1, 0)
                need = -(-total // bs)
                if need > sm.config.num_blocks - 1:  # block 0 is the null
                    raise RuntimeError(
                        f"request uid={head.uid} cannot be scheduled: "
                        f"{len(head.prompt)}+{head.max_new_tokens} tokens "
                        f"need {need} KV blocks, pool has "
                        f"{sm.config.num_blocks - 1}")
                # mutual exhaustion: several long prompts were admitted
                # concurrently and none can finish prefill — free the
                # most recent partial so the head makes progress.
                if self._evict_partial_prefill(exclude={head.uid}):
                    return 0
                raise RuntimeError(
                    f"request uid={head.uid} cannot be scheduled: KV "
                    f"pool exhausted with no running sequences to drain")
            return 0

        if (decode_reqs and len(decode_reqs) == len(uids)
                and all(r.temperature <= 0.0 for r in decode_reqs)):
            # pure-GREEDY-decode step: device argmax, [N] int32 to host
            # instead of [N, vocab] logits (same fast path generate()
            # uses). Gated on EVERY piece being a decode — a 1-token
            # final prompt chunk also has len(t) == 1 but needs the
            # put() path's prefill-completion handling — and on greedy
            # rows only (sampled requests draw from host rngs).
            assert all(len(t) == 1 for t in toks)
            window = getattr(self.engine, "decode_window", 1)
            if window > 1:
                # fused multi-step window. Reaching this path means the
                # composition loop above added NO prompt chunk this step
                # — the queue is empty or blocked (sequence slots full,
                # KV pool tight, or the budget consumed by decodes), so
                # no prefill work is stalled by running K steps at once;
                # composition re-runs after every window, so prefill
                # admission latency is bounded by one window (<= K
                # tokens/row). Each request carries its own budget/eos,
                # so rows finish mid-window (masked on device); every
                # emitted token still flows through _emit -> on_token,
                # arriving in bursts of up to K per step.
                return self._step_fused_window(uids, toks, decode_reqs,
                                               window)
            nxt_map = self.engine._decode_batch_greedy(
                uids, [t[0] for t in toks])
            self.steps += 1
            self._m_steps.inc()
            self._m_step_tokens.observe(len(uids))
            for req in decode_reqs:
                self._emit(req, nxt_map[req.uid])
            self._update_depth_gauges()
            return len(uids)

        # mixed composition: with ragged attention enabled (engine
        # default) put() emits this step as ONE RaggedBatch launch —
        # chunks and decode rows packed into the unified ragged program
        # (engine_v2.step_ragged) — instead of sequencing the
        # prefill/continue/decode families, so the scheduler never
        # trades prefill against decode across separate dispatches
        logits = np.asarray(self.engine.put(uids, toks))
        self.steps += 1
        self._m_steps.inc()
        self._m_step_tokens.observe(sum(len(t) for t in toks))
        now = self.clock()

        for i, uid in enumerate(uids):
            req = self._all[uid]
            if req in decode_reqs:
                self._emit(req, req.pick(logits[i]))
            elif req.prefill_done:
                # final prompt chunk: its last-token logits yield the
                # first generated token (TTFT is measured here)
                req.first_token_t = now
                req.t_first_tok_pc = time.perf_counter()
                start = req.t_prefill_pc or req.t_first_tok_pc
                trace.record("request_prefill", start,
                             req.t_first_tok_pc - start, uid=req.uid,
                             prompt_tokens=len(req.prompt),
                             **req.trace_attr())
                self._queue.remove(req)
                if req.max_new_tokens <= 0:
                    self._finish(req)
                else:
                    self._running.append(req)
                    self._emit(req, req.pick(logits[i]))
            # else: mid-prompt chunk — logits ignored
        self._update_depth_gauges()
        return sum(len(t) for t in toks)

    def _step_fused_window(self, uids: List[int], toks: List[List[int]],
                           decode_reqs: List["_Request"],
                           window: int) -> int:
        """One fused K-step decode window over the composed greedy
        decode set; emits every produced token through _emit (streaming
        on_token hooks fire per token, deadlines/cancellation re-check
        at the window boundary)."""
        remaining = [r.max_new_tokens - len(r.generated)
                     for r in decode_reqs]
        sl = self.engine._window_steps_left(uids, remaining)
        eos = [(-1 if r.eos_token_id is None else int(r.eos_token_id))
               for r in decode_reqs]
        em = self.engine._decode_window_greedy(
            uids, [t[0] for t in toks], sl, eos)
        self.steps += 1
        self._m_steps.inc()
        total = sum(len(em[u]) for u in uids)
        self._m_step_tokens.observe(total)
        for req in decode_reqs:
            for tok in em[req.uid]:
                self._emit(req, tok)
        self._update_depth_gauges()
        return total

    def _emit(self, req: _Request, tok: int) -> None:
        """Record a produced token; finish or queue it as the next decode
        input. Matches generate(): eos is included in the output, and the
        final emitted token is never fed back (no wasted forward)."""
        now = self.clock()
        if req.last_emit_t is not None:
            # inter-token gap = the serving TPOT distribution (first
            # token is TTFT territory, not TPOT)
            self._m_tpot.observe(now - req.last_emit_t)
        req.last_emit_t = now
        req.generated.append(tok)
        if ((req.eos_token_id is not None and tok == req.eos_token_id)
                or len(req.generated) >= req.max_new_tokens):
            self._finish(req)
        else:
            req.next_token = tok
        if req.on_token is not None:
            req.on_token(req.uid, tok, req.done)

    # ------------------------------------------------------------------
    def run(self, max_steps: int = 10 ** 6) -> None:
        while self.pending() and max_steps > 0:
            self.step()
            max_steps -= 1

    def results(self) -> Dict[int, np.ndarray]:
        return {uid: np.asarray(r.prompt + r.generated)
                for uid, r in self._all.items() if r.done}

    def metrics(self) -> Dict[int, Dict[str, float]]:
        """Per-request latency bookkeeping (TTFT / total / tokens)."""
        out = {}
        for uid, r in self._all.items():
            if not r.done:
                continue
            out[uid] = {
                "ttft_s": (r.first_token_t or r.finish_t) - r.submit_t,
                "total_s": r.finish_t - r.submit_t,
                "new_tokens": len(r.generated),
            }
        return out
