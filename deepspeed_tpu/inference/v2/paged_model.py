"""Paged (blocked-KV) transformer forward for the ragged engine.

Device-side core of inference v2. Reference counterparts:
  * blocked flash attention over the paged KV cache
    (inference/v2/kernels/ragged_ops/blocked_flash/)
  * fused rotary + KV-block append
    (ragged_ops/blocked_kv_rotary/)
  * ragged embedding + logits gather (ragged_ops/ragged_embed, logits_gather)

Two entry points, both pure and jit-compiled by the engine:
  * ``paged_prefill``: one new sequence's prompt chunk [1, C] — causal
    attention within the chunk, K/V scattered into the sequence's cache
    blocks, returns the last-token logits.
  * ``paged_decode``: one token for each of N sequences — K/V appended at
    each sequence's next slot, attention over the sequence's block table
    (gathered pages), returns [N, V] logits.

The KV pool is ``[L, num_blocks, block_size, kv_heads, head_dim]``; block 0
is the null block (padding writes land there). Static shapes throughout:
prompt lengths bucket to multiples of ``prefill_bucket`` and the decode
batch pads to the next power-of-two bucket — each bucket compiles once
(the XLA analogue of the reference's CUDA-graph'd atom sizes).

Design note — why there is no dedicated rotary+KV-append kernel (reference
inference/v2/kernels/ragged_ops/blocked_kv_rotary/): that CUDA kernel exists
because torch eager would otherwise launch separate rotary, transpose and
scatter kernels per layer. Here the rotary and the ``.at[block_ids,
offsets].set`` cache write sit INSIDE the jitted, scanned layer body, so XLA
fuses them into the same program as the qkv projections — the "fusion" the
reference hand-writes is the compiler's default. The Pallas budget goes
where fusion cannot: the attention reads (paged_attention.py,
ops/decode_attention.py, flash prefill).
"""

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

from ...models.transformer import TransformerConfig, out_proj, qkv_proj

NEG_INF = -1e30


def init_paged_kv_cache(cfg: TransformerConfig, num_blocks: int,
                        block_size: int, dtype,
                        kv_quant: bool = False) -> Dict[str, jnp.ndarray]:
    """``kv_quant`` stores the pool int8 with PER-BLOCK (page x kv-head)
    fp32 scales — ~0.5x the bf16 bytes (scale overhead 4/(bs*hd) per
    element instead of the old per-slot 4/hd), so the same HBM holds
    ~2x the tokens. Writes quantize against a running per-block absmax
    (requantizing the block's earlier content when the scale grows);
    reads dequantize. The per-block granularity is what lets the Pallas
    decode/ragged kernels dequantize IN-KERNEL: one (kvh,) scale row per
    streamed page tile, so int8 KV serves through the same one-program
    kernel family as bf16 (kernels/paged_attention.py ragged_attention.py
    quant variants). Scales init to 0 = "nothing written"."""
    assert cfg.is_causal and cfg.norm_scheme == "pre", \
        "paged serving requires a causal pre-LN model (the MLM/post-LN " \
        "encoder family does not decode)"
    shape = (cfg.num_layers, num_blocks, block_size, cfg.kv_heads,
             cfg.head_dim)
    if kv_quant:
        sshape = (cfg.num_layers, num_blocks, cfg.kv_heads)
        return {"k": jnp.zeros(shape, jnp.int8),
                "v": jnp.zeros(shape, jnp.int8),
                "ks": jnp.zeros(sshape, jnp.float32),
                "vs": jnp.zeros(sshape, jnp.float32)}
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}


def _kv_write(kc, ksc, l, blocks, offs, k):
    """Scatter one write-set into the pool. Under kv_quant the pool is
    int8 with per-(block, kv-head) scales: the block scale is a running
    absmax over everything written to the block, so a write whose
    magnitude exceeds the current scale first rescales the block's
    existing int8 content to the grown scale (deterministic
    round-to-nearest requant — grow-only, so earlier tokens only ever
    lose up to half an LSB per growth), then quantizes the new tokens.
    Duplicate block indices in one write-set (a prefill chunk spanning a
    block) scatter identical per-block values, so the duplicate-index
    writes stay deterministic; the final per-slot writes are unique."""
    if ksc is None:
        return kc.at[l, blocks, offs].set(k.astype(kc.dtype)), None
    xf = k.astype(jnp.float32)                          # [C, kvh, hd]
    tok_scale = jnp.max(jnp.abs(xf), axis=-1) / 127.0   # [C, kvh]
    old = ksc[l]                                        # [nb, kvh]
    new = old.at[blocks].max(tok_scale)                 # running absmax

    def _requant(c):
        ratio = jnp.where(new > 0, old / jnp.where(new > 0, new, 1.0), 0.0)
        r_tok = ratio[blocks]                           # [C, kvh]
        pages = c[l, blocks].astype(jnp.float32)        # [C, bs, kvh, hd]
        pages = jnp.round(pages * r_tok[:, None, :, None])
        return c.at[l, blocks].set(pages.astype(jnp.int8))

    # steady-state decode almost never grows a block's absmax, so the
    # full-page rescale RMW is condition-gated: a ratio-1 requant is the
    # identity on the (integer-valued) int8 content, and never-written
    # blocks keep scale 0 (dequant reads 0 either way) — skipping is
    # bit-identical, and only the slot write below touches the pool
    kc = jax.lax.cond(jnp.any(tok_scale > old[blocks]), _requant,
                      lambda c: c, kc)
    s_tok = jnp.where(new > 0, new, 1.0)[blocks]        # [C, kvh]
    q = jnp.clip(jnp.round(xf / s_tok[..., None]), -127, 127)
    kc = kc.at[l, blocks, offs].set(q.astype(jnp.int8))
    return kc, ksc.at[l].set(new)


def _cache_dict(kc, vc, ksc, vsc):
    out = {"k": kc, "v": vc}
    if ksc is not None:
        out["ks"], out["vs"] = ksc, vsc
    return out


def _kv_read(kc, ksc, l, table, dtype):
    """Gather pages [*, bs, kvh, hd], dequantizing when scales exist
    (per-block scale row broadcast over the page's slot and head-dim
    axes — the same multiply the kernels' quant variants run per tile,
    so kernel and gather dequant agree bit-for-bit at fp32)."""
    pages = kc[l][table]
    if ksc is None:
        return pages
    return (pages.astype(jnp.float32)
            * ksc[l][table][..., None, :, None]).astype(dtype)


def _lora_delta(a, b, hn, aid):
    """Per-row LoRA delta ``(hn @ a[aid]) @ b[aid]`` gathered from a
    stacked adapter bank. ``a`` [S, h, r] and ``b`` [S, r, o] hold one
    layer's A/B factors for every hot slot (the adapter scale is folded
    into ``b`` at load time, so this matches the training-side fused
    semantics ``W + scale * (a @ b)`` bit-for-bit under fp32); ``hn``
    [T, h]; ``aid`` int32 [T] per row, or a scalar for single-sequence
    chunks (prefill/continue), which skips the gather entirely. Slot 0
    is all-zeros — base-model rows add an exact +0.0."""
    aid = jnp.asarray(aid)
    if aid.ndim == 0:
        t = hn @ a[aid].astype(hn.dtype)
        return t @ b[aid].astype(hn.dtype)
    t = jnp.einsum("ti,tir->tr", hn, a[aid].astype(hn.dtype))
    return jnp.einsum("tr,tro->to", t, b[aid].astype(hn.dtype))


def _lora_qv(ll, hn, aid, q, v):
    """Add one layer's per-row LoRA deltas to the FLAT q/v projections
    (classic LoRA targets the q and v projections); ``ll`` is the scan-
    sliced bank layer {"qa","qb","va","vb"} or None (bank disabled)."""
    if ll is None:
        return q, v
    return (q + _lora_delta(ll["qa"], ll["qb"], hn, aid),
            v + _lora_delta(ll["va"], ll["vb"], hn, aid))


def init_lora_bank(cfg: TransformerConfig, slots: int, rank: int,
                   dtype) -> Dict[str, jnp.ndarray]:
    """All-zero stacked adapter bank: ``slots`` INCLUDES the reserved
    base slot 0. Allocated once at engine init so every jitted program's
    signature is stable from boot — hot-deploying an adapter is a same-
    shape ``.at[:, slot].set`` update, never a recompile."""
    h, r = cfg.hidden_size, int(rank)
    L = cfg.num_layers
    nh, nkv, hd = cfg.num_heads, cfg.kv_heads, cfg.head_dim
    return {"qa": jnp.zeros((L, slots, h, r), dtype),
            "qb": jnp.zeros((L, slots, r, nh * hd), dtype),
            "va": jnp.zeros((L, slots, h, r), dtype),
            "vb": jnp.zeros((L, slots, r, nkv * hd), dtype)}


def _norm(cfg, x, w, b=None):
    from ...ops.norms import layer_norm, rms_norm

    if cfg.norm == "rmsnorm":
        return rms_norm(x, w, cfg.norm_eps)
    return layer_norm(x, w, b, cfg.norm_eps)


def _rope_at(cfg: TransformerConfig, pos: jnp.ndarray):
    """cos/sin tables at integer positions `pos` [...]-> [..., half]
    (half = rotating dims / 2; partial rotary leaves the tail alone)."""
    from ...models.transformer import rotary_dims
    half = rotary_dims(cfg) // 2
    freqs = 1.0 / (cfg.rope_theta
                   ** (jnp.arange(0, half, dtype=jnp.float32) / half))
    angles = pos.astype(jnp.float32)[..., None] * freqs
    return jnp.cos(angles), jnp.sin(angles)


def _rotate(x: jnp.ndarray, cos: jnp.ndarray, sin: jnp.ndarray):
    """x [..., D]; cos/sin broadcastable to [..., rot/2] — when rot < D
    (partial rotary) the trailing dims pass through untouched."""
    rot = 2 * cos.shape[-1]
    tail = x[..., rot:]
    xr = x[..., :rot]
    half = rot // 2
    x1, x2 = xr[..., :half], xr[..., half:]
    out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos],
                          axis=-1)
    if tail.shape[-1]:
        out = jnp.concatenate([out, tail], axis=-1)
    return out.astype(x.dtype)


def _mlp(cfg, lp, x, topo=None):
    if cfg.moe_num_experts > 0:
        return _moe_mlp(cfg, lp, x, topo)
    if cfg.is_gated_mlp:
        from ...models.transformer import gate_act
        return (gate_act(cfg)(x @ lp["w_gate"])
                * (x @ lp["w_up"])) @ lp["w_down"]
    from ...models.transformer import dense_mlp
    return dense_mlp(cfg, lp, x)


def _moe_mlp(cfg, lp, x, topo=None):
    """Routed-expert MLP for serving (reference v2 serves Mixtral-class
    MoE, inference/v2/model_implementations/): dropless sorted-token
    grouped GEMM via jax.lax.ragged_dot — no [T,E,C] capacity tensor, no
    token drops (dropping tokens at inference corrupts outputs), ep=1.

    Routing matches the training graph so serving is parity-testable
    against the same weights: top-1 uses the raw gate probability
    (sharded_moe.top1gating g1); top-k>=2 renormalizes over the chosen
    set (top2gating's g1/g2 normalization; for k>2 the same convention
    is the Mixtral/Qwen-MoE/DBRX one — serving-only, training gates are
    top-1/top-2).
    """
    from ...moe.sharded_moe import dropless_topk_dispatch

    orig_shape = x.shape
    H = orig_shape[-1]
    xt = x.reshape(-1, H)
    gate_w = lp["moe_gate_w"]
    E = gate_w.shape[-1]
    k = cfg.moe_top_k
    if topo is not None and topo.axis_size("expert") > 1:
        # expert-parallel serving: experts live sharded over the "expert"
        # axis, so the ragged grouped GEMM (device-local experts) cannot
        # run — route through the worst-case-capacity dropless dispatch
        # (serving must never drop a token) and let GSPMD insert the
        # expert all-to-all. Same gating math as training's moe_layer, so
        # ep>1 == ep=1 logits (parity-tested). Quadratic-dispatch regime
        # (long prefill chunks) is rejected loudly by the helper.
        from ...moe.sharded_moe import moe_layer_dropless_ep

        def expert_fn(p, xe):
            g_, u_, d_ = p
            return (jax.nn.silu(xe @ g_) * (xe @ u_)) @ d_

        out3, _aux = moe_layer_dropless_ep(
            xt[None], gate_w, (lp["e_gate"], lp["e_up"], lp["e_down"]),
            expert_fn, topo, top_k=k)
        out = out3[0]
    else:
        logits = xt.astype(jnp.float32) @ gate_w.astype(jnp.float32)
        gates = jax.nn.softmax(logits, axis=-1)
        topv, topi = jax.lax.top_k(gates, k)                # [T, k]
        if k > 1:
            topv = topv / jnp.sum(topv, axis=-1, keepdims=True)
        experts = (lp["e_gate"], lp["e_up"], lp["e_down"])
        out = dropless_topk_dispatch(xt, topi, topv, experts, E)
    if cfg.moe_use_residual:
        from ...moe.sharded_moe import residual_moe_combine
        dense = (jax.nn.silu(xt @ lp["res_gate"])
                 * (xt @ lp["res_up"])) @ lp["res_down"]
        out = residual_moe_combine(xt, out, dense, lp["res_coef_w"],
                                   lp["res_coef_b"])
    return out.reshape(orig_shape)


def _deq_nonlayer(params):
    """Dequantize every WOQ leaf OUTSIDE params["layers"] (embed/lm_head;
    one-shot temps XLA frees after use). Layer leaves stay quantized: the
    lax.scan slices them and the body dequantizes ONE layer at a time —
    dequantizing the stack up front materializes every layer's bf16
    weights as scan inputs (the r05 AOT serving fit measured 13 GiB of
    them on a 7B model, making int8 serving WORSE than bf16 at peak)."""
    from ..quantization import dequantize_params
    return {k: (v if k == "layers" else dequantize_params(v))
            for k, v in params.items()}


def _deq_layer(lp):
    """Dequantize one scan-sliced layer's WOQ leaves (identity on dense
    params); runs inside the scan body where XLA fuses the dequant into
    the consuming matmul."""
    from ..quantization import dequantize_params
    return dequantize_params(lp)


def _embed_ln(cfg, params, x):
    """Bloom/BERT-family embeddings LayerNorm (keyed on param presence)."""
    if "embed_ln_w" in params:
        from ...ops.norms import layer_norm
        return layer_norm(x, params["embed_ln_w"],
                          params.get("embed_ln_b"), cfg.norm_eps)
    return x


def _alibi_row(cfg, positions):
    """[nh, 1, len(positions)] softmax-invariant ALiBi bias row."""
    from ...models.transformer import alibi_slopes
    return (alibi_slopes(cfg.num_heads)[:, None, None]
            * positions.astype(jnp.float32)[None, None, :])


def _logits(cfg, params, x):
    head = params["embed"].T if cfg.tie_embeddings else params["lm_head"]
    out = (x @ head.astype(x.dtype)).astype(jnp.float32)
    if "lm_head_b" in params:
        out = out + params["lm_head_b"].astype(jnp.float32)
    return out


# ---------------------------------------------------------------------------
# Prefill
# ---------------------------------------------------------------------------
def paged_prefill(cfg: TransformerConfig, params, ids: jnp.ndarray,
                  prompt_len: jnp.ndarray, cache: Dict[str, jnp.ndarray],
                  block_ids: jnp.ndarray, offsets: jnp.ndarray,
                  use_kernel: bool = True, topo=None,
                  lora=None, adapter_ids=None
                  ) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
    """ids [1, C] (padded prompt); prompt_len scalar; block_ids/offsets [C]
    map chunk position -> (cache block, slot) with padding -> null block.
    Returns (last-token logits [V], cache).

    ``use_kernel`` runs the prompt's causal self-attention through the
    Pallas flash kernel (the reference's blocked-flash prefill,
    inference/v2/kernels/ragged_ops/blocked_flash/) — padding keys sit at
    positions AFTER every valid query, so causal masking excludes them and
    no explicit valid mask is needed; K/V still scatter into the cache
    blocks in the same pass."""
    C = ids.shape[1]
    nh, nkv, hd = cfg.num_heads, cfg.kv_heads, cfg.head_dim
    # shape gates only: off-TPU the kernel runs in interpret mode (slow but
    # identical math), which is what lets CPU tests cover this path
    flash_ok = (use_kernel and C % 128 == 0 and hd % 8 == 0
                and cfg.positional != "alibi")
    params = _deq_nonlayer(params)
    x = params["embed"][ids[0]]                                # [C, H]
    if cfg.embed_scale != 1.0:
        x = x * jnp.asarray(cfg.embed_scale, x.dtype)
    x = _embed_ln(cfg, params, x)
    if cfg.positional == "learned":
        # the bucket C may round past max_seq_len; clip like paged_continue
        x = x + params["pos_embed"][
            jnp.clip(jnp.arange(C), 0, cfg.max_seq_len - 1)]
    pos = jnp.arange(C)
    cos, sin = _rope_at(cfg, pos)                              # [C, half]
    valid = pos < prompt_len                                   # [C]
    causal = pos[:, None] >= pos[None, :]
    mask = causal & valid[None, :]                             # [C, C]

    def layer_fn(carry, inputs):
        x, kc, vc, ksc, vsc = carry
        lp, l = inputs[0], inputs[1]
        ll = inputs[2] if lora is not None else None
        lp = _deq_layer(lp)
        hn = _norm(cfg, x, lp["attn_norm"], lp.get("attn_norm_b"))
        q, k, v = qkv_proj(lp, hn)
        q, v = _lora_qv(ll, hn, adapter_ids, q, v)
        q = q.reshape(C, nh, hd)
        k = k.reshape(C, nkv, hd)
        v = v.reshape(C, nkv, hd)
        if cfg.positional == "rope":
            q = _rotate(q, cos[:, None], sin[:, None])
            k = _rotate(k, cos[:, None], sin[:, None])
        kc, ksc = _kv_write(kc, ksc, l, block_ids, offsets, k)
        vc, vsc = _kv_write(vc, vsc, l, block_ids, offsets, v)
        if flash_ok:
            from ...ops.flash_attention import flash_attention

            o = flash_attention(
                q.transpose(1, 0, 2)[None],      # [1, nh, C, hd]
                k.transpose(1, 0, 2)[None],      # [1, nkv, C, hd]
                v.transpose(1, 0, 2)[None],
                causal=True)[0].transpose(1, 0, 2).reshape(C, nh * hd)
        else:
            kf, vf = k, v
            if nkv != nh:
                kf = jnp.repeat(kf, nh // nkv, axis=1)
                vf = jnp.repeat(vf, nh // nkv, axis=1)
            scores = jnp.einsum("qhd,khd->hqk", q, kf).astype(jnp.float32)
            scores = scores / jnp.sqrt(jnp.asarray(hd, jnp.float32))
            if cfg.positional == "alibi":
                scores = scores + _alibi_row(cfg, pos)
            scores = jnp.where(mask[None], scores, NEG_INF)
            probs = jax.nn.softmax(scores, axis=-1).astype(x.dtype)
            o = jnp.einsum("hqk,khd->qhd", probs, vf).reshape(C, nh * hd)
        if cfg.parallel_residual:
            # Falcon block: attention and MLP both read the normed input;
            # one residual add (NeoX parallel_norms norms separately)
            hn2 = (_norm(cfg, x, lp["mlp_norm"], lp.get("mlp_norm_b"))
                   if cfg.parallel_norms else hn)
            x = x + out_proj(lp, o) + _mlp(cfg, lp, hn2, topo)
            return (x, kc, vc, ksc, vsc), None
        x = x + out_proj(lp, o)
        hn = _norm(cfg, x, lp["mlp_norm"], lp.get("mlp_norm_b"))
        x = x + _mlp(cfg, lp, hn, topo)
        return (x, kc, vc, ksc, vsc), None

    (x, kc, vc, ksc, vsc), _ = jax.lax.scan(
        layer_fn, (x, cache["k"], cache["v"],
                   cache.get("ks"), cache.get("vs")),
        (params["layers"], jnp.arange(cfg.num_layers))
        + ((lora,) if lora is not None else ()))
    x = _norm(cfg, x, params["final_norm"], params.get("final_norm_b"))
    last = jnp.take(x, prompt_len - 1, axis=0)                  # [H]
    return _logits(cfg, params, last), _cache_dict(kc, vc, ksc, vsc)


# ---------------------------------------------------------------------------
# Chunked continuation
# ---------------------------------------------------------------------------
def paged_continue(cfg: TransformerConfig, params, ids: jnp.ndarray,
                   start_pos: jnp.ndarray, n_new: jnp.ndarray,
                   cache: Dict[str, jnp.ndarray], block_ids: jnp.ndarray,
                   offsets: jnp.ndarray, block_table: jnp.ndarray,
                   block_size: int, topo=None,
                   greedy_window: int = 0,
                   lora=None, adapter_ids=None
                   ) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
    """Multi-token continuation of ONE existing sequence in a single pass
    (the reference's chunked prefill over ragged atoms,
    inference/v2/kernels/ragged_ops/atom_builder + blocked_flash): the
    chunk's K/V are scattered into the sequence's cache blocks, then every
    chunk token attends over the sequence's full block table (cached prefix
    + the chunk itself) with causal masking — replacing the token-at-a-time
    decode loop the engine previously ran for multi-token puts.

    ids [1, C] (padded chunk); start_pos = tokens already cached; n_new =
    valid tokens in the chunk; block_ids/offsets [C] map chunk position ->
    (cache block, slot), padding -> null block; block_table [MB] is the
    sequence's full table. Returns (last-token logits [V], cache).
    """
    C = ids.shape[1]
    MB = block_table.shape[0]
    ctx = MB * block_size
    nh, nkv, hd = cfg.num_heads, cfg.kv_heads, cfg.head_dim
    params = _deq_nonlayer(params)
    x = params["embed"][ids[0]]                                 # [C, H]
    if cfg.embed_scale != 1.0:
        x = x * jnp.asarray(cfg.embed_scale, x.dtype)
    x = _embed_ln(cfg, params, x)
    pos = start_pos + jnp.arange(C)                             # [C]
    if cfg.positional == "learned":
        x = x + params["pos_embed"][jnp.clip(pos, 0, cfg.max_seq_len - 1)]
    cos, sin = _rope_at(cfg, pos)
    ctx_pos = jnp.arange(ctx)
    # each chunk token sees cache positions up to and including itself
    mask = ctx_pos[None, :] <= pos[:, None]                     # [C, ctx]

    def layer_fn(carry, inputs):
        x, kc, vc, ksc, vsc = carry
        lp, l = inputs[0], inputs[1]
        ll = inputs[2] if lora is not None else None
        lp = _deq_layer(lp)
        hn = _norm(cfg, x, lp["attn_norm"], lp.get("attn_norm_b"))
        q, k, v = qkv_proj(lp, hn)
        q, v = _lora_qv(ll, hn, adapter_ids, q, v)
        q = q.reshape(C, nh, hd)
        k = k.reshape(C, nkv, hd)
        v = v.reshape(C, nkv, hd)
        if cfg.positional == "rope":
            q = _rotate(q, cos[:, None], sin[:, None])
            k = _rotate(k, cos[:, None], sin[:, None])
        kc, ksc = _kv_write(kc, ksc, l, block_ids, offsets, k)
        vc, vsc = _kv_write(vc, vsc, l, block_ids, offsets, v)
        kpages = _kv_read(kc, ksc, l, block_table,
                          x.dtype).reshape(ctx, nkv, hd)
        vpages = _kv_read(vc, vsc, l, block_table,
                          x.dtype).reshape(ctx, nkv, hd)
        if nkv != nh:
            kpages = jnp.repeat(kpages, nh // nkv, axis=1)
            vpages = jnp.repeat(vpages, nh // nkv, axis=1)
        scores = jnp.einsum("qhd,chd->hqc", q, kpages).astype(jnp.float32)
        scores = scores / jnp.sqrt(jnp.asarray(hd, jnp.float32))
        if cfg.positional == "alibi":
            scores = scores + _alibi_row(cfg, ctx_pos)
        scores = jnp.where(mask[None], scores, NEG_INF)
        probs = jax.nn.softmax(scores, axis=-1).astype(x.dtype)
        o = jnp.einsum("hqc,chd->qhd", probs, vpages).reshape(C, nh * hd)
        if cfg.parallel_residual:
            # Falcon block: attention and MLP both read the normed input;
            # one residual add (NeoX parallel_norms norms separately)
            hn2 = (_norm(cfg, x, lp["mlp_norm"], lp.get("mlp_norm_b"))
                   if cfg.parallel_norms else hn)
            x = x + out_proj(lp, o) + _mlp(cfg, lp, hn2, topo)
            return (x, kc, vc, ksc, vsc), None
        x = x + out_proj(lp, o)
        hn = _norm(cfg, x, lp["mlp_norm"], lp.get("mlp_norm_b"))
        x = x + _mlp(cfg, lp, hn, topo)
        return (x, kc, vc, ksc, vsc), None

    (x, kc, vc, ksc, vsc), _ = jax.lax.scan(
        layer_fn, (x, cache["k"], cache["v"],
                   cache.get("ks"), cache.get("vs")),
        (params["layers"], jnp.arange(cfg.num_layers))
        + ((lora,) if lora is not None else ()))
    x = _norm(cfg, x, params["final_norm"], params.get("final_norm_b"))
    if greedy_window:
        # speculative verification: greedy token ids for the first
        # ``greedy_window`` fed positions — the projection runs on the
        # sliced window (not the padded bucket) and only [window] int32
        # crosses to host, keeping the decode loop's transfer discipline
        from .sampling import greedy_tokens
        ids_out = greedy_tokens(_logits(cfg, params, x[:greedy_window]))
        return ids_out, _cache_dict(kc, vc, ksc, vsc)
    last = jnp.take(x, n_new - 1, axis=0)
    return _logits(cfg, params, last), _cache_dict(kc, vc, ksc, vsc)


# ---------------------------------------------------------------------------
# Decode
# ---------------------------------------------------------------------------
def paged_decode(cfg: TransformerConfig, params, toks: jnp.ndarray,
                 pos: jnp.ndarray, block_tables: jnp.ndarray,
                 cache: Dict[str, jnp.ndarray], active: jnp.ndarray,
                 block_size: int, use_kernel: bool = True, topo=None,
                 lora=None, adapter_ids=None
                 ) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
    """toks/pos/active [N]; block_tables [N, MB]. One token per sequence;
    returns ([N, V] logits, cache). Inactive rows write to the null block
    and produce garbage logits (masked by the caller). ``use_kernel`` runs
    the Pallas paged-attention kernel (kernels/paged_attention.py) instead
    of the materializing gather fallback."""
    N, MB = block_tables.shape
    nh, nkv, hd = cfg.num_heads, cfg.kv_heads, cfg.head_dim
    ctx = MB * block_size
    params = _deq_nonlayer(params)
    x = params["embed"][toks]                                   # [N, H]
    if cfg.embed_scale != 1.0:
        x = x * jnp.asarray(cfg.embed_scale, x.dtype)
    x = _embed_ln(cfg, params, x)
    if cfg.positional == "learned":
        x = x + params["pos_embed"][jnp.clip(pos, 0, cfg.max_seq_len - 1)]
    cos, sin = _rope_at(cfg, pos)                               # [N, half]
    blk = jnp.take_along_axis(block_tables,
                              (pos // block_size)[:, None], axis=1)[:, 0]
    blk = jnp.where(active, blk, 0)
    off = pos % block_size
    ctx_pos = jnp.arange(ctx)
    attn_mask = ctx_pos[None, :] <= pos[:, None]                # [N, ctx]

    def layer_fn(carry, inputs):
        x, kc, vc, ksc, vsc = carry
        lp, l = inputs[0], inputs[1]
        ll = inputs[2] if lora is not None else None
        lp = _deq_layer(lp)
        hn = _norm(cfg, x, lp["attn_norm"], lp.get("attn_norm_b"))
        q, k, v = qkv_proj(lp, hn)
        q, v = _lora_qv(ll, hn, adapter_ids, q, v)
        q = q.reshape(N, nh, hd)
        k = k.reshape(N, nkv, hd)
        v = v.reshape(N, nkv, hd)
        if cfg.positional == "rope":
            q = _rotate(q, cos[:, None], sin[:, None])
            k = _rotate(k, cos[:, None], sin[:, None])
        kc, ksc = _kv_write(kc, ksc, l, blk, off, k)
        vc, vsc = _kv_write(vc, vsc, l, blk, off, v)
        if use_kernel:
            from .kernels.paged_attention import paged_attention
            o = paged_attention(
                q, kc[l], vc[l], block_tables, pos + 1,
                k_scale=None if ksc is None else ksc[l],
                v_scale=None if vsc is None else vsc[l]).reshape(N, nh * hd)
        else:
            # gather this sequence's pages: [N, MB, bs, nkv, hd] -> [N, ctx, ..]
            kpages = _kv_read(kc, ksc, l, block_tables,
                              x.dtype).reshape(N, ctx, nkv, hd)
            vpages = _kv_read(vc, vsc, l, block_tables,
                              x.dtype).reshape(N, ctx, nkv, hd)
            if nkv != nh:
                kpages = jnp.repeat(kpages, nh // nkv, axis=2)
                vpages = jnp.repeat(vpages, nh // nkv, axis=2)
            scores = jnp.einsum("nhd,nchd->nhc", q, kpages).astype(jnp.float32)
            scores = scores / jnp.sqrt(jnp.asarray(hd, jnp.float32))
            if cfg.positional == "alibi":
                scores = scores + _alibi_row(cfg, ctx_pos)[None, :, 0, :]
            scores = jnp.where(attn_mask[:, None, :], scores, NEG_INF)
            probs = jax.nn.softmax(scores, axis=-1).astype(x.dtype)
            o = jnp.einsum("nhc,nchd->nhd", probs, vpages).reshape(N, nh * hd)
        if cfg.parallel_residual:
            # Falcon block: attention and MLP both read the normed input;
            # one residual add (NeoX parallel_norms norms separately)
            hn2 = (_norm(cfg, x, lp["mlp_norm"], lp.get("mlp_norm_b"))
                   if cfg.parallel_norms else hn)
            x = x + out_proj(lp, o) + _mlp(cfg, lp, hn2, topo)
            return (x, kc, vc, ksc, vsc), None
        x = x + out_proj(lp, o)
        hn = _norm(cfg, x, lp["mlp_norm"], lp.get("mlp_norm_b"))
        x = x + _mlp(cfg, lp, hn, topo)
        return (x, kc, vc, ksc, vsc), None

    (x, kc, vc, ksc, vsc), _ = jax.lax.scan(
        layer_fn, (x, cache["k"], cache["v"],
                   cache.get("ks"), cache.get("vs")),
        (params["layers"], jnp.arange(cfg.num_layers))
        + ((lora,) if lora is not None else ()))
    x = _norm(cfg, x, params["final_norm"], params.get("final_norm_b"))
    return _logits(cfg, params, x), _cache_dict(kc, vc, ksc, vsc)


# ---------------------------------------------------------------------------
# Ragged unified step (mixed prefill + decode, one launch)
# ---------------------------------------------------------------------------
def paged_ragged_step(cfg: TransformerConfig, params, ids: jnp.ndarray,
                      row_ids: jnp.ndarray, pos: jnp.ndarray,
                      lengths: jnp.ndarray, write_blocks: jnp.ndarray,
                      write_offsets: jnp.ndarray,
                      block_tables: jnp.ndarray, last_index: jnp.ndarray,
                      cache: Dict[str, jnp.ndarray], block_size: int,
                      use_kernel: bool = True, topo=None,
                      lora=None, adapter_ids=None
                      ) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
    """One compiled program for a MIXED batch (the Ragged Paged
    Attention layout, kernels/ragged_attention.py): prefill chunks,
    continuations and decode rows arrive as one flat token buffer
    ``ids`` [TB] with per-token descriptors — ``row_ids`` (token ->
    batch row), ``pos`` (absolute cache position), ``lengths`` (causal
    bound = pos+1; 0 for padding) and the KV write-set
    ``write_blocks``/``write_offsets`` — plus per-row ``block_tables``
    [RB, MBw] and ``last_index`` [RB] (flat index of each row's last
    valid token). Replaces the separate paged_prefill / paged_continue /
    paged_decode dispatches for everything the scheduler composes into a
    step. Returns ([RB, V] last-token logits per row, cache).

    The new tokens' K/V scatter into the pool inside the scanned layer
    body (padding tokens land in the null block), then every token
    attends over ITS row's block table up to its own causal bound —
    in-chunk causality and cached-prefix attention are the same page
    walk. Padding rows/tokens produce garbage logits the caller
    discards; garbage never reaches live rows because tokens only mix
    through attention, which is row-local by construction."""
    T = ids.shape[0]
    RB, MBw = block_tables.shape
    ctx = MBw * block_size
    nh, nkv, hd = cfg.num_heads, cfg.kv_heads, cfg.head_dim
    params = _deq_nonlayer(params)
    x = params["embed"][ids]                                     # [T, H]
    if cfg.embed_scale != 1.0:
        x = x * jnp.asarray(cfg.embed_scale, x.dtype)
    x = _embed_ln(cfg, params, x)
    if cfg.positional == "learned":
        x = x + params["pos_embed"][jnp.clip(pos, 0, cfg.max_seq_len - 1)]
    cos, sin = _rope_at(cfg, pos)                                # [T, half]
    ctx_pos = jnp.arange(ctx)
    attn_mask = ctx_pos[None, :] < lengths[:, None]              # [T, ctx]
    # multi-tenant LoRA: ``adapter_ids`` arrives PER ROW [RB] (the
    # descriptor layout carries one adapter per sequence); gather it to
    # per token here so the bank lookup inside the scanned layer body is
    # a plain [T] indexed read — padding rows carry slot 0 (base)
    tok_aid = adapter_ids[row_ids] if lora is not None else None

    def layer_fn(carry, inputs):
        x, kc, vc, ksc, vsc = carry
        lp, l = inputs[0], inputs[1]
        ll = inputs[2] if lora is not None else None
        lp = _deq_layer(lp)
        hn = _norm(cfg, x, lp["attn_norm"], lp.get("attn_norm_b"))
        q, k, v = qkv_proj(lp, hn)
        q, v = _lora_qv(ll, hn, tok_aid, q, v)
        q = q.reshape(T, nh, hd)
        k = k.reshape(T, nkv, hd)
        v = v.reshape(T, nkv, hd)
        if cfg.positional == "rope":
            q = _rotate(q, cos[:, None], sin[:, None])
            k = _rotate(k, cos[:, None], sin[:, None])
        kc, ksc = _kv_write(kc, ksc, l, write_blocks, write_offsets, k)
        vc, vsc = _kv_write(vc, vsc, l, write_blocks, write_offsets, v)
        if use_kernel:
            from .kernels.ragged_attention import ragged_attention
            o = ragged_attention(
                q, kc[l], vc[l], row_ids, lengths, block_tables,
                k_scale=None if ksc is None else ksc[l],
                v_scale=None if vsc is None else vsc[l]).reshape(T, nh * hd)
        else:
            # gather each ROW's pages once, indirect per token: the
            # materializing fallback (parity reference + tp/alibi/quant)
            kpages = _kv_read(kc, ksc, l, block_tables,
                              x.dtype).reshape(RB, ctx, nkv, hd)
            vpages = _kv_read(vc, vsc, l, block_tables,
                              x.dtype).reshape(RB, ctx, nkv, hd)
            ktok = kpages[row_ids]                      # [T, ctx, nkv, hd]
            vtok = vpages[row_ids]
            if nkv != nh:
                ktok = jnp.repeat(ktok, nh // nkv, axis=2)
                vtok = jnp.repeat(vtok, nh // nkv, axis=2)
            scores = jnp.einsum("thd,tchd->thc", q,
                                ktok).astype(jnp.float32)
            scores = scores / jnp.sqrt(jnp.asarray(hd, jnp.float32))
            if cfg.positional == "alibi":
                scores = scores + _alibi_row(cfg, ctx_pos)[None, :, 0, :]
            scores = jnp.where(attn_mask[:, None, :], scores, NEG_INF)
            probs = jax.nn.softmax(scores, axis=-1).astype(x.dtype)
            o = jnp.einsum("thc,tchd->thd", probs,
                           vtok).reshape(T, nh * hd)
        if cfg.parallel_residual:
            # Falcon block: attention and MLP both read the normed input;
            # one residual add (NeoX parallel_norms norms separately)
            hn2 = (_norm(cfg, x, lp["mlp_norm"], lp.get("mlp_norm_b"))
                   if cfg.parallel_norms else hn)
            x = x + out_proj(lp, o) + _mlp(cfg, lp, hn2, topo)
            return (x, kc, vc, ksc, vsc), None
        x = x + out_proj(lp, o)
        hn = _norm(cfg, x, lp["mlp_norm"], lp.get("mlp_norm_b"))
        x = x + _mlp(cfg, lp, hn, topo)
        return (x, kc, vc, ksc, vsc), None

    (x, kc, vc, ksc, vsc), _ = jax.lax.scan(
        layer_fn, (x, cache["k"], cache["v"],
                   cache.get("ks"), cache.get("vs")),
        (params["layers"], jnp.arange(cfg.num_layers))
        + ((lora,) if lora is not None else ()))
    x = _norm(cfg, x, params["final_norm"], params.get("final_norm_b"))
    last = x[last_index]                                         # [RB, H]
    return _logits(cfg, params, last), _cache_dict(kc, vc, ksc, vsc)


# ---------------------------------------------------------------------------
# Fused multi-token decode window
# ---------------------------------------------------------------------------
def paged_decode_window(cfg: TransformerConfig, params, toks: jnp.ndarray,
                        pos: jnp.ndarray, block_tables: jnp.ndarray,
                        cache: Dict[str, jnp.ndarray],
                        steps_left: jnp.ndarray, eos_ids: jnp.ndarray,
                        block_size: int, window: int,
                        rng=None, row_seeds: jnp.ndarray = None,
                        gen_idx0: jnp.ndarray = None,
                        temp: jnp.ndarray = None, topp: jnp.ndarray = None,
                        topk: jnp.ndarray = None,
                        use_kernel: bool = True, topo=None,
                        lora=None, adapter_ids=None
                        ) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
    """Up to ``window`` decode steps entirely on device — the answer to
    the dispatch-bound per-token loop (one Python round-trip + [N] int32
    transfer PER TOKEN). One ``lax.while_loop`` runs cache write, paged
    attention, sampling, EOS masking and block-table advancement for K
    steps; the only host traffic per window is the [N, window] int32
    token block (plus the donated cache staying resident).

    Block tables never change on device: block boundaries are arithmetic
    in the token position (``pos // block_size``), so as long as the host
    pre-allocates every block the window can write (``steps_left[i]``
    tokens from ``pos[i]``), advancement is just the existing indexing in
    ``paged_decode``. That pre-allocation is the caller's contract.

    Per-row state: ``toks``/``pos`` [N] are the fed token and its cache
    position; ``steps_left`` [N] caps each row's steps (rows with
    exhausted generation budget or sequence room mask out — K stays a
    compile-time constant across ragged budgets); ``eos_ids`` [N] is the
    per-row stop token (-1 = none). A row that emits its EOS goes
    inactive: the EOS is emitted but never fed back (the same
    last-token-never-fed invariant as the per-token loop), later steps
    write to the null block. The loop exits early when every row is
    inactive.

    Sampling (``rng`` is not None): per-row keys
    ``fold_in(fold_in(rng, row_seeds[i]), gen_idx0[i] + s)`` make each
    row's draw depend only on its own seed and its own generated-token
    index — invariant to batch composition, so fused and per-token
    streams are bit-identical under a fixed seed.

    Returns (tokens [N, window] int32 with -1 in steps a row did not
    take, cache). Emitted tokens form a prefix of each row.
    """
    N = toks.shape[0]
    sampled = rng is not None

    def body(state):
        s, toks, pos, active, out, cache = state
        logits, cache = paged_decode(cfg, params, toks, pos, block_tables,
                                     cache, active, block_size,
                                     use_kernel=use_kernel, topo=topo,
                                     lora=lora, adapter_ids=adapter_ids)
        if sampled:
            from .sampling import fold_in_rows, sample_tokens_rowwise
            keys = fold_in_rows(rng, row_seeds, gen_idx0 + s)
            nxt = sample_tokens_rowwise(logits, keys, temp, topp, topk)
        else:
            from .sampling import greedy_tokens
            nxt = greedy_tokens(logits)
        out = out.at[:, s].set(jnp.where(active, nxt, -1))
        pos = jnp.where(active, pos + 1, pos)
        toks = jnp.where(active, nxt, toks)
        active = active & (nxt != eos_ids) & (s + 1 < steps_left)
        return s + 1, toks, pos, active, out, cache

    def cond(state):
        s, _, _, active, _, _ = state
        return (s < window) & jnp.any(active)

    state = (jnp.asarray(0, jnp.int32), toks, pos, steps_left > 0,
             jnp.full((N, window), -1, jnp.int32), cache)
    _, _, _, _, out, cache = jax.lax.while_loop(cond, body, state)
    return out, cache


# ---------------------------------------------------------------------------
# Speculative decode window (draft-model propose -> target verify, on device)
# ---------------------------------------------------------------------------
def _paged_verify(cfg: TransformerConfig, params, fed: jnp.ndarray,
                  pos0: jnp.ndarray, block_tables: jnp.ndarray,
                  cache: Dict[str, jnp.ndarray], active: jnp.ndarray,
                  block_size: int, use_kernel: bool = True, topo=None,
                  lora=None, adapter_ids=None
                  ) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
    """Multi-query target forward for in-window speculation: score the
    ``S = spec_k + 1`` fed tokens of every row in ONE pass. ``fed``
    [N, S] (fed[:, 0] is the row's pending token, fed[:, 1:] the draft's
    proposals); ``pos0`` [N] is fed[:, 0]'s cache position. The fed
    tokens' K/V scatter into each row's blocks at pos0..pos0+S-1
    (inactive rows -> null block), then every fed token attends over its
    row's table up to its own position — the same masked-softmax math as
    :func:`paged_continue`'s verify (pinned bit-identical to the decode
    loop), batched over rows. Returns (greedy ids [N, S] int32, cache):
    ids[:, j] is the target's next token AFTER seeing fed[:, :j+1], which
    is exactly what the plain loop would emit at that step — the accept
    rule compares ids[:, :S-1] against fed[:, 1:]."""
    N, S = fed.shape
    MB = block_tables.shape[1]
    ctx = MB * block_size
    nh, nkv, hd = cfg.num_heads, cfg.kv_heads, cfg.head_dim
    params = _deq_nonlayer(params)
    x = params["embed"][fed]                                    # [N, S, H]
    if cfg.embed_scale != 1.0:
        x = x * jnp.asarray(cfg.embed_scale, x.dtype)
    x = _embed_ln(cfg, params, x)
    posm = pos0[:, None] + jnp.arange(S)[None, :]               # [N, S]
    if cfg.positional == "learned":
        x = x + params["pos_embed"][jnp.clip(posm, 0, cfg.max_seq_len - 1)]
    cos, sin = _rope_at(cfg, posm)                              # [N, S, half]
    blkm = jnp.take_along_axis(block_tables, posm // block_size, axis=1)
    blkm = jnp.where(active[:, None], blkm, 0).reshape(N * S)
    offm = (posm % block_size).reshape(N * S)
    ctx_pos = jnp.arange(ctx)
    # each fed token sees cache positions up to and including itself
    mask = ctx_pos[None, None, :] <= posm[:, :, None]           # [N, S, ctx]
    row_ids = jnp.repeat(jnp.arange(N, dtype=jnp.int32), S)     # [N*S]
    lengths = jnp.where(active[:, None], posm + 1, 0).reshape(N * S)

    def layer_fn(carry, inputs):
        x, kc, vc, ksc, vsc = carry
        lp, l = inputs[0], inputs[1]
        ll = inputs[2] if lora is not None else None
        lp = _deq_layer(lp)
        hn = _norm(cfg, x, lp["attn_norm"], lp.get("attn_norm_b"))
        q, k, v = qkv_proj(lp, hn)
        if ll is not None:
            # bank gather broadcast over the S fed positions of each row
            q = q + _lora_delta(ll["qa"], ll["qb"],
                                hn.reshape(N * S, -1),
                                jnp.repeat(adapter_ids, S)).reshape(q.shape)
            v = v + _lora_delta(ll["va"], ll["vb"],
                                hn.reshape(N * S, -1),
                                jnp.repeat(adapter_ids, S)).reshape(v.shape)
        q = q.reshape(N, S, nh, hd)
        k = k.reshape(N, S, nkv, hd)
        v = v.reshape(N, S, nkv, hd)
        if cfg.positional == "rope":
            q = _rotate(q, cos[..., None, :], sin[..., None, :])
            k = _rotate(k, cos[..., None, :], sin[..., None, :])
        kc, ksc = _kv_write(kc, ksc, l, blkm, offm,
                            k.reshape(N * S, nkv, hd))
        vc, vsc = _kv_write(vc, vsc, l, blkm, offm,
                            v.reshape(N * S, nkv, hd))
        if use_kernel:
            from .kernels.ragged_attention import ragged_attention
            o = ragged_attention(
                q.reshape(N * S, nh, hd), kc[l], vc[l], row_ids, lengths,
                block_tables,
                k_scale=None if ksc is None else ksc[l],
                v_scale=None if vsc is None else vsc[l]
            ).reshape(N, S, nh * hd)
        else:
            kpages = _kv_read(kc, ksc, l, block_tables,
                              x.dtype).reshape(N, ctx, nkv, hd)
            vpages = _kv_read(vc, vsc, l, block_tables,
                              x.dtype).reshape(N, ctx, nkv, hd)
            if nkv != nh:
                kpages = jnp.repeat(kpages, nh // nkv, axis=2)
                vpages = jnp.repeat(vpages, nh // nkv, axis=2)
            scores = jnp.einsum("nshd,nchd->nhsc", q,
                                kpages).astype(jnp.float32)
            scores = scores / jnp.sqrt(jnp.asarray(hd, jnp.float32))
            if cfg.positional == "alibi":
                scores = scores + _alibi_row(cfg, ctx_pos)[None]
            scores = jnp.where(mask[:, None], scores, NEG_INF)
            probs = jax.nn.softmax(scores, axis=-1).astype(x.dtype)
            o = jnp.einsum("nhsc,nchd->nshd", probs,
                           vpages).reshape(N, S, nh * hd)
        if cfg.parallel_residual:
            hn2 = (_norm(cfg, x, lp["mlp_norm"], lp.get("mlp_norm_b"))
                   if cfg.parallel_norms else hn)
            x = x + out_proj(lp, o) + _mlp(cfg, lp, hn2, topo)
            return (x, kc, vc, ksc, vsc), None
        x = x + out_proj(lp, o)
        hn = _norm(cfg, x, lp["mlp_norm"], lp.get("mlp_norm_b"))
        x = x + _mlp(cfg, lp, hn, topo)
        return (x, kc, vc, ksc, vsc), None

    (x, kc, vc, ksc, vsc), _ = jax.lax.scan(
        layer_fn, (x, cache["k"], cache["v"],
                   cache.get("ks"), cache.get("vs")),
        (params["layers"], jnp.arange(cfg.num_layers))
        + ((lora,) if lora is not None else ()))
    x = _norm(cfg, x, params["final_norm"], params.get("final_norm_b"))
    from .sampling import greedy_tokens
    return greedy_tokens(_logits(cfg, params, x)), \
        _cache_dict(kc, vc, ksc, vsc)


def paged_spec_decode_window(cfg: TransformerConfig, dcfg: TransformerConfig,
                             params, dparams, toks: jnp.ndarray,
                             pos: jnp.ndarray, block_tables: jnp.ndarray,
                             cache: Dict[str, jnp.ndarray],
                             dcache: Dict[str, jnp.ndarray],
                             steps_left: jnp.ndarray, eos_ids: jnp.ndarray,
                             block_size: int, window: int, spec_k: int,
                             use_kernel: bool = True, topo=None,
                             lora=None, adapter_ids=None):
    """Draft-model speculative decoding fused into the jitted decode
    window: every ``lax.while_loop`` round runs propose(k) -> target-
    verify -> accept-prefix entirely on device, so speculation adds ZERO
    host round-trips on top of the fused window's one [N, window] token
    transfer. Greedy-only (the engine rejects sampling + speculation).

    Per round, for every running row (active and window not yet full):

      1. the DRAFT model proposes ``spec_k`` greedy tokens with
         ``spec_k + 1`` sequential single-token decodes over its OWN KV
         pool sharing the target's block tables (same paged layout, so
         block advancement is the same position arithmetic). The extra
         (k+1)-th feed writes the last proposal's draft K/V so an all-
         accept round leaves no hole in the draft cache; rejected
         positions hold stale K/V that position masking never attends
         and the next round overwrites — rollback is free, exactly like
         the host n-gram path.
      2. the TARGET verifies all ``spec_k + 1`` fed tokens in ONE
         multi-query pass (:func:`_paged_verify`) — K/V written, greedy
         ids returned.
      3. accept the longest matching prefix: ``m = accepted + 1``
         emissions (the +1 is the target's own next token — correction
         on a miss, bonus on an all-accept), truncated by the row's
         remaining window/steps budget and by an emitted EOS.

    ``spec_k`` is a compile-time constant (the draft loop is unrolled),
    bucketed by the engine like the window itself — per-request draft
    lengths ride the steady jit cache instead of growing it.

    The host's pre-allocation contract widens: the window can write up
    to ``steps_left[i] + spec_k`` tokens from ``pos[i]`` (the last
    round's rejected tail), so the caller pre-allocates blocks AND
    leaves ``spec_k`` tokens of sequence room beyond the step budget.

    Returns (tokens [N, window] int32, -1 padded — emissions form a
    prefix of each row; stats [4] int32 = (drafted, accepted,
    miss_rounds, row_rounds); target cache; draft cache).
    """
    N = toks.shape[0]
    S = spec_k + 1
    sidx = jnp.arange(S)
    rows = jnp.arange(N)

    def body(state):
        oi, toks, pos, active, out, cache, dcache, st = state
        run = active & (oi < window)
        # -- 1. draft proposes (unrolled: spec_k is static) -------------
        t, p = toks, pos
        seq = [toks]
        for j in range(S):
            dlogits, dcache = paged_decode(
                dcfg, dparams, t, p, block_tables, dcache, run,
                block_size, use_kernel=use_kernel, topo=topo)
            if j < spec_k:
                from .sampling import greedy_tokens
                t = greedy_tokens(dlogits)
                seq.append(t)
                p = p + 1
        fed = jnp.stack(seq, axis=1)                         # [N, S]
        # -- 2. target verifies every fed token in one pass -------------
        ids_v, cache = _paged_verify(
            cfg, params, fed, pos, block_tables, cache, run, block_size,
            use_kernel=use_kernel, topo=topo, lora=lora,
            adapter_ids=adapter_ids)
        # -- 3. accept the matching prefix + the target's own token -----
        matches = ids_v[:, :spec_k] == fed[:, 1:]            # [N, k]
        acc = jnp.sum(jnp.cumprod(matches.astype(jnp.int32), axis=1),
                      axis=1)                                # [N]
        m = jnp.minimum(acc + 1, jnp.minimum(window - oi, steps_left - oi))
        m = jnp.where(run, jnp.maximum(m, 0), 0)
        # an emitted EOS truncates the acceptance and retires the row
        # (emitted, never fed back — the plain loop's invariant)
        within = sidx[None, :] < m[:, None]
        is_eos = within & (ids_v == eos_ids[:, None])
        any_eos = jnp.any(is_eos, axis=1)
        m = jnp.where(any_eos, jnp.argmax(is_eos, axis=1) + 1, m)
        # -- emit: out[i, oi+j] = ids_v[i, j] for j < m (unrolled; cols
        # past the row's slice land out of bounds and drop) ------------
        for j in range(S):
            col = jnp.where(run & (j < m), oi + j, window)
            out = out.at[rows, col].set(ids_v[:, j], mode="drop")
        # -- advance ----------------------------------------------------
        m_safe = jnp.maximum(m, 1)
        last = jnp.take_along_axis(ids_v, (m_safe - 1)[:, None],
                                   axis=1)[:, 0]
        toks = jnp.where(run, last, toks)
        pos = jnp.where(run, pos + m, pos)
        oi = oi + m
        active = jnp.where(run, (~any_eos) & (oi < steps_left), active)
        drafted, accepted, miss, rounds = st
        st = (drafted + jnp.sum(jnp.where(run, spec_k, 0)),
              accepted + jnp.sum(jnp.maximum(m - 1, 0)),
              miss + jnp.sum((run & (acc == 0)).astype(jnp.int32)),
              rounds + jnp.sum(run.astype(jnp.int32)))
        return oi, toks, pos, active, out, cache, dcache, st

    def cond(state):
        oi, _, _, active, *_ = state
        return jnp.any(active & (oi < window))

    zero = jnp.asarray(0, jnp.int32)
    state = (jnp.zeros(N, jnp.int32), toks, pos, steps_left > 0,
             jnp.full((N, window), -1, jnp.int32), cache, dcache,
             (zero, zero, zero, zero))
    oi, _, _, _, out, cache, dcache, st = jax.lax.while_loop(
        cond, body, state)
    return out, jnp.stack(st), cache, dcache
