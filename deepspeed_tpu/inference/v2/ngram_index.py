"""Incremental n-gram occurrence index for prompt-lookup drafting.

``InferenceEngineV2._lookup_draft`` finds the most recent earlier
occurrence of the history's trailing n-gram by scanning the last
``window`` tokens right-to-left — O(window * ngram) pure Python per
speculative round, which is the per-round cost cap the engine's
``_SPEC_SCAN_WINDOW`` exists to bound. :class:`NGramIndex` replaces the
scan with a dict of occurrence positions per n-gram, updated
incrementally as tokens append: O(ngram) per append, O(ngram + log occ)
per draft lookup, and — by construction — the exact same answer as the
scan (window bound included; parity-tested in
tests/unit/inference/test_speculative.py).
"""

from bisect import bisect_left
from typing import Dict, List, Tuple


class NGramIndex:
    """Occurrence positions of every 2..max_n-gram of a growing token
    sequence. Histories only ever grow in the speculative decode loop
    (KV rollback rewinds cache positions, never the emitted rows) —
    ``sync`` leans on that append-only contract to ADOPT the caller's
    row by reference rather than copying it; the index itself adds only
    the gram dict."""

    def __init__(self, max_n: int, window: int):
        self.max_n = max(int(max_n), 2)
        self.window = int(window)
        self.tokens: List[int] = []
        self._indexed = 0          # tokens of self.tokens indexed so far
        # n-gram tuple -> ascending start positions of its LAST TWO
        # occurrences. Two suffice for exactness under the
        # index-then-draft usage: at draft time the trailing gram's
        # latest occurrence IS the tail, the candidate is the one before
        # it, and anything older is even further outside the window.
        # Keys are bounded by the distinct grams in the history (itself
        # bounded by max_seq_len), positions by 2 per gram.
        self._occ: Dict[Tuple[int, ...], List[int]] = {}

    def extend(self, toks) -> None:
        self.tokens.extend(int(t) for t in toks)
        self._index_tail()

    def append(self, tok) -> None:
        self.tokens.append(int(tok))
        self._index_tail()

    def sync(self, history: List[int]) -> None:
        """Adopt ``history`` (the engine's prompt+generated row) by
        reference and index whatever lies beyond the indexed prefix.
        Valid because rows only append — the invariant the engine's
        speculative loop maintains."""
        self.tokens = history
        self._index_tail()

    def _index_tail(self) -> None:
        toks = self.tokens
        while self._indexed < len(toks):
            self._indexed += 1
            i = self._indexed
            for n in range(2, self.max_n + 1):
                if i >= n:
                    occ = self._occ.setdefault(tuple(toks[i - n:i]), [])
                    occ.append(i - n)
                    if len(occ) > 2:
                        del occ[0]

    def has_candidate(self, ngram: int) -> bool:
        """Whether :meth:`draft` would find a candidate right now (any
        matching trailing n-gram inside the window) — the speculation
        chooser's cheap repetitiveness prior before either source has
        accept-rate history for a request."""
        return bool(self.draft(1, ngram))

    def draft(self, k: int, ngram: int) -> List[int]:
        """The k tokens that followed the most recent earlier occurrence
        of the trailing n-gram (n = ngram..2, longest first), with both
        the tail and the matched occurrence inside the trailing
        ``window`` tokens — byte-for-byte the ``_lookup_draft`` scan."""
        if k <= 0:
            return []
        self._index_tail()
        toks = self.tokens
        L = len(toks)
        base = max(0, L - self.window)
        for n in range(min(ngram, self.max_n), 1, -1):
            if L - base <= n:
                continue
            occ = self._occ.get(tuple(toks[L - n:]))
            if not occ:
                continue
            # latest occurrence strictly left of the tail itself...
            j = bisect_left(occ, L - n) - 1
            # ...and starting inside the scan window
            if j >= 0 and occ[j] >= base:
                start = occ[j] + n
                return [int(t) for t in toks[start:start + k]]
        return []
