"""Weight-only quantization (WOQ) for inference.

Reference parity: ``inference/quantization/quantization.py:111`` (int4/int8
weight-only quant for ZeRO-inference). TPU-native design: weight matrices are
stored in HBM as int8 (+per-block fp32 scales; int4 packed two-per-byte) and
dequantized *inside* the jitted forward right before use — XLA fuses the
dequant into the consuming matmul, so at-rest HBM is 1/2 (int8) or 1/4
(packed int4) of bf16 while the MXU still sees bf16 operands. No custom CUDA
dequant kernels needed (reference csrc dequantize kernels).
"""

from typing import Any, Tuple

import jax
import jax.numpy as jnp

from ..ops import quantizer as Q

_MIN_QUANT_SIZE = 4096  # don't quantize norms/biases/small tables


@jax.tree_util.register_pytree_node_class
class QuantizedTensor:
    """int8 blocks + fp32 scales standing in for a dense weight; int4 is
    packed two-per-byte (real 4x at-rest saving).

    A pytree node whose children are the device arrays and whose aux data
    is the logical (shape, dtype, bits, stacked) — so it flows through
    jit/device_put intact.

    ``stacked=True`` marks a per-layer stacked weight (``[L, ...]`` under
    a ``lax.scan``): blocks are laid out ``q [L, nb, block]`` with
    ``shape`` holding the PER-LAYER logical shape, so the scan slices the
    children to ``[nb, block]`` and the body's ``dequantize()`` rebuilds
    one layer — the dequant stays inside the scan body where XLA fuses
    it, instead of materializing every layer's weights up front (which
    made int8 serving use MORE peak HBM than bf16: the r05 AOT serving
    fit caught ``program 13.06G`` of dequantized scan inputs)."""

    def __init__(self, q, s, shape: Tuple[int, ...], dtype: str,
                 bits: int = 8, stacked: bool = False):
        self.q, self.s, self.shape, self.dtype = q, s, tuple(shape), dtype
        self.bits = bits
        self.stacked = stacked

    def dequantize(self):
        unpack = Q.unpack_int4 if self.bits == 4 else (lambda x: x)
        if self.stacked and self.q.ndim == 3:
            # full stacked tensor (outside a scan): [L, nb, block]
            return jax.vmap(lambda q, s: Q.dequantize_symmetric(
                unpack(q), s, self.shape,
                dtype=jnp.dtype(self.dtype)))(self.q, self.s)
        # plain leaf, or one scan-sliced layer ([nb, block])
        return Q.dequantize_symmetric(unpack(self.q), self.s, self.shape,
                                      dtype=jnp.dtype(self.dtype))

    def tree_flatten(self):
        return (self.q, self.s), (self.shape, self.dtype, self.bits,
                                  self.stacked)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], children[1], *aux)

    def __repr__(self):
        return (f"QuantizedTensor(shape={self.shape}, dtype={self.dtype}, "
                f"bits={self.bits}, stacked={self.stacked})")


def _is_qleaf(x) -> bool:
    return isinstance(x, QuantizedTensor)


def _should_quantize(path: Tuple, leaf) -> bool:
    if leaf.ndim < 2 or leaf.size < _MIN_QUANT_SIZE:
        return False
    name = str(path[-1]) if path else ""
    key = getattr(path[-1], "key", name) if path else name
    # biases are stacked per layer into 2-D arrays (b_q [L, nh*hd] etc.),
    # so the ndim/size gate alone would quantize them — additive biases
    # must stay exact
    if str(key).startswith("b_") or str(key).endswith("_b"):
        return False
    return "norm" not in name


def _under_scan(path: Tuple) -> bool:
    """Leaves under a per-layer stack (scanned with layer axis 0)."""
    return any(getattr(k, "key", None) == "layers" for k in path)


def quantize_params(params, bits: int = 8, block: int = 2048):
    """Returns (pytree with QuantizedTensor leaves, meta).

    Leaves under ``params["layers"]`` are stacked ``[L, ...]`` and
    consumed one layer at a time by ``lax.scan`` — they quantize
    per-layer (``stacked=True``) so the scan slices them and dequant
    runs inside the body (see QuantizedTensor)."""
    if bits not in (4, 8):
        # the quantizer's range pick defaults anything != 8 to the int4
        # range (ops/quantizer.py), so e.g. bits=16 would silently serve
        # 15-level weights
        raise ValueError(f"quant_bits must be 4 or 8, got {bits}")
    flat, treedef = jax.tree_util.tree_flatten_with_path(params)
    out = []
    meta = {"bits": bits, "block": block, "n_quantized": 0}
    pack = Q.pack_int4 if bits == 4 else (lambda x: x)
    for path, leaf in flat:
        stacked = _under_scan(path) and leaf.ndim >= 3
        per_layer = leaf[0] if stacked else leaf
        if _should_quantize(path, per_layer):
            if stacked:
                q, s = jax.vmap(lambda x: Q.quantize_symmetric(
                    x, block=block, bits=bits))(leaf)
                q = jax.vmap(pack)(q)
                out.append(QuantizedTensor(
                    q, s, per_layer.shape, str(leaf.dtype), bits=bits,
                    stacked=True))
            else:
                q, s = Q.quantize_symmetric(leaf, block=block, bits=bits)
                out.append(QuantizedTensor(pack(q), s, leaf.shape,
                                           str(leaf.dtype), bits=bits))
            meta["n_quantized"] += 1
        else:
            out.append(leaf)
    return jax.tree_util.tree_unflatten(treedef, out), meta


def dequantize_params(params):
    """Inverse of quantize_params; call INSIDE jit so XLA fuses dequant into
    the consuming matmuls."""
    return jax.tree.map(
        lambda x: x.dequantize() if _is_qleaf(x) else x,
        params, is_leaf=_is_qleaf)


def quantized_nbytes(params) -> int:
    total = 0
    for leaf in jax.tree.leaves(params):
        total += leaf.size * leaf.dtype.itemsize
    return total
