"""Inference config.

Reference parity: ``DeepSpeedInferenceConfig`` (inference/config.py) — the
subset that is meaningful on TPU. ``tensor_parallel.tp_size`` maps to the
"model" mesh axis; ``replace_with_kernel_inject`` is implicit (the model
family always runs the Pallas/XLA kernel path); CUDA-graph replay maps to
jit compilation caching, which XLA does for free.
"""

from dataclasses import dataclass, field
from typing import Any, Dict, Optional


@dataclass
class TensorParallelConfig:
    tp_size: int = 1


@dataclass
class DeepSpeedInferenceConfig:
    dtype: str = "bfloat16"
    tensor_parallel: TensorParallelConfig = field(
        default_factory=TensorParallelConfig)
    max_out_tokens: int = 1024          # reference: config.max_out_tokens
    min_out_tokens: int = 1
    max_batch_size: int = 8
    replace_with_kernel_inject: bool = True
    enable_cuda_graph: bool = False      # accepted, ignored (XLA jit caches)
    checkpoint: Optional[str] = None
    quant_bits: Optional[int] = None     # 8/4 weight-only quant (WOQ)
    seed: int = 0
    # FastGen: route init_inference to the v2 ragged/paged engine
    # (reference serves v2 through mii.serve; here it is one flag away)
    use_ragged: bool = False
    ragged: Optional[Dict[str, Any]] = None  # RaggedInferenceEngineConfig

    @classmethod
    def from_dict_or_kwargs(cls, config: Optional[Dict[str, Any]], kwargs):
        merged: Dict[str, Any] = dict(config or {})
        merged.update({k: v for k, v in kwargs.items() if v is not None})
        tp = merged.pop("tensor_parallel", {})
        if isinstance(tp, int):
            tp = {"tp_size": tp}
        if "mp_size" in merged:              # reference legacy alias
            tp = {"tp_size": merged.pop("mp_size")}
        known = {f for f in cls.__dataclass_fields__ if f != "tensor_parallel"}
        unknown = set(merged) - known
        if unknown:
            # the reference's pydantic config rejects unknown fields; warn
            # loudly instead of silently running with defaults
            from ..utils.logging import logger
            logger.warning(
                f"init_inference: ignoring unknown config keys {sorted(unknown)} "
                f"(known: {sorted(known | {'tensor_parallel', 'mp_size'})})")
        cfg = cls(**{k: v for k, v in merged.items() if k in known})
        cfg.tensor_parallel = TensorParallelConfig(**tp) if isinstance(tp, dict) else tp
        if isinstance(cfg.dtype, type):      # allow jnp dtype objects
            cfg.dtype = cfg.dtype.__name__
        aliases = {"fp32": "float32", "float": "float32", "float32": "float32",
                   "fp16": "float16", "half": "float16", "float16": "float16",
                   "bf16": "bfloat16", "bfloat16": "bfloat16"}
        key = str(cfg.dtype).replace("torch.", "").replace("jnp.", "")
        if key not in aliases:
            raise ValueError(
                f"unsupported inference dtype {cfg.dtype!r}; one of "
                f"{sorted(set(aliases))}")
        cfg.dtype = aliases[key]
        return cfg
