"""Experiment monitoring backends.

Analogue of reference ``deepspeed/monitor/`` (MonitorMaster monitor.py:29;
TensorBoard/W&B/CSV writers). Events are (tag, value, step) triples; the master
fans them out to every enabled backend, writing only from process 0.
"""

import csv
import os
from typing import List, Tuple

import jax

from ..utils.logging import logger

Event = Tuple[str, float, int]


class Monitor:
    def __init__(self, config):
        self.enabled = getattr(config, "enabled", False)

    def write_events(self, event_list: List[Event]):
        raise NotImplementedError


class TensorBoardMonitor(Monitor):
    """reference monitor/tensorboard.py:13 (torch SummaryWriter backend)."""

    def __init__(self, config):
        super().__init__(config)
        self.summary_writer = None
        if self.enabled and jax.process_index() == 0:
            try:
                from torch.utils.tensorboard import SummaryWriter
                path = os.path.join(config.output_path or "./runs", config.job_name)
                self.summary_writer = SummaryWriter(log_dir=path)
            except Exception as e:
                logger.warning(f"tensorboard unavailable: {e}")
                self.enabled = False

    def write_events(self, event_list: List[Event]):
        if self.summary_writer is None:
            return
        for tag, value, step in event_list:
            self.summary_writer.add_scalar(tag, value, step)
        self.summary_writer.flush()


class WandbMonitor(Monitor):
    """reference monitor/wandb.py:12."""

    def __init__(self, config):
        super().__init__(config)
        self._wandb = None
        if self.enabled and jax.process_index() == 0:
            try:
                import wandb
                wandb.init(project=config.project, group=config.group,
                           entity=config.team)
                self._wandb = wandb
            except Exception as e:
                logger.warning(f"wandb unavailable: {e}")
                self.enabled = False

    def write_events(self, event_list: List[Event]):
        if self._wandb is None:
            return
        for tag, value, step in event_list:
            self._wandb.log({tag: value}, step=step)


class CSVMonitor(Monitor):
    """reference monitor/csv_monitor.py:12 — one csv file per event tag."""

    def __init__(self, config):
        super().__init__(config)
        self.output_path = None
        if self.enabled and jax.process_index() == 0:
            self.output_path = os.path.join(config.output_path or ".",
                                            config.job_name)
            os.makedirs(self.output_path, exist_ok=True)
        else:
            self.enabled = False

    def write_events(self, event_list: List[Event]):
        if not self.enabled:
            return
        for tag, value, step in event_list:
            fname = os.path.join(self.output_path,
                                 tag.replace("/", "_") + ".csv")
            new = not os.path.exists(fname)
            with open(fname, "a", newline="") as fh:
                w = csv.writer(fh)
                if new:
                    w.writerow(["step", tag])
                w.writerow([step, value])


class MonitorMaster(Monitor):
    """Fan-out master (reference monitor/monitor.py:29)."""

    def __init__(self, ds_config):
        self.tb = TensorBoardMonitor(ds_config.tensorboard)
        self.wandb = WandbMonitor(ds_config.wandb)
        self.csv = CSVMonitor(ds_config.csv_monitor)
        self.enabled = self.tb.enabled or self.wandb.enabled or self.csv.enabled
        self.telemetry = None

    def attach_telemetry(self, registry=None, flush_interval: int = 1):
        """Attach a TelemetryBridge flushing the metrics registry's
        scalars into this master's backends every ``flush_interval``
        steps (telemetry/bridge.py)."""
        from ..telemetry.bridge import TelemetryBridge
        self.telemetry = TelemetryBridge(self, registry=registry,
                                         flush_interval=flush_interval)
        return self.telemetry

    def write_events(self, event_list: List[Event]):
        if jax.process_index() != 0:
            return
        for backend in (self.tb, self.wandb, self.csv):
            if backend.enabled:
                backend.write_events(event_list)
