"""Mixture-of-Experts gating + dispatch.

TPU-native analogue of the reference's expert parallelism
(deepspeed/moe/sharded_moe.py: top1gating :184, top2gating :282, MOELayer
:425, _AllToAll :95; deepspeed/moe/layer.py:16 MoE). The reference dispatches
tokens with an explicit all-to-all over the expert process group; here the
dispatch is the GShard-style einsum against a static-capacity one-hot tensor,
with expert-stacked parameters sharded over the "expert" mesh axis — XLA
lowers the resharding of the dispatched [E, C, H] activations onto the same
ICI all-to-all the reference issues by hand.

Static shapes (capacity = ceil(tokens/E * capacity_factor)) are exactly the
reference's drop_tokens=True mode — which is also the only mode that maps
well onto XLA; dropless variants need ragged kernels (future ragged_dot path).
"""

import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def _capacity(num_tokens: int, num_experts: int, capacity_factor: float,
              min_capacity: int) -> int:
    cap = int(math.ceil(num_tokens / num_experts * capacity_factor))
    return max(cap, min_capacity)


def _one_hot(x, n):
    return jax.nn.one_hot(x, n, dtype=jnp.float32)


def top1gating(logits, capacity_factor: float = 1.0, min_capacity: int = 4,
               noisy_gate_policy: Optional[str] = None, rng=None,
               drop_tokens: bool = True):
    if not drop_tokens:
        raise NotImplementedError(
            "use moe_layer_dropless (jax.lax.ragged_dot grouped GEMM) for "
            "drop_tokens=False; the einsum dispatch path is capacity-based")
    """Switch-style top-1 gating (reference sharded_moe.py:184).

    logits: [T, E]. Returns (aux_loss, combine [T,E,C], dispatch mask [T,E,C]).
    """
    T, E = logits.shape
    C = _capacity(T, E, capacity_factor, min_capacity)
    if noisy_gate_policy == "RSample" and rng is not None:
        logits_w_noise = logits + jax.random.gumbel(rng, logits.shape)
    else:
        logits_w_noise = logits
    gates = jax.nn.softmax(logits, axis=-1)                     # [T, E]
    idx = jnp.argmax(logits_w_noise, axis=-1)                   # [T]
    mask1 = _one_hot(idx, E)                                    # [T, E]

    # load-balancing aux loss (Switch eq. 4; reference l_aux at :253)
    me = jnp.mean(gates, axis=0)                                # [E]
    ce = jnp.mean(mask1, axis=0)                                # [E]
    aux_loss = jnp.sum(me * ce) * E

    # position of each token within its expert's capacity
    pos = jnp.cumsum(mask1, axis=0) - mask1                     # [T, E]
    pos_in_expert = jnp.sum(pos * mask1, axis=-1)               # [T]
    keep = (pos_in_expert < C).astype(jnp.float32)              # drop overflow
    mask1 = mask1 * keep[:, None]

    gate1 = jnp.sum(gates * mask1, axis=-1)                     # [T]
    pos_oh = _one_hot(pos_in_expert.astype(jnp.int32), C)       # [T, C]
    dispatch = mask1[:, :, None] * pos_oh[:, None, :]           # [T, E, C]
    combine = dispatch * gate1[:, None, None]
    return aux_loss, combine, dispatch


def top2gating(logits, capacity_factor: float = 1.0, min_capacity: int = 4,
               rng=None, drop_tokens: bool = True):
    """GShard top-2 gating (reference sharded_moe.py:282); deterministic
    second expert (argmax after masking expert 1)."""
    if not drop_tokens:
        raise NotImplementedError(
            "dropless MoE is not supported; see top1gating")
    T, E = logits.shape
    C = _capacity(T, E, capacity_factor * 2.0, min_capacity)
    gates = jax.nn.softmax(logits, axis=-1)

    idx1 = jnp.argmax(gates, axis=-1)
    mask1 = _one_hot(idx1, E)
    gates_wo1 = gates * (1.0 - mask1)
    idx2 = jnp.argmax(gates_wo1, axis=-1)
    mask2 = _one_hot(idx2, E)

    me = jnp.mean(gates, axis=0)
    ce = jnp.mean(mask1, axis=0)
    aux_loss = jnp.sum(me * ce) * E

    pos1 = jnp.cumsum(mask1, axis=0) - mask1
    pos_in1 = jnp.sum(pos1 * mask1, axis=-1)
    # expert-2 positions come after all expert-1 claims (reference locations2
    # += sum of mask1)
    pos2 = jnp.cumsum(mask2, axis=0) - mask2 + jnp.sum(mask1, axis=0, keepdims=True)
    pos_in2 = jnp.sum(pos2 * mask2, axis=-1)

    mask1 = mask1 * (pos_in1 < C).astype(jnp.float32)[:, None]
    mask2 = mask2 * (pos_in2 < C).astype(jnp.float32)[:, None]

    g1 = jnp.sum(gates * mask1, axis=-1)
    g2 = jnp.sum(gates * mask2, axis=-1)
    denom = jnp.maximum(g1 + g2, 1e-9)
    g1, g2 = g1 / denom, g2 / denom

    disp1 = mask1[:, :, None] * _one_hot(pos_in1.astype(jnp.int32), C)[:, None, :]
    disp2 = mask2[:, :, None] * _one_hot(pos_in2.astype(jnp.int32), C)[:, None, :]
    dispatch = disp1 + disp2
    combine = disp1 * g1[:, None, None] + disp2 * g2[:, None, None]
    return aux_loss, combine, dispatch


def _gate_and_dispatch(xt, gate_w, top_k, capacity_factor, min_capacity,
                       noisy_gate_policy, rng):
    """Shared gating prologue of every capacity-routed MoE variant: fp32
    router logits + top-1/top-2 gating. Returns (aux, combine, dispatch)."""
    logits = (xt.astype(jnp.float32) @ gate_w.astype(jnp.float32))
    if top_k == 1:
        return top1gating(logits, capacity_factor, min_capacity,
                          noisy_gate_policy, rng)
    return top2gating(logits, capacity_factor, min_capacity, rng)


def moe_layer(x, gate_w, expert_params, expert_fn, topo=None,
              top_k: int = 1, capacity_factor: float = 1.0,
              min_capacity: int = 4, rng=None,
              noisy_gate_policy: Optional[str] = None) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Apply an expert-parallel MoE layer.

    x: [B, S, H]; gate_w: [H, E]; expert_params: pytree with leading expert
    dim [E, ...] (sharded over the "expert" axis by the caller's specs);
    expert_fn(params_e, x_e) applies one expert to [C', H].

    Returns (output [B,S,H], aux_loss scalar).
    """
    B, S, H = x.shape
    xt = x.reshape(B * S, H)
    aux, combine, dispatch = _gate_and_dispatch(
        xt, gate_w, top_k, capacity_factor, min_capacity, noisy_gate_policy,
        rng)

    # dispatch: [T,E,C] x [T,H] -> [E,C,H]   (the all-to-all happens here when
    # E is sharded over the expert axis and T over the data axes)
    xe = jnp.einsum("tec,th->ech", dispatch.astype(x.dtype), xt)
    if topo is not None and topo.axis_size("expert") > 1:
        from jax.sharding import PartitionSpec as P
        from jax.sharding import NamedSharding

        xe = jax.lax.with_sharding_constraint(
            xe, NamedSharding(topo.mesh, P("expert", None, None)))

    ye = jax.vmap(expert_fn)(expert_params, xe)                 # [E, C, H]
    out = jnp.einsum("tec,ech->th", combine.astype(x.dtype), ye)
    return out.reshape(B, S, H), aux.astype(jnp.float32)


def moe_layer_manual(x, gate_w, expert_params_local, expert_fn,
                     ep_axis: str = "expert",
                     top_k: int = 1, capacity_factor: float = 1.0,
                     min_capacity: int = 4, rng=None,
                     noisy_gate_policy: Optional[str] = None
                     ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Expert-parallel MoE with an EXPLICIT all-to-all dispatch, for use
    inside a manual shard_map program — the compiled 1F1B pipeline, where
    GSPMD cannot insert the expert collective (the reference's _AllToAll
    autograd op, sharded_moe.py:95, done by hand the same way).

    x: the device-LOCAL [B, S, H] token block (the expert axis is a batch
    axis, so every expert peer holds different tokens);
    gate_w: [H, E_global] (replicated over the expert axis);
    expert_params_local: pytree with leading LOCAL expert dim [E/ep, ...].

    Dispatch: capacity-pad locally to [E, C, H], all_to_all the per-owner
    blocks over `ep_axis`, run the local experts on [E/ep, ep*C, H], and
    all_to_all back before the combine. All shapes are static (capacity
    routing), which is what makes this legal inside the compiled pipeline.
    """
    B, S, H = x.shape
    from ..comm.quantized import _one_axis_size
    ep = _one_axis_size(ep_axis)
    xt = x.reshape(B * S, H)
    E = gate_w.shape[-1]
    assert E % ep == 0, f"num_experts {E} not divisible by ep {ep}"
    aux, combine, dispatch = _gate_and_dispatch(
        xt, gate_w, top_k, capacity_factor, min_capacity, noisy_gate_policy,
        rng)

    xe = jnp.einsum("tec,th->ech", dispatch.astype(x.dtype), xt)  # [E, C, H]
    C = xe.shape[1]
    e_loc = E // ep
    # block o = my tokens for peer o's experts -> peer o; received block p =
    # peer p's tokens for MY experts
    xr = jax.lax.all_to_all(xe, ep_axis, split_axis=0, concat_axis=0,
                            tiled=True)                    # [ep*e_loc, C, H]
    xr = xr.reshape(ep, e_loc, C, H).transpose(1, 0, 2, 3) \
           .reshape(e_loc, ep * C, H)
    ye = jax.vmap(expert_fn)(expert_params_local, xr)      # [e_loc, ep*C, H]
    ye = ye.reshape(e_loc, ep, C, H).transpose(1, 0, 2, 3).reshape(E, C, H)
    ye = jax.lax.all_to_all(ye, ep_axis, split_axis=0, concat_axis=0,
                            tiled=True)                    # back to senders
    out = jnp.einsum("tec,ech->th", combine.astype(x.dtype), ye)
    return out.reshape(B, S, H), aux.astype(jnp.float32)


def ragged_swiglu_experts(expert_params, xs, group_sizes):
    """SwiGLU expert stack as grouped GEMMs over token groups.

    The TPU-native equivalent of the reference's CUTLASS MoE grouped GEMM
    (inference/v2/kernels/cutlass_ops/moe_gemm): `jax.lax.ragged_dot` tiles
    the per-expert segments onto the MXU without materializing the [E, C, H]
    capacity tensor. xs: [T, H] tokens SORTED by expert; group_sizes: [E].
    """
    wg, wu, wd = expert_params                                 # [E, H, F] ...
    g = jax.lax.ragged_dot(xs, wg, group_sizes)
    u = jax.lax.ragged_dot(xs, wu, group_sizes)
    return jax.lax.ragged_dot(jax.nn.silu(g) * u, wd, group_sizes)


def dropless_topk_dispatch(xt, topi, topv, expert_params, num_experts: int,
                           ragged_expert_fn=None):
    """Sorted-token grouped-GEMM core shared by the training dropless MoE
    and the v2 serving path (_moe_mlp): route every (token, choice) row to
    its expert with one argsort + `jax.lax.ragged_dot`, unsort, and weight
    by the gate value. xt: [T, H]; topi/topv: [T, k]. Returns [T, H]."""
    T, H = xt.shape
    k = topi.shape[-1]
    idx = topi.reshape(-1)                       # [T*k], token-major
    order = jnp.argsort(idx)                     # stable
    xs = xt[order // k]                          # row t*k+j <-> (token t, j)
    group_sizes = jnp.bincount(idx, length=num_experts).astype(jnp.int32)
    fn = ragged_expert_fn or ragged_swiglu_experts
    ys = fn(expert_params, xs, group_sizes)      # [T*k, H]
    ys = jnp.zeros_like(ys).at[order].set(ys)    # unsort
    return jnp.sum(ys.reshape(T, k, H) * topv[..., None].astype(ys.dtype),
                   axis=1)


def moe_layer_dropless(x, gate_w, expert_params, ragged_expert_fn=None,
                       topo=None, rng=None,
                       noisy_gate_policy: Optional[str] = None):
    """Dropless top-1 MoE (the reference's drop_tokens=False mode,
    sharded_moe.py top1gating dynamic-capacity branch) via sorted tokens +
    `jax.lax.ragged_dot` grouped GEMM — no token is ever dropped and no
    [T, E, C] dispatch tensor is built.

    Expert parameters must be device-local (ep=1) on THIS path: ragged
    groups have data-dependent sizes, which cannot cross a static SPMD
    all-to-all. The reference composes dropless with EP by all-reducing a
    dynamic capacity at runtime (reference sharded_moe.py:214-218) —
    torch can reshape to a step-dependent capacity, XLA cannot. The
    static-shape equivalent is ``moe_layer_dropless_ep`` below: worst-case
    capacity C=T compiled in, memory traded for droplessness.
    """
    if topo is not None and topo.axis_size("expert") > 1:
        raise NotImplementedError(
            "ragged dropless MoE needs device-local experts (expert axis "
            "must be 1): ragged group sizes are data-dependent and cannot "
            "ride a static expert all-to-all. For ep>1 use "
            "moe_layer_dropless_ep (worst-case static capacity).")
    B, S, H = x.shape
    T = B * S
    E = gate_w.shape[-1]
    xt = x.reshape(T, H)
    logits = xt.astype(jnp.float32) @ gate_w.astype(jnp.float32)
    if noisy_gate_policy == "RSample" and rng is not None:
        logits_w_noise = logits + jax.random.gumbel(rng, logits.shape)
    else:
        logits_w_noise = logits
    gates = jax.nn.softmax(logits, axis=-1)
    idx = jnp.argmax(logits_w_noise, axis=-1)                   # [T]

    me = jnp.mean(gates, axis=0)
    ce = jnp.mean(_one_hot(idx, E), axis=0)
    aux = jnp.sum(me * ce) * E

    gate_p = jnp.take_along_axis(gates, idx[:, None], axis=-1)  # [T, 1]
    out = dropless_topk_dispatch(xt, idx[:, None], gate_p, expert_params, E,
                                 ragged_expert_fn)
    return out.reshape(B, S, H), aux.astype(jnp.float32)


def moe_layer_dropless_ep(x, gate_w, expert_params, expert_fn, topo,
                          top_k: int = 1, rng=None,
                          noisy_gate_policy: Optional[str] = None,
                          max_dispatch_elems: int = 1 << 28
                          ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Dropless top-1/top-2 MoE UNDER expert parallelism (reference
    drop_tokens=False with ep>1). The reference sizes its dispatch buffers
    with a runtime all-reduced max capacity (sharded_moe.py:214-218);
    XLA's static shapes can't — so the worst case (C = T for top-1, 2T for
    top-2: ``capacity_factor=E`` through ``_capacity``, whose top-2 branch
    doubles it) is compiled in and the standard einsum dispatch + GSPMD
    expert all-to-all runs over it. Semantically dropless: per-expert load
    can never exceed that capacity, so it never binds.

    MEMORY TRADE (read before using): the dispatch/combine tensors are
    [T, E, k*T] — quadratic in local tokens. Fine for modest T (decode
    batches, short prefill chunks, the routed block after dp/sp sharding),
    ruinous for long sequences — ``max_dispatch_elems`` rejects that
    regime loudly instead of OOMing; prefer capacity routing or ep=1
    ragged dropless there.
    """
    B, S, _ = x.shape
    T = B * S
    E = gate_w.shape[-1]
    if T * E * (top_k * T) > max_dispatch_elems:
        raise NotImplementedError(
            f"dropless-under-ep worst-case dispatch is [T,E,k*T] = "
            f"[{T},{E},{top_k * T}] (> {max_dispatch_elems} elements): "
            f"quadratic in tokens. Chunk the sequence (smaller prefill "
            f"bucket), use capacity routing, or serve with ep=1.")
    # capacity_factor = E makes _capacity == ceil(T/E * E) == T
    return moe_layer(x, gate_w, expert_params, expert_fn, topo,
                     top_k=top_k, capacity_factor=float(E), min_capacity=1,
                     rng=rng, noisy_gate_policy=noisy_gate_policy)


def residual_moe_combine(x, moe_out, mlp_out, coef_w, coef_b=None):
    """Residual-MoE mixture (reference moe/layer.py:118-123, the PR-MoE
    building block, arXiv:2201.05596): a 2-way softmax over a learned
    coefficient head weights the routed-expert output against a dense MLP
    applied to the same input."""
    coef = x @ coef_w.astype(x.dtype)
    if coef_b is not None:
        coef = coef + coef_b.astype(x.dtype)
    coef = jax.nn.softmax(coef.astype(jnp.float32), axis=-1).astype(x.dtype)
    return moe_out * coef[..., 0:1] + mlp_out * coef[..., 1:2]
