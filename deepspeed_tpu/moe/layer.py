"""MoE layer facade — reference-parity class API over the functional core.

Reference counterpart: ``deepspeed.moe.layer.MoE`` (moe/layer.py:16), an
nn.Module holding a gate + experts with expert-parallel groups. The
TPU-native core is functional (sharded_moe.moe_layer and friends: einsum
dispatch under jit, the expert axis as a mesh dimension); this class packages
the same constructor surface — num_experts / k / capacity_factor /
min_capacity / use_residual (PR-MoE, layer.py:29) / noisy_gate_policy /
drop_tokens — around param init + partition specs + apply, so a user
migrating from the reference finds the same object shape.
"""

from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .sharded_moe import moe_layer, moe_layer_dropless, residual_moe_combine


class MoE:
    """Top-k routed expert MLP (SwiGLU experts by default).

    Parameters mirror the reference constructor (moe/layer.py:16); ep_size
    is not stored here — expert placement comes from the topology's
    "expert" mesh axis at apply time, the way every other parallel axis
    works in this framework.
    """

    def __init__(self, hidden_size: int, intermediate_size: int,
                 num_experts: int = 1, k: int = 1,
                 capacity_factor: float = 1.0,
                 eval_capacity_factor: float = 1.0,
                 min_capacity: int = 4,
                 use_residual: bool = False,
                 noisy_gate_policy: Optional[str] = None,
                 drop_tokens: bool = True,
                 expert_fn: Optional[Callable] = None):
        assert k in (1, 2), "top-1 and top-2 gating only (reference parity)"
        if k == 2 and noisy_gate_policy is not None:
            raise NotImplementedError(
                "noisy_gate_policy applies to top-1 gating only (top2gating "
                "has no noise path, matching reference sharded_moe.py:282)")
        if not drop_tokens:
            # same guard as the config path (models/transformer.py
            # moe_dropless): the ragged grouped-GEMM path is top-1 with its
            # own SwiGLU expert kernel — silently ignoring k/expert_fn would
            # train a different model than the user asked for
            if k != 1:
                raise NotImplementedError(
                    f"drop_tokens=False supports top-1 routing only (got k={k})")
            if expert_fn is not None:
                raise NotImplementedError(
                    "drop_tokens=False uses the ragged SwiGLU grouped-GEMM "
                    "experts; a custom expert_fn is not supported there")
        self.hidden_size = hidden_size
        self.intermediate_size = intermediate_size
        self.num_experts = num_experts
        self.k = k
        self.capacity_factor = capacity_factor
        self.eval_capacity_factor = eval_capacity_factor
        self.min_capacity = min_capacity
        self.use_residual = use_residual
        self.noisy_gate_policy = noisy_gate_policy
        self.drop_tokens = drop_tokens
        self._expert_fn = expert_fn or self._swiglu_expert

    @staticmethod
    def _swiglu_expert(p, xe):
        wg, wu, wd = p
        return (jax.nn.silu(xe @ wg) * (xe @ wu)) @ wd

    def init_params(self, rng) -> Dict[str, Any]:
        h, f, e = self.hidden_size, self.intermediate_size, self.num_experts
        k = jax.random.split(rng, 8)
        std = 0.02

        def init(key, shape):
            return jax.random.normal(key, shape, jnp.float32) * std

        params = {
            "gate_w": init(k[0], (h, e)),
            "e_gate": init(k[1], (e, h, f)),
            "e_up": init(k[2], (e, h, f)),
            "e_down": init(k[3], (e, f, h)),
        }
        if self.use_residual:
            params.update({
                "res_gate": init(k[4], (h, f)),
                "res_up": init(k[5], (h, f)),
                "res_down": init(k[6], (f, h)),
                "res_coef_w": init(k[7], (h, 2)),
                "res_coef_b": jnp.zeros((2,), jnp.float32),
            })
        return params

    def partition_specs(self, topo) -> Dict[str, Any]:
        ep = ("expert" if topo is not None
              and topo.axis_size("expert") > 1 else None)
        specs = {
            "gate_w": P(None, None),
            "e_gate": P(ep, None, None),
            "e_up": P(ep, None, None),
            "e_down": P(ep, None, None),
        }
        if self.use_residual:
            specs.update({"res_gate": P(None, None), "res_up": P(None, None),
                          "res_down": P(None, None),
                          "res_coef_w": P(None, None), "res_coef_b": P(None)})
        return specs

    def __call__(self, params, x, topo=None, rng=None,
                 train: bool = True) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """x [B, S, H] -> (output [B, S, H], aux_loss scalar)."""
        experts = (params["e_gate"], params["e_up"], params["e_down"])
        cf = self.capacity_factor if train else self.eval_capacity_factor
        if not self.drop_tokens:
            if topo is not None and topo.axis_size("expert") > 1:
                # ep>1: worst-case static-capacity dispatch (C=T) — the
                # XLA equivalent of the reference's dynamic-capacity
                # allreduce (sharded_moe.py:214-218); memory trade
                # documented on moe_layer_dropless_ep
                from .sharded_moe import moe_layer_dropless_ep
                out, aux = moe_layer_dropless_ep(
                    x, params["gate_w"], experts, self._expert_fn, topo,
                    rng=rng,
                    noisy_gate_policy=(self.noisy_gate_policy
                                       if train else None))
            else:
                out, aux = moe_layer_dropless(
                    x, params["gate_w"], experts, topo=topo, rng=rng,
                    noisy_gate_policy=(self.noisy_gate_policy
                                       if train else None))
        else:
            out, aux = moe_layer(
                x, params["gate_w"], experts, self._expert_fn, topo,
                top_k=self.k, capacity_factor=cf,
                min_capacity=self.min_capacity, rng=rng,
                noisy_gate_policy=self.noisy_gate_policy if train else None)
        if self.use_residual:
            res = self._swiglu_expert(
                (params["res_gate"], params["res_up"], params["res_down"]), x)
            out = residual_moe_combine(
                x, out, res, params["res_coef_w"], params["res_coef_b"])
        return out, aux
