from .layer import MoE  # noqa: F401
from .sharded_moe import (moe_layer, moe_layer_dropless,  # noqa: F401
                          residual_moe_combine, top1gating, top2gating)
