"""Elastic training configuration math.

TPU-native port of the reference's elasticity subsystem
(elasticity/elasticity.py:233 compute_elastic_config + the v0.1/v0.2 schema,
elasticity/config.py): pre-compute a set of global batch sizes compatible
with every admissible accelerator count so that a run can be
stopped/restarted on a different slice size with IDENTICAL optimization
behavior (`train_batch_size` constant).

Same algorithm as the reference: candidate batch sizes are
micro_batch x (highly composite multipliers) capped by max_train_batch_size;
the chosen batch is the largest candidate with the most admissible chip
counts; the (micro_batch, gas) for the current world size follows.
"""

from dataclasses import dataclass, field
from typing import List, Optional, Tuple

from ..utils.logging import logger


class ElasticityError(Exception):
    pass


@dataclass
class ElasticityConfigData:
    """Schema of the 'elasticity' config block (reference elasticity/config.py)."""

    enabled: bool = False
    max_train_batch_size: int = 2000
    micro_batch_sizes: List[int] = field(default_factory=lambda: [2, 4, 6])
    min_gpus: int = 1
    max_gpus: int = 10_000
    min_time: int = 0
    prefer_larger_batch: bool = True
    ignore_non_elastic_batch_info: bool = False
    version: float = 0.2
    model_parallel_size: int = 1
    num_gpus_per_node: int = 1


def _candidate_multipliers(max_acceptable: int) -> List[int]:
    """Highly-composite multipliers (reference get_candidate_batch_sizes
    uses powers of 2 x {1, 3, 5, 7} style sets)."""
    base = [1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 12, 14, 15, 16, 18, 20, 21, 24,
            28, 30, 32, 36, 40, 42, 48, 56, 60, 64, 72, 80, 84, 96, 112, 120,
            128, 144, 160, 168, 192, 224, 240, 256]
    return [m for m in base if m <= max_acceptable]


def get_valid_gpus(batch_size: int, micro_batches: List[int], min_gpus: int,
                   max_gpus: int) -> List[int]:
    """All chip counts that divide batch_size with some micro batch
    (reference elasticity.py get_valid_gpus)."""
    valid = set()
    for mb in micro_batches:
        if batch_size % mb:
            continue
        max_chips = batch_size // mb
        for chips in range(1, max_chips + 1):
            if max_chips % chips == 0 and min_gpus <= chips <= max_gpus:
                valid.add(chips)
    return sorted(valid)


def get_best_candidate_batch_size(max_batch: int, micro_batches: List[int],
                                  min_gpus: int, max_gpus: int,
                                  prefer_larger: bool = True
                                  ) -> Tuple[int, List[int]]:
    """Candidate with the most valid chip counts (ties: batch size order
    by `prefer_larger`) — reference elasticity.py:150 _get_compatible_gpus_v01
    candidate search."""
    candidates = set()
    for base in micro_batches:
        for mult in _candidate_multipliers(max_batch // max(1, base)):
            if base * mult <= max_batch:
                candidates.add(base * mult)
    best: Tuple[int, List[int]] = (0, [])
    for candidate in sorted(candidates):
        valid = get_valid_gpus(candidate, micro_batches, min_gpus, max_gpus)
        better = len(valid) > len(best[1]) or (
            len(valid) == len(best[1]) and (
                candidate > best[0] if prefer_larger else candidate < best[0]))
        if better:
            best = (candidate, valid)
    if not best[1]:
        raise ElasticityError(
            f"no batch size <= {max_batch} admits any chip count in "
            f"[{min_gpus}, {max_gpus}] with micro batches {micro_batches}")
    return best


def compute_elastic_config(ds_config: dict, world_size: int = 0,
                           return_microbatch: bool = False):
    """Reference compute_elastic_config (elasticity/elasticity.py:233).

    Returns (final_batch_size, valid_chip_counts[, micro_batch]) and — when
    `world_size` is given — validates that world_size is admissible and
    computes the per-chip micro batch.
    """
    block = ds_config.get("elasticity", None)
    if block is None or not block.get("enabled", False):
        raise ElasticityError("'elasticity' block missing or disabled")
    cfg = ElasticityConfigData(**{k: v for k, v in block.items()
                                  if k in ElasticityConfigData.__dataclass_fields__})
    mp = max(cfg.model_parallel_size, 1)
    final_batch, valid = get_best_candidate_batch_size(
        cfg.max_train_batch_size, cfg.micro_batch_sizes, cfg.min_gpus,
        cfg.max_gpus, cfg.prefer_larger_batch)
    if world_size:
        dp = world_size // mp
        if dp not in valid:
            raise ElasticityError(
                f"world size {world_size} (dp={dp}) is not in the elastic "
                f"schedule {valid} for batch {final_batch}")
        micro = final_batch // dp
        # snap to the largest configured micro batch that divides
        chosen = max((mb for mb in cfg.micro_batch_sizes if micro % mb == 0),
                     default=micro)
        gas = micro // chosen
        logger.info(f"elasticity: batch={final_batch} dp={dp} "
                    f"micro={chosen} gas={gas}")
        if return_microbatch:
            return final_batch, valid, chosen
        return final_batch, valid
    if return_microbatch:
        return final_batch, valid, None
    return final_batch, valid
