"""Elastic restart agent.

TPU-native analogue of the reference's ``DSElasticAgent``
(elasticity/elastic_agent.py:28, extending torch-elastic's
LocalElasticAgent): babysit the local worker group and restart it — with a
fresh rendezvous — when a worker fails, up to ``max_restarts`` times. The
elastic batch schedule (elasticity.py compute_elastic_config) guarantees the
global batch size stays constant when the restart comes back with a
different admissible world size.

Design departure: torch-elastic rendezvous is a c10d store negotiation; the
JAX equivalent is simply re-running ``jax.distributed.initialize`` in the
fresh worker processes, so the agent's job reduces to (a) deciding the new
world layout, (b) re-spawning via NodeLauncher with bumped restart env, and
(c) giving checkpoint-based resume a chance (workers are expected to
load_checkpoint on start, which the engine already supports across dp
resizes via per-tensor fragments).
"""

import os
import time
from typing import Callable, Dict, List, Optional

from ..launcher.launch import NodeLauncher
from ..utils.logging import logger
from .elasticity import ElasticityError, compute_elastic_config


class ElasticAgentError(Exception):
    pass


class DSElasticAgent:
    """Restart loop around the node launcher (reference elastic_agent.py:28).

    Parameters
    ----------
    cmd : worker command (argv list).
    nproc : processes per node.
    max_restarts : worker-group failures tolerated before giving up
        (torch-elastic's ``max_restarts``).
    coordinator : ``host:port`` of global process 0.
    ds_config : optional config dict with an ``elasticity`` block; when
        given, the agent validates each (re)start's world size against the
        elastic schedule before spawning.
    world_size_fn : optional callable returning the world size to use for
        the next restart (hook for cluster-size discovery); defaults to a
        constant ``nnodes * nproc``.
    """

    def __init__(self,
                 cmd: List[str],
                 nproc: int = 1,
                 nnodes: int = 1,
                 node_rank: int = 0,
                 max_restarts: int = 3,
                 coordinator: str = "localhost:29500",
                 ds_config: Optional[dict] = None,
                 world_size_fn: Optional[Callable[[], int]] = None,
                 restart_backoff_s: float = 1.0,
                 extra_env: Optional[Dict[str, str]] = None):
        self.cmd = cmd
        self.nproc = nproc
        self.nnodes = nnodes
        self.node_rank = node_rank
        self.max_restarts = max_restarts
        self.coordinator = coordinator
        self.ds_config = ds_config
        self.world_size_fn = world_size_fn or (lambda: nnodes * nproc)
        self.restart_backoff_s = restart_backoff_s
        self.extra_env = extra_env or {}
        self.restart_count = 0

    def _validate_world(self, world_size: int):
        if self.ds_config and self.ds_config.get("elasticity", {}).get(
                "enabled", False):
            # raises ElasticityError if this world size is not admissible
            compute_elastic_config(self.ds_config, world_size=world_size)

    def run(self) -> int:
        """Spawn; on failure restart until success or restarts exhausted.
        Returns the final exit code (0 = a generation ran to completion)."""
        while True:
            world = self.world_size_fn()
            try:
                self._validate_world(world)
            except ElasticityError as e:
                raise ElasticAgentError(
                    f"world size {world} rejected by elastic schedule: {e}"
                ) from e
            # process grid: contiguous blocks of nproc per node. A shrunken
            # world clips this node's block so process ids stay < world
            # (otherwise jax.distributed.initialize rejects them).
            base = self.node_rank * self.nproc
            local_n = max(0, min(self.nproc, world - base))
            if local_n == 0:
                logger.info(
                    f"elastic agent: node_rank={self.node_rank} not part of "
                    f"world={world}; idle exit")
                return 0
            env = dict(self.extra_env)
            env["DS_TPU_RESTART_COUNT"] = str(self.restart_count)
            launcher = NodeLauncher(
                self.cmd,
                nproc=local_n,
                base_process_id=base,
                num_processes=world,
                coordinator=self.coordinator,
                extra_env=env)
            rc = launcher.run()
            if rc == 0:
                logger.info(
                    f"elastic agent: worker group completed "
                    f"(restarts used: {self.restart_count})")
                return 0
            if self.restart_count >= self.max_restarts:
                logger.error(
                    f"elastic agent: worker group failed rc={rc} and "
                    f"max_restarts={self.max_restarts} exhausted")
                return rc
            self.restart_count += 1
            logger.warning(
                f"elastic agent: worker group failed rc={rc}; restart "
                f"{self.restart_count}/{self.max_restarts} in "
                f"{self.restart_backoff_s}s")
            time.sleep(self.restart_backoff_s)


def main(argv=None) -> int:
    """CLI: ``ds_tpu_elastic --max_restarts N -- script.py args``
    (reference bin/ds_elastic)."""
    import argparse
    import sys

    p = argparse.ArgumentParser(
        prog="ds_tpu_elastic",
        description="deepspeed_tpu elastic restart agent")
    p.add_argument("--nproc_per_node", type=int, default=1)
    p.add_argument("--nnodes", type=int, default=1)
    p.add_argument("--node_rank", type=int, default=0)
    p.add_argument("--max_restarts", type=int, default=3)
    p.add_argument("--master_addr", default="localhost")
    p.add_argument("--master_port", type=int, default=29500)
    p.add_argument("user_script")
    p.add_argument("user_args", nargs=argparse.REMAINDER)
    args = p.parse_args(argv)
    agent = DSElasticAgent(
        [sys.executable, args.user_script] + args.user_args,
        nproc=args.nproc_per_node,
        nnodes=args.nnodes,
        node_rank=args.node_rank,
        max_restarts=args.max_restarts,
        coordinator=f"{args.master_addr}:{args.master_port}")
    return agent.run()


if __name__ == "__main__":
    import sys
    sys.exit(main())
