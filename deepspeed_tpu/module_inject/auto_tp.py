"""HF-model injection: HuggingFace checkpoints -> TPU model family.

TPU-native analogue of the reference's module_inject stack
(replace_transformer_layer module_inject/replace_module.py:182; AutoTP
auto_tp.py:175 with tp_parser :259; per-arch policy containers in
module_inject/containers/). The reference swaps HF torch modules for fused
CUDA kernels and shards Linear layers by parsing module names. Here the
same job is a weight-format conversion: an HF model (or its state dict)
maps onto the TransformerLM family (models/transformer.py), whose partition
specs already carry the AutoTP column/row sharding — loading the converted
params under a "model" mesh axis IS tensor-parallel injection.

Supported architectures (reference policy containers, and the reference's
in-tree inference-v2 families inference/v2/model_implementations/
{llama_v2,mistral,opt,mixtral}): LlamaForCausalLM / MistralForCausalLM
(RMSNorm+RoPE+SwiGLU+GQA, optional attention_bias), MixtralForCausalLM
(sparse-MoE experts), Qwen2ForCausalLM (qkv-only biases),
Phi3ForCausalLM (fused qkv_proj/gate_up_proj, split at conversion),
GemmaForCausalLM (GeGLU, head-dim override, sqrt(H)-scaled embeddings,
(1+w) RMSNorm baked), FalconForCausalLM (parallel residual, fused MQA
qkv, bias-free MLP), Starcoder2ForCausalLM (biased LayerNorms +
projections, non-gated tanh-gelu MLP), GPT2LMHeadModel (LayerNorm+learned
positions+GELU+attn biases), OPTForCausalLM (pre-LN LayerNorm+learned
positions with the HF +2 offset+ReLU+biases) and the post-LN MLM
encoders BertForMaskedLM / RobertaForMaskedLM / DistilBertForMaskedLM
(embeddings LayerNorm + MLM prediction head, exact-erf gelu; RoBERTa's
+2 position offset handled like OPT's). torch weights are consumed as
numpy; torch never touches the device path.
"""

from typing import Any, Dict, Optional, Tuple

import numpy as np

from ..models.transformer import TransformerConfig, TransformerLM
from ..utils.logging import logger


def _np(t) -> np.ndarray:
    if hasattr(t, "detach"):
        return t.detach().cpu().numpy()
    return np.asarray(t)


# ---------------------------------------------------------------------------
# Config mapping (reference containers read the same HF config fields)
# ---------------------------------------------------------------------------
def _cap_to_window(hf_config, max_seq: int) -> int:
    """Sliding-window attention is not implemented; within the window
    full attention is IDENTICAL, so cap the sequence length there rather
    than silently diverging from HF beyond it. Qwen2-style configs carry
    sliding_window but only APPLY it when use_sliding_window is set."""
    window = getattr(hf_config, "sliding_window", None)
    if not getattr(hf_config, "use_sliding_window", True):
        window = None
    if window is not None and window < max_seq:
        logger.warning(
            f"sliding_window={window} < max_position_embeddings={max_seq}: "
            f"capping max_seq_len to the window (full attention matches "
            f"HF exactly within it; sliding-window masking is not "
            f"implemented)")
        return window
    return max_seq


def _llama_family_config(hf_config, **extra) -> TransformerConfig:
    """Shared llama/mistral/mixtral geometry (rmsnorm + rope + swiglu)."""
    # plain RoPE only: scaled/partial rotary variants (YaRN/longrope
    # extended-context Qwen2.5/Phi-4-class configs, partial_rotary_factor)
    # would silently produce wrong logits — reject loudly instead
    scaling = getattr(hf_config, "rope_scaling", None)
    if scaling:
        raise ValueError(
            f"rope_scaling={scaling!r} is not implemented; only plain-RoPE "
            f"configs convert")
    prf = float(getattr(hf_config, "partial_rotary_factor", 1.0) or 1.0)
    max_seq = _cap_to_window(
        hf_config, getattr(hf_config, "max_position_embeddings", 2048))
    return TransformerConfig(
        vocab_size=hf_config.vocab_size,
        hidden_size=hf_config.hidden_size,
        intermediate_size=hf_config.intermediate_size,
        num_layers=hf_config.num_hidden_layers,
        num_heads=hf_config.num_attention_heads,
        num_kv_heads=getattr(hf_config, "num_key_value_heads", None),
        max_seq_len=max_seq,
        norm="rmsnorm", norm_eps=hf_config.rms_norm_eps,
        activation=extra.pop("activation", "swiglu"), positional="rope",
        rope_theta=getattr(hf_config, "rope_theta", 10000.0),
        tie_embeddings=getattr(hf_config, "tie_word_embeddings", False),
        attn_bias=extra.pop(
            "attn_bias", getattr(hf_config, "attention_bias", False)),
        rotary_pct=prf,
        **extra,
    )


def config_from_hf(hf_config) -> TransformerConfig:
    mt = getattr(hf_config, "model_type", "llama")
    if mt == "mixtral":
        # Mixtral-class sparse MoE (reference
        # inference/v2/model_implementations/mixtral/): Mistral attention
        # geometry + top-k routed experts
        return _llama_family_config(
            hf_config,
            moe_num_experts=hf_config.num_local_experts,
            moe_top_k=hf_config.num_experts_per_tok)
    if mt in ("llama", "mistral"):
        return _llama_family_config(hf_config)
    if mt == "qwen2":
        # Qwen2: Llama geometry with q/k/v biases and NO o_proj bias
        # (Qwen2Config hardcodes the split rather than exposing
        # attention_bias); the missing o bias maps to zeros — exact
        return _llama_family_config(hf_config, attn_bias=True)
    if mt == "gemma":
        # Gemma-1: llama skeleton with GeGLU, q/o projecting to
        # num_heads*head_dim (7B: 4096 != H=3072), sqrt(H)-scaled
        # embeddings, and (1+w) RMSNorm weights (baked into the converted
        # norm tensors). Gemma-2 (softcapping, alternating sliding
        # window) is not implemented.
        # HF GemmaMLP ignores ``hidden_act`` whenever ``hidden_activation``
        # is None/absent and forces gelu_pytorch_tanh (GemmaConfig warns
        # and overrides) — so only an EXPLICIT hidden_activation value may
        # select the exact erf form; a legacy config carrying
        # hidden_act="gelu" still runs the tanh approximation.
        act = getattr(hf_config, "hidden_activation", None)
        if act is None:
            act = "gelu_pytorch_tanh"
        # HF "gelu" is the exact erf form, "gelu_pytorch_tanh" the tanh
        # approximation — map to distinct gate activations (~1e-3 apart)
        gate = {"gelu_pytorch_tanh": "geglu", "gelu": "geglu_exact"}.get(act)
        if gate is None:
            raise ValueError(f"gemma hidden_activation {act!r} is not "
                             f"supported")
        return _llama_family_config(
            hf_config, activation=gate,
            head_dim_override=hf_config.head_dim,
            embed_scale=float(hf_config.hidden_size) ** 0.5)
    if mt == "phi":
        # Phi-1/2: parallel residual with a single biased input
        # LayerNorm, biased projections/MLP (fc1/fc2), PARTIAL rotary
        # (rotary_pct from partial_rotary_factor), tanh gelu, and a
        # biased untied lm_head
        if getattr(hf_config, "qk_layernorm", False):
            raise ValueError("phi qk_layernorm=True is not implemented")
        if getattr(hf_config, "rope_scaling", None):
            raise ValueError("phi rope_scaling is not implemented")
        if hf_config.hidden_act not in ("gelu_new", "gelu_pytorch_tanh"):
            raise ValueError(f"phi hidden_act {hf_config.hidden_act!r} "
                             f"is not supported")
        return TransformerConfig(
            vocab_size=hf_config.vocab_size,
            hidden_size=hf_config.hidden_size,
            intermediate_size=hf_config.intermediate_size,
            num_layers=hf_config.num_hidden_layers,
            num_heads=hf_config.num_attention_heads,
            num_kv_heads=getattr(hf_config, "num_key_value_heads", None),
            max_seq_len=hf_config.max_position_embeddings,
            norm="layernorm", norm_eps=hf_config.layer_norm_eps,
            activation="gelu", positional="rope",
            rope_theta=getattr(hf_config, "rope_theta", 10000.0),
            rotary_pct=float(getattr(hf_config, "partial_rotary_factor",
                                     1.0)),
            tie_embeddings=getattr(hf_config, "tie_word_embeddings",
                                   False),
            attn_bias=True, mlp_bias=True, parallel_residual=True,
            lm_head_bias=True)
    if mt == "bloom":
        # Bloom: ALiBi positions (no rotary), embeddings LayerNorm,
        # per-head-interleaved fused qkv like NeoX, tanh gelu, tied head
        return TransformerConfig(
            vocab_size=hf_config.vocab_size,
            hidden_size=hf_config.hidden_size,
            intermediate_size=4 * hf_config.hidden_size,
            num_layers=hf_config.n_layer,
            num_heads=hf_config.n_head,
            max_seq_len=getattr(hf_config, "seq_length", 2048),
            norm="layernorm", norm_eps=hf_config.layer_norm_epsilon,
            activation="gelu", positional="alibi",
            tie_embeddings=getattr(hf_config, "tie_word_embeddings", True),
            attn_bias=True, mlp_bias=True, embed_ln=True)
    if mt == "gpt_neox":
        # GPT-NeoX/Pythia: dual-norm parallel residual
        # (x + attn(ln1 x) + mlp(ln2 x)), per-head-interleaved fused qkv
        # (deinterleaved at conversion), partial rotary, exact-erf gelu,
        # biased everything, untied embed_out head
        if getattr(hf_config, "rope_scaling", None):
            raise ValueError("gpt_neox rope_scaling is not implemented")
        if hf_config.hidden_act not in ("gelu", "gelu_new",
                                        "gelu_pytorch_tanh"):
            raise ValueError(f"gpt_neox hidden_act "
                             f"{hf_config.hidden_act!r} is not supported")
        parallel = getattr(hf_config, "use_parallel_residual", True)
        return TransformerConfig(
            vocab_size=hf_config.vocab_size,
            hidden_size=hf_config.hidden_size,
            intermediate_size=hf_config.intermediate_size,
            num_layers=hf_config.num_hidden_layers,
            num_heads=hf_config.num_attention_heads,
            max_seq_len=hf_config.max_position_embeddings,
            norm="layernorm", norm_eps=hf_config.layer_norm_eps,
            activation="gelu_exact" if hf_config.hidden_act == "gelu"
            else "gelu",
            positional="rope",
            rope_theta=getattr(hf_config, "rotary_emb_base", 10000.0),
            rotary_pct=float(getattr(hf_config, "rotary_pct", 1.0)),
            tie_embeddings=getattr(hf_config, "tie_word_embeddings",
                                   False),
            attn_bias=getattr(hf_config, "attention_bias", True),
            mlp_bias=True,
            parallel_residual=parallel, parallel_norms=parallel)
    if mt == "starcoder2":
        # StarCoder2: llama skeleton with biased LayerNorms, biased
        # projections, and a non-gated tanh-gelu MLP (c_fc/c_proj)
        if hf_config.hidden_act not in ("gelu_pytorch_tanh", "gelu"):
            raise ValueError(f"starcoder2 hidden_act "
                             f"{hf_config.hidden_act!r} is not supported")
        max_seq = _cap_to_window(hf_config,
                                 hf_config.max_position_embeddings)
        use_bias = getattr(hf_config, "use_bias", True)
        return TransformerConfig(
            vocab_size=hf_config.vocab_size,
            hidden_size=hf_config.hidden_size,
            intermediate_size=hf_config.intermediate_size,
            num_layers=hf_config.num_hidden_layers,
            num_heads=hf_config.num_attention_heads,
            num_kv_heads=getattr(hf_config, "num_key_value_heads", None),
            max_seq_len=max_seq,
            norm="layernorm", norm_eps=hf_config.norm_epsilon,
            activation="gelu" if hf_config.hidden_act
            == "gelu_pytorch_tanh" else "gelu_exact",
            positional="rope",
            rope_theta=getattr(hf_config, "rope_theta", 10000.0),
            tie_embeddings=getattr(hf_config, "tie_word_embeddings", True),
            attn_bias=use_bias, mlp_bias=use_bias)
    if mt == "falcon":
        # Falcon-7B-class: parallel residual (x + attn(ln x) + mlp(ln x)),
        # fused MQA qkv, bias-free projections/MLP, LayerNorm with bias,
        # exact-erf GELU. Variants outside that envelope reject loudly.
        if getattr(hf_config, "alibi", False):
            raise ValueError("falcon with alibi positions is not "
                             "implemented (rope variants only)")
        if getattr(hf_config, "new_decoder_architecture", False):
            raise ValueError("falcon new_decoder_architecture (40B/180B "
                             "grouped-qkv layout) is not implemented")
        if not getattr(hf_config, "parallel_attn", True):
            raise ValueError("falcon with parallel_attn=False is not "
                             "implemented")
        if getattr(hf_config, "bias", False):
            raise ValueError("falcon with projection biases is not "
                             "implemented")
        if not getattr(hf_config, "multi_query", True):
            # that layout interleaves qkv PER HEAD ([nh, 3, hd] rows) —
            # the flat [q|k|v] split below would scramble it
            raise ValueError("falcon with multi_query=False (per-head "
                             "interleaved qkv) is not implemented")
        nkv = 1
        return TransformerConfig(
            vocab_size=hf_config.vocab_size,
            hidden_size=hf_config.hidden_size,
            intermediate_size=getattr(hf_config, "ffn_hidden_size", None)
            or 4 * hf_config.hidden_size,
            num_layers=hf_config.num_hidden_layers,
            num_heads=hf_config.num_attention_heads,
            num_kv_heads=nkv,
            max_seq_len=getattr(hf_config, "max_position_embeddings", 2048),
            norm="layernorm", norm_eps=hf_config.layer_norm_epsilon,
            activation="gelu_exact", positional="rope",
            rope_theta=getattr(hf_config, "rope_theta", 10000.0),
            tie_embeddings=getattr(hf_config, "tie_word_embeddings", True),
            parallel_residual=True, mlp_bias=False)
    if mt == "phi3":
        # Phi-3: Llama geometry with FUSED qkv_proj / gate_up_proj
        # weights (split in params_from_hf); the shared guard rejects
        # longrope/partial-rotary variants (Phi-4-class)
        return _llama_family_config(hf_config)
    if mt == "gpt2":
        return TransformerConfig(
            vocab_size=hf_config.vocab_size,
            hidden_size=hf_config.n_embd,
            intermediate_size=4 * hf_config.n_embd,
            num_layers=hf_config.n_layer,
            num_heads=hf_config.n_head,
            max_seq_len=hf_config.n_positions,
            norm="layernorm", norm_eps=hf_config.layer_norm_epsilon,
            activation="gelu", positional="learned", tie_embeddings=True,
            attn_bias=True,
        )
    if mt == "opt":
        if not getattr(hf_config, "do_layer_norm_before", True):
            raise ValueError(
                "OPT with do_layer_norm_before=False (OPT-350M) is post-LN; "
                "post-LN is only supported for the MLM encoder family — "
                "the causal decode/pipeline paths require pre-LN")
        if getattr(hf_config, "word_embed_proj_dim",
                   hf_config.hidden_size) != hf_config.hidden_size:
            raise ValueError(
                "OPT word_embed_proj_dim != hidden_size (project_in/out) "
                "is not supported")
        act = {"relu": "relu", "gelu": "gelu"}.get(
            hf_config.activation_function)
        if act is None:
            raise ValueError(
                f"OPT activation_function "
                f"{hf_config.activation_function!r} is not supported; "
                f"supported: relu, gelu")
        return TransformerConfig(
            vocab_size=hf_config.vocab_size,
            hidden_size=hf_config.hidden_size,
            intermediate_size=hf_config.ffn_dim,
            num_layers=hf_config.num_hidden_layers,
            num_heads=hf_config.num_attention_heads,
            max_seq_len=hf_config.max_position_embeddings,
            norm="layernorm", norm_eps=1e-5,
            activation=act, positional="learned",
            tie_embeddings=getattr(hf_config, "tie_word_embeddings", True),
            attn_bias=True,
        )
    if mt == "bert":
        if getattr(hf_config, "position_embedding_type",
                   "absolute") != "absolute":
            raise ValueError(
                f"BERT position_embedding_type "
                f"{hf_config.position_embedding_type!r} is not supported; "
                f"only 'absolute' learned positions convert")
        # HF "gelu" is the exact erf form; our "gelu" is the tanh
        # approximation (HF gelu_new) — map accordingly
        act = {"gelu": "gelu_exact", "gelu_new": "gelu",
               "relu": "relu"}.get(hf_config.hidden_act)
        if act is None:
            raise ValueError(
                f"BERT hidden_act {hf_config.hidden_act!r} is not "
                f"supported; supported: gelu, gelu_new, relu")
        return TransformerConfig(
            vocab_size=hf_config.vocab_size,
            hidden_size=hf_config.hidden_size,
            intermediate_size=hf_config.intermediate_size,
            num_layers=hf_config.num_hidden_layers,
            num_heads=hf_config.num_attention_heads,
            max_seq_len=hf_config.max_position_embeddings,
            norm="layernorm", norm_eps=hf_config.layer_norm_eps,
            activation=act,
            positional="learned", attn_bias=True,
            tie_embeddings=getattr(hf_config, "tie_word_embeddings", True),
            objective="mlm", norm_scheme="post", embed_ln=True,
            mlm_head=True,
        )
    if mt == "roberta":
        if getattr(hf_config, "position_embedding_type",
                   "absolute") != "absolute":
            raise ValueError(
                f"RoBERTa position_embedding_type "
                f"{hf_config.position_embedding_type!r} is not supported")
        act = {"gelu": "gelu_exact", "gelu_new": "gelu",
               "relu": "relu"}.get(hf_config.hidden_act)
        if act is None:
            raise ValueError(
                f"RoBERTa hidden_act {hf_config.hidden_act!r} is not "
                f"supported; supported: gelu, gelu_new, relu")
        return TransformerConfig(
            vocab_size=hf_config.vocab_size,
            hidden_size=hf_config.hidden_size,
            intermediate_size=hf_config.intermediate_size,
            num_layers=hf_config.num_hidden_layers,
            num_heads=hf_config.num_attention_heads,
            # the HF table has pad_token_id+1 (=2) offset rows, like OPT
            max_seq_len=hf_config.max_position_embeddings - 2,
            norm="layernorm", norm_eps=hf_config.layer_norm_eps,
            activation=act,
            positional="learned", attn_bias=True,
            tie_embeddings=getattr(hf_config, "tie_word_embeddings", True),
            objective="mlm", norm_scheme="post", embed_ln=True,
            mlm_head=True,
        )
    if mt == "distilbert":
        if getattr(hf_config, "sinusoidal_pos_embds", False):
            raise ValueError(
                "DistilBERT sinusoidal_pos_embds=True is not supported; "
                "only learned positions convert")
        act = {"gelu": "gelu_exact", "relu": "relu"}.get(
            hf_config.activation)
        if act is None:
            raise ValueError(
                f"DistilBERT activation {hf_config.activation!r} is not "
                f"supported; supported: gelu, relu")
        return TransformerConfig(
            vocab_size=hf_config.vocab_size,
            hidden_size=hf_config.dim,
            intermediate_size=hf_config.hidden_dim,
            num_layers=hf_config.n_layers,
            num_heads=hf_config.n_heads,
            max_seq_len=hf_config.max_position_embeddings,
            norm="layernorm", norm_eps=1e-12,
            activation=act,
            positional="learned", attn_bias=True,
            tie_embeddings=getattr(hf_config, "tie_word_embeddings", True),
            objective="mlm", norm_scheme="post", embed_ln=True,
            mlm_head=True,
        )
    raise ValueError(
        f"unsupported model_type '{mt}'; supported: llama, mistral, "
        f"mixtral, qwen2, phi (1/2), phi3, gemma, falcon, starcoder2, "
        f"gpt_neox, bloom, gpt2, opt, bert, roberta, distilbert (add a "
        f"mapping here the way the reference adds policy containers)")


# ---------------------------------------------------------------------------
# Weight mapping
# ---------------------------------------------------------------------------
def _stack(sd: Dict[str, np.ndarray], fmt: str, L: int,
           transpose: bool = False) -> np.ndarray:
    mats = [sd[fmt.format(i)] for i in range(L)]
    out = np.stack([m.T if transpose else m for m in mats])
    return np.ascontiguousarray(out, np.float32)


def _llama_family_attn_layers(sd, cfg: TransformerConfig,
                              p: str) -> Dict[str, np.ndarray]:
    """The llama/mistral/mixtral shared attention + norm sub-mapping."""
    L = cfg.num_layers
    layers = {
        "attn_norm": _stack(sd, p + "input_layernorm.weight", L),
        "wq": _stack(sd, p + "self_attn.q_proj.weight", L, transpose=True),
        "wk": _stack(sd, p + "self_attn.k_proj.weight", L, transpose=True),
        "wv": _stack(sd, p + "self_attn.v_proj.weight", L, transpose=True),
        "wo": _stack(sd, p + "self_attn.o_proj.weight", L, transpose=True),
        "mlp_norm": _stack(sd, p + "post_attention_layernorm.weight", L),
    }
    if cfg.attn_bias:
        layers["b_q"] = _stack(sd, p + "self_attn.q_proj.bias", L)
        layers["b_k"] = _stack(sd, p + "self_attn.k_proj.bias", L)
        layers["b_v"] = _stack(sd, p + "self_attn.v_proj.bias", L)
        if (p + "self_attn.o_proj.bias").format(0) in sd:
            layers["b_o"] = _stack(sd, p + "self_attn.o_proj.bias", L)
        else:
            # Qwen2-style qkv-only bias: a missing o bias IS zero
            layers["b_o"] = np.zeros(
                (L, layers["wo"].shape[-1]), np.float32)
    return layers


def _llama_family_top(sd, cfg: TransformerConfig,
                      layers: Dict[str, np.ndarray]) -> Dict[str, Any]:
    params = {
        "embed": np.ascontiguousarray(sd["model.embed_tokens.weight"],
                                      np.float32),
        "layers": layers,
        "final_norm": np.ascontiguousarray(sd["model.norm.weight"],
                                           np.float32),
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = np.ascontiguousarray(sd["lm_head.weight"].T,
                                                 np.float32)
    return params


def _params_from_llama(sd, cfg: TransformerConfig) -> Dict[str, Any]:
    L = cfg.num_layers
    p = "model.layers.{}."
    layers = _llama_family_attn_layers(sd, cfg, p)
    layers.update({
        "w_gate": _stack(sd, p + "mlp.gate_proj.weight", L, transpose=True),
        "w_up": _stack(sd, p + "mlp.up_proj.weight", L, transpose=True),
        "w_down": _stack(sd, p + "mlp.down_proj.weight", L, transpose=True),
    })
    return _llama_family_top(sd, cfg, layers)


def _interleaved_qkv(sd, fmt: str, nh: int, hd: int, H: int, L: int):
    """Deinterleave NeoX/Bloom-style fused qkv ([nh, 3, hd] rows): returns
    stacked (wq, wk, wv, b_q, b_k, b_v) with weights transposed to
    [L, H, nh*hd]."""
    def qkv(i, j):
        w = _np(sd[(fmt + ".weight").format(i)])
        return w.reshape(nh, 3, hd, H)[:, j].reshape(nh * hd, H)

    def qkv_b(i, j):
        b = _np(sd[(fmt + ".bias").format(i)])
        return b.reshape(nh, 3, hd)[:, j].reshape(nh * hd)

    def stack(fn):
        return np.ascontiguousarray(np.stack([fn(i) for i in range(L)]),
                                    np.float32)

    return (stack(lambda i: qkv(i, 0).T), stack(lambda i: qkv(i, 1).T),
            stack(lambda i: qkv(i, 2).T),
            stack(lambda i: qkv_b(i, 0)), stack(lambda i: qkv_b(i, 1)),
            stack(lambda i: qkv_b(i, 2)))


def _interleaved_weights_only(sd, fmt, nh, hd, H, L):
    def qkv(i, j):
        w = _np(sd[(fmt + ".weight").format(i)])
        return w.reshape(nh, 3, hd, H)[:, j].reshape(nh * hd, H)

    def stack(fn):
        return np.ascontiguousarray(np.stack([fn(i) for i in range(L)]),
                                    np.float32)

    return (stack(lambda i: qkv(i, 0).T), stack(lambda i: qkv(i, 1).T),
            stack(lambda i: qkv(i, 2).T))


def _params_from_bloom(sd, cfg: TransformerConfig) -> Dict[str, Any]:
    """HF Bloom: NeoX-style per-head-interleaved fused qkv under
    transformer.h.{i}.self_attention, embeddings LayerNorm, tied head."""
    L = cfg.num_layers
    t = "transformer.h.{}."
    wq, wk, wv, b_q, b_k, b_v = _interleaved_qkv(
        sd, t + "self_attention.query_key_value", cfg.num_heads,
        cfg.head_dim, cfg.hidden_size, L)
    layers = {
        "attn_norm": _stack(sd, t + "input_layernorm.weight", L),
        "attn_norm_b": _stack(sd, t + "input_layernorm.bias", L),
        "mlp_norm": _stack(sd, t + "post_attention_layernorm.weight", L),
        "mlp_norm_b": _stack(sd, t + "post_attention_layernorm.bias", L),
        "wq": wq, "wk": wk, "wv": wv,
        "wo": _stack(sd, t + "self_attention.dense.weight", L,
                     transpose=True),
        "b_q": b_q, "b_k": b_k, "b_v": b_v,
        "b_o": _stack(sd, t + "self_attention.dense.bias", L),
        "w_up": _stack(sd, t + "mlp.dense_h_to_4h.weight", L,
                       transpose=True),
        "b_up": _stack(sd, t + "mlp.dense_h_to_4h.bias", L),
        "w_down": _stack(sd, t + "mlp.dense_4h_to_h.weight", L,
                         transpose=True),
        "b_down": _stack(sd, t + "mlp.dense_4h_to_h.bias", L),
    }
    out = {
        "embed": np.ascontiguousarray(
            sd["transformer.word_embeddings.weight"], np.float32),
        "embed_ln_w": np.ascontiguousarray(
            sd["transformer.word_embeddings_layernorm.weight"], np.float32),
        "embed_ln_b": np.ascontiguousarray(
            sd["transformer.word_embeddings_layernorm.bias"], np.float32),
        "layers": layers,
        "final_norm": np.ascontiguousarray(sd["transformer.ln_f.weight"],
                                           np.float32),
        "final_norm_b": np.ascontiguousarray(sd["transformer.ln_f.bias"],
                                             np.float32),
    }
    if not cfg.tie_embeddings:
        out["lm_head"] = np.ascontiguousarray(sd["lm_head.weight"].T,
                                              np.float32)
    return out


def _params_from_gpt_neox(sd, cfg: TransformerConfig) -> Dict[str, Any]:
    """HF GPT-NeoX: attention.query_key_value fuses qkv PER HEAD
    ([nh, 3, hd] rows) — deinterleave via reshape; both LayerNorms are
    biased; mlp dense_h_to_4h / dense_4h_to_h; untied embed_out head."""
    L = cfg.num_layers
    t = "gpt_neox.layers.{}."
    wq, wk, wv, b_q, b_k, b_v = _interleaved_qkv(
        sd, t + "attention.query_key_value", cfg.num_heads, cfg.head_dim,
        cfg.hidden_size, L) if cfg.attn_bias else (
        *_interleaved_weights_only(sd, t + "attention.query_key_value",
                                   cfg.num_heads, cfg.head_dim,
                                   cfg.hidden_size, L), None, None, None)
    layers = {
        "attn_norm": _stack(sd, t + "input_layernorm.weight", L),
        "attn_norm_b": _stack(sd, t + "input_layernorm.bias", L),
        "mlp_norm": _stack(sd, t + "post_attention_layernorm.weight", L),
        "mlp_norm_b": _stack(sd, t + "post_attention_layernorm.bias", L),
        "wq": wq, "wk": wk, "wv": wv,
        "wo": _stack(sd, t + "attention.dense.weight", L, transpose=True),
        "w_up": _stack(sd, t + "mlp.dense_h_to_4h.weight", L,
                       transpose=True),
        "b_up": _stack(sd, t + "mlp.dense_h_to_4h.bias", L),
        "w_down": _stack(sd, t + "mlp.dense_4h_to_h.weight", L,
                         transpose=True),
        "b_down": _stack(sd, t + "mlp.dense_4h_to_h.bias", L),
    }
    if cfg.attn_bias:   # attention_bias=False variants carry no biases
        layers["b_q"], layers["b_k"], layers["b_v"] = b_q, b_k, b_v
        layers["b_o"] = _stack(sd, t + "attention.dense.bias", L)
    out = {
        "embed": np.ascontiguousarray(sd["gpt_neox.embed_in.weight"],
                                      np.float32),
        "layers": layers,
        "final_norm": np.ascontiguousarray(
            sd["gpt_neox.final_layer_norm.weight"], np.float32),
        "final_norm_b": np.ascontiguousarray(
            sd["gpt_neox.final_layer_norm.bias"], np.float32),
    }
    if not cfg.tie_embeddings:
        out["lm_head"] = np.ascontiguousarray(sd["embed_out.weight"].T,
                                              np.float32)
    return out


def _params_from_phi(sd, cfg: TransformerConfig) -> Dict[str, Any]:
    """HF Phi-1/2: llama-style q/k/v names, o_proj spelled
    self_attn.dense, fc1/fc2 MLP, one biased input LayerNorm per layer
    (parallel residual), biased untied lm_head."""
    L = cfg.num_layers
    p = "model.layers.{}."
    layers = {
        "attn_norm": _stack(sd, p + "input_layernorm.weight", L),
        "attn_norm_b": _stack(sd, p + "input_layernorm.bias", L),
        "wq": _stack(sd, p + "self_attn.q_proj.weight", L, transpose=True),
        "wk": _stack(sd, p + "self_attn.k_proj.weight", L, transpose=True),
        "wv": _stack(sd, p + "self_attn.v_proj.weight", L, transpose=True),
        "wo": _stack(sd, p + "self_attn.dense.weight", L, transpose=True),
        "b_q": _stack(sd, p + "self_attn.q_proj.bias", L),
        "b_k": _stack(sd, p + "self_attn.k_proj.bias", L),
        "b_v": _stack(sd, p + "self_attn.v_proj.bias", L),
        "b_o": _stack(sd, p + "self_attn.dense.bias", L),
        "w_up": _stack(sd, p + "mlp.fc1.weight", L, transpose=True),
        "b_up": _stack(sd, p + "mlp.fc1.bias", L),
        "w_down": _stack(sd, p + "mlp.fc2.weight", L, transpose=True),
        "b_down": _stack(sd, p + "mlp.fc2.bias", L),
    }
    out = {
        "embed": np.ascontiguousarray(sd["model.embed_tokens.weight"],
                                      np.float32),
        "layers": layers,
        "final_norm": np.ascontiguousarray(
            sd["model.final_layernorm.weight"], np.float32),
        "final_norm_b": np.ascontiguousarray(
            sd["model.final_layernorm.bias"], np.float32),
        # the logit bias survives tying (HF keeps it a separate param)
        "lm_head_b": np.ascontiguousarray(sd["lm_head.bias"], np.float32),
    }
    if not cfg.tie_embeddings:
        out["lm_head"] = np.ascontiguousarray(sd["lm_head.weight"].T,
                                              np.float32)
    return out


def _params_from_starcoder2(sd, cfg: TransformerConfig) -> Dict[str, Any]:
    """HF StarCoder2: llama-style attention names (with biases), biased
    LayerNorms, and mlp.c_fc/c_proj for the non-gated MLP."""
    L = cfg.num_layers
    p = "model.layers.{}."
    layers = _llama_family_attn_layers(sd, cfg, p)
    layers.update({
        "attn_norm_b": _stack(sd, p + "input_layernorm.bias", L),
        "mlp_norm_b": _stack(sd, p + "post_attention_layernorm.bias", L),
        "w_up": _stack(sd, p + "mlp.c_fc.weight", L, transpose=True),
        "w_down": _stack(sd, p + "mlp.c_proj.weight", L, transpose=True),
    })
    if cfg.mlp_bias:   # use_bias=False checkpoints carry no biases
        layers["b_up"] = _stack(sd, p + "mlp.c_fc.bias", L)
        layers["b_down"] = _stack(sd, p + "mlp.c_proj.bias", L)
    out = _llama_family_top(sd, cfg, layers)
    out["final_norm_b"] = np.ascontiguousarray(sd["model.norm.bias"],
                                               np.float32)
    return out


def _params_from_falcon(sd, cfg: TransformerConfig) -> Dict[str, Any]:
    """HF Falcon: transformer.h.{i}.self_attention.query_key_value fuses
    [q(nh*hd), k(nkv*hd), v(nkv*hd)] rows; parallel-residual layers carry
    one (biased) input LayerNorm and a bias-free MLP."""
    L = cfg.num_layers
    t = "transformer.h.{}."
    q_rows = cfg.num_heads * cfg.head_dim
    kv_rows = cfg.kv_heads * cfg.head_dim

    def split(i, lo, hi):
        return _np(sd[(t + "self_attention.query_key_value.weight"
                       ).format(i)])[lo:hi]

    layers = {
        "attn_norm": _stack(sd, t + "input_layernorm.weight", L),
        "attn_norm_b": _stack(sd, t + "input_layernorm.bias", L),
        "wq": np.ascontiguousarray(np.stack(
            [split(i, 0, q_rows).T for i in range(L)]), np.float32),
        "wk": np.ascontiguousarray(np.stack(
            [split(i, q_rows, q_rows + kv_rows).T
             for i in range(L)]), np.float32),
        "wv": np.ascontiguousarray(np.stack(
            [split(i, q_rows + kv_rows, q_rows + 2 * kv_rows).T
             for i in range(L)]), np.float32),
        "wo": _stack(sd, t + "self_attention.dense.weight", L,
                     transpose=True),
        "w_up": _stack(sd, t + "mlp.dense_h_to_4h.weight", L,
                       transpose=True),
        "w_down": _stack(sd, t + "mlp.dense_4h_to_h.weight", L,
                         transpose=True),
    }
    out = {
        "embed": np.ascontiguousarray(
            sd["transformer.word_embeddings.weight"], np.float32),
        "layers": layers,
        "final_norm": np.ascontiguousarray(
            sd["transformer.ln_f.weight"], np.float32),
        "final_norm_b": np.ascontiguousarray(
            sd["transformer.ln_f.bias"], np.float32),
    }
    if not cfg.tie_embeddings:
        out["lm_head"] = np.ascontiguousarray(sd["lm_head.weight"].T,
                                              np.float32)
    return out


def _params_from_gemma(sd, cfg: TransformerConfig) -> Dict[str, Any]:
    """HF Gemma: llama-style weight names, but RMSNorm computes
    x * (1 + w) — bake the +1 into every converted norm tensor so the
    model's plain rms_norm is exact."""
    out = _params_from_llama(sd, cfg)
    layers = out["layers"]
    for key in ("attn_norm", "mlp_norm"):
        layers[key] = layers[key] + 1.0
    out["final_norm"] = out["final_norm"] + 1.0
    return out


def _params_from_phi3(sd, cfg: TransformerConfig) -> Dict[str, Any]:
    """HF Phi-3 fuses q/k/v into self_attn.qkv_proj ([nh+2*nkv]*hd rows)
    and gate/up into mlp.gate_up_proj ([2F] rows): split them into
    llama-style keys, then reuse the llama mapping."""
    L = cfg.num_layers
    p = "model.layers.{}."
    q_rows = cfg.num_heads * cfg.head_dim
    kv_rows = cfg.kv_heads * cfg.head_dim
    F = cfg.intermediate_size
    out = dict(sd)
    for i in range(L):
        qkv = _np(sd[(p + "self_attn.qkv_proj.weight").format(i)])
        out[(p + "self_attn.q_proj.weight").format(i)] = qkv[:q_rows]
        out[(p + "self_attn.k_proj.weight").format(i)] = \
            qkv[q_rows:q_rows + kv_rows]
        out[(p + "self_attn.v_proj.weight").format(i)] = \
            qkv[q_rows + kv_rows:q_rows + 2 * kv_rows]
        gu = _np(sd[(p + "mlp.gate_up_proj.weight").format(i)])
        out[(p + "mlp.gate_proj.weight").format(i)] = gu[:F]
        out[(p + "mlp.up_proj.weight").format(i)] = gu[F:]
    return _params_from_llama(out, cfg)


def _params_from_mixtral(sd, cfg: TransformerConfig) -> Dict[str, Any]:
    """HF Mixtral: llama/mistral attention + block_sparse_moe experts
    (w1=gate, w3=up, w2=down per expert; gate.weight is the router)."""
    L, E = cfg.num_layers, cfg.moe_num_experts
    p = "model.layers.{}."

    def expert_stack(proj: str) -> np.ndarray:
        fmt = p + "block_sparse_moe.experts.{}." + proj + ".weight"
        out = np.stack([
            np.stack([sd[fmt.format(i, e)].T for e in range(E)])
            for i in range(L)])
        return np.ascontiguousarray(out, np.float32)

    layers = _llama_family_attn_layers(sd, cfg, p)
    layers.update({
        "moe_gate_w": _stack(sd, p + "block_sparse_moe.gate.weight", L,
                             transpose=True),
        "e_gate": expert_stack("w1"),   # [L, E, H, F]
        "e_up": expert_stack("w3"),     # [L, E, H, F]
        "e_down": expert_stack("w2"),   # [L, E, F, H]
    })
    return _llama_family_top(sd, cfg, layers)


def _params_from_gpt2(sd, cfg: TransformerConfig) -> Dict[str, Any]:
    L, h = cfg.num_layers, cfg.hidden_size
    p = "transformer.h.{}."
    # GPT2 Conv1D weights are already [in, out]; c_attn fuses qkv on out dim
    c_attn = np.stack([sd[(p + "attn.c_attn.weight").format(i)]
                       for i in range(L)]).astype(np.float32)
    c_attn_b = np.stack([sd[(p + "attn.c_attn.bias").format(i)]
                         for i in range(L)]).astype(np.float32)
    layers = {
        "attn_norm": _stack(sd, p + "ln_1.weight", L),
        "attn_norm_b": _stack(sd, p + "ln_1.bias", L),
        "wq": np.ascontiguousarray(c_attn[:, :, :h]),
        "wk": np.ascontiguousarray(c_attn[:, :, h:2 * h]),
        "wv": np.ascontiguousarray(c_attn[:, :, 2 * h:]),
        "b_q": np.ascontiguousarray(c_attn_b[:, :h]),
        "b_k": np.ascontiguousarray(c_attn_b[:, h:2 * h]),
        "b_v": np.ascontiguousarray(c_attn_b[:, 2 * h:]),
        "wo": _stack(sd, p + "attn.c_proj.weight", L),
        "b_o": _stack(sd, p + "attn.c_proj.bias", L),
        "mlp_norm": _stack(sd, p + "ln_2.weight", L),
        "mlp_norm_b": _stack(sd, p + "ln_2.bias", L),
        "w_up": _stack(sd, p + "mlp.c_fc.weight", L),
        "b_up": _stack(sd, p + "mlp.c_fc.bias", L),
        "w_down": _stack(sd, p + "mlp.c_proj.weight", L),
        "b_down": _stack(sd, p + "mlp.c_proj.bias", L),
    }
    return {
        "embed": np.ascontiguousarray(sd["transformer.wte.weight"],
                                      np.float32),
        "pos_embed": np.ascontiguousarray(sd["transformer.wpe.weight"],
                                          np.float32),
        "layers": layers,
        "final_norm": np.ascontiguousarray(sd["transformer.ln_f.weight"],
                                           np.float32),
        "final_norm_b": np.ascontiguousarray(sd["transformer.ln_f.bias"],
                                             np.float32),
    }


def _params_from_opt(sd, cfg: TransformerConfig) -> Dict[str, Any]:
    L = cfg.num_layers
    p = "model.decoder.layers.{}."
    layers = {
        "attn_norm": _stack(sd, p + "self_attn_layer_norm.weight", L),
        "attn_norm_b": _stack(sd, p + "self_attn_layer_norm.bias", L),
        "wq": _stack(sd, p + "self_attn.q_proj.weight", L, transpose=True),
        "wk": _stack(sd, p + "self_attn.k_proj.weight", L, transpose=True),
        "wv": _stack(sd, p + "self_attn.v_proj.weight", L, transpose=True),
        "b_q": _stack(sd, p + "self_attn.q_proj.bias", L),
        "b_k": _stack(sd, p + "self_attn.k_proj.bias", L),
        "b_v": _stack(sd, p + "self_attn.v_proj.bias", L),
        "wo": _stack(sd, p + "self_attn.out_proj.weight", L, transpose=True),
        "b_o": _stack(sd, p + "self_attn.out_proj.bias", L),
        "mlp_norm": _stack(sd, p + "final_layer_norm.weight", L),
        "mlp_norm_b": _stack(sd, p + "final_layer_norm.bias", L),
        "w_up": _stack(sd, p + "fc1.weight", L, transpose=True),
        "b_up": _stack(sd, p + "fc1.bias", L),
        "w_down": _stack(sd, p + "fc2.weight", L, transpose=True),
        "b_down": _stack(sd, p + "fc2.bias", L),
    }
    # HF OPTLearnedPositionalEmbedding carries a +2 offset: the table has
    # max_position_embeddings + 2 rows and position i reads row i + 2 —
    # slicing the first two rows off lets plain arange indexing work
    params = {
        "embed": np.ascontiguousarray(
            sd["model.decoder.embed_tokens.weight"], np.float32),
        "pos_embed": np.ascontiguousarray(
            sd["model.decoder.embed_positions.weight"][2:], np.float32),
        "layers": layers,
        "final_norm": np.ascontiguousarray(
            sd["model.decoder.final_layer_norm.weight"], np.float32),
        "final_norm_b": np.ascontiguousarray(
            sd["model.decoder.final_layer_norm.bias"], np.float32),
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = np.ascontiguousarray(sd["lm_head.weight"].T,
                                                 np.float32)
    return params


def _params_from_bert(sd, cfg: TransformerConfig) -> Dict[str, Any]:
    """BertForMaskedLM (post-LN encoder + cls.predictions MLM head). The
    token-type-0 embedding row is folded into the position table — a
    position-independent constant for single-segment inputs (token_type_ids
    other than 0 are not representable)."""
    L = cfg.num_layers
    p = "bert.encoder.layer.{}."
    layers = {
        "wq": _stack(sd, p + "attention.self.query.weight", L, transpose=True),
        "wk": _stack(sd, p + "attention.self.key.weight", L, transpose=True),
        "wv": _stack(sd, p + "attention.self.value.weight", L, transpose=True),
        "b_q": _stack(sd, p + "attention.self.query.bias", L),
        "b_k": _stack(sd, p + "attention.self.key.bias", L),
        "b_v": _stack(sd, p + "attention.self.value.bias", L),
        "wo": _stack(sd, p + "attention.output.dense.weight", L,
                     transpose=True),
        "b_o": _stack(sd, p + "attention.output.dense.bias", L),
        # post-LN: attention.output.LayerNorm lands AFTER the attn residual
        "attn_norm": _stack(sd, p + "attention.output.LayerNorm.weight", L),
        "attn_norm_b": _stack(sd, p + "attention.output.LayerNorm.bias", L),
        "w_up": _stack(sd, p + "intermediate.dense.weight", L, transpose=True),
        "b_up": _stack(sd, p + "intermediate.dense.bias", L),
        "w_down": _stack(sd, p + "output.dense.weight", L, transpose=True),
        "b_down": _stack(sd, p + "output.dense.bias", L),
        "mlp_norm": _stack(sd, p + "output.LayerNorm.weight", L),
        "mlp_norm_b": _stack(sd, p + "output.LayerNorm.bias", L),
    }
    pos = np.asarray(sd["bert.embeddings.position_embeddings.weight"],
                     np.float32)
    tok0 = np.asarray(sd["bert.embeddings.token_type_embeddings.weight"][0],
                      np.float32)
    out = {
        "embed": np.ascontiguousarray(
            sd["bert.embeddings.word_embeddings.weight"], np.float32),
        "pos_embed": np.ascontiguousarray(pos + tok0[None], np.float32),
        "embed_ln_w": np.ascontiguousarray(
            sd["bert.embeddings.LayerNorm.weight"], np.float32),
        "embed_ln_b": np.ascontiguousarray(
            sd["bert.embeddings.LayerNorm.bias"], np.float32),
        "layers": layers,
        "mlm_transform_w": np.ascontiguousarray(
            sd["cls.predictions.transform.dense.weight"].T, np.float32),
        "mlm_transform_b": np.ascontiguousarray(
            sd["cls.predictions.transform.dense.bias"], np.float32),
        "mlm_ln_w": np.ascontiguousarray(
            sd["cls.predictions.transform.LayerNorm.weight"], np.float32),
        "mlm_ln_b": np.ascontiguousarray(
            sd["cls.predictions.transform.LayerNorm.bias"], np.float32),
        "mlm_bias": np.ascontiguousarray(
            sd["cls.predictions.bias"], np.float32),
    }
    if not cfg.tie_embeddings:
        # untied decoder: use the trained cls.predictions.decoder weights,
        # not word_embeddings.T
        out["lm_head"] = np.ascontiguousarray(
            sd["cls.predictions.decoder.weight"].T, np.float32)
    return out


def _params_from_roberta(sd, cfg: TransformerConfig) -> Dict[str, Any]:
    """RobertaForMaskedLM: same post-LN encoder as BERT with roberta.*
    prefixes, a +2 position offset (positions start at padding_idx+1,
    like OPT) and an lm_head.{dense,layer_norm,bias} MLM head."""
    L = cfg.num_layers
    p = "roberta.encoder.layer.{}."
    layers = {
        "wq": _stack(sd, p + "attention.self.query.weight", L, transpose=True),
        "wk": _stack(sd, p + "attention.self.key.weight", L, transpose=True),
        "wv": _stack(sd, p + "attention.self.value.weight", L, transpose=True),
        "b_q": _stack(sd, p + "attention.self.query.bias", L),
        "b_k": _stack(sd, p + "attention.self.key.bias", L),
        "b_v": _stack(sd, p + "attention.self.value.bias", L),
        "wo": _stack(sd, p + "attention.output.dense.weight", L,
                     transpose=True),
        "b_o": _stack(sd, p + "attention.output.dense.bias", L),
        "attn_norm": _stack(sd, p + "attention.output.LayerNorm.weight", L),
        "attn_norm_b": _stack(sd, p + "attention.output.LayerNorm.bias", L),
        "w_up": _stack(sd, p + "intermediate.dense.weight", L, transpose=True),
        "b_up": _stack(sd, p + "intermediate.dense.bias", L),
        "w_down": _stack(sd, p + "output.dense.weight", L, transpose=True),
        "b_down": _stack(sd, p + "output.dense.bias", L),
        "mlp_norm": _stack(sd, p + "output.LayerNorm.weight", L),
        "mlp_norm_b": _stack(sd, p + "output.LayerNorm.bias", L),
    }
    pos = np.asarray(sd["roberta.embeddings.position_embeddings.weight"][2:],
                     np.float32)
    tok0 = np.asarray(
        sd["roberta.embeddings.token_type_embeddings.weight"][0], np.float32)
    out = {
        "embed": np.ascontiguousarray(
            sd["roberta.embeddings.word_embeddings.weight"], np.float32),
        "pos_embed": np.ascontiguousarray(pos + tok0[None], np.float32),
        "embed_ln_w": np.ascontiguousarray(
            sd["roberta.embeddings.LayerNorm.weight"], np.float32),
        "embed_ln_b": np.ascontiguousarray(
            sd["roberta.embeddings.LayerNorm.bias"], np.float32),
        "layers": layers,
        "mlm_transform_w": np.ascontiguousarray(
            sd["lm_head.dense.weight"].T, np.float32),
        "mlm_transform_b": np.ascontiguousarray(
            sd["lm_head.dense.bias"], np.float32),
        "mlm_ln_w": np.ascontiguousarray(
            sd["lm_head.layer_norm.weight"], np.float32),
        "mlm_ln_b": np.ascontiguousarray(
            sd["lm_head.layer_norm.bias"], np.float32),
        "mlm_bias": np.ascontiguousarray(sd["lm_head.bias"], np.float32),
    }
    if not cfg.tie_embeddings:
        out["lm_head"] = np.ascontiguousarray(
            sd["lm_head.decoder.weight"].T, np.float32)
    return out


def _params_from_distilbert(sd, cfg: TransformerConfig) -> Dict[str, Any]:
    """DistilBertForMaskedLM: BERT-style post-LN encoder without token
    types; MLM head = vocab_transform + vocab_layer_norm + vocab_projector
    (tied to word embeddings)."""
    L = cfg.num_layers
    p = "distilbert.transformer.layer.{}."
    layers = {
        "wq": _stack(sd, p + "attention.q_lin.weight", L, transpose=True),
        "wk": _stack(sd, p + "attention.k_lin.weight", L, transpose=True),
        "wv": _stack(sd, p + "attention.v_lin.weight", L, transpose=True),
        "b_q": _stack(sd, p + "attention.q_lin.bias", L),
        "b_k": _stack(sd, p + "attention.k_lin.bias", L),
        "b_v": _stack(sd, p + "attention.v_lin.bias", L),
        "wo": _stack(sd, p + "attention.out_lin.weight", L, transpose=True),
        "b_o": _stack(sd, p + "attention.out_lin.bias", L),
        "attn_norm": _stack(sd, p + "sa_layer_norm.weight", L),
        "attn_norm_b": _stack(sd, p + "sa_layer_norm.bias", L),
        "w_up": _stack(sd, p + "ffn.lin1.weight", L, transpose=True),
        "b_up": _stack(sd, p + "ffn.lin1.bias", L),
        "w_down": _stack(sd, p + "ffn.lin2.weight", L, transpose=True),
        "b_down": _stack(sd, p + "ffn.lin2.bias", L),
        "mlp_norm": _stack(sd, p + "output_layer_norm.weight", L),
        "mlp_norm_b": _stack(sd, p + "output_layer_norm.bias", L),
    }
    out = {
        "embed": np.ascontiguousarray(
            sd["distilbert.embeddings.word_embeddings.weight"], np.float32),
        "pos_embed": np.ascontiguousarray(
            sd["distilbert.embeddings.position_embeddings.weight"],
            np.float32),
        "embed_ln_w": np.ascontiguousarray(
            sd["distilbert.embeddings.LayerNorm.weight"], np.float32),
        "embed_ln_b": np.ascontiguousarray(
            sd["distilbert.embeddings.LayerNorm.bias"], np.float32),
        "layers": layers,
        "mlm_transform_w": np.ascontiguousarray(
            sd["vocab_transform.weight"].T, np.float32),
        "mlm_transform_b": np.ascontiguousarray(
            sd["vocab_transform.bias"], np.float32),
        "mlm_ln_w": np.ascontiguousarray(
            sd["vocab_layer_norm.weight"], np.float32),
        "mlm_ln_b": np.ascontiguousarray(
            sd["vocab_layer_norm.bias"], np.float32),
        "mlm_bias": np.ascontiguousarray(
            sd["vocab_projector.bias"], np.float32),
    }
    if not cfg.tie_embeddings:
        out["lm_head"] = np.ascontiguousarray(
            sd["vocab_projector.weight"].T, np.float32)
    return out


def params_from_hf(state_dict: Dict[str, Any],
                   cfg: TransformerConfig,
                   model_type: str = "llama") -> Dict[str, Any]:
    """Convert an HF state dict (torch tensors or numpy) to the TransformerLM
    parameter tree (fp32 host arrays; the engine casts/shards on load)."""
    sd = {k: _np(v) for k, v in state_dict.items()}
    if model_type in ("llama", "mistral", "qwen2"):
        return _params_from_llama(sd, cfg)
    if model_type == "phi3":
        return _params_from_phi3(sd, cfg)
    if model_type == "gemma":
        return _params_from_gemma(sd, cfg)
    if model_type == "falcon":
        return _params_from_falcon(sd, cfg)
    if model_type == "starcoder2":
        return _params_from_starcoder2(sd, cfg)
    if model_type == "phi":
        return _params_from_phi(sd, cfg)
    if model_type == "gpt_neox":
        return _params_from_gpt_neox(sd, cfg)
    if model_type == "bloom":
        return _params_from_bloom(sd, cfg)
    if model_type == "mixtral":
        return _params_from_mixtral(sd, cfg)
    if model_type == "gpt2":
        return _params_from_gpt2(sd, cfg)
    if model_type == "opt":
        return _params_from_opt(sd, cfg)
    if model_type == "bert":
        return _params_from_bert(sd, cfg)
    if model_type == "roberta":
        return _params_from_roberta(sd, cfg)
    if model_type == "distilbert":
        return _params_from_distilbert(sd, cfg)
    raise ValueError(f"unsupported model_type '{model_type}'")


def load_hf_model(hf_model) -> Tuple[TransformerLM, Dict[str, Any]]:
    """One-call injection (reference replace_transformer_layer entry): HF
    torch model -> (TransformerLM, params)."""
    cfg = config_from_hf(hf_model.config)
    params = params_from_hf(hf_model.state_dict(), cfg,
                            hf_model.config.model_type)
    logger.info(f"injected HF {hf_model.config.model_type} "
                f"({cfg.num_layers}L, {cfg.hidden_size}H) into TransformerLM")
    return TransformerLM(cfg), params


def replace_transformer_layer(orig_layer_impl=None, model=None,
                              checkpoint_dict=None, config=None,
                              model_config=None):
    """Reference-compat signature (replace_module.py:182): returns the
    converted (TransformerLM, params) for `model`."""
    return load_hf_model(model)
