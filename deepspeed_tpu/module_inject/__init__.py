from .auto_tp import (config_from_hf, load_hf_model,  # noqa: F401
                      params_from_hf, replace_transformer_layer)
