"""MII-style one-call serving pipeline.

The reference's serving front end (DeepSpeed-MII) wraps FastGen as
``pipe = mii.pipeline(model); pipe(prompts, max_new_tokens=...)``; this is
the TPU-native equivalent over the ragged v2 engine + Dynamic SplitFuse
scheduler. ``pipeline()`` accepts a native functional model, an HF torch
module (converted via module_inject like init_inference), or an HF hub
name (needs network/cache); the returned callable takes prompts as
strings (with a tokenizer) or token-id lists (without) and runs the whole
batch through one SplitFuse schedule.

    pipe = deepspeed_tpu.pipeline(model, tokenizer)
    texts = pipe(["a prompt", "another"], max_new_tokens=64)
"""

from typing import Optional, Sequence

import numpy as np


class ServePipeline:
    def __init__(self, engine, tokenizer=None,
                 token_budget: Optional[int] = None,
                 chunk: Optional[int] = None):
        self.engine = engine
        self.tokenizer = tokenizer
        self.token_budget = token_budget
        self.chunk = chunk
        self._uid = 0

    def __call__(self, prompts, max_new_tokens: int = 64,
                 eos_token_id: Optional[int] = None,
                 return_full_text: bool = False,
                 temperature: float = 0.0, top_p: float = 1.0,
                 top_k: int = 0, seed: Optional[int] = None):
        """prompts: str | Sequence[str] (tokenizer required) or
        Sequence[Sequence[int]]. Returns decoded strings when a tokenizer
        is present, else token-id arrays; generated-only by default."""
        from .inference.v2.scheduler import DynamicSplitFuseScheduler

        single = isinstance(prompts, str)
        if single:
            prompts = [prompts]
        if prompts and isinstance(prompts[0], str):
            assert self.tokenizer is not None, \
                "string prompts need a tokenizer; pass token-id lists " \
                "or pipeline(..., tokenizer=...)"
            ids = [self._encode(p) for p in prompts]
        else:
            ids = [list(map(int, p)) for p in prompts]
        if eos_token_id is None and self.tokenizer is not None:
            eos_token_id = getattr(self.tokenizer, "eos_token_id", None)

        sched = DynamicSplitFuseScheduler(self.engine,
                                          token_budget=self.token_budget,
                                          chunk=self.chunk)
        uids = []
        for i, p in enumerate(ids):
            uid = self._uid = self._uid + 1
            sched.submit(uid, p, max_new_tokens=max_new_tokens,
                         eos_token_id=eos_token_id,
                         temperature=temperature, top_p=top_p,
                         top_k=top_k,
                         seed=None if seed is None else seed + i)
            uids.append(uid)
        sched.run()
        res = sched.results()
        outs = []
        for uid, p in zip(uids, ids):
            toks = res[uid] if return_full_text else res[uid][len(p):]
            outs.append(self._decode(toks) if self.tokenizer is not None
                        else np.asarray(toks))
        return outs[0] if single else outs

    # -- tokenizer adapters (HF tokenizers and anything encode/decode) --
    def _encode(self, text: str):
        tk = self.tokenizer
        if hasattr(tk, "encode"):
            return list(map(int, tk.encode(text)))
        return list(map(int, tk(text)["input_ids"]))

    def _decode(self, toks):
        return self.tokenizer.decode(list(map(int, toks)))


def pipeline(model=None, tokenizer=None, config=None, params=None,
             token_budget: Optional[int] = None,
             chunk: Optional[int] = None, **kwargs) -> ServePipeline:
    """Build a ServePipeline. ``model`` may be a native functional model
    (pass trained weights via ``params``), an HF torch module, or an HF
    hub name string (resolved via transformers, which needs network or a
    local cache)."""
    from . import init_inference

    if isinstance(model, str):
        import transformers
        name = model
        model = transformers.AutoModelForCausalLM.from_pretrained(name)
        if tokenizer is None:
            tokenizer = transformers.AutoTokenizer.from_pretrained(name)
    cfg = dict(config or {})
    cfg["use_ragged"] = True
    engine = init_inference(model=model, config=cfg, params=params,
                            **kwargs)
    return ServePipeline(engine, tokenizer=tokenizer,
                         token_budget=token_budget, chunk=chunk)
