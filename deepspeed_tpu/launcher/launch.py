"""Node-local process spawner.

TPU-native analogue of the reference's ``launcher/launch.py`` (main :132):
spawn one worker process per local rank with the rendezvous env set, write a
PID file, monitor the children, and on any child failure kill the whole local
group (the reference's ``sigkill_handler``, runner.py:573 / launch.py signal
handling) so a hung ensemble never outlives its first casualty.

Differences driven by the TPU runtime: the reference forks one process per
GPU and hands each CUDA_VISIBLE_DEVICES; on TPU hosts jax normally owns all
local chips in ONE process, so ``--nproc_per_node`` defaults to 1. Values >1
are the multi-process-per-host mode used for CPU-mesh testing and for
TPU-VM configurations that split chips between processes (each worker gets
the env to claim its slice).

Env protocol written for each worker (consumed by comm.init_distributed):
  DS_TPU_COORDINATOR     host:port of global process 0
  DS_TPU_NUM_PROCESSES   global process count
  DS_TPU_PROCESS_ID      this worker's global process id
  LOCAL_RANK             this worker's local index on the node
"""

import argparse
import os
import signal
import subprocess
import sys
import time
from typing import Dict, List, Optional

from ..utils.logging import logger


class NodeLauncher:
    """Spawn + babysit the local worker group (reference launch.py:132)."""

    def __init__(self,
                 cmd: List[str],
                 nproc: int = 1,
                 base_process_id: int = 0,
                 num_processes: int = 1,
                 coordinator: str = "localhost:29500",
                 extra_env: Optional[Dict[str, Optional[str]]] = None,
                 pid_file: Optional[str] = None,
                 poll_interval: float = 0.2):
        self.cmd = cmd
        self.nproc = nproc
        self.base_process_id = base_process_id
        self.num_processes = num_processes
        if ":" not in coordinator:
            raise ValueError(
                f"coordinator must be 'host:port', got {coordinator!r}")
        self.coordinator = coordinator
        self.extra_env = extra_env or {}
        self.pid_file = pid_file
        self.poll_interval = poll_interval
        self.procs: List[subprocess.Popen] = []
        self._signalled = False

    def _worker_env(self, local_rank: int) -> Dict[str, str]:
        env = dict(os.environ)
        # an extra_env value of None DELETES the inherited var (there is no
        # other way to un-inherit, since update() can only add/overwrite)
        for k, v in self.extra_env.items():
            if v is None:
                env.pop(k, None)
            else:
                env[k] = v
        env.update({
            "DS_TPU_COORDINATOR": self.coordinator,
            "DS_TPU_NUM_PROCESSES": str(self.num_processes),
            "DS_TPU_PROCESS_ID": str(self.base_process_id + local_rank),
            "LOCAL_RANK": str(local_rank),
            # torch-style aliases so user scripts written against the
            # reference env protocol keep working (reference launch.py sets
            # RANK/LOCAL_RANK/WORLD_SIZE/MASTER_ADDR/MASTER_PORT)
            "RANK": str(self.base_process_id + local_rank),
            "WORLD_SIZE": str(self.num_processes),
            "MASTER_ADDR": self.coordinator.rsplit(":", 1)[0],
            "MASTER_PORT": self.coordinator.rsplit(":", 1)[1],
        })
        return env

    def spawn(self):
        try:
            for lr in range(self.nproc):
                p = subprocess.Popen(self.cmd, env=self._worker_env(lr))
                self.procs.append(p)
        except Exception:
            # partial spawn must not leak the workers that did start
            self.kill_all()
            raise
        if self.pid_file:
            os.makedirs(os.path.dirname(self.pid_file) or ".", exist_ok=True)
            with open(self.pid_file, "w") as fh:
                fh.write("\n".join(str(p.pid) for p in self.procs) + "\n")
        logger.info(f"spawned {self.nproc} worker(s): "
                    f"pids={[p.pid for p in self.procs]}")
        return self

    def _install_signal_handlers(self):
        def handler(signum, _frame):
            self._signalled = True
            logger.warning(f"received signal {signum}; killing worker group")
            self.kill_all(signum)
            sys.exit(128 + signum)

        for s in (signal.SIGINT, signal.SIGTERM):
            signal.signal(s, handler)

    def kill_all(self, signum=signal.SIGTERM):
        """The reference's sigkill_handler (runner.py:573): take the whole
        local group down together."""
        for p in self.procs:
            if p.poll() is None:
                try:
                    p.send_signal(signum)
                except ProcessLookupError:
                    pass
        deadline = time.time() + 5.0
        for p in self.procs:
            while p.poll() is None and time.time() < deadline:
                time.sleep(0.05)
            if p.poll() is None:
                # workers may trap SIGTERM (jax installs a preemption
                # notifier); escalate and reap so nothing survives us
                try:
                    p.kill()
                except ProcessLookupError:
                    pass
                try:
                    p.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    logger.error(f"worker pid={p.pid} survived SIGKILL")

    def monitor(self) -> int:
        """Wait for the group; first nonzero exit kills the rest and becomes
        the group's exit code. Returns 0 only if every worker succeeded."""
        try:
            while True:
                alive = False
                for p in self.procs:
                    rc = p.poll()
                    if rc is None:
                        alive = True
                    elif rc != 0:
                        logger.error(
                            f"worker pid={p.pid} failed rc={rc}; "
                            f"killing local group")
                        self.kill_all()
                        return rc
                if not alive:
                    return 0
                time.sleep(self.poll_interval)
        finally:
            if self.pid_file and os.path.exists(self.pid_file):
                try:
                    os.remove(self.pid_file)
                except OSError:
                    pass

    def run(self) -> int:
        self._install_signal_handlers()
        self.spawn()
        return self.monitor()


def parse_args(argv=None):
    p = argparse.ArgumentParser(
        prog="ds_tpu_launch",
        description="deepspeed_tpu node-local worker spawner "
                    "(reference launcher/launch.py)")
    p.add_argument("--nproc_per_node", type=int, default=1)
    p.add_argument("--node_rank", type=int, default=0,
                   help="index of this node in the cluster")
    p.add_argument("--nnodes", type=int, default=1)
    p.add_argument("--master_addr", default="localhost")
    p.add_argument("--master_port", type=int, default=29500)
    p.add_argument("--pid_file", default=None,
                   help="file to record worker pids (reference launch.py "
                        "--save_pid)")
    p.add_argument("--module", action="store_true",
                   help="run user_script with python -m")
    p.add_argument("--no_python", action="store_true",
                   help="user_script is an executable, not a python file")
    p.add_argument("user_script")
    p.add_argument("user_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def main(argv=None) -> int:
    args = parse_args(argv)
    if args.module and args.no_python:
        raise ValueError("--module and --no_python are mutually exclusive")
    if args.no_python:
        cmd = [args.user_script]
    elif args.module:
        cmd = [sys.executable, "-m", args.user_script]
    else:
        cmd = [sys.executable, args.user_script]
    cmd += args.user_args
    launcher = NodeLauncher(
        cmd,
        nproc=args.nproc_per_node,
        base_process_id=args.node_rank * args.nproc_per_node,
        num_processes=args.nnodes * args.nproc_per_node,
        coordinator=f"{args.master_addr}:{args.master_port}",
        pid_file=args.pid_file)
    return launcher.run()


if __name__ == "__main__":
    sys.exit(main())
