"""Cluster launcher: hostfile parsing + multi-node command construction.

TPU-native analogue of the reference launcher (launcher/runner.py:389 main,
multinode_runner.py PDSH/OpenMPI/Slurm runners). Differences driven by the
TPU runtime: there is ONE process per host (jax owns all local chips), and
rendezvous is `jax.distributed.initialize(coordinator, num_processes,
process_id)` instead of torch's env:// store — so the runner's job is to
compute the process grid, pick the coordinator, and ssh/pdsh/srun the node
command everywhere with the right env (the reference's hostfile/filter UX is
kept).

Env protocol consumed by deepspeed_tpu.comm.init_distributed:
  DS_TPU_COORDINATOR  host:port of process 0
  DS_TPU_NUM_PROCESSES
  DS_TPU_PROCESS_ID
"""

import argparse
import os
import shlex
import subprocess
import sys
from collections import OrderedDict
from typing import Dict, List, Optional

from ..utils.logging import logger

DEFAULT_COORD_PORT = 29500


def parse_hostfile(path: str) -> "OrderedDict[str, int]":
    """'hostname slots=N' lines -> {host: slots} (reference runner.py:201
    fetch_hostfile)."""
    hosts: "OrderedDict[str, int]" = OrderedDict()
    with open(path) as fh:
        for line in fh:
            line = line.split("#")[0].strip()
            if not line:
                continue
            parts = line.split()
            host = parts[0]
            slots = 1
            for p in parts[1:]:
                if p.startswith("slots="):
                    slots = int(p.split("=")[1])
            if host in hosts:
                raise ValueError(f"duplicate host {host} in hostfile")
            hosts[host] = slots
    if not hosts:
        raise ValueError(f"hostfile {path} is empty")
    return hosts


def parse_inclusion_exclusion(hosts: Dict[str, int], include: str = "",
                              exclude: str = "") -> "OrderedDict[str, int]":
    """'--include host1@host2' / '--exclude host3' filters (reference
    runner.py:256 parse_resource_filter; TPU hosts are atomic — no per-slot
    selection, jax owns all chips on an included host)."""
    sel = OrderedDict(hosts)
    if include:
        wanted = include.split("@")
        unknown = [h for h in wanted if h not in sel]
        if unknown:
            raise ValueError(f"--include names unknown hosts {unknown}")
        sel = OrderedDict((h, sel[h]) for h in wanted)
    if exclude:
        for h in exclude.split("@"):
            if h not in sel:
                raise ValueError(f"--exclude names unknown host {h}")
            del sel[h]
    if not sel:
        raise ValueError("resource filters removed every host")
    return sel


def build_node_command(script: str, script_args: List[str], process_id: int,
                       num_processes: int, coordinator: str,
                       extra_env: Optional[Dict[str, str]] = None) -> str:
    """The per-node shell command (reference launch.py env setup)."""
    env = {
        "DS_TPU_COORDINATOR": coordinator,
        "DS_TPU_NUM_PROCESSES": str(num_processes),
        "DS_TPU_PROCESS_ID": str(process_id),
    }
    env.update(extra_env or {})
    exports = " ".join(f"{k}={shlex.quote(v)}" for k, v in env.items())
    args = " ".join(shlex.quote(a) for a in script_args)
    return f"{exports} {sys.executable} {shlex.quote(script)} {args}".strip()


class MultiNodeRunner:
    """Base: turns (hosts, node commands) into a cluster launch command
    (reference multinode_runner.py:25)."""

    name = "base"

    def __init__(self, args):
        self.args = args

    def backend_exists(self) -> bool:
        from shutil import which

        return which(self._binary()) is not None

    def _binary(self) -> str:
        raise NotImplementedError

    def get_cmd(self, hosts: Dict[str, int], node_cmds: List[str]) -> List[str]:
        raise NotImplementedError


class PDSHRunner(MultiNodeRunner):
    """reference multinode_runner.py:51 — same command per host; the node
    command reads its process id from a per-host env injected via pdsh's
    %n/%h substitution is not portable, so we pass an id map file instead."""

    name = "pdsh"

    def _binary(self):
        return "pdsh"

    def get_cmd(self, hosts, node_cmds):
        hostlist = ",".join(hosts)
        # every host runs the same wrapper; process id = line number of this
        # host in the host list, matched against short AND fqdn hostnames so
        # hostfile entries written either way still resolve; no match at all
        # fails loudly instead of handing out an out-of-range id
        wrapper = (
            "HOSTS=\"" + " ".join(hosts) + "\"; PID=0; FOUND=0; "
            "for h in $HOSTS; do "
            "if [ \"$h\" = \"$(hostname)\" ] || [ \"$h\" = \"$(hostname -s)\" ]"
            " || [ \"$h\" = \"$(hostname -f 2>/dev/null)\" ]; then FOUND=1; break; fi; "
            "PID=$((PID+1)); done; "
            "if [ \"$FOUND\" != 1 ]; then "
            "echo \"deepspeed-tpu: $(hostname) not in hostfile ($HOSTS)\" >&2; exit 1; fi; "
            + node_cmds[0].replace("DS_TPU_PROCESS_ID=0",
                                   "DS_TPU_PROCESS_ID=$PID"))
        return ["pdsh", "-S", "-f", "1024", "-w", hostlist, wrapper]


class OpenMPIRunner(MultiNodeRunner):
    """reference multinode_runner.py:109 — mpirun provides the rank."""

    name = "openmpi"

    def _binary(self):
        return "mpirun"

    def get_cmd(self, hosts, node_cmds):
        hostlist = ",".join(f"{h}:1" for h in hosts)
        base = node_cmds[0]
        # strip the static process id; read it from OMPI at runtime
        base = base.replace(
            "DS_TPU_PROCESS_ID=0",
            "DS_TPU_PROCESS_ID=$OMPI_COMM_WORLD_RANK")
        return ["mpirun", "-np", str(len(hosts)), "--host", hostlist,
                "--map-by", "ppr:1:node", "bash", "-c", base]


class SlurmRunner(MultiNodeRunner):
    """reference multinode_runner.py:318 — srun provides the rank."""

    name = "slurm"

    def _binary(self):
        return "srun"

    def get_cmd(self, hosts, node_cmds):
        base = node_cmds[0].replace("DS_TPU_PROCESS_ID=0",
                                    "DS_TPU_PROCESS_ID=$SLURM_PROCID")
        return ["srun", "--nodes", str(len(hosts)), "--ntasks-per-node", "1",
                "bash", "-c", base]


RUNNERS = {r.name: r for r in (PDSHRunner, OpenMPIRunner, SlurmRunner)}


def parse_args(argv=None):
    p = argparse.ArgumentParser(
        prog="dstpu",
        description="deepspeed_tpu cluster launcher (reference bin/deepspeed)")
    p.add_argument("-H", "--hostfile", default="/job/hostfile")
    p.add_argument("-i", "--include", default="")
    p.add_argument("-e", "--exclude", default="")
    p.add_argument("--num_nodes", type=int, default=-1)
    p.add_argument("--master_addr", default=None,
                   help="coordinator host (default: first host)")
    p.add_argument("--master_port", type=int, default=DEFAULT_COORD_PORT)
    p.add_argument("--launcher", default="pdsh", choices=sorted(RUNNERS))
    p.add_argument("--force_multi", action="store_true")
    # reference bin/deepspeed --autotuning {tune,run}: tune knobs
    # before/instead of the real run. Here tuning is chip-free offline
    # replay (autotuning/offline.py) — no launched experiment subprocesses
    p.add_argument("--autotuning", choices=("tune", "run"), default=None)
    p.add_argument("--autotuning_config", default=None,
                   help="JSON file with the base engine config for autotuning")
    p.add_argument("--autotuning_exp_dir", default="autotuning_exps")
    p.add_argument("--autotuning_workload", default=None,
                   help="workload artifact (scripts/autotune.py capture) "
                        "to replay; default = a synthesized load_bench mix")
    p.add_argument("user_script")
    p.add_argument("user_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def run_autotuning(args):
    """reference launcher/runner.py:360 semantics, offline machinery:
    replay a workload artifact through the chip-free tuner
    (autotuning/offline.py) and write the ranked report + the winning
    config. Returns 0/1 in mode 'tune'; in mode 'run' returns the
    winning-config path so main() proceeds to launch the real run with
    it."""
    import json

    from .. import autotuning

    base = {"optimizer": {"type": "adamw", "params": {"lr": 1e-4}},
            "steps_per_print": 10 ** 9}
    if args.autotuning_config:
        with open(args.autotuning_config) as fh:
            base = json.load(fh)
    if args.autotuning_workload:
        artifact = autotuning.load(args.autotuning_workload)
    else:
        artifact = autotuning.synthesize()
    tuner = autotuning.OfflineTuner(artifact, base_config=base)
    result = tuner.tune()
    if result["improved_signals"] < 1:
        logger.error("autotuning: no registered cost signal improved over "
                     "defaults on this workload")
        return 1
    os.makedirs(args.autotuning_exp_dir, exist_ok=True)
    with open(os.path.join(args.autotuning_exp_dir,
                           "autotune_results.json"), "w") as fh:
        json.dump({"report": result["report"],
                   "improved_signals": result["improved_signals"],
                   "trials": result["trials"]}, fh, indent=2)
    # absolute: the path is exported into remote node commands, whose shells
    # start in $HOME, not this launcher's cwd
    best_path = os.path.abspath(
        os.path.join(args.autotuning_exp_dir, "best_config.json"))
    with open(best_path, "w") as fh:
        json.dump(result["config"], fh, indent=2)
    top = result["report"][0] if result["report"] else {}
    logger.info(f"autotuning: {result['improved_signals']} cost signal(s) "
                f"improved over {result['trials']} trials (best: "
                f"{top.get('knob')} -> {top.get('tuned')}) — report in "
                f"{args.autotuning_exp_dir}/autotune_results.json, winning "
                f"config in {best_path}")
    if args.autotuning == "run":
        return best_path   # caller launches the real run with this config
    return 0


def main(argv=None):
    args = parse_args(argv)
    extra_env: Dict[str, str] = {}
    if args.autotuning:
        out = run_autotuning(args)
        if not isinstance(out, str):
            return out
        # mode 'run' (reference bin/deepspeed semantics): tune, then launch
        # the real training with the winning config exported for the user
        # script / engine to pick up. The var rides the per-node command
        # (pdsh/mpirun/srun shells do NOT inherit this launcher's environ);
        # note best_config.json lives on this host — multi-node runs need it
        # on a shared filesystem, like the reference's rewritten config files
        os.environ["DS_TPU_AUTOTUNED_CONFIG"] = out
        extra_env["DS_TPU_AUTOTUNED_CONFIG"] = out
        logger.info("autotuning done; launching user script with "
                    f"DS_TPU_AUTOTUNED_CONFIG={out}")
    multi_node = args.force_multi or os.path.exists(args.hostfile)
    if not multi_node:
        # single host: exec in place with a 1-process grid
        cmd = build_node_command(args.user_script, args.user_args, 0, 1,
                                 f"localhost:{args.master_port}",
                                 extra_env=extra_env)
        logger.info(f"single-node launch: {cmd}")
        return subprocess.call(["bash", "-c", cmd])

    hosts = parse_hostfile(args.hostfile)
    hosts = parse_inclusion_exclusion(hosts, args.include, args.exclude)
    if args.num_nodes > 0:
        hosts = OrderedDict(list(hosts.items())[:args.num_nodes])
    coordinator = (args.master_addr or next(iter(hosts))) + \
        f":{args.master_port}"
    node_cmds = [build_node_command(args.user_script, args.user_args, pid,
                                    len(hosts), coordinator,
                                    extra_env=extra_env)
                 for pid in range(len(hosts))]
    runner = RUNNERS[args.launcher](args)
    if not runner.backend_exists():
        raise RuntimeError(f"launcher backend '{args.launcher}' not installed")
    cmd = runner.get_cmd(hosts, node_cmds)
    logger.info(f"multi-node launch over {len(hosts)} hosts: {cmd}")
    return subprocess.call(cmd)


if __name__ == "__main__":
    sys.exit(main())
