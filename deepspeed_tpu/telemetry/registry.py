"""Process-local metrics registry.

Three metric kinds with Prometheus semantics:

  * :class:`Counter` — monotonically increasing float (``_total`` names),
  * :class:`Gauge` — a value that goes up and down,
  * :class:`Histogram` — fixed-bucket distribution; ``observe`` is a
    bisect over a precomputed bound tuple plus one list increment, so the
    hot path allocates nothing.

Label handling follows the client-library convention: a family is
registered once with its ``labelnames``; ``family.labels(op="x")``
resolves (and caches) the concrete series, so steady-state
instrumentation touches plain Python attributes. A family with no label
names IS its single series — ``inc``/``set``/``observe`` work directly
on it.

Exports: ``render_prometheus()`` (text exposition format 0.0.4) and
``snapshot()`` (JSON-serializable dict; round-trips through ``json``).
Registration is idempotent: re-registering a name returns the existing
family and raises only on a kind/labelnames mismatch.
"""

import threading
from bisect import bisect_left
from contextlib import contextmanager
from typing import Dict, Iterable, Iterator, List, Optional, Tuple

# Prometheus default buckets suit request latencies in seconds; the
# sub-millisecond tail matters for per-step decode timings on TPU.
DEFAULT_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                   0.25, 0.5, 1.0, 2.5, 5.0, 10.0)

_INF = float("inf")


def _escape_label_value(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _escape_help(v: str) -> str:
    # HELP text escapes ONLY backslash and newline (exposition format
    # 0.0.4); quotes are legal there, unlike in label values
    return v.replace("\\", "\\\\").replace("\n", "\\n")


def _format_value(v: float) -> str:
    if v == _INF:
        return "+Inf"
    if v == -_INF:
        return "-Inf"
    f = float(v)
    return repr(int(f)) if f == int(f) else repr(f)


def _label_str(names: Tuple[str, ...], values: Tuple[str, ...],
               extra: str = "") -> str:
    parts = [f'{n}="{_escape_label_value(v)}"'
             for n, v in zip(names, values)]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


class _CounterSeries:
    __slots__ = ("value",)
    kind = "counter"

    def __init__(self):
        self.value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError("counters only increase; use a gauge")
        self.value += amount


class _GaugeSeries:
    __slots__ = ("value",)
    kind = "gauge"

    def __init__(self):
        self.value = 0.0

    def set(self, value: float) -> None:
        self.value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        self.value += amount

    def dec(self, amount: float = 1.0) -> None:
        self.value -= amount


class _HistogramSeries:
    __slots__ = ("bounds", "bucket_counts", "sum", "count")
    kind = "histogram"

    def __init__(self, bounds: Tuple[float, ...]):
        self.bounds = bounds
        # one slot per finite bound plus the +Inf overflow slot
        self.bucket_counts = [0] * (len(bounds) + 1)
        self.sum = 0.0
        self.count = 0

    def observe(self, value: float) -> None:
        self.bucket_counts[bisect_left(self.bounds, value)] += 1
        self.sum += value
        self.count += 1

    @property
    def mean(self) -> float:
        return self.sum / self.count if self.count else 0.0

    def quantile(self, q: float) -> float:
        """Estimate the q-quantile (0 <= q <= 1) from the fixed buckets
        by linear interpolation inside the bucket holding the target
        rank — the same estimate PromQL's ``histogram_quantile`` makes
        server-side, available process-locally (the SLO monitor and
        /statusz p50/p95/p99 read it without raw-sample lists).

        Error is bounded by the width of the bucket the quantile lands
        in (observations are uniform-within-bucket by assumption). The
        first bucket interpolates from 0; a quantile landing in the
        +Inf overflow bucket returns the largest finite bound (there is
        no upper edge to interpolate toward). NaN when empty."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile q must be in [0, 1], got {q}")
        if not self.count:
            return float("nan")
        target = q * self.count
        acc = 0
        for i, c in enumerate(self.bucket_counts):
            if not c:
                continue
            if acc + c >= target:
                if i >= len(self.bounds):      # +Inf overflow bucket
                    return float(self.bounds[-1]) if self.bounds \
                        else float("nan")
                lo = float(self.bounds[i - 1]) if i else 0.0
                hi = float(self.bounds[i])
                frac = (target - acc) / c
                return lo + (hi - lo) * min(max(frac, 0.0), 1.0)
            acc += c
        return float(self.bounds[-1]) if self.bounds else float("nan")


_SERIES_CLS = {"counter": _CounterSeries, "gauge": _GaugeSeries,
               "histogram": _HistogramSeries}


class _Family:
    """One named metric: a set of series keyed by label values."""

    def __init__(self, name: str, kind: str, help: str, unit: str,
                 labelnames: Tuple[str, ...],
                 buckets: Optional[Tuple[float, ...]] = None):
        self.name = name
        self.kind = kind
        self.help = help
        self.unit = unit
        self.labelnames = labelnames
        self.buckets = tuple(sorted(buckets)) if buckets else None
        self._series: Dict[Tuple[str, ...], object] = {}
        self._lock = threading.Lock()
        if not labelnames:
            self._default = self._make()
            self._series[()] = self._default

    def _make(self):
        if self.kind == "histogram":
            return _HistogramSeries(self.buckets or DEFAULT_BUCKETS)
        return _SERIES_CLS[self.kind]()

    def labels(self, **kw):
        if set(kw) != set(self.labelnames):
            raise ValueError(
                f"{self.name}: got labels {sorted(kw)}, declared "
                f"{sorted(self.labelnames)}")
        key = tuple(str(kw[n]) for n in self.labelnames)
        series = self._series.get(key)
        if series is None:
            with self._lock:
                series = self._series.setdefault(key, self._make())
        return series

    def series(self) -> Iterable[Tuple[Tuple[str, ...], object]]:
        return list(self._series.items())

    # -- no-label families proxy their single series -------------------
    def inc(self, amount: float = 1.0) -> None:
        self._default.inc(amount)

    def dec(self, amount: float = 1.0) -> None:
        self._default.dec(amount)

    def set(self, value: float) -> None:
        self._default.set(value)

    def observe(self, value: float) -> None:
        self._default.observe(value)

    def quantile(self, q: float) -> float:
        return self._default.quantile(q)

    @property
    def value(self) -> float:
        return self._default.value

    @property
    def sum(self) -> float:
        return self._default.sum

    @property
    def count(self) -> int:
        return self._default.count

    @property
    def mean(self) -> float:
        return self._default.mean


Counter = Gauge = Histogram = _Family  # exported aliases for isinstance/docs


class MetricsRegistry:
    """Named metric families; see module docstring."""

    def __init__(self):
        self._families: Dict[str, _Family] = {}
        self._lock = threading.Lock()

    # -- registration --------------------------------------------------
    def _register(self, name: str, kind: str, help: str, unit: str,
                  labelnames, buckets=None) -> _Family:
        labelnames = tuple(labelnames or ())
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if fam.kind != kind or fam.labelnames != labelnames:
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{fam.kind}{fam.labelnames}, not "
                        f"{kind}{labelnames}")
                if (kind == "histogram" and buckets is not None
                        and tuple(sorted(buckets)) != fam.buckets):
                    # silently keeping the first buckets would bin the
                    # second caller's observations into bounds it never
                    # asked for — as loud as a kind mismatch
                    raise ValueError(
                        f"histogram {name!r} already registered with "
                        f"buckets {fam.buckets}, not "
                        f"{tuple(sorted(buckets))}")
                return fam
            fam = _Family(name, kind, help, unit, labelnames, buckets)
            self._families[name] = fam
            return fam

    def counter(self, name: str, help: str = "", unit: str = "",
                labelnames=()) -> _Family:
        return self._register(name, "counter", help, unit, labelnames)

    def gauge(self, name: str, help: str = "", unit: str = "",
              labelnames=()) -> _Family:
        return self._register(name, "gauge", help, unit, labelnames)

    def histogram(self, name: str, help: str = "", unit: str = "",
                  labelnames=(), buckets=DEFAULT_BUCKETS) -> _Family:
        return self._register(name, "histogram", help, unit, labelnames,
                              buckets)

    def get(self, name: str) -> Optional[_Family]:
        return self._families.get(name)

    def family_total(self, name: str) -> float:
        """Sum of every series of a (possibly labeled) family; 0.0 when
        the family doesn't exist (benches/gates summing labeled
        counters like the watchdog's per-program series)."""
        fam = self._families.get(name)
        if fam is None:
            return 0.0
        return sum(s.value for _, s in fam.series())

    def families(self) -> List[_Family]:
        return list(self._families.values())

    # -- exports -------------------------------------------------------
    def snapshot(self) -> dict:
        """JSON-serializable view of every series (round-trips through
        ``json.dumps``/``loads`` unchanged: plain dicts/lists/str/num)."""
        out = {}
        for fam in self.families():
            series = []
            for values, s in fam.series():
                entry = {"labels": dict(zip(fam.labelnames, values))}
                if fam.kind == "histogram":
                    entry["count"] = s.count
                    entry["sum"] = s.sum
                    entry["buckets"] = {
                        _format_value(b): c for b, c in
                        zip(list(s.bounds) + [_INF], s.bucket_counts)}
                else:
                    entry["value"] = s.value
                series.append(entry)
            out[fam.name] = {"type": fam.kind, "help": fam.help,
                             "unit": fam.unit, "series": series}
        return {"metrics": out}

    def render_prometheus(self) -> str:
        """Text exposition format 0.0.4 (the format Prometheus scrapes).

        Correctness contract (pinned by the round-trip parse test in
        tests/unit/telemetry/test_registry.py): ``# HELP``/``# TYPE``
        appear exactly once per family, immediately before its samples;
        HELP text escapes backslash and newline; label values escape
        backslash, quote, and newline."""
        lines: List[str] = []
        for fam in self.families():
            if fam.help:
                lines.append(f"# HELP {fam.name} {_escape_help(fam.help)}")
            lines.append(f"# TYPE {fam.name} {fam.kind}")
            for values, s in fam.series():
                label_s = _label_str(fam.labelnames, values)
                if fam.kind == "histogram":
                    acc = 0
                    for b, c in zip(list(s.bounds) + [_INF],
                                    s.bucket_counts):
                        acc += c  # exposition buckets are cumulative
                        le = f'le="{_format_value(b)}"'
                        lines.append(
                            f"{fam.name}_bucket"
                            f"{_label_str(fam.labelnames, values, le)}"
                            f" {acc}")
                    lines.append(f"{fam.name}_sum{label_s} "
                                 f"{_format_value(s.sum)}")
                    lines.append(f"{fam.name}_count{label_s} {s.count}")
                else:
                    lines.append(f"{fam.name}{label_s} "
                                 f"{_format_value(s.value)}")
        return "\n".join(lines) + "\n"

    def scalar_items(self) -> List[Tuple[str, float]]:
        """Flatten every series to (tag, value) pairs for scalar backends
        (the TelemetryBridge's feed). Histograms flatten to their
        ``_count``/``_sum``/``_mean``; labeled series append
        ``/key.value`` segments to the tag."""
        out: List[Tuple[str, float]] = []
        for fam in self.families():
            for values, s in fam.series():
                tag = fam.name
                if values:
                    tag += "/" + "/".join(
                        f"{n}.{v}" for n, v in zip(fam.labelnames, values))
                if fam.kind == "histogram":
                    if s.count:
                        out.append((tag + "_count", float(s.count)))
                        out.append((tag + "_sum", s.sum))
                        out.append((tag + "_mean", s.mean))
                else:
                    out.append((tag, float(s.value)))
        return out

    def reset(self) -> None:
        """Drop every family (tests / fresh serving epoch)."""
        with self._lock:
            self._families.clear()


_default_registry = MetricsRegistry()
_registry_lock = threading.Lock()


def get_registry() -> MetricsRegistry:
    """The process-local default registry every subsystem records into."""
    return _default_registry


def set_registry(registry: MetricsRegistry) -> MetricsRegistry:
    """Swap the process default (tests isolate with a fresh registry);
    returns the previous one."""
    global _default_registry
    with _registry_lock:
        prev = _default_registry
        _default_registry = registry
    return prev


@contextmanager
def scoped_registry(registry: MetricsRegistry) -> Iterator[MetricsRegistry]:
    """Make ``registry`` the process default for the enclosed block.

    Construction-time scoping for per-replica registries: serving
    components resolve (and cache) their series via ``get_registry()``
    when they are BUILT, so building a replica's serving stack inside
    this scope lands its metrics in the replica's own registry — the
    unit the router's /metrics federation labels. The swap is process-
    global, so scope construction, not steady-state traffic."""
    prev = set_registry(registry)
    try:
        yield registry
    finally:
        set_registry(prev)


def render_federated(sources: Iterable[Tuple[str, MetricsRegistry]],
                     label: str = "replica") -> str:
    """One Prometheus exposition over N registries, each source's series
    labeled ``{label}="<name>"`` — the router's federated /metrics view
    of its replica fleet (same text format contract as
    :meth:`MetricsRegistry.render_prometheus`: TYPE/HELP exactly once
    per family even when several sources register it).

    Sources listing the SAME registry object are deduplicated (shared-
    registry replicas are already covered by the first source naming
    it); families whose kind/labels disagree across sources keep the
    first definition and skip the conflicting series."""
    sources = list(sources)
    seen_regs: Dict[int, str] = {}
    merged: "Dict[str, Tuple[_Family, List[Tuple[str, _Family]]]]" = {}
    order: List[str] = []
    for src_name, reg in sources:
        if id(reg) in seen_regs:
            continue
        seen_regs[id(reg)] = src_name
        for fam in reg.families():
            if fam.name not in merged:
                merged[fam.name] = (fam, [])
                order.append(fam.name)
            first, members = merged[fam.name]
            if (fam.kind == first.kind
                    and fam.labelnames == first.labelnames):
                members.append((src_name, fam))
    lines: List[str] = []
    for name in order:
        first, members = merged[name]
        if first.help:
            lines.append(f"# HELP {name} {_escape_help(first.help)}")
        lines.append(f"# TYPE {name} {first.kind}")
        for src_name, fam in members:
            names = (label,) + fam.labelnames
            for values, s in fam.series():
                vals = (src_name,) + values
                label_s = _label_str(names, vals)
                if fam.kind == "histogram":
                    acc = 0
                    for b, c in zip(list(s.bounds) + [_INF],
                                    s.bucket_counts):
                        acc += c
                        le = f'le="{_format_value(b)}"'
                        lines.append(f"{name}_bucket"
                                     f"{_label_str(names, vals, le)}"
                                     f" {acc}")
                    lines.append(f"{name}_sum{label_s} "
                                 f"{_format_value(s.sum)}")
                    lines.append(f"{name}_count{label_s} {s.count}")
                else:
                    lines.append(f"{name}{label_s} "
                                 f"{_format_value(s.value)}")
    return "\n".join(lines) + "\n"
