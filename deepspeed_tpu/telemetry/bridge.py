"""Registry -> monitor bridge.

:class:`TelemetryBridge` flushes every scalar the registry holds
(counters, gauges, histogram count/sum/mean) into a ``MonitorMaster``
(TensorBoard/W&B/CSV backends) at a configurable step cadence — the
training-loop path from the unified registry to the experiment trackers
the reference wires ad hoc per metric (engine.py:2141 monitor writes).

The bridge writes only series that CHANGED since the last flush, so an
idle subsystem (e.g. inference metrics during training) adds no event
spam to the backends.

``close()`` is the final flush: a run ending mid-interval (engine
``destroy()``, serving drain) would otherwise silently drop every
metric recorded since the last cadence boundary.
"""

from typing import Dict, Optional

from .registry import MetricsRegistry, get_registry


class TelemetryBridge:
    def __init__(self, monitor, registry: Optional[MetricsRegistry] = None,
                 flush_interval: int = 1):
        """``monitor``: anything with ``write_events([(tag, value, step)])``
        and an ``enabled`` attribute (MonitorMaster). ``flush_interval``:
        flush every N ``step()`` calls (1 = every step)."""
        self.monitor = monitor
        self.registry = registry or get_registry()
        self.flush_interval = max(int(flush_interval), 1)
        self._calls = 0
        self._last: Dict[str, float] = {}
        self._last_step = 0
        self._closed = False

    @property
    def enabled(self) -> bool:
        return bool(getattr(self.monitor, "enabled", False))

    def step(self, step: int) -> bool:
        """Cadence-gated flush; returns True when a flush happened."""
        self._calls += 1
        self._last_step = int(step)
        if self._calls % self.flush_interval:
            return False
        return self.flush(step)

    def flush(self, step: int) -> bool:
        """Write every changed registry scalar as a (tag, value, step)
        event to the monitor backends."""
        self._last_step = int(step)
        if not self.enabled:
            return False
        events = []
        for tag, value in self.registry.scalar_items():
            if self._last.get(tag) != value:
                self._last[tag] = value
                events.append((tag, value, int(step)))
        if events:
            self.monitor.write_events(events)
        return bool(events)

    def close(self, step: Optional[int] = None) -> bool:
        """Final flush, ignoring the cadence: write whatever changed
        since the last flush interval (engine shutdown / serving drain
        would otherwise drop the tail). Idempotent — the first call
        flushes, later calls are no-ops. ``step`` defaults to the last
        step seen."""
        if self._closed:
            return False
        # mark closed only after a successful flush: a backend failure
        # (swallowed by the drain/destroy callers) must leave the final
        # flush retryable, or the tail metrics are permanently dropped
        out = self.flush(self._last_step if step is None else step)
        self._closed = True
        return out
