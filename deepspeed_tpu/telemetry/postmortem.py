"""Post-mortem bundles: one call writes everything a debugger needs.

A production incident's evidence is scattered across the registry, the
trace ring, the memory tables, the compiler fingerprint, the flight
recorder, and the anomaly ledger — and all of it is process-local, so
it dies with the process. :func:`write_bundle` snapshots the lot into a
dated directory::

    postmortems/postmortem-20260803-141523-nan_loss/
      manifest.json     # reason, time, versions, file index
      metrics.json      # registry snapshot() (every series)
      timeline.json     # Chrome-trace JSON of the span ring buffer
      memory.json       # memory.oom_report() (programs + buffers)
      fingerprint.json  # env_report.compiler_fingerprint()
      recorder.json     # last-N flight-recorder events
      anomalies.json    # recent anomaly verdicts

Surfaces: ``POST /debug/postmortem`` on the serving API, the training
engine's anomaly hook (``diagnostics.postmortem_on_anomaly``), and
:func:`install_crash_handler` — an unhandled-exception hook (bundle +
re-raise) plus an ``atexit`` pass that writes a bundle only when
anomalies were recorded and none was captured yet (a clean exit stays
silent).

Bundles are rate-limited (``diagnostics.postmortem_min_interval_s``):
an anomaly firing every step must not turn the disk into the hot path.
"""

import atexit
import json
import os
import sys
import threading
import time
from typing import Any, Dict, Optional

from ..utils.logging import logger
from . import anomaly as ds_anomaly
from . import memory as ds_memory
from . import recorder as ds_recorder
from . import timeline
from .anomaly import DiagnosticsConfig
from .registry import get_registry

_lock = threading.Lock()
_last_bundle_t = 0.0
_last_bundle_path: Optional[str] = None
_installed = False


def _dump(path: str, obj: Any) -> str:
    with open(path, "w") as fh:
        json.dump(obj, fh, indent=2, default=str)
    return os.path.basename(path)


def last_bundle() -> Optional[str]:
    """Path of the most recent bundle this process wrote (None yet)."""
    return _last_bundle_path


def write_bundle(reason: str = "manual",
                 config: Optional[DiagnosticsConfig] = None,
                 out_dir: Optional[str] = None,
                 extra: Optional[Dict[str, Any]] = None,
                 force: bool = True) -> Optional[str]:
    """Write one bundle; returns its directory path.

    ``force=False`` honors the rate limit
    (``postmortem_min_interval_s`` since the last bundle → returns the
    previous path instead of writing). Collection is best-effort per
    artifact: a failing section is recorded in the manifest, never an
    exception out of a crash handler."""
    global _last_bundle_t, _last_bundle_path
    cfg = config or DiagnosticsConfig()
    with _lock:
        now = time.time()
        if (not force and _last_bundle_path is not None
                and now - _last_bundle_t < cfg.postmortem_min_interval_s):
            return _last_bundle_path
        _last_bundle_t = now
    safe_reason = "".join(c if c.isalnum() or c in "-_" else "_"
                          for c in reason)[:48] or "manual"
    stamp = time.strftime("%Y%m%d-%H%M%S", time.localtime(now))
    root = out_dir or cfg.postmortem_dir
    path = os.path.join(root, f"postmortem-{stamp}-{safe_reason}")
    suffix = 1
    while os.path.exists(path):   # several bundles in one second
        suffix += 1
        path = os.path.join(root,
                            f"postmortem-{stamp}-{safe_reason}-{suffix}")
    os.makedirs(path, exist_ok=True)

    files: Dict[str, str] = {}
    errors: Dict[str, str] = {}

    def section(name: str, fn):
        try:
            files[name] = _dump(os.path.join(path, f"{name}.json"), fn())
        except Exception as e:   # pragma: no cover - defensive
            errors[name] = f"{type(e).__name__}: {e}"

    section("metrics", lambda: get_registry().snapshot())
    section("timeline", lambda: timeline.to_chrome_trace())
    section("memory", lambda: ds_memory.oom_report())
    section("recorder", lambda: {
        "stats": ds_recorder.get_recorder().stats(),
        "events": ds_recorder.get_recorder().events(
            last=cfg.postmortem_last_events)})
    section("anomalies", lambda: ds_anomaly.recent())

    def fingerprint():
        from ..env_report import compiler_fingerprint
        return compiler_fingerprint()
    section("fingerprint", fingerprint)

    manifest: Dict[str, Any] = {
        "reason": reason, "written_at": now,
        "written_at_iso": time.strftime("%Y-%m-%dT%H:%M:%S",
                                        time.localtime(now)),
        "pid": os.getpid(), "files": files,
    }
    if extra:
        manifest["extra"] = extra
    if errors:
        manifest["collection_errors"] = errors
    _dump(os.path.join(path, "manifest.json"), manifest)
    with _lock:
        _last_bundle_path = path
    logger.warning(f"post-mortem bundle written: {path} (reason={reason})")
    return path


def maybe_write_bundle(reason: str,
                       config: Optional[DiagnosticsConfig] = None,
                       **kw) -> Optional[str]:
    """Rate-limited :func:`write_bundle` (the anomaly-hook entry)."""
    return write_bundle(reason, config=config, force=False, **kw)


def install_crash_handler(config: Optional[DiagnosticsConfig] = None,
                          out_dir: Optional[str] = None) -> bool:
    """Install the unhandled-exception and atexit bundle hooks
    (idempotent; returns True the first time). The excepthook chains to
    the previous one — the traceback still prints."""
    global _installed
    with _lock:
        if _installed:
            return False
        _installed = True
    cfg = config or DiagnosticsConfig()
    prev_hook = sys.excepthook

    def _hook(exc_type, exc, tb):
        try:
            write_bundle(f"unhandled_{exc_type.__name__}", config=cfg,
                         out_dir=out_dir,
                         extra={"exception": repr(exc)})
        except Exception:   # the handler must never mask the crash
            pass
        prev_hook(exc_type, exc, tb)

    sys.excepthook = _hook

    def _at_exit():
        # a clean exit writes nothing; an exit after anomalies with no
        # bundle captured yet is the black box's last chance
        try:
            if ds_anomaly.recent() and last_bundle() is None:
                write_bundle("atexit_with_anomalies", config=cfg,
                             out_dir=out_dir)
        except Exception:
            pass

    atexit.register(_at_exit)
    return True


def _reset_for_tests() -> None:
    """Drop the rate-limit/bundle-path state (test isolation only)."""
    global _last_bundle_t, _last_bundle_path
    with _lock:
        _last_bundle_t = 0.0
        _last_bundle_path = None
