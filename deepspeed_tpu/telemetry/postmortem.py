"""Post-mortem bundles: one call writes everything a debugger needs.

A production incident's evidence is scattered across the registry, the
trace ring, the memory tables, the compiler fingerprint, the flight
recorder, and the anomaly ledger — and all of it is process-local, so
it dies with the process. :func:`write_bundle` snapshots the lot into a
dated directory::

    postmortems/postmortem-20260803-141523-nan_loss/
      manifest.json     # reason, time, versions, file index
      metrics.json      # registry snapshot() (every series)
      timeline.json     # Chrome-trace JSON of the span ring buffer
      memory.json       # memory.oom_report() (programs + buffers)
      fingerprint.json  # env_report.compiler_fingerprint()
      recorder.json     # last-N flight-recorder events
      anomalies.json    # recent anomaly verdicts

Surfaces: ``POST /debug/postmortem`` on the serving API, the training
engine's anomaly hook (``diagnostics.postmortem_on_anomaly``), and
:func:`install_crash_handler` — an unhandled-exception hook (bundle +
re-raise) plus an ``atexit`` pass that writes a bundle only when
anomalies were recorded and none was captured yet (a clean exit stays
silent).

Bundles are rate-limited (``diagnostics.postmortem_min_interval_s``)
PER REASON KIND: an anomaly firing every step must not turn the disk
into the hot path, but a chatty ``slo_burn`` must also never suppress
the bundle for a subsequent ``nan_loss`` or ``stall`` verdict — each
kind owns its own interval.

Fleet bundles (:func:`write_fleet_bundle`): a routed deployment's
incident evidence spans the router and every replica. The router
collects one dated ``fleet-*`` bundle — its own routing state, the
shared process artifacts, and a per-replica section (metrics from the
replica's registry, the replica's lane of the trace ring) — under one
cross-replica manifest (docs/SERVING.md § Post-mortem bundles).
"""

import atexit
import json
import os
import sys
import threading
import time
from typing import Any, Dict, Optional

from ..utils.logging import logger
from . import anomaly as ds_anomaly
from . import memory as ds_memory
from . import recorder as ds_recorder
from . import timeline
from .anomaly import DiagnosticsConfig
from .registry import get_registry

_lock = threading.Lock()
# rate-limit clocks keyed per reason kind (satellite fix: one chatty
# kind must not suppress bundles for the others inside its window)
_last_bundle_t: Dict[str, float] = {}
_last_bundle_path_by_kind: Dict[str, str] = {}
_last_bundle_path: Optional[str] = None
_installed = False


def _dump(path: str, obj: Any) -> str:
    with open(path, "w") as fh:
        json.dump(obj, fh, indent=2, default=str)
    return os.path.basename(path)


def last_bundle() -> Optional[str]:
    """Path of the most recent bundle this process wrote (None yet)."""
    return _last_bundle_path


# -- shared bundle scaffolding ---------------------------------------------
def _check_rate_limit(kind: str, cfg: DiagnosticsConfig, force: bool):
    """(now, prev_path): ``prev_path`` is non-None when ``kind`` is
    inside its rate window and the caller must return it unwritten."""
    with _lock:
        now = time.time()
        prev = _last_bundle_path_by_kind.get(kind)
        if (not force and prev is not None
                and now - _last_bundle_t.get(kind, 0.0)
                < cfg.postmortem_min_interval_s):
            return now, prev
        _last_bundle_t[kind] = now
    return now, None


def _bundle_dir(prefix: str, reason: str, now: float, root: str) -> str:
    """Create and return the dated, reason-sanitized, collision-suffixed
    bundle directory."""
    safe_reason = "".join(c if c.isalnum() or c in "-_" else "_"
                          for c in reason)[:48] or "manual"
    stamp = time.strftime("%Y%m%d-%H%M%S", time.localtime(now))
    path = os.path.join(root, f"{prefix}-{stamp}-{safe_reason}")
    suffix = 1
    while os.path.exists(path):   # several bundles in one second
        suffix += 1
        path = os.path.join(root,
                            f"{prefix}-{stamp}-{safe_reason}-{suffix}")
    os.makedirs(path, exist_ok=True)
    return path


def _section_writer(path: str):
    """(section, files, errors): ``section(name, fn, sub=None)`` dumps
    ``fn()`` to ``[sub/]name.json`` best-effort — a failing artifact is
    recorded in ``errors``, never raised out of a crash handler."""
    files: Dict[str, str] = {}
    errors: Dict[str, str] = {}

    def section(name: str, fn, sub: Optional[str] = None) -> None:
        key = f"{sub}/{name}" if sub else name
        try:
            d = os.path.join(path, sub) if sub else path
            os.makedirs(d, exist_ok=True)
            rel = _dump(os.path.join(d, f"{name}.json"), fn())
            files[key] = os.path.join(sub, rel) if sub else rel
        except Exception as e:   # pragma: no cover - defensive
            errors[key] = f"{type(e).__name__}: {e}"

    return section, files, errors


def _finish_bundle(path: str, kind: str, manifest: Dict[str, Any],
                   extra: Optional[Dict[str, Any]],
                   errors: Dict[str, str]) -> None:
    """Write the manifest and publish the path under ``kind``'s
    rate-limit clock."""
    global _last_bundle_path
    if extra:
        manifest["extra"] = extra
    if errors:
        manifest["collection_errors"] = errors
    _dump(os.path.join(path, "manifest.json"), manifest)
    with _lock:
        _last_bundle_path = path
        _last_bundle_path_by_kind[kind] = path


def write_bundle(reason: str = "manual",
                 config: Optional[DiagnosticsConfig] = None,
                 out_dir: Optional[str] = None,
                 extra: Optional[Dict[str, Any]] = None,
                 force: bool = True) -> Optional[str]:
    """Write one bundle; returns its directory path.

    ``force=False`` honors the rate limit
    (``postmortem_min_interval_s`` since the last bundle OF THIS REASON
    KIND → returns that kind's previous path instead of writing; a
    different kind inside the window still writes). Collection is
    best-effort per artifact: a failing section is recorded in the
    manifest, never an exception out of a crash handler."""
    cfg = config or DiagnosticsConfig()
    now, prev = _check_rate_limit(reason, cfg, force)
    if prev is not None:
        return prev
    path = _bundle_dir("postmortem", reason, now,
                       out_dir or cfg.postmortem_dir)
    section, files, errors = _section_writer(path)

    section("metrics", lambda: get_registry().snapshot())
    section("timeline", lambda: timeline.to_chrome_trace())
    section("memory", lambda: ds_memory.oom_report())
    section("recorder", lambda: {
        "stats": ds_recorder.get_recorder().stats(),
        "events": ds_recorder.get_recorder().events(
            last=cfg.postmortem_last_events)})
    section("anomalies", lambda: ds_anomaly.recent())

    def fingerprint():
        from ..env_report import compiler_fingerprint
        return compiler_fingerprint()
    section("fingerprint", fingerprint)

    manifest: Dict[str, Any] = {
        "reason": reason, "written_at": now,
        "written_at_iso": time.strftime("%Y-%m-%dT%H:%M:%S",
                                        time.localtime(now)),
        "pid": os.getpid(), "files": files,
    }
    _finish_bundle(path, reason, manifest, extra, errors)
    logger.warning(f"post-mortem bundle written: {path} (reason={reason})")
    return path


def maybe_write_bundle(reason: str,
                       config: Optional[DiagnosticsConfig] = None,
                       **kw) -> Optional[str]:
    """Rate-limited :func:`write_bundle` (the anomaly-hook entry)."""
    return write_bundle(reason, config=config, force=False, **kw)


def write_fleet_bundle(reason: str, router,
                       config: Optional[DiagnosticsConfig] = None,
                       out_dir: Optional[str] = None,
                       extra: Optional[Dict[str, Any]] = None,
                       force: bool = True) -> Optional[str]:
    """One dated ``fleet-*`` bundle for a routed deployment: the
    router's routing state, the shared process artifacts, and a section
    per replica, under one cross-replica manifest.

    ``router`` is duck-typed (the :class:`~...serve.router.ReplicaRouter`
    surface: ``replicas``, ``health()``, ``router_statusz()``,
    ``replica_statusz()``, optional per-replica ``registry``). Layout::

        fleet-20260803-141523-stall/
          manifest.json        # reason, replica roster + states, index
          router.json          # health + routing + per-replica rollups
          metrics.json         # process-default registry snapshot
          timeline.json        # stitched fleet trace (all lanes)
          recorder.json        # last-N flight-recorder events
          anomalies.json       # recent verdicts
          fingerprint.json     # compiler fingerprint
          <replica>/metrics.json   # the replica's own registry (when
                                   # it has one) — federation unit
          <replica>/timeline.json  # the replica's lane of the trace

    Same per-kind rate limit as single-process bundles (``force=False``
    defers to the last fleet bundle of this reason kind; the ``fleet:``
    key prefix keeps fleet and single-process windows distinct)."""
    cfg = config or DiagnosticsConfig()
    kind = f"fleet:{reason}"
    now, prev = _check_rate_limit(kind, cfg, force)
    if prev is not None:
        return prev
    path = _bundle_dir("fleet", reason, now, out_dir or cfg.postmortem_dir)
    section, files, errors = _section_writer(path)

    section("router", lambda: {"health": router.health(),
                               "routing": router.router_statusz(),
                               "replicas": router.replica_statusz()})
    section("metrics", lambda: get_registry().snapshot())
    section("timeline", lambda: timeline.stitch_fleet())
    section("recorder", lambda: {
        "stats": ds_recorder.get_recorder().stats(),
        "events": ds_recorder.get_recorder().events(
            last=cfg.postmortem_last_events)})
    section("anomalies", lambda: ds_anomaly.recent())

    def fingerprint():
        from ..env_report import compiler_fingerprint
        return compiler_fingerprint()
    section("fingerprint", fingerprint)

    from . import trace as ds_trace
    spans = ds_trace.export()
    roster: Dict[str, Any] = {}
    default_reg = get_registry()
    for replica in getattr(router, "replicas", ()):
        name = replica.name
        roster[name] = {"state": replica.state}
        reg = getattr(replica, "registry", None)
        if reg is not None and reg is not default_reg:
            section("metrics", reg.snapshot, sub=name)
        section("timeline",
                lambda nm=name: timeline.stitch_fleet(
                    {nm: [s for s in spans if s.get("lane") == nm]}),
                sub=name)

    manifest: Dict[str, Any] = {
        "reason": reason, "kind": "fleet", "written_at": now,
        "written_at_iso": time.strftime("%Y-%m-%dT%H:%M:%S",
                                        time.localtime(now)),
        "pid": os.getpid(), "replicas": roster, "files": files,
    }
    _finish_bundle(path, kind, manifest, extra, errors)
    logger.warning(
        f"fleet post-mortem bundle written: {path} (reason={reason}, "
        f"{len(roster)} replica(s))")
    return path


def maybe_write_fleet_bundle(reason: str, router,
                             config: Optional[DiagnosticsConfig] = None,
                             **kw) -> Optional[str]:
    """Rate-limited :func:`write_fleet_bundle` (the router's anomaly
    trigger entry)."""
    return write_fleet_bundle(reason, router, config=config, force=False,
                              **kw)


def install_crash_handler(config: Optional[DiagnosticsConfig] = None,
                          out_dir: Optional[str] = None) -> bool:
    """Install the unhandled-exception and atexit bundle hooks
    (idempotent; returns True the first time). The excepthook chains to
    the previous one — the traceback still prints."""
    global _installed
    with _lock:
        if _installed:
            return False
        _installed = True
    cfg = config or DiagnosticsConfig()
    prev_hook = sys.excepthook

    def _hook(exc_type, exc, tb):
        try:
            write_bundle(f"unhandled_{exc_type.__name__}", config=cfg,
                         out_dir=out_dir,
                         extra={"exception": repr(exc)})
        except Exception:   # the handler must never mask the crash
            pass
        prev_hook(exc_type, exc, tb)

    sys.excepthook = _hook

    def _at_exit():
        # a clean exit writes nothing; an exit after anomalies with no
        # bundle captured yet is the black box's last chance
        try:
            if ds_anomaly.recent() and last_bundle() is None:
                write_bundle("atexit_with_anomalies", config=cfg,
                             out_dir=out_dir)
        except Exception:
            pass

    atexit.register(_at_exit)
    return True


def _reset_for_tests() -> None:
    """Drop the rate-limit/bundle-path state (test isolation only)."""
    global _last_bundle_path
    with _lock:
        _last_bundle_t.clear()
        _last_bundle_path_by_kind.clear()
        _last_bundle_path = None
