"""Unified telemetry: metrics registry + span tracing.

One process-local :class:`MetricsRegistry` (labeled counters, gauges,
fixed-bucket histograms) feeds every consumer from the same series:

  * ``render_prometheus()`` — Prometheus text exposition a serving
    deployment scrapes,
  * ``snapshot()`` — machine-readable JSON snapshot (benchmarks,
    dashboards, tests),
  * :class:`TelemetryBridge` — periodic flush of registry scalars into
    the ``MonitorMaster`` backends (TensorBoard/W&B/CSV).

Span tracing (``with trace.span("decode_step"):``) records wall-clock
spans into a ring buffer and can mirror them into ``jax.profiler`` trace
annotations (see :mod:`deepspeed_tpu.telemetry.trace`).

Both stacks are instrumented: the training engine (step/loss/grad-norm/
loss-scale + comms bytes) and inference v2 (TTFT, decode tokens/s, queue
depth, KV-pool utilization, preemptions, prefix-cache hits, speculative
accepts). See docs/TELEMETRY.md for the metrics catalog.
"""

from .registry import (Counter, Gauge, Histogram, MetricsRegistry,
                       get_registry, render_federated, scoped_registry,
                       set_registry)
from .bridge import TelemetryBridge
from . import anomaly, context, memory, postmortem, recorder, timeline, \
    trace, watchdog
from .anomaly import DiagnosticsConfig
from .context import TraceContext
from .recorder import FlightRecorder, get_recorder, set_recorder

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry",
    "get_registry", "set_registry", "scoped_registry",
    "render_federated", "TelemetryBridge", "trace", "timeline",
    "watchdog", "memory", "recorder", "anomaly", "postmortem", "context",
    "TraceContext", "DiagnosticsConfig", "FlightRecorder",
    "get_recorder", "set_recorder",
]
