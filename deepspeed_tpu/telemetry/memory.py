"""Device-memory accounting from XLA's own numbers.

XLA already knows every program's device footprint
(``compiled.memory_analysis()``: argument / output / temp / code bytes)
— this module publishes it, chip-free, as registry gauges plus a
one-call OOM-forensics report, instead of leaving it buried in
``benchmarks/aot_scale.py``.

Two record kinds:

  * **programs** — :func:`record_memory_analysis` extracts an
    AOT-compiled program's memory stats (and its cost analysis: flops /
    bytes accessed, the MFU inputs) into
    ``xla_program_{peak,argument,temp,output}_bytes{program=...}``
    gauges. ``runtime.engine.lower_train_step`` records the train step;
    ``InferenceEngineV2.memory_report()`` AOT-lowers the decode/prefill
    programs at representative bucket shapes (no chip needed — the
    compiler runs on the host).
  * **buffers** — :func:`record_buffer` publishes long-lived allocations
    the programs reference (KV pool, weights, optimizer state) as
    ``device_buffer_bytes{buffer=...}``.

:func:`oom_report` ranks both and names the largest — the first thing to
read after a RESOURCE_EXHAUSTED (docs/PROFILING.md, "Triaging OOMs").
"""

import threading
from typing import Any, Dict, Optional

from .registry import get_registry

_lock = threading.Lock()
_programs: Dict[str, Dict[str, Any]] = {}
_buffers: Dict[str, int] = {}

_MEM_FIELDS = ("argument_size_in_bytes", "output_size_in_bytes",
               "temp_size_in_bytes", "alias_size_in_bytes",
               "generated_code_size_in_bytes")


def _gauges():
    reg = get_registry()
    return {
        "peak": reg.gauge("xla_program_peak_bytes",
                          "arguments + temps + code of a compiled "
                          "program (donated inputs alias outputs)",
                          unit="bytes", labelnames=("program",)),
        "argument": reg.gauge("xla_program_argument_bytes",
                              "argument bytes of a compiled program",
                              unit="bytes", labelnames=("program",)),
        "temp": reg.gauge("xla_program_temp_bytes",
                          "temp/scratch bytes of a compiled program",
                          unit="bytes", labelnames=("program",)),
        "output": reg.gauge("xla_program_output_bytes",
                            "output bytes of a compiled program",
                            unit="bytes", labelnames=("program",)),
    }


def cost_analysis_dict(compiled) -> Dict[str, float]:
    """``compiled.cost_analysis()`` normalized to a plain dict (older
    jax returns ``[dict]``) — the ONE copy of this shim; bench.py and
    the perf gate share it."""
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    return dict(ca or {})


def record_memory_analysis(program: str, compiled) -> Dict[str, Any]:
    """Extract ``compiled.memory_analysis()`` (+ ``cost_analysis()``)
    into gauges and the program table; returns the record."""
    ma = compiled.memory_analysis()
    rec: Dict[str, Any] = {k: int(getattr(ma, k)) for k in _MEM_FIELDS
                           if hasattr(ma, k)}
    # donated inputs alias outputs, so peak live state is args + temps
    # (+ the program text itself) — the aot_scale.py convention
    rec["peak_bytes"] = (rec.get("argument_size_in_bytes", 0)
                         + rec.get("temp_size_in_bytes", 0)
                         + rec.get("generated_code_size_in_bytes", 0))
    try:
        ca = cost_analysis_dict(compiled)
        rec["flops"] = float(ca.get("flops", 0.0))
        rec["bytes_accessed"] = float(ca.get("bytes accessed", 0.0))
    except Exception:  # cost analysis is a bonus, never a blocker
        pass
    g = _gauges()
    g["peak"].labels(program=program).set(rec["peak_bytes"])
    g["argument"].labels(program=program).set(
        rec.get("argument_size_in_bytes", 0))
    g["temp"].labels(program=program).set(rec.get("temp_size_in_bytes", 0))
    g["output"].labels(program=program).set(
        rec.get("output_size_in_bytes", 0))
    with _lock:
        _programs[program] = dict(rec)
    return rec


def tree_bytes(tree) -> int:
    """Total bytes of a pytree of arrays (KV cache, params, opt state)."""
    import jax
    total = 0
    for leaf in jax.tree.leaves(tree):
        nbytes = getattr(leaf, "nbytes", None)
        if nbytes is None and hasattr(leaf, "shape"):
            import numpy as np
            nbytes = int(np.prod(leaf.shape)) * leaf.dtype.itemsize
        total += int(nbytes or 0)
    return total


def record_buffer(name: str, nbytes: int) -> None:
    """Publish a long-lived device allocation (KV pool, weights, ...)."""
    get_registry().gauge(
        "device_buffer_bytes",
        "long-lived device allocations (KV pool, weights, optimizer "
        "state)", unit="bytes", labelnames=("buffer",)).labels(
        buffer=name).set(int(nbytes))
    with _lock:
        _buffers[name] = int(nbytes)


def programs() -> Dict[str, Dict[str, Any]]:
    with _lock:
        return {k: dict(v) for k, v in _programs.items()}


def buffers() -> Dict[str, int]:
    with _lock:
        return dict(_buffers)


def reset() -> None:
    with _lock:
        _programs.clear()
        _buffers.clear()


def oom_report(top: int = 5) -> Dict[str, Any]:
    """One-call OOM forensics: programs by peak bytes and buffers by
    size, largest first, plus the headline culprit."""
    all_buffers = buffers()
    progs = sorted(
        ({"program": name, **rec} for name, rec in programs().items()),
        key=lambda r: -r.get("peak_bytes", 0))[:top]
    bufs = sorted(({"buffer": name, "bytes": b}
                   for name, b in all_buffers.items()),
                  key=lambda r: -r["bytes"])[:top]
    rep: Dict[str, Any] = {
        "programs": progs,
        "buffers": bufs,
        # the total covers EVERY recorded buffer, not just the top-N
        # shown — a truncated "total" would mislead the OOM triage
        "total_buffer_bytes": sum(all_buffers.values()),
    }
    if progs:
        rep["largest_program"] = progs[0]["program"]
        rep["largest_program_peak_bytes"] = progs[0].get("peak_bytes", 0)
    if bufs:
        rep["largest_buffer"] = bufs[0]["buffer"]
        rep["largest_buffer_bytes"] = bufs[0]["bytes"]
    return rep


def format_oom_report(rep: Optional[Dict[str, Any]] = None) -> str:
    """Human-readable :func:`oom_report` (what to paste into an OOM
    issue)."""
    rep = rep or oom_report()
    lines = ["device-memory forensics (largest first):", "  programs:"]
    for p in rep["programs"]:
        lines.append(
            f"    {p['program']:<24} peak={p.get('peak_bytes', 0) / 2**20:8.1f} MiB "
            f"(args={p.get('argument_size_in_bytes', 0) / 2**20:.1f} "
            f"temps={p.get('temp_size_in_bytes', 0) / 2**20:.1f})")
    lines.append("  buffers:")
    for b in rep["buffers"]:
        lines.append(f"    {b['buffer']:<24} {b['bytes'] / 2**20:8.1f} MiB")
    if not rep["programs"] and not rep["buffers"]:
        lines.append("    (nothing recorded yet — run memory_report() "
                     "or lower_train_step first)")
    return "\n".join(lines)
