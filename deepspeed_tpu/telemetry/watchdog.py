"""Recompile watchdog: every jit compile point, counted and attributed.

On TPU a silent XLA recompile is a multi-second stall that looks like a
latency spike; the bucketing layers (engine_v2's power-of-two decode
buckets, the scheduler's chunk-aligned prefill sizes) exist precisely so
steady-state serving never retraces. This module makes that property
observable and enforceable:

  * :func:`watch` wraps a jitted callable in a proxy that detects cache
    growth (``fn._cache_size()`` delta around each call), recording the
    program name, the argument shape signature (the bucket key), and the
    compile wall time into registry counters.
  * :func:`mark_steady` flips the process into steady-state mode — from
    then on ANY compile increments
    ``xla_steady_state_recompiles_total`` and logs a warning naming the
    program and the shapes that triggered it. Benches call it after
    their warmup pass; serving can call it once traffic is warm.
  * :func:`record_compile` covers explicit compile points that don't go
    through a jit call (``engine.lower_train_step`` AOT compiles).

Compile wall time comes from jax.monitoring's
``backend_compile_duration`` events accumulated on the calling thread
(compiles run synchronously on it); when the event doesn't fire (e.g. a
persistent-cache hit still traces and loads) the call's wall time is
recorded as an upper bound.

Registry series (docs/TELEMETRY.md): ``xla_compile_events_total``,
``xla_compile_seconds_total``, ``xla_steady_state_recompiles_total``
(all labeled by ``program``) and the ``xla_compiled_programs`` gauge
(live jit-cache size per program).
"""

import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..utils.logging import logger
from .registry import get_registry

_EVENT_CAPACITY = 256

_lock = threading.Lock()
_events: deque = deque(maxlen=_EVENT_CAPACITY)
_steady = False
_listener_installed = False
_tls = threading.local()


def _install_listener() -> None:
    """Accumulate jax backend-compile durations per thread (idempotent;
    jax.monitoring listeners cannot be unregistered individually, so one
    process-lifetime hook serves every watched function)."""
    global _listener_installed
    if _listener_installed:
        return
    with _lock:
        if _listener_installed:
            return
        try:
            import jax.monitoring

            def _on_duration(name: str, dur: float, **kw) -> None:
                if name.endswith("backend_compile_duration"):
                    _tls.compile_s = getattr(_tls, "compile_s", 0.0) + dur

            jax.monitoring.register_event_duration_secs_listener(
                _on_duration)
        except Exception:  # no jax / API drift: wall-time fallback only
            pass
        _listener_installed = True


def _metrics():
    reg = get_registry()
    return (
        reg.counter("xla_compile_events_total",
                    "XLA program compiles observed by the watchdog",
                    labelnames=("program",)),
        reg.counter("xla_compile_seconds_total",
                    "wall time spent compiling, per program", unit="s",
                    labelnames=("program",)),
        reg.counter("xla_steady_state_recompiles_total",
                    "compiles AFTER mark_steady() — a supposedly-bucketed "
                    "path retraced at steady state",
                    labelnames=("program",)),
        reg.gauge("xla_compiled_programs",
                  "live jit-cache entries per watched program",
                  labelnames=("program",)),
    )


def mark_steady(on: bool = True) -> None:
    """Enter (or leave) steady-state mode: further compiles are counted
    as recompile violations and logged."""
    global _steady
    _steady = on


def is_steady() -> bool:
    return _steady


def reset() -> None:
    """Drop the event log and leave steady-state mode (tests/benches)."""
    global _steady
    _steady = False
    with _lock:
        _events.clear()


def _signature(args: tuple, kwargs: dict) -> Tuple:
    """Shape/dtype signature of the array arguments — the bucket key a
    compile was keyed on."""
    try:
        import jax
        leaves = jax.tree.leaves((args, kwargs))
    except Exception:
        leaves = list(args) + list(kwargs.values())
    return tuple((tuple(x.shape), str(x.dtype)) for x in leaves
                 if hasattr(x, "shape") and hasattr(x, "dtype"))


def record_compile(program: str, seconds: float,
                   signature: Optional[Tuple] = None,
                   cached_programs: Optional[int] = None,
                   analysis: bool = False) -> None:
    """Record one observed compile of ``program`` (counters + event log;
    warns when it happened at steady state). ``analysis=True`` marks a
    deliberate AOT analysis compile (``lower_train_step``,
    ``memory_report``): counted in the compile totals but never a
    steady-state violation — it is not a hot path retracing."""
    ev_total, sec_total, steady_total, progs = _metrics()
    ev_total.labels(program=program).inc()
    sec_total.labels(program=program).inc(max(float(seconds), 0.0))
    if cached_programs is not None:
        progs.labels(program=program).set(cached_programs)
    rec = {"program": program, "seconds": float(seconds),
           "signature": signature, "steady_state": _steady and not analysis,
           "time": time.time()}
    with _lock:
        _events.append(rec)
    # mirror into the flight recorder: a compile near an incident is a
    # prime suspect, and the black box should hold it without anyone
    # having to correlate the watchdog's own deque after the fact
    from . import recorder as ds_recorder
    ds_recorder.record(
        "xla_compile", program=program, seconds=round(float(seconds), 4),
        signature=repr(signature) if signature else None,
        steady_state=_steady and not analysis, analysis=analysis)
    if _steady and not analysis:
        steady_total.labels(program=program).inc()
        logger.warning(
            f"steady-state recompile: program={program!r} took "
            f"{seconds * 1e3:.1f}ms for shapes {signature} — a bucketed "
            f"path retraced after warmup (check bucket keys / weak types)")


def events() -> List[Dict[str, Any]]:
    """The recent compile events (oldest first, bounded)."""
    with _lock:
        return list(_events)


def summary() -> Dict[str, Dict[str, float]]:
    """Per-program rollup: {program: {compiles, seconds,
    steady_state_recompiles}}. Built from the registry counters — the
    authoritative totals — not the bounded event log, so a long-lived
    server's /statusz matches /metrics even after the deque wraps."""
    reg = get_registry()
    out: Dict[str, Dict[str, float]] = {}
    for metric, key in (
            ("xla_compile_events_total", "compiles"),
            ("xla_compile_seconds_total", "seconds"),
            ("xla_steady_state_recompiles_total",
             "steady_state_recompiles")):
        fam = reg.get(metric)
        if fam is None:
            continue
        for values, s in fam.series():
            prog = values[0] if values else ""
            out.setdefault(prog, {"compiles": 0, "seconds": 0.0,
                                  "steady_state_recompiles": 0})[key] = \
                s.value
    return out


class WatchedFunction:
    """Transparent proxy over a jitted callable: forwards calls and
    attribute access (``.lower``, ``._cache_size`` keep working),
    recording a compile event whenever the jit cache grows."""

    def __init__(self, program: str, fn: Callable):
        self.program = program
        self._fn = fn
        _install_listener()

    def __call__(self, *args, **kwargs):
        fn = self._fn
        try:
            before = fn._cache_size()
        except Exception:
            before = None
        _tls.compile_s = 0.0
        t0 = time.perf_counter()
        out = fn(*args, **kwargs)
        if before is not None:
            try:
                after = fn._cache_size()
            except Exception:
                after = before
            if after > before:
                compile_s = getattr(_tls, "compile_s", 0.0)
                record_compile(
                    self.program,
                    compile_s if compile_s > 0
                    else time.perf_counter() - t0,
                    signature=_signature(args, kwargs),
                    cached_programs=after)
        return out

    def __getattr__(self, name):
        return getattr(self._fn, name)

    def __repr__(self) -> str:
        return f"WatchedFunction({self.program!r}, {self._fn!r})"


def watch(program: str, fn: Callable) -> WatchedFunction:
    """Wrap ``fn`` (typically ``jax.jit(...)``) so its compiles are
    counted under ``program``. Idempotent on already-watched functions."""
    if isinstance(fn, WatchedFunction):
        return fn
    return WatchedFunction(program, fn)
