"""Lightweight span tracing with a ring-buffer exporter.

``with trace.span("decode_step"):`` records (name, start, duration,
depth) into a bounded deque — overhead is two ``perf_counter`` calls and
one locked append, so the serving hot path can stay instrumented in
production.
``export()`` drains a copy for offline analysis; ``durations(name)``
feeds assertions and benchmarks.

Every span also carries a process-unique ``id``, the ``parent`` id of
the enclosing span (None at top level), and a ``track`` — by default the
recording thread's name, overridable with :func:`set_track` — so the
ring buffer reconstructs into per-thread timelines
(:mod:`deepspeed_tpu.telemetry.timeline` exports them as Chrome trace
events). :func:`record` appends a RETROACTIVE span from saved
timestamps (e.g. a request's queue wait, measured between two scheduler
events rather than around a ``with`` block).

Fleet serving adds a second grouping axis: the ``lane`` — which serving
REPLICA (or the router) recorded the span. In-process replicas share
this one ring buffer, so each replica's loop thread names its lane once
(:func:`set_lane`; the router passes ``lane=`` explicitly) and the
fleet timeline export groups lanes into per-replica process rows —
exactly the shape N remote rings would stitch into. Spans without a
lane belong to no replica (single-engine serving, training).

``enable_xla_annotations(True)`` mirrors every span into a
``jax.profiler.TraceAnnotation`` so spans line up with device activity
in a TensorBoard/XProf trace captured via
``deepspeed_tpu.utils.xla_profile.capture_trace`` (the hook is optional:
absent/failed jax.profiler leaves spans host-only).
"""

import itertools
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Dict, List, Optional

_DEFAULT_CAPACITY = 4096

_lock = threading.Lock()
_buffer: deque = deque(maxlen=_DEFAULT_CAPACITY)
_xla_annotations = False
_local = threading.local()
_ids = itertools.count(1)


def enable_xla_annotations(on: bool = True) -> None:
    """Mirror spans into jax.profiler trace annotations (see module
    docstring)."""
    global _xla_annotations
    _xla_annotations = on


def set_capacity(capacity: int) -> None:
    """Resize the ring buffer (drops recorded spans)."""
    global _buffer
    with _lock:
        _buffer = deque(maxlen=int(capacity))


def set_track(name: Optional[str]) -> None:
    """Name this thread's timeline track (None restores the default —
    the thread's own name). Tracks map to rows in the Chrome trace
    export."""
    _local.track = name


def current_track() -> str:
    track = getattr(_local, "track", None)
    return track if track is not None else threading.current_thread().name


def set_lane(name: Optional[str]) -> None:
    """Name this thread's fleet lane (the replica whose spans it
    records; None clears it). Lanes map to process rows in the stitched
    fleet timeline (:func:`timeline.stitch_fleet`)."""
    _local.lane = name


def current_lane() -> Optional[str]:
    return getattr(_local, "lane", None)


@contextmanager
def span(name: str, lane: Optional[str] = None, **attrs):
    """Record a wall-clock span; nests (depth reflects enclosing spans).
    ``lane`` overrides the thread's fleet lane for this span."""
    depth = getattr(_local, "depth", 0)
    parent = getattr(_local, "span_id", None)
    span_id = next(_ids)
    _local.depth = depth + 1
    _local.span_id = span_id
    annotation = None
    if _xla_annotations:
        try:
            import jax
            annotation = jax.profiler.TraceAnnotation(name)
            annotation.__enter__()
        except Exception:
            annotation = None
    start = time.perf_counter()
    try:
        yield
    finally:
        dur = time.perf_counter() - start
        if annotation is not None:
            annotation.__exit__(None, None, None)
        _local.depth = depth
        _local.span_id = parent
        rec = {"name": name, "start": start, "duration_s": dur,
               "depth": depth, "id": span_id, "parent": parent,
               "track": current_track()}
        ln = lane if lane is not None else current_lane()
        if ln is not None:
            rec["lane"] = ln
        if attrs:
            rec["attrs"] = attrs
        # under _lock: export() snapshots the deque while other threads
        # record, and set_capacity() swaps the buffer out entirely
        with _lock:
            _buffer.append(rec)


def record(name: str, start: float, duration_s: float,
           track: Optional[str] = None, lane: Optional[str] = None,
           **attrs) -> None:
    """Append a retroactive span from saved ``perf_counter`` timestamps.

    For phases whose boundaries are events rather than a ``with`` block
    (a request's queue wait between submit and first prefill chunk, its
    decode phase between first token and finish). Retroactive spans are
    top-level (no parent) on ``track`` (default: the calling thread's
    track) in fleet lane ``lane`` (default: the thread's lane)."""
    rec = {"name": name, "start": float(start),
           "duration_s": float(duration_s), "depth": 0, "id": next(_ids),
           "parent": None,
           "track": track if track is not None else current_track()}
    ln = lane if lane is not None else current_lane()
    if ln is not None:
        rec["lane"] = ln
    if attrs:
        rec["attrs"] = attrs
    with _lock:
        _buffer.append(rec)


def export(name: Optional[str] = None) -> List[Dict]:
    """Copy of the recorded spans (oldest first), optionally filtered."""
    with _lock:
        spans = list(_buffer)
    if name is not None:
        spans = [s for s in spans if s["name"] == name]
    return spans


def durations(name: str) -> List[float]:
    return [s["duration_s"] for s in export(name)]


def clear() -> None:
    with _lock:
        _buffer.clear()
