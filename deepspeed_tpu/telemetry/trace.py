"""Lightweight span tracing with a ring-buffer exporter.

``with trace.span("decode_step"):`` records (name, start, duration,
depth) into a bounded deque — overhead is two ``perf_counter`` calls and
one locked append, so the serving hot path can stay instrumented in
production.
``export()`` drains a copy for offline analysis; ``durations(name)``
feeds assertions and benchmarks.

``enable_xla_annotations(True)`` mirrors every span into a
``jax.profiler.TraceAnnotation`` so spans line up with device activity
in a TensorBoard/XProf trace captured via
``deepspeed_tpu.utils.xla_profile.capture_trace`` (the hook is optional:
absent/failed jax.profiler leaves spans host-only).
"""

import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Dict, List, Optional

_DEFAULT_CAPACITY = 4096

_lock = threading.Lock()
_buffer: deque = deque(maxlen=_DEFAULT_CAPACITY)
_xla_annotations = False
_local = threading.local()


def enable_xla_annotations(on: bool = True) -> None:
    """Mirror spans into jax.profiler trace annotations (see module
    docstring)."""
    global _xla_annotations
    _xla_annotations = on


def set_capacity(capacity: int) -> None:
    """Resize the ring buffer (drops recorded spans)."""
    global _buffer
    with _lock:
        _buffer = deque(maxlen=int(capacity))


@contextmanager
def span(name: str, **attrs):
    """Record a wall-clock span; nests (depth reflects enclosing spans)."""
    depth = getattr(_local, "depth", 0)
    _local.depth = depth + 1
    annotation = None
    if _xla_annotations:
        try:
            import jax
            annotation = jax.profiler.TraceAnnotation(name)
            annotation.__enter__()
        except Exception:
            annotation = None
    start = time.perf_counter()
    try:
        yield
    finally:
        dur = time.perf_counter() - start
        if annotation is not None:
            annotation.__exit__(None, None, None)
        _local.depth = depth
        rec = {"name": name, "start": start, "duration_s": dur,
               "depth": depth}
        if attrs:
            rec["attrs"] = attrs
        # under _lock: export() snapshots the deque while other threads
        # record, and set_capacity() swaps the buffer out entirely
        with _lock:
            _buffer.append(rec)


def export(name: Optional[str] = None) -> List[Dict]:
    """Copy of the recorded spans (oldest first), optionally filtered."""
    with _lock:
        spans = list(_buffer)
    if name is not None:
        spans = [s for s in spans if s["name"] == name]
    return spans


def durations(name: str) -> List[float]:
    return [s["duration_s"] for s in export(name)]


def clear() -> None:
    with _lock:
        _buffer.clear()
