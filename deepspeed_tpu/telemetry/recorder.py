"""Flight recorder: an always-on, bounded, structured event black box.

The passive observability spine (registry gauges, trace spans) answers
"what is the system doing NOW"; the flight recorder answers "what
happened in the seconds BEFORE it went wrong". It is a ring buffer of
typed events — plain dicts with a ``kind``, a monotonic timestamp, a
process-wide sequence number, and free-form correlation fields
(``uid``, ``step``, ...) — capped by a BYTE budget rather than an event
count, so one chatty producer (e.g. per-window decode events) cannot
silently change how much history a quiet producer (e.g. anomaly
verdicts) keeps.

Producers (docs/TELEMETRY.md § Flight recorder):

  * training (``runtime/engine.py``): one ``train_step`` event per
    batch (loss, grad norm, loss scale, skip flag, duration),
  * serving: ``request_submit`` / ``request_finish`` /
    ``request_cancel`` (scheduler), ``admit`` / ``shed`` (admission),
    ``prefill`` / ``decode_window`` (engine), ``kv_alloc`` /
    ``kv_free`` (state manager),
  * the recompile watchdog mirrors every compile as ``xla_compile``,
  * anomaly detectors append their verdicts as ``anomaly`` events.

Cost: one dict build, one approximate size estimate, one locked deque
append — single-digit microseconds. ``scripts/perf_gate.py`` gates
``recorder_ns_per_event`` so the black box can never become the hot
path. Post-mortem bundles (:mod:`.postmortem`) snapshot the last-N
events; ``events()`` serves them live.

Like the metrics registry, there is one process default
(:func:`get_recorder`), swappable for test isolation
(:func:`set_recorder`).
"""

import itertools
import threading
import time
from collections import deque
from typing import Dict, List, Optional

from .registry import get_registry

DEFAULT_MAX_BYTES = 2 << 20          # ~2 MiB of history by default

# wall time is derived from one process-lifetime anchor instead of a
# time.time() syscall per event; sub-ms anchor drift is irrelevant for
# forensics timestamps
_WALL_ANCHOR = time.time() - time.perf_counter()

# fixed per-event overhead estimate (dict + bookkeeping fields), plus a
# per-field estimate below. Approximate by design: the budget bounds
# memory to the right order, it is not an allocator.
_EVENT_BASE_BYTES = 96
_FIELD_BYTES = 24


def _event_bytes(fields: Dict) -> int:
    n = _EVENT_BASE_BYTES + _FIELD_BYTES * len(fields)
    for v in fields.values():
        t = type(v)
        if t is str:
            n += len(v)
        elif t is list or t is tuple:
            n += 8 * len(v)
        elif t is dict:
            n += 48 * len(v)
    return n


class FlightRecorder:
    """Byte-bounded ring of typed events; see module docstring."""

    def __init__(self, max_bytes: int = DEFAULT_MAX_BYTES):
        self.max_bytes = int(max_bytes)
        self._lock = threading.Lock()
        self._events: deque = deque()
        self._bytes = 0
        self._seq = itertools.count(1)
        self._dropped = 0
        self._recorded = 0
        self.enabled = True
        # registry series are resolved lazily and cached against the
        # registry object's identity (plus a per-kind series cache), so
        # a test's set_registry() swap is picked up without paying a
        # family lookup per record()
        self._reg = None
        self._m_events = None
        self._m_dropped = None
        self._m_bytes = None
        self._kind_series: Dict[str, object] = {}

    # -- metrics -------------------------------------------------------
    def _metrics(self, kind: str):
        reg = get_registry()
        if reg is not self._reg:
            # assign _reg LAST: a concurrent record() that observes
            # `reg is self._reg` must find every series attribute
            # already in place (re-running this branch on a race is
            # idempotent — registration is — but a half-initialized
            # fast path is an AttributeError inside admit()/submit())
            self._kind_series = {}
            self._m_events = reg.counter(
                "recorder_events_total",
                "flight-recorder events recorded", labelnames=("kind",))
            self._m_dropped = reg.counter(
                "recorder_dropped_events_total",
                "flight-recorder events evicted to hold the byte budget")
            self._m_bytes = reg.gauge(
                "recorder_buffer_bytes",
                "approximate bytes of retained flight-recorder history",
                unit="bytes")
            self._reg = reg
        series = self._kind_series.get(kind)
        if series is None:
            series = self._kind_series[kind] = \
                self._m_events.labels(kind=kind)
        return series, self._m_dropped, self._m_bytes

    # -- recording -----------------------------------------------------
    def record(self, kind: str, **fields) -> Optional[Dict]:
        """Append one event; returns the event dict (None when the
        recorder is disabled). ``fields`` must be JSON-serializable —
        they land verbatim in post-mortem bundles."""
        if not self.enabled:
            return None
        t = time.perf_counter()
        ev = {"kind": kind, "t": t, "wall": _WALL_ANCHOR + t,
              "seq": next(self._seq)}
        ev.update(fields)
        size = _event_bytes(ev)
        kind_total, m_dropped, m_bytes = self._metrics(kind)
        with self._lock:
            self._events.append((size, ev))
            self._bytes += size
            self._recorded += 1
            dropped = 0
            while self._bytes > self.max_bytes and len(self._events) > 1:
                s, _ = self._events.popleft()
                self._bytes -= s
                dropped += 1
            self._dropped += dropped
            buf_bytes = self._bytes
        kind_total.inc()
        if dropped:
            m_dropped.inc(dropped)
        m_bytes.set(buf_bytes)
        return ev

    # -- reading -------------------------------------------------------
    def events(self, kind: Optional[str] = None,
               last: Optional[int] = None) -> List[Dict]:
        """Copy of retained events (oldest first); ``kind`` filters,
        ``last`` keeps only the most recent N after filtering."""
        with self._lock:
            evs = [e for _, e in self._events]
        if kind is not None:
            evs = [e for e in evs if e["kind"] == kind]
        if last is not None:
            evs = evs[-int(last):]
        return evs

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {"retained": len(self._events), "bytes": self._bytes,
                    "recorded": self._recorded, "dropped": self._dropped,
                    "max_bytes": self.max_bytes}

    # -- management ----------------------------------------------------
    def set_budget(self, max_bytes: int) -> None:
        """Resize the byte budget (evicts oldest events immediately)."""
        with self._lock:
            self.max_bytes = int(max_bytes)
            while self._bytes > self.max_bytes and len(self._events) > 1:
                s, _ = self._events.popleft()
                self._bytes -= s
                self._dropped += 1

    def clear(self) -> None:
        with self._lock:
            self._events.clear()
            self._bytes = 0


_default_recorder = FlightRecorder()
_recorder_lock = threading.Lock()


def get_recorder() -> FlightRecorder:
    """The process-local default recorder every subsystem feeds."""
    return _default_recorder


def set_recorder(recorder: FlightRecorder) -> FlightRecorder:
    """Swap the process default (tests isolate with a fresh recorder);
    returns the previous one."""
    global _default_recorder
    with _recorder_lock:
        prev = _default_recorder
        _default_recorder = recorder
    return prev


def record(kind: str, **fields) -> Optional[Dict]:
    """Record into the process-default recorder (the instrumentation
    call sites' one-liner)."""
    return _default_recorder.record(kind, **fields)
