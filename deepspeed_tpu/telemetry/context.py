"""Request-scoped distributed trace context.

One request crossing the serving fleet — router dispatch, a prefill
replica, a serialized KV handoff, a decode replica — must land in the
telemetry of every hop under ONE identity, or per-request attribution
stops at the first process boundary. :class:`TraceContext` is that
identity: a 128-bit ``trace_id``, the 64-bit id of the enclosing span
(``span_id`` — the PARENT of whatever the receiving side records), and
a small string ``baggage`` dict for deployment-defined correlation
(tenant, experiment arm).

Three codecs, one per boundary the context crosses:

  * **HTTP** — the W3C Trace Context headers (``traceparent:
    00-<trace_id>-<span_id>-<flags>`` plus an optional ``baggage:
    k=v,...``), so external load balancers and clients interoperate
    (:func:`from_headers` / :meth:`TraceContext.to_traceparent`);
  * **wire payloads** — a plain JSON-able dict
    (:meth:`TraceContext.to_wire` / :func:`from_wire`) embedded in the
    KV handoff manifest (serve/handoff.py), so the decode replica
    CONTINUES the prefill replica's trace rather than starting its own;
  * **in-process** — a :mod:`contextvars` variable
    (:func:`current` / :func:`use`), which asyncio propagates per task,
    so the serving frontend never threads the context by hand.

The serving loop thread does not share the asyncio context: request
records (scheduler ``_Request``, frontend ``_Entry``) carry the context
explicitly across that boundary, and span call sites attach
``trace_id`` to their attrs — the stitched fleet timeline
(telemetry/timeline.py) selects on it.

``trace_contexts_total{origin=new|header|wire}`` counts where contexts
came from (all-new under a router with no upstream means nobody is
propagating headers to you).
"""

import os
import re
from contextlib import contextmanager
from contextvars import ContextVar
from dataclasses import dataclass, field
from typing import Dict, Iterator, Mapping, Optional

from .registry import get_registry

_TRACEPARENT_RE = re.compile(
    r"^([0-9a-f]{2})-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})$")
_BAGGAGE_MAX_ENTRIES = 16
_BAGGAGE_MAX_CHARS = 256


def _count(origin: str) -> None:
    get_registry().counter(
        "trace_contexts_total",
        "distributed trace contexts minted (origin=new) or continued "
        "from a traceparent header / handoff wire payload",
        labelnames=("origin",)).labels(origin=origin).inc()


@dataclass(frozen=True)
class TraceContext:
    """One hop's view of a distributed trace (module docstring)."""

    trace_id: str                      # 32 lowercase hex chars
    span_id: str                       # 16 lowercase hex chars (parent)
    baggage: Mapping[str, str] = field(default_factory=dict)
    sampled: bool = True

    def child(self) -> "TraceContext":
        """The context a downstream hop should receive: same trace,
        fresh span id (this hop becomes the parent)."""
        return TraceContext(self.trace_id, os.urandom(8).hex(),
                            dict(self.baggage), self.sampled)

    # -- HTTP (W3C Trace Context) --------------------------------------
    def to_traceparent(self) -> str:
        return (f"00-{self.trace_id}-{self.span_id}-"
                f"{'01' if self.sampled else '00'}")

    def to_baggage_header(self) -> str:
        return ",".join(f"{k}={v}" for k, v in self.baggage.items())

    # -- wire payloads (handoff manifest) ------------------------------
    def to_wire(self) -> Dict[str, object]:
        out: Dict[str, object] = {"trace_id": self.trace_id,
                                  "span_id": self.span_id,
                                  "sampled": self.sampled}
        if self.baggage:
            out["baggage"] = dict(self.baggage)
        return out


def new_context(**baggage: str) -> TraceContext:
    """Mint a fresh root context (a request arriving with no upstream
    trace)."""
    _count("new")
    return TraceContext(os.urandom(16).hex(), os.urandom(8).hex(),
                        {str(k): str(v) for k, v in baggage.items()})


def from_traceparent(header: Optional[str],
                     baggage_header: Optional[str] = None
                     ) -> Optional[TraceContext]:
    """Parse the W3C ``traceparent`` (+ optional ``baggage``) headers;
    None on anything malformed (a bad header must degrade to a fresh
    trace, never a 500)."""
    if not header:
        return None
    m = _TRACEPARENT_RE.match(header.strip().lower())
    if m is None:
        return None
    version, trace_id, span_id, flags = m.groups()
    if version == "ff":
        return None     # explicitly invalid version per the spec
    if trace_id == "0" * 32 or span_id == "0" * 16:
        return None     # all-zero ids are invalid per the spec
    baggage: Dict[str, str] = {}
    if baggage_header:
        for item in baggage_header.split(",")[:_BAGGAGE_MAX_ENTRIES]:
            key, sep, value = item.strip().partition("=")
            if sep and key:
                baggage[key[:_BAGGAGE_MAX_CHARS]] = \
                    value[:_BAGGAGE_MAX_CHARS]
    _count("header")
    return TraceContext(trace_id, span_id, baggage,
                        sampled=bool(int(flags, 16) & 1))


def from_headers(headers: Mapping[str, str]) -> Optional[TraceContext]:
    """Extract a context from lowercase-keyed HTTP headers."""
    return from_traceparent(headers.get("traceparent"),
                            headers.get("baggage"))


def from_wire(d: Optional[Mapping[str, object]]
              ) -> Optional[TraceContext]:
    """Rebuild a context from :meth:`TraceContext.to_wire`; None on
    missing/malformed payloads (old handoff payloads have no trace)."""
    if not isinstance(d, Mapping):
        return None
    trace_id, span_id = d.get("trace_id"), d.get("span_id")
    if (not isinstance(trace_id, str) or len(trace_id) != 32
            or not isinstance(span_id, str) or len(span_id) != 16):
        return None
    baggage = d.get("baggage") or {}
    if not isinstance(baggage, Mapping):
        baggage = {}
    _count("wire")
    return TraceContext(trace_id, span_id,
                        {str(k): str(v) for k, v in baggage.items()},
                        sampled=bool(d.get("sampled", True)))


# ---------------------------------------------------------------------------
# in-process propagation (asyncio-side; contextvars follow tasks)
# ---------------------------------------------------------------------------
_current: ContextVar[Optional[TraceContext]] = ContextVar(
    "ds_tpu_trace_context", default=None)


def current() -> Optional[TraceContext]:
    return _current.get()


@contextmanager
def use(ctx: Optional[TraceContext]) -> Iterator[Optional[TraceContext]]:
    """Bind ``ctx`` as the current context for the enclosed block."""
    token = _current.set(ctx)
    try:
        yield ctx
    finally:
        _current.reset(token)


def get_or_new(**baggage: str) -> TraceContext:
    """The current context, or a fresh root when none is bound."""
    ctx = _current.get()
    return ctx if ctx is not None else new_context(**baggage)
