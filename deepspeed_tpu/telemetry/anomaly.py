"""Online anomaly detection over the registry + flight-recorder signals.

The passive layer (metrics, spans, recorder events) only *stores*
evidence; this module watches it and raises typed verdicts while the
process is still alive — the difference between "the dashboard looked
odd yesterday" and an ``anomaly`` event with attribution written the
moment it happened. Four detectors (docs/TELEMETRY.md § Anomaly
detectors):

  * :class:`LossAnomalyDetector` — non-finite or z-score-spiking
    training loss/grad-norm, with per-parameter-bucket attribution: the
    train step exports each gradient leaf's squared norm, the detector
    keeps rolling per-bucket statistics and names the top offending
    buckets (non-finite first, then largest z-score).
  * :class:`SLOBurnRateMonitor` — multi-window (fast/slow) SLO
    burn-rate alerting over the serving TTFT/TPOT histograms, using the
    registry's bucket counts (quantile-style interpolation, no raw
    samples). Burn rate = (fraction of observations over the SLO bound)
    / (error budget); 1.0 means exactly consuming budget, >1 burning it.
  * :class:`StallWatchdog` — a daemon thread watching heartbeat
    channels (serving decode loop, training host sync). No beat within
    ``max(min_deadline, factor × rolling-median interval)`` while the
    channel is active ⇒ ``stall`` anomaly carrying a stack dump of
    every live thread (the wedged frame is in there).
  * :class:`KVLeakDetector` — at serving drain, reconciles the KV block
    pool against the scheduler's in-flight set: sequences still tracked
    with no owner, or allocated blocks no live sequence or prefix-cache
    entry accounts for, are leaks.

Every verdict goes through :func:`report`: the
``anomaly_events_total{kind=...}`` counter, an ``anomaly`` flight-
recorder event, a bounded recent-verdicts ledger (``/statusz`` and
post-mortem bundles read it), and a warning log.
"""

import math
import sys
import threading
import time
import traceback
from collections import deque
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from ..utils.logging import logger
from . import recorder as ds_recorder
from .registry import get_registry


@dataclass
class DiagnosticsConfig:
    """The ``diagnostics`` config block (runtime JSON config and
    ``ServingConfig.diagnostics``): flight recorder + anomaly detector +
    post-mortem knobs. Everything is on by default — the point of a
    black box is that it was recording BEFORE the incident."""

    enabled: bool = True
    # flight recorder (telemetry/recorder.py)
    recorder_max_bytes: int = 2 << 20
    # loss/grad anomaly (LossAnomalyDetector)
    loss_window: int = 64          # rolling window for loss z-scores
    loss_zscore: float = 8.0       # spike threshold in sigmas
    grad_attribution: bool = True  # export per-leaf grad norms from jit
    attribution_top_k: int = 3     # buckets named per verdict
    # SLO burn rate (SLOBurnRateMonitor)
    ttft_slo_s: float = 1.0        # TTFT objective bound
    tpot_slo_s: float = 0.25       # per-output-token objective bound
    slo_target: float = 0.99       # attainment target (error budget 1%)
    burn_threshold: float = 2.0    # alert when BOTH windows exceed this
    slo_fast_window_s: float = 30.0
    slo_slow_window_s: float = 600.0
    # a window with fewer observations than this reads burn 0: one
    # compile-inflated first token out of a handful of samples is
    # noise, not a 14x burn (a 1% error budget needs >= ~100 samples
    # before a fraction means anything)
    slo_min_samples: int = 50
    # stall watchdog (StallWatchdog)
    stall_enabled: bool = True
    stall_factor: float = 8.0          # k x rolling-median interval
    # floor on the deadline. Generous by default: a channel with no
    # beat history yet (first serving step, first train batch) may be
    # sitting in a cold XLA compile, which legitimately takes tens of
    # seconds — tune down once warm if faster detection matters
    stall_min_deadline_s: float = 60.0
    stall_check_interval_s: float = 0.25
    # post-mortem bundles (telemetry/postmortem.py)
    postmortem_dir: str = "postmortems"
    postmortem_on_anomaly: bool = False
    # install the process-wide unhandled-exception/atexit bundle hooks
    # (postmortem.install_crash_handler); off by default because the
    # hooks are global, not per-engine
    postmortem_on_crash: bool = False
    postmortem_min_interval_s: float = 60.0
    postmortem_last_events: int = 512

    def __post_init__(self):
        if not 0.0 < self.slo_target < 1.0:
            raise ValueError(
                f"diagnostics.slo_target must be in (0, 1), got "
                f"{self.slo_target}")
        if self.slo_fast_window_s > self.slo_slow_window_s:
            raise ValueError(
                "diagnostics.slo_fast_window_s must not exceed "
                "slo_slow_window_s")


# ---------------------------------------------------------------------------
# verdict ledger
# ---------------------------------------------------------------------------
_RECENT_CAP = 64
_recent: deque = deque(maxlen=_RECENT_CAP)
_recent_lock = threading.Lock()


def report(kind: str, summary: str, **details) -> Dict:
    """Raise one anomaly verdict: counter + recorder event + recent
    ledger + warning log. Returns the verdict dict."""
    get_registry().counter(
        "anomaly_events_total",
        "anomaly-detector verdicts raised (see docs/TELEMETRY.md)",
        labelnames=("kind",)).labels(kind=kind).inc()
    verdict = {"kind": kind, "summary": summary, "wall": time.time(),
               **details}
    ds_recorder.record("anomaly", anomaly=kind, summary=summary, **details)
    with _recent_lock:
        _recent.append(verdict)
    logger.warning(f"ANOMALY[{kind}]: {summary}")
    return verdict


def recent(n: int = _RECENT_CAP) -> List[Dict]:
    """Most recent verdicts, oldest first (bounded ledger)."""
    with _recent_lock:
        return list(_recent)[-int(n):]


def reset() -> None:
    """Drop the verdict ledger (test isolation)."""
    with _recent_lock:
        _recent.clear()


# ---------------------------------------------------------------------------
# training: loss / gradient anomalies with parameter-bucket attribution
# ---------------------------------------------------------------------------
class _Rolling:
    """Fixed-window mean/std (loss z-scores)."""

    __slots__ = ("values",)

    def __init__(self, window: int):
        self.values: deque = deque(maxlen=max(int(window), 4))

    def push(self, v: float) -> None:
        self.values.append(float(v))

    def zscore(self, v: float) -> Optional[float]:
        n = len(self.values)
        if n < 8:
            return None
        mean = sum(self.values) / n
        var = sum((x - mean) ** 2 for x in self.values) / n
        std = math.sqrt(var)
        if std <= 0:
            return None
        return (v - mean) / std


class LossAnomalyDetector:
    """Per-step training anomaly check; see module docstring.

    ``leaf_names`` are the gradient pytree's leaf paths (the
    "parameter buckets" attribution names); ``update`` takes the same
    step's per-leaf squared norms when the engine exports them
    (``diagnostics.grad_attribution``)."""

    def __init__(self, config: Optional[DiagnosticsConfig] = None,
                 leaf_names: Optional[Sequence[str]] = None):
        self.config = config or DiagnosticsConfig()
        self.leaf_names = list(leaf_names or ())
        self._loss = _Rolling(self.config.loss_window)
        self._gnorm = _Rolling(self.config.loss_window)
        # per-bucket EMA of mean/var — O(buckets) floats, no windows
        self._b_mean: Dict[int, float] = {}
        self._b_var: Dict[int, float] = {}
        self._decay = 0.98

    # -- attribution ---------------------------------------------------
    def _bucket_name(self, i: int) -> str:
        return (self.leaf_names[i] if i < len(self.leaf_names)
                else f"leaf[{i}]")

    def _attribute(self, leaf_sqnorms) -> List[Dict]:
        """Rank parameter buckets: non-finite norms first, then largest
        z-score against each bucket's own EMA statistics."""
        if leaf_sqnorms is None:
            return []
        scored: List[Tuple[float, Dict]] = []
        for i, sq in enumerate(leaf_sqnorms):
            sq = float(sq)
            norm = math.sqrt(sq) if (math.isfinite(sq) and sq >= 0) \
                else float("nan")
            if not math.isfinite(norm):
                scored.append((float("inf"),
                               {"bucket": self._bucket_name(i),
                                "grad_norm": None, "z": None,
                                "non_finite": True}))
                continue
            mean = self._b_mean.get(i, norm)
            var = self._b_var.get(i, 0.0)
            # std floored at 5% of the mean: a bucket whose norm never
            # moved (var 0) must still rank by deviation when it jumps
            std = max(math.sqrt(var), 0.05 * abs(mean), 1e-12)
            z = (norm - mean) / std
            scored.append((z, {"bucket": self._bucket_name(i),
                               "grad_norm": norm, "z": round(z, 2),
                               "non_finite": False}))
        scored.sort(key=lambda s: -s[0])
        return [rec for _, rec in scored[:self.config.attribution_top_k]]

    def _absorb_buckets(self, leaf_sqnorms) -> None:
        if leaf_sqnorms is None:
            return
        d = self._decay
        for i, sq in enumerate(leaf_sqnorms):
            sq = float(sq)
            if not (math.isfinite(sq) and sq >= 0):
                continue   # never poison the baseline with the anomaly
            norm = math.sqrt(sq)
            mean = self._b_mean.get(i)
            if mean is None:
                self._b_mean[i] = norm
                self._b_var[i] = 0.0
            else:
                delta = norm - mean
                self._b_mean[i] = mean + (1 - d) * delta
                self._b_var[i] = d * (self._b_var.get(i, 0.0)
                                      + (1 - d) * delta * delta)

    # -- the per-step check --------------------------------------------
    def update(self, step: int, loss: float, grad_norm: float,
               leaf_sqnorms=None, skipped: bool = False) -> Optional[Dict]:
        """Check one completed train step; returns the verdict (already
        reported) or None. Finite healthy steps feed the rolling
        baselines; anomalous values never do."""
        loss = float(loss)
        grad_norm = float(grad_norm)
        verdict = None
        if skipped and math.isfinite(loss):
            # fp16 dynamic loss scaling doing its job: an overflowed
            # grad with a finite loss is a skip-step, not an anomaly
            # (the engine records it as a train_step event with
            # skipped=true; training_skipped_steps_total counts it)
            return None
        if not math.isfinite(loss) or not math.isfinite(grad_norm):
            kind = "nan_loss" if not math.isfinite(loss) else "nan_grad"
            top = self._attribute(leaf_sqnorms)
            names = ", ".join(t["bucket"] for t in top) or "unattributed"
            verdict = report(
                kind,
                f"step {step}: non-finite "
                f"{'loss' if kind == 'nan_loss' else 'grad norm'} "
                f"(loss={loss}, grad_norm={grad_norm}); top buckets: "
                f"{names}",
                step=int(step), loss=loss, grad_norm=grad_norm,
                top_buckets=top, skipped=bool(skipped))
        else:
            z = self._loss.zscore(loss)
            gz = self._gnorm.zscore(grad_norm)
            if z is not None and z > self.config.loss_zscore:
                top = self._attribute(leaf_sqnorms)
                verdict = report(
                    "loss_spike",
                    f"step {step}: loss {loss:.5g} is {z:.1f} sigma over "
                    f"the rolling window; top buckets: "
                    f"{', '.join(t['bucket'] for t in top) or 'n/a'}",
                    step=int(step), loss=loss, grad_norm=grad_norm,
                    zscore=round(z, 2), top_buckets=top)
            elif gz is not None and gz > self.config.loss_zscore:
                top = self._attribute(leaf_sqnorms)
                verdict = report(
                    "grad_spike",
                    f"step {step}: grad norm {grad_norm:.5g} is "
                    f"{gz:.1f} sigma over the rolling window; top "
                    f"buckets: "
                    f"{', '.join(t['bucket'] for t in top) or 'n/a'}",
                    step=int(step), loss=loss, grad_norm=grad_norm,
                    zscore=round(gz, 2), top_buckets=top)
            else:
                self._loss.push(loss)
                self._gnorm.push(grad_norm)
                self._absorb_buckets(leaf_sqnorms)
        return verdict


# ---------------------------------------------------------------------------
# serving: SLO burn-rate monitoring from histogram bucket counts
# ---------------------------------------------------------------------------
def estimate_over(series, threshold: float) -> float:
    """Estimated number of a histogram series' observations exceeding
    ``threshold``, interpolating linearly inside the straddling bucket
    (±bucket-width error — the same estimate ``quantile`` makes in the
    other direction)."""
    bounds = series.bounds
    counts = series.bucket_counts
    under = 0.0
    for i, c in enumerate(counts[:len(bounds)]):
        hi = float(bounds[i])
        if hi <= threshold:
            under += c
            continue
        lo = float(bounds[i - 1]) if i else 0.0
        if lo < threshold < hi and c:
            under += c * (threshold - lo) / (hi - lo)
        break
    return max(float(series.count) - under, 0.0)


class SLOBurnRateMonitor:
    """Multi-window SLO burn-rate over registry latency histograms.

    ``tick()`` snapshots each watched histogram's (count, est. count
    over the SLO bound), computes the bad fraction over the fast and
    slow windows, publishes ``slo_burn_rate{signal=...,window=...}``
    gauges, and raises one ``slo_burn`` verdict per excursion when BOTH
    windows exceed ``burn_threshold`` (the classic fast+slow gate: fast
    for reaction time, slow so a blip cannot page). The alert re-arms
    when the fast window drops back under the threshold.

    Burn rate 1.0 = consuming error budget exactly at the sustainable
    rate; e.g. with ``slo_target=0.99``, 3% of requests over the bound
    is a burn rate of 3. No traffic in a window reads as burn 0.

    **Fleet mode**: pass ``registries`` (N per-replica registries) and
    the monitor burns over the AGGREGATED histograms — bucket counts
    summed across every replica's TTFT/TPOT series — so the alert fires
    on the fleet's attainment, not any one replica's. The router owns
    one (``fleet_slo_burn_rate`` gauges, ``fleet_slo_burn`` verdicts,
    distinct names so per-replica monitors sharing a registry never
    collide with it)."""

    def __init__(self, config: Optional[DiagnosticsConfig] = None,
                 registry=None, clock=time.monotonic,
                 signals: Optional[Iterable[Tuple[str, str, float]]]
                 = None, registries: Optional[Iterable] = None,
                 gauge_name: str = "slo_burn_rate",
                 verdict_kind: str = "slo_burn"):
        self.config = config or DiagnosticsConfig()
        self.registry = registry or get_registry()
        # the registries the latency histograms are READ from (fleet
        # mode: one per replica); gauges/verdicts always publish into
        # self.registry / the process ledger
        self.registries = (list(registries) if registries is not None
                           else [self.registry])
        self.verdict_kind = verdict_kind
        self.clock = clock
        cfg = self.config
        self.signals = list(signals) if signals is not None else [
            ("ttft", "serving_ttft_seconds", cfg.ttft_slo_s),
            ("tpot", "serving_tpot_seconds", cfg.tpot_slo_s),
        ]
        self._snaps: Dict[str, deque] = {s[0]: deque()
                                         for s in self.signals}
        self._alerting: Dict[str, bool] = {s[0]: False
                                           for s in self.signals}
        # tick() runs on the serving-loop thread AND on /statusz's
        # asyncio thread; the snapshot rings need one owner at a time
        self._lock = threading.Lock()
        # literal registrations for the two known names keep
        # scripts/check_telemetry_docs.py's literal scan honest (a
        # variable name would read as an unregistered catalog row)
        if gauge_name == "fleet_slo_burn_rate":
            self._gauge = self.registry.gauge(
                "fleet_slo_burn_rate",
                "SLO error-budget burn rate per signal and window, "
                "aggregated across the replica fleet's histograms "
                "(1.0 = consuming budget exactly at the sustainable "
                "rate)", labelnames=("signal", "window"))
        elif gauge_name == "slo_burn_rate":
            self._gauge = self.registry.gauge(
                "slo_burn_rate",
                "SLO error-budget burn rate per signal and window "
                "(1.0 = consuming budget exactly at the sustainable "
                "rate)", labelnames=("signal", "window"))
        else:
            self._gauge = self.registry.gauge(
                gauge_name,
                "SLO error-budget burn rate per signal and window "
                "(1.0 = consuming budget exactly at the sustainable "
                "rate)", labelnames=("signal", "window"))

    @staticmethod
    def _family_series(reg, metric: str):
        fam = reg.get(metric)
        if fam is None:
            return None
        return fam._series.get(()) or next(
            (s for _, s in fam.series()), None)

    def _series(self, metric: str):
        """The metric's histogram series — or, in fleet mode, a merged
        view with bucket counts summed across every source registry
        (sources whose bucket bounds disagree are skipped: summing
        misaligned bins would fabricate a distribution)."""
        found = []
        for reg in self.registries:
            s = self._family_series(reg, metric)
            if s is not None:
                found.append(s)
        if not found:
            return None
        if len(found) == 1:
            return found[0]
        from .registry import _HistogramSeries
        merged = _HistogramSeries(found[0].bounds)
        for s in found:
            if tuple(s.bounds) != tuple(merged.bounds):
                continue
            merged.bucket_counts = [
                a + b for a, b in zip(merged.bucket_counts,
                                      s.bucket_counts)]
            merged.sum += s.sum
            merged.count += s.count
        return merged

    def _window_burn(self, snaps: deque, now: float, window_s: float,
                     budget: float) -> float:
        """Burn over [now - window_s, now] from the snapshot ring.

        The base is the newest snapshot at-or-before the window edge,
        falling back to the OLDEST snapshot when the monitor is younger
        than the window — never 0: a fresh monitor attached to a
        long-lived shared registry must burn over what it OBSERVED, not
        over the registry's whole pre-history (a histogram full of
        earlier traffic would otherwise fire a phantom verdict on the
        very first tick)."""
        cur_t, cur_n, cur_over = snaps[-1]
        base_n, base_over = snaps[0][1], snaps[0][2]
        cutoff = now - window_s
        for t, n, over in reversed(snaps):
            if t <= cutoff:
                base_n, base_over = n, over
                break
        dn = cur_n - base_n
        if dn < max(self.config.slo_min_samples, 1):
            return 0.0    # too few observations for a fraction to mean
            # anything (and a cold monitor must not page on a blip)
        bad_frac = max(cur_over - base_over, 0.0) / dn
        return bad_frac / budget

    def tick(self) -> Dict[str, Dict[str, float]]:
        """One monitoring pass; returns {signal: {fast, slow}} burns."""
        with self._lock:
            return self._tick_locked()

    def _tick_locked(self) -> Dict[str, Dict[str, float]]:
        cfg = self.config
        now = self.clock()
        budget = 1.0 - cfg.slo_target
        out: Dict[str, Dict[str, float]] = {}
        for name, metric, slo in self.signals:
            series = self._series(metric)
            snaps = self._snaps[name]
            if series is None:
                continue
            snaps.append((now, float(series.count),
                          estimate_over(series, slo)))
            horizon = now - cfg.slo_slow_window_s - 1.0
            while len(snaps) > 2 and snaps[1][0] <= horizon:
                snaps.popleft()
            fast = self._window_burn(snaps, now, cfg.slo_fast_window_s,
                                     budget)
            slow = self._window_burn(snaps, now, cfg.slo_slow_window_s,
                                     budget)
            self._gauge.labels(signal=name, window="fast").set(fast)
            self._gauge.labels(signal=name, window="slow").set(slow)
            out[name] = {"fast": fast, "slow": slow}
            over = (fast > cfg.burn_threshold
                    and slow > cfg.burn_threshold)
            if over and not self._alerting[name]:
                self._alerting[name] = True
                report(self.verdict_kind,
                       f"{name} SLO burn rate {fast:.1f}x (fast) / "
                       f"{slow:.1f}x (slow) exceeds "
                       f"{cfg.burn_threshold}x of the "
                       f"{1 - cfg.slo_target:.1%} error budget "
                       f"(bound {slo}s)",
                       signal=name, slo_s=slo, burn_fast=round(fast, 2),
                       burn_slow=round(slow, 2),
                       threshold=cfg.burn_threshold)
            elif self._alerting[name] and fast <= cfg.burn_threshold:
                self._alerting[name] = False
                ds_recorder.record("slo_recovered", signal=name,
                                   burn_fast=round(fast, 2),
                                   burn_slow=round(slow, 2))
        return out

    def burning(self) -> bool:
        """True while ANY watched signal's fast+slow alert is latched
        (between the ``slo_burn`` verdict and its fast-window
        recovery) — the router autoscaler's scale-up signal."""
        return any(self._alerting.values())

    def quantiles(self) -> Dict[str, Dict[str, float]]:
        """p50/p95/p99 per watched signal from the histogram buckets
        (the /statusz SLO section — no raw-sample lists)."""
        out: Dict[str, Dict[str, float]] = {}
        for name, metric, slo in self.signals:
            series = self._series(metric)
            if series is None or not series.count:
                continue
            out[name] = {
                "p50": series.quantile(0.5),
                "p95": series.quantile(0.95),
                "p99": series.quantile(0.99),
                "slo_s": slo, "count": series.count,
            }
        return out


# ---------------------------------------------------------------------------
# stall / straggler watchdog
# ---------------------------------------------------------------------------
def thread_stacks(max_frames: int = 20) -> Dict[str, List[str]]:
    """Formatted stack of every live thread (the post-mortem evidence a
    stall verdict carries: the wedged frame is one of these). Duplicate
    thread names — N serving replicas each run a 'ds-tpu-serving-loop'
    thread — are disambiguated with the thread ident so no stack
    silently overwrites another."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out: Dict[str, List[str]] = {}
    for ident, frame in sys._current_frames().items():
        stack = traceback.format_stack(frame)[-max_frames:]
        name = names.get(ident, f"thread-{ident}")
        if name in out:
            name = f"{name}#{ident}"
        out[name] = [line.rstrip() for line in stack]
    return out


class _Channel:
    __slots__ = ("last_beat", "intervals", "active", "stalled",
                 "min_deadline", "factor")

    def __init__(self, min_deadline: float, factor: float):
        self.last_beat: Optional[float] = None
        self.intervals: deque = deque(maxlen=32)
        self.active = False
        self.stalled = False
        self.min_deadline = min_deadline
        self.factor = factor

    def deadline(self) -> float:
        if self.intervals:
            ordered = sorted(self.intervals)
            median = ordered[len(ordered) // 2]
            return max(self.min_deadline, self.factor * median)
        return self.min_deadline


class StallWatchdog:
    """Heartbeat-deadline watchdog; see module docstring.

    A channel only arms while ``set_active(channel, True)`` — an idle
    serving loop or a training engine between batches is silence, not a
    stall. The deadline adapts: ``factor ×`` the rolling median of the
    channel's own beat intervals, floored at ``min_deadline_s``, so a
    workload whose windows take 2s is judged on its own cadence."""

    def __init__(self, config: Optional[DiagnosticsConfig] = None,
                 clock=time.monotonic):
        self.config = config or DiagnosticsConfig()
        self.clock = clock
        self._channels: Dict[str, _Channel] = {}
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    def register(self, channel: str,
                 min_deadline_s: Optional[float] = None,
                 factor: Optional[float] = None) -> None:
        with self._lock:
            if channel not in self._channels:
                self._channels[channel] = _Channel(
                    min_deadline_s if min_deadline_s is not None
                    else self.config.stall_min_deadline_s,
                    factor if factor is not None
                    else self.config.stall_factor)

    def beat(self, channel: str) -> None:
        now = self.clock()
        with self._lock:
            ch = self._channels.get(channel)
            if ch is None:
                ch = self._channels[channel] = _Channel(
                    self.config.stall_min_deadline_s,
                    self.config.stall_factor)
            if ch.last_beat is not None:
                ch.intervals.append(now - ch.last_beat)
            ch.last_beat = now
            recovered = ch.stalled
            ch.stalled = False
        if recovered:
            ds_recorder.record("stall_recovered", channel=channel)

    def set_active(self, channel: str, active: bool) -> None:
        with self._lock:
            ch = self._channels.get(channel)
            if ch is None:
                return
            if active and not ch.active:
                ch.last_beat = self.clock()   # arm from now, not history
            ch.active = active

    def heartbeat_age(self, channel: str) -> Optional[float]:
        """Seconds since the channel's last heartbeat while ARMED, or
        None when the channel is unknown, idle (idle is silence, not a
        stall) or has never beaten. The serving router reads this to
        declare a replica dead: a loop wedged mid-step stays active
        with a growing age, while an idle loop reads None."""
        now = self.clock()
        with self._lock:
            ch = self._channels.get(channel)
            if ch is None or ch.last_beat is None or not ch.active:
                return None
            return now - ch.last_beat

    # -- scanning ------------------------------------------------------
    def check_now(self) -> List[Dict]:
        """One scan (what the thread runs each interval); returns the
        verdicts raised. Exposed for deterministic tests."""
        now = self.clock()
        victims: List[Tuple[str, float, float]] = []
        with self._lock:
            for name, ch in self._channels.items():
                if not ch.active or ch.stalled or ch.last_beat is None:
                    continue
                waited = now - ch.last_beat
                deadline = ch.deadline()
                if waited > deadline:
                    ch.stalled = True
                    victims.append((name, waited, deadline))
        verdicts = []
        for name, waited, deadline in victims:
            verdicts.append(report(
                "stall",
                f"channel {name!r}: no heartbeat for {waited:.2f}s "
                f"(deadline {deadline:.2f}s = max(min_deadline, "
                f"factor x rolling-median interval)); thread stacks "
                f"attached",
                channel=name, waited_s=round(waited, 3),
                deadline_s=round(deadline, 3), stacks=thread_stacks()))
        return verdicts

    def _run(self) -> None:
        while not self._stop.wait(self.config.stall_check_interval_s):
            try:
                self.check_now()
            except Exception:   # the watchdog must never kill the host
                logger.exception("stall watchdog scan failed")

    def start(self) -> "StallWatchdog":
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="ds-tpu-stall-watchdog",
                daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None


# ---------------------------------------------------------------------------
# KV-pool leak detection
# ---------------------------------------------------------------------------
class KVLeakDetector:
    """Reconcile the KV block pool against the scheduler at drain.

    At a clean drain nothing is in flight, so every tracked sequence is
    an orphan and every allocated block must be explained by a tracked
    sequence or a prefix-cache index entry. Anything else leaked — the
    free path was skipped somewhere (a cancel that didn't flush, an
    exception between allocate and release)."""

    def __init__(self, config: Optional[DiagnosticsConfig] = None):
        self.config = config or DiagnosticsConfig()

    def check_at_drain(self, state_manager,
                       inflight_uids: Iterable[int] = ()) -> Optional[Dict]:
        """Returns the reported ``kv_leak`` verdict, or None when the
        pool reconciles."""
        inflight = set(int(u) for u in inflight_uids)
        orphans = {int(uid): len(seq.blocks)
                   for uid, seq in state_manager.seqs.items()
                   if int(uid) not in inflight}
        usable = max(state_manager.config.num_blocks - 1, 0)
        allocated = usable - state_manager.free_blocks()
        accounted = set()
        for seq in state_manager.seqs.values():
            accounted.update(int(b) for b in seq.blocks)
        for blk in getattr(state_manager, "_prefix", {}).values():
            accounted.add(int(blk))
        unaccounted = allocated - len(accounted)
        if not orphans and unaccounted <= 0:
            ds_recorder.record("kv_drain_clean", allocated=int(allocated),
                               prefix_retained=len(
                                   getattr(state_manager, "_prefix", {})))
            return None
        detail = (f"{len(orphans)} orphaned sequence(s) holding "
                  f"{sum(orphans.values())} block(s)"
                  if orphans else "")
        if unaccounted > 0:
            detail += (" and " if detail else "") + \
                f"{unaccounted} allocated block(s) owned by nothing"
        return report(
            "kv_leak",
            f"KV pool failed to reconcile at drain: {detail} "
            f"(allocated={allocated}, inflight={len(inflight)})",
            orphan_uids=sorted(orphans),
            orphan_blocks=int(sum(orphans.values())),
            unaccounted_blocks=max(int(unaccounted), 0),
            allocated_blocks=int(allocated))
