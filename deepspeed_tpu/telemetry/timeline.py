"""Timeline export: telemetry spans -> Chrome trace events.

Converts the :mod:`~deepspeed_tpu.telemetry.trace` ring buffer into the
Chrome trace-event JSON format (the ``{"traceEvents": [...]}`` shape
``chrome://tracing`` and https://ui.perfetto.dev load directly), so a
serving incident or a slow training step can be inspected as a timeline
without a TensorBoard/XProf capture.

Each span becomes one complete ("X") event; span ``track``s (one per
recording thread by default) become trace threads, named via metadata
events. Request-correlated spans carry the request ``uid`` in their
``args``, so one request's lifeline — admission, queue wait, prefill,
decode windows, finish — filters out of the mixed serving timeline with
:func:`request_spans` / :func:`request_lifeline`.

Fleet stitching: a routed deployment records spans in N replica rings
plus the router's (in-process replicas share one ring, distinguished by
per-span ``lane``; remote replicas each own a ring). :func:`stitch_fleet`
merges them into ONE Chrome trace with a process row per lane, and
``trace_id``-filtered views (:func:`trace_spans`) follow a single
request across router dispatch, prefill, KV handoff and decode — the
distributed-tracing surface (docs/PROFILING.md § Distributed tracing).

Surfaces: ``bench.py --trace-out`` and ``serving_bench --trace-out``
write the file after a run (``--router`` writes the stitched fleet
form); the serving API exposes ``GET /debug/timeline[?uid=N][&trace=ID]``
live (docs/PROFILING.md).
"""

import json
import os
from typing import Dict, Iterable, List, Mapping, Optional

from . import trace

# phases of one serving request, in lifeline order (scheduler.py emits
# them; the names are the contract the timeline tests pin)
REQUEST_PHASES = ("request_queue", "request_prefill", "request_decode",
                  "request")


def to_chrome_trace(spans: Optional[Iterable[Dict]] = None) -> Dict:
    """Chrome-trace-event JSON dict for ``spans`` (default: the current
    ring buffer). Timestamps are microseconds relative to the earliest
    span; tracks map to tids with thread_name metadata."""
    spans = trace.export() if spans is None else list(spans)
    pid = os.getpid()
    if not spans:
        return {"traceEvents": [], "displayTimeUnit": "ms"}
    t0 = min(s["start"] for s in spans)
    tracks: Dict[str, int] = {}
    events: List[Dict] = []
    for s in spans:
        track = s.get("track") or "main"
        tid = tracks.setdefault(track, len(tracks) + 1)
        ev = {"name": s["name"], "ph": "X", "cat": "span", "pid": pid,
              "tid": tid, "ts": round((s["start"] - t0) * 1e6, 3),
              "dur": round(s["duration_s"] * 1e6, 3)}
        args = dict(s.get("attrs") or {})
        if s.get("id") is not None:
            args["span_id"] = s["id"]
        if s.get("parent") is not None:
            args["parent_id"] = s["parent"]
        if args:
            ev["args"] = args
        events.append(ev)
    meta = [{"name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
             "args": {"name": track}} for track, tid in tracks.items()]
    return {"traceEvents": meta + events, "displayTimeUnit": "ms"}


def write_chrome_trace(path: str,
                       spans: Optional[Iterable[Dict]] = None) -> str:
    """Write :func:`to_chrome_trace` JSON to ``path``; returns the path."""
    obj = to_chrome_trace(spans)
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "w") as fh:
        json.dump(obj, fh)
    return path


def stitch_fleet(rings: Optional[Mapping[str, Iterable[Dict]]] = None,
                 trace_id: Optional[str] = None) -> Dict:
    """Merge N span rings into ONE Chrome trace with a process row per
    fleet lane.

    ``rings`` maps a source name to its exported spans — one entry per
    remote replica ring, or the default ``None`` for the in-process
    case (one shared ring, every span already lane-tagged). A span's
    own ``lane`` wins over its ring's name (the router and its
    in-process replicas share a ring), spans with neither group under
    the ring name, and a lane-less default ring groups under ``host``.
    ``trace_id`` filters every ring to one request's trace first.

    All timestamps must share a clock (in-process: ``perf_counter``;
    remote rings need their exporter to rebase) — events are offset
    from the earliest span across ALL rings, so causal order is
    preserved fleet-wide."""
    if rings is None:
        rings = {"host": trace.export()}
    lanes: Dict[str, List[Dict]] = {}
    for ring_name, spans in rings.items():
        spans = list(spans)
        if trace_id is not None:
            spans = trace_spans(trace_id, spans)
        for s in spans:
            lanes.setdefault(s.get("lane") or ring_name, []).append(s)
    if not any(lanes.values()):
        return {"traceEvents": [], "displayTimeUnit": "ms"}
    t0 = min(s["start"] for spans in lanes.values() for s in spans)
    events: List[Dict] = []
    meta: List[Dict] = []
    for pid, lane in enumerate(sorted(lanes), start=1):
        meta.append({"name": "process_name", "ph": "M", "pid": pid,
                     "tid": 0, "args": {"name": lane}})
        tracks: Dict[str, int] = {}
        for s in lanes[lane]:
            track = s.get("track") or "main"
            tid = tracks.setdefault(track, len(tracks) + 1)
            ev = {"name": s["name"], "ph": "X", "cat": "span",
                  "pid": pid, "tid": tid,
                  "ts": round((s["start"] - t0) * 1e6, 3),
                  "dur": round(s["duration_s"] * 1e6, 3)}
            args = dict(s.get("attrs") or {})
            if s.get("id") is not None:
                args["span_id"] = s["id"]
            if args:
                ev["args"] = args
            events.append(ev)
        meta.extend({"name": "thread_name", "ph": "M", "pid": pid,
                     "tid": tid, "args": {"name": track}}
                    for track, tid in tracks.items())
    events.sort(key=lambda e: e["ts"])
    return {"traceEvents": meta + events, "displayTimeUnit": "ms"}


def write_fleet_trace(path: str,
                      rings: Optional[Mapping[str, Iterable[Dict]]] = None,
                      trace_id: Optional[str] = None) -> str:
    """Write :func:`stitch_fleet` JSON to ``path``; returns the path."""
    obj = stitch_fleet(rings, trace_id=trace_id)
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "w") as fh:
        json.dump(obj, fh)
    return path


def _touches_uid(s: Dict, uid: int) -> bool:
    attrs = s.get("attrs") or {}
    if attrs.get("uid") == uid:
        return True
    uids = attrs.get("uids")
    return bool(uids) and uid in uids


def _touches_trace(s: Dict, trace_id: str) -> bool:
    attrs = s.get("attrs") or {}
    if attrs.get("trace_id") == trace_id:
        return True
    tids = attrs.get("trace_ids")
    return bool(tids) and trace_id in tids


def trace_spans(trace_id: str,
                spans: Optional[Iterable[Dict]] = None) -> List[Dict]:
    """Every span correlated with distributed trace ``trace_id`` —
    spans whose attrs carry ``trace_id`` or include it in a batch
    ``trace_ids`` list (engine steps serve many traces at once)."""
    spans = trace.export() if spans is None else list(spans)
    return [s for s in spans if _touches_trace(s, str(trace_id))]


def request_spans(uid: int,
                  spans: Optional[Iterable[Dict]] = None) -> List[Dict]:
    """Every span correlated with request ``uid`` — spans whose attrs
    carry ``uid=<uid>`` or include it in a batch ``uids`` list (decode
    steps/windows serve many requests at once)."""
    spans = trace.export() if spans is None else list(spans)
    return [s for s in spans if _touches_uid(s, int(uid))]


def request_lifeline(uid: int,
                     spans: Optional[Iterable[Dict]] = None) -> Dict:
    """The request's phase spans keyed by name (queue -> prefill ->
    decode -> total; missing phases are absent). ``decode_batches``
    collects the shared decode-step/window spans the uid rode in."""
    mine = request_spans(uid, spans)
    out: Dict = {"uid": int(uid)}
    for s in mine:
        if s["name"] in REQUEST_PHASES:
            out[s["name"]] = s
    out["decode_batches"] = [s for s in mine
                             if s["name"] in ("decode_step",
                                              "decode_window")]
    return out
