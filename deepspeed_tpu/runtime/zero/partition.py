"""ZeRO partitioning as sharding specs.

TPU-native re-design of the reference's ZeRO optimizers:
  - stage 1/2: runtime/zero/stage_1_and_2.py:96 (optimizer-state (+grad)
    partitioning with bucketed reduce)
  - stage 3:   runtime/zero/stage3.py:72 + partition_parameters.py:723
    (parameter partitioning with allgather-on-use and trace-based prefetch)

The torch implementation is ~7,000 lines of hook machinery because eager
execution forces manual gather/release/prefetch. Under XLA the same semantics
are *sharding specs*: we assign each state tensor a `PartitionSpec` placing its
ZeRO shard on the data-parallel mesh axes, and XLA's SPMD partitioner inserts
exactly the collectives the reference issues by hand —

  stage 1: optimizer state sharded  -> allgather of updated params after step
  stage 2: + gradients sharded      -> reduce-scatter instead of all-reduce
  stage 3: + parameters sharded     -> allgather-on-use in fwd/bwd (XLA's
           latency-hiding scheduler overlaps these with compute, replacing the
           reference's __allgather_stream / prefetch coordinator,
           stage3.py:1151, partitioned_param_coordinator.py:256)

Parameters smaller than `stage3_param_persistence_threshold` stay replicated,
mirroring the reference's persistent-param optimization
(parameter_offload.py persistence thresholds).
"""

import math
from dataclasses import dataclass
from typing import Any, Optional, Tuple

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ...parallel.topology import MeshTopology


def _numel(shape) -> int:
    return int(np.prod(shape)) if len(shape) else 1


def add_zero_axes(shape: Tuple[int, ...],
                  base_spec: Optional[P],
                  zero_axes: Tuple[str, ...],
                  zero_size: int,
                  threshold: int = 0,
                  axis_sizes: Optional[dict] = None) -> P:
    """Extend `base_spec` (TP placement) with the ZeRO axes on the best free dim.

    Picks the largest dimension that is (a) not already sharded by the base
    spec and (b) divisible by the ZeRO world size. Returns the base spec
    unchanged when nothing qualifies or the tensor is below the persistence
    threshold (small params stay replicated: cheaper than gathering).
    """
    base = tuple(base_spec) if base_spec is not None else ()
    base = base + (None,) * (len(shape) - len(base))
    # axes already used by the base (TP/EP/PP) spec cannot be reused: an
    # expert-sharded param's ZeRO shard spans only the remaining data axes
    used = set()
    for entry in base:
        for a in (entry if isinstance(entry, tuple) else (entry,)):
            if a is not None:
                used.add(a)
    free_axes = tuple(a for a in zero_axes if a not in used)
    if axis_sizes is not None:
        zero_size = 1
        for a in free_axes:
            zero_size *= axis_sizes[a]
    if not free_axes or zero_size <= 1:
        return P(*base)
    if threshold and _numel(shape) < threshold:
        return P(*base)
    # candidate dims: unsharded in base, divisible by zero_size
    candidates = [(d, shape[d]) for d in range(len(shape))
                  if base[d] in (None, ()) and shape[d] % zero_size == 0]
    if not candidates:
        return P(*base)
    dim = max(candidates, key=lambda t: t[1])[0]
    new = list(base)
    new[dim] = free_axes if len(free_axes) > 1 else free_axes[0]
    return P(*new)


@dataclass
class ZeroPlan:
    """Per-pytree sharding plan for one training state.

    Fields are pytrees of NamedSharding matching the params pytree structure.
    """

    stage: int
    param_sharding: Any   # compute params (fwd/bwd)
    grad_sharding: Any    # accumulated gradients
    master_sharding: Any  # fp32 master weights + optimizer moments

    def shardings_for_opt_state(self, opt_state_template):
        """Optimizer moments mirror master-weight sharding, leaf-for-leaf."""
        # opt_state is {name: params-like pytree}; map each sub-tree.
        return jax.tree.map(
            lambda _: None, opt_state_template)  # placeholder; engine uses master_sharding per subtree


def build_zero_plan(topo: MeshTopology,
                    stage: int,
                    param_shapes,
                    base_specs=None,
                    persistence_threshold: int = 0,
                    secondary_axes=None,
                    include_seq_axis: bool = False) -> ZeroPlan:
    """Construct the sharding plan for a given ZeRO stage.

    `param_shapes`: pytree of jax.ShapeDtypeStruct (or arrays).
    `base_specs`: optional pytree of PartitionSpec carrying TP/EP placement
    (the reference takes TP from an external mpu, engine.py:94; here the model
    supplies specs and ZeRO composes with them).
    `secondary_axes`: ZeRO++ hpZ (reference partition_parameters.py:639
    secondary tensors): stage-3 COMPUTE params shard over these axes only
    (the within-group sub-axis) while master/opt/grads keep the full
    `dp_axes` shard — the fwd/bwd gather then stays inside the group.
    `include_seq_axis`: shard model state over the "seq" axis too — the
    reference's Ulysses x ZeRO composition (sp ranks ARE dp ranks to ZeRO,
    stage3.py:1181); engine enables it for the standard auto-SPMD step.
    """
    mesh = topo.mesh
    zero_axes = (topo.zero_shard_axes if include_seq_axis
                 else topo.dp_axes)
    zero_size = topo.dp_world_size
    if include_seq_axis:
        zero_size *= topo.axis_size("seq")

    if base_specs is None:
        base_specs = jax.tree.map(lambda _: P(), param_shapes)

    def spec_of(threshold, axes=None):
        axes = axes if axes is not None else zero_axes

        def fn(leaf, base):
            shape = leaf.shape if hasattr(leaf, "shape") else tuple(leaf)
            return add_zero_axes(shape, base, axes, zero_size,
                                 threshold=threshold, axis_sizes=topo.sizes)
        return fn

    # Optimizer-state/master/grad shards always partition (no threshold);
    # stage-3 *compute* params below the persistence threshold stay gathered
    # (parameter_offload.py persistent params) — their master is still sharded.
    opt_specs = jax.tree.map(spec_of(0), param_shapes, base_specs)
    param3_specs = jax.tree.map(
        spec_of(persistence_threshold, axes=secondary_axes), param_shapes,
        base_specs)

    def ns(spec_tree):
        return jax.tree.map(lambda s: NamedSharding(mesh, s), spec_tree,
                            is_leaf=lambda x: isinstance(x, P))

    base_ns = ns(base_specs)
    opt_ns = ns(opt_specs)

    if stage <= 0:
        return ZeroPlan(stage, base_ns, base_ns, base_ns)
    if stage == 1:
        # grads replicated (all-reduced), optimizer state sharded
        return ZeroPlan(stage, base_ns, base_ns, opt_ns)
    if stage == 2:
        # grads reduce-scattered into shards, params still gathered
        return ZeroPlan(stage, base_ns, opt_ns, opt_ns)
    # stage 3: params sharded too (modulo persistence threshold)
    return ZeroPlan(stage, ns(param3_specs), opt_ns, opt_ns)


def estimate_zero_memory(param_count: int, stage: int, dp: int,
                         bytes_per_param_low: int = 2) -> dict:
    """Model-state memory per device, the reference's 4+K breakdown
    (ZeRO paper / docs/_pages/training.md:67): 2-byte params, 2-byte grads,
    12-byte fp32 master+moments for Adam."""
    p, g, o = 2, 2, 12
    if stage >= 1:
        o /= dp
    if stage >= 2:
        g /= dp
    if stage >= 3:
        p /= dp
    total = param_count * (p + g + o)
    return {"params_bytes": param_count * p, "grads_bytes": param_count * g,
            "optstate_bytes": param_count * o, "total_bytes": total}
