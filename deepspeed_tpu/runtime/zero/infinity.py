"""ZeRO-Infinity NVMe parameter tier: per-layer streamed execution.

TPU-native analogue of the reference's parameter swapper
(``swap_tensor/partitioned_param_swapper.py:36`` — fp16 params live on
NVMe, are async-read into pinned host buffers and shipped to device right
before a submodule runs, then released) and of the stage-3 module hooks
that drive it (``runtime/zero/parameter_offload.py:201``).

The reference can hook arbitrary eager submodules; under XLA the
equivalent design is an explicit **per-layer executor**: one jitted
single-layer forward, one jitted single-layer VJP, and jitted stem/crown
(embedding / loss-head) programs. The Python driver walks the layer
stack, double-buffering NVMe reads through the AIO C++ library
(``csrc/aio/async_io.cpp``) so layer ``i+1``'s disk read overlaps layer
``i``'s device compute — the same overlap the reference gets from its
swap-out/swap-in streams. Backward re-fetches each layer in reverse
order and recomputes its forward inside ``jax.vjp`` (layer-granularity
rematerialization), so device HBM never holds more than one layer's
parameters plus the boundary activations.

Storage layout under ``offload_param.nvme_path``:

* ``layer_{i:05d}.params`` — the layer's compute-dtype (bf16) leaves,
  concatenated (read twice per microbatch: forward + backward).
* ``layer_{i:05d}.optim``  — fp32 ``[master | moment0 | moment1 ...]``
  per leaf, concatenated (read+written once per optimizer sweep, with
  the reference's PipelinedOptimizerSwapper-style read-ahead). With
  ``offload_optimizer.device != "nvme"`` this state stays in host RAM
  instead (ZeRO-Offload params-on-NVMe, states-in-RAM).

Persistent (non-layer) parameters — embeddings, final norm, untied LM
head — stay device-resident with host-RAM fp32 master/moments, mirroring
the reference's ``stage3_param_persistence_threshold`` behavior for
small tensors. Gradients accumulate in host fp32 buffers across the
gradient-accumulation loop, matching the reference's CPU-resident
partitioned gradients under Infinity.

Restrictions (all rejected loudly at engine init): causal-LM pre-LN
models only (same surface as the 1F1B pipeline), bf16/fp32 compute (no
fp16 loss scaling), no MoE / pipeline / sequence / expert axes, no
1-bit optimizers or compression. dp x tp meshes are supported — each
streamed layer is ``device_put`` with its tensor-parallel sharding.
"""

import os
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ...ops.cpu_optimizers import build_host_optimizer
from ...utils.logging import logger


def _np_dtype(jnp_dtype):
    import ml_dtypes
    if jnp_dtype == jnp.bfloat16:
        return np.dtype(ml_dtypes.bfloat16)
    return np.dtype(jnp_dtype)


class _LayerFileStream:
    """Double-buffered AIO reader over per-layer files of equal size.

    A slot's buffer is only rewritten after (a) its AIO read completed and
    (b) any in-flight host->device transfer sourced from it finished
    (``note_transfer`` + ``block_until_ready`` guard) — device_put from a
    numpy view does not promise the host memory is consumed by return on
    an async backend."""

    def __init__(self, aio, paths: List[str], nbytes: int, dtype):
        self.aio = aio
        self.paths = paths
        self.bufs = [np.zeros(nbytes // dtype.itemsize, dtype)
                     for _ in range(2)]
        self._pending: Dict[int, int] = {}   # layer idx -> aio req id
        self._slot_of: Dict[int, int] = {}   # layer idx -> buffer slot
        self._transfer: Dict[int, Any] = {}  # slot -> device tree in flight

    def note_transfer(self, i: int, dev_tree):
        self._transfer[self._slot_of[i]] = dev_tree

    def _claim_slot(self, i: int, keep: Optional[int]) -> Optional[int]:
        used = set(self._slot_of.values())
        free = [s for s in (0, 1) if s not in used]
        if free:
            slot = free[0]
        else:  # evict a layer that isn't the caller's pinned one
            victim = next((k for k in self._slot_of
                           if k != keep and k not in self._pending), None)
            if victim is None:
                victim = next((k for k in self._pending if k != keep), None)
                if victim is None:
                    return None   # both slots pinned; caller falls back
                self.aio.wait(self._pending.pop(victim))
            slot = self._slot_of.pop(victim)
        t = self._transfer.pop(slot, None)
        if t is not None:
            # the buffer may still be feeding an async H2D copy
            jax.block_until_ready(t)
        self._slot_of[i] = slot
        return slot

    def prefetch(self, i: int, keep: Optional[int] = None):
        if i < 0 or i >= len(self.paths) or i in self._pending \
                or i in self._slot_of:
            return
        slot = self._claim_slot(i, keep)
        if slot is not None:
            self._pending[i] = self.aio.pread(self.paths[i], self.bufs[slot])

    def get(self, i: int, prefetch_next: Optional[int] = None) -> np.ndarray:
        if i in self._pending:
            self.aio.wait(self._pending.pop(i))
        elif i not in self._slot_of:
            slot = self._claim_slot(i, keep=None)
            assert slot is not None, "layer stream: no free buffer slot"
            self.aio.sync_pread(self.paths[i], self.bufs[slot])
        buf = self.bufs[self._slot_of[i]]
        if prefetch_next is not None:
            self.prefetch(prefetch_next, keep=i)
        return buf

    def invalidate(self):
        """Drop all cached/ready layers (files were rewritten)."""
        for i, req in list(self._pending.items()):
            self.aio.wait(req)
        self._pending.clear()
        self._slot_of.clear()


class InfinityParamEngine:
    """Owns NVMe parameter + optimizer storage and the per-layer step.

    Built by DeepSpeedEngine when ``offload_param.device == "nvme"``.
    """

    _instance_counter = 0

    def __init__(self, model, topology, rng, *, opt_name: str,
                 opt_params: Dict[str, Any], param_nvme_path: str,
                 optim_device: str, optim_nvme_path: Optional[str],
                 aio_block_size: int, aio_threads: int, gas: int,
                 clip: float, compute_dtype=jnp.bfloat16):
        from ...ops.aio import AsyncIOHandle
        from .offload import _leaf_names

        self.model = model
        self.cfg = model.cfg
        self.topology = topology
        self.gas = gas
        self.clip = clip
        self.compute_dtype = compute_dtype
        self._np_cdtype = _np_dtype(compute_dtype)
        self.L = self.cfg.num_layers
        self.opt = build_host_optimizer(opt_name, opt_params)
        self.state_keys = self.opt.state_keys()
        self._n_fields = 1 + len(self.state_keys)
        self.optim_on_nvme = optim_device == "nvme"

        InfinityParamEngine._instance_counter += 1
        self.param_dir = os.path.join(
            param_nvme_path, "ds_tpu_param_swap",
            f"pid{os.getpid()}_{InfinityParamEngine._instance_counter}")
        os.makedirs(self.param_dir, exist_ok=True)
        self.optim_dir = self.param_dir if not optim_nvme_path else \
            os.path.join(optim_nvme_path, "ds_tpu_param_swap",
                         f"pid{os.getpid()}_"
                         f"{InfinityParamEngine._instance_counter}_optim")
        if self.optim_on_nvme:
            os.makedirs(self.optim_dir, exist_ok=True)
        self.aio = AsyncIOHandle(aio_block_size, aio_threads)

        # ---- initial full tree on host (fp32), then split + spill ----
        cpu0 = jax.local_devices(backend="cpu")[0]
        with jax.default_device(cpu0):
            full = model.init_params(rng)
        # owned writable buffers: the C++ optimizer updates through the
        # raw pointer (np.asarray of a jax array can be a read-only view)
        full = jax.tree.map(lambda x: np.array(x, np.float32, copy=True),
                            full)
        layers = full.pop("layers")
        self.persist_tree_np = full                      # fp32 masters
        self.persist_names = _leaf_names(full)
        self.persist_leaves = jax.tree.leaves(full)
        _, self.persist_treedef = jax.tree_util.tree_flatten(full)
        self.persist_state = [[np.zeros(m.shape, np.float32)
                               for _ in self.state_keys]
                              for m in self.persist_leaves]

        layer_leaves, self.layer_treedef = jax.tree_util.tree_flatten(layers)
        self.layer_shapes = [l.shape[1:] for l in layer_leaves]   # minus L
        self.layer_sizes = [int(np.prod(s)) for s in self.layer_shapes]
        self.layer_elems = int(sum(self.layer_sizes))
        self.param_files = [os.path.join(self.param_dir,
                                         f"layer_{i:05d}.params")
                            for i in range(self.L)]
        self.optim_files = [os.path.join(self.optim_dir,
                                         f"layer_{i:05d}.optim")
                            for i in range(self.L)]
        # one layer at a time so peak host RAM stays O(one layer)
        pbuf = np.zeros(self.layer_elems, self._np_cdtype)
        obuf = np.zeros(self.layer_elems * self._n_fields, np.float32)
        self._optim_ram: List[Optional[np.ndarray]] = [None] * self.L
        for i in range(self.L):
            off = 0
            ooff = 0
            for leaf, sz in zip(layer_leaves, self.layer_sizes):
                flat = leaf[i].ravel()
                pbuf[off:off + sz] = flat.astype(self._np_cdtype)
                obuf[ooff:ooff + sz] = flat
                obuf[ooff + sz:ooff + sz * self._n_fields] = 0.0
                off += sz
                ooff += sz * self._n_fields
            self.aio.sync_pwrite(self.param_files[i], pbuf)
            if self.optim_on_nvme:
                self.aio.sync_pwrite(self.optim_files[i], obuf)
            else:
                self._optim_ram[i] = obuf.copy()
        del full, layers, layer_leaves
        param_gb = self.layer_elems * self.L * pbuf.itemsize / 1e9
        logger.info(
            f"ZeRO-Infinity: {self.L} layer param files on NVMe at "
            f"{self.param_dir} ({param_gb:.2f} GB bf16); optimizer state "
            f"{'on NVMe' if self.optim_on_nvme else 'in host RAM'}")

        # ---- working buffers ----
        self._pstream = _LayerFileStream(
            self.aio, self.param_files, self.layer_elems * pbuf.itemsize,
            self._np_cdtype)
        self.grad_acc = [np.zeros(self.layer_elems, np.float32)
                         for _ in range(self.L)]
        self.persist_grad_acc = [np.zeros(m.shape, np.float32)
                                 for m in self.persist_leaves]
        self._obufs = [np.zeros(self.layer_elems * self._n_fields,
                                np.float32) for _ in range(2)]

        # ---- shardings + device-resident persistent params ----
        mesh = topology.mesh
        base = model.param_partition_specs(topology) \
            if hasattr(model, "param_partition_specs") else None
        lspecs = (base or {}).get("layers", {})
        # strip the leading stacked-L axis entry from each layer spec
        self.layer_sharding = jax.tree_util.tree_unflatten(
            self.layer_treedef,
            [NamedSharding(mesh, P(*(tuple(lspecs[k])[1:]
                                     if isinstance(lspecs, dict)
                                     and k in lspecs else ())))
             for k in self._layer_keys()])
        self.persist_sharding = jax.tree.map(
            lambda _: NamedSharding(mesh, P()), self.persist_tree_np)
        if base:
            for k, spec in base.items():
                if k != "layers" and k in self.persist_sharding:
                    self.persist_sharding[k] = NamedSharding(mesh, spec)
        self._push_persist()
        self._build_fns()
        self._rope_cache: Dict[int, Any] = {}

    # ------------------------------------------------------------------
    def _layer_keys(self):
        # flatten order of the layers dict (sorted keys for dict pytrees)
        dummy = jax.tree_util.tree_unflatten(
            self.layer_treedef, list(range(len(self.layer_sizes))))
        keys = [k for k, _ in sorted(dummy.items())]
        return keys

    def _push_persist(self):
        tree = jax.tree_util.tree_unflatten(
            self.persist_treedef,
            [l.astype(self._np_cdtype) for l in self.persist_leaves])
        self.pp_dev = jax.tree.map(jax.device_put, tree,
                                   self.persist_sharding)

    def _layer_tree_from(self, buf: np.ndarray):
        views, off = [], 0
        for shape, sz in zip(self.layer_shapes, self.layer_sizes):
            views.append(buf[off:off + sz].reshape(shape))
            off += sz
        return jax.tree_util.tree_unflatten(self.layer_treedef, views)

    def _fetch_layer(self, i: int, prefetch: Optional[int]):
        buf = self._pstream.get(i, prefetch)
        tree = self._layer_tree_from(buf)
        dev = jax.tree.map(jax.device_put, tree, self.layer_sharding)
        # guard the host buffer against reuse while the H2D copy is in
        # flight (released by the stream before the slot is rewritten)
        self._pstream.note_transfer(i, dev)
        return dev

    # ------------------------------------------------------------------
    # jitted programs (stem / layer fwd / layer vjp / crown vjp)
    # ------------------------------------------------------------------
    def _build_fns(self):
        model, cfg = self.model, self.cfg
        from ...models.transformer import (_chunked_ce_loss, _rope_tables,
                                           layer_norm)

        def stem(pp, ids):
            x = pp["embed"][ids]
            if cfg.positional == "learned":
                x = x + pp["pos_embed"][:ids.shape[1]].astype(x.dtype)
            if cfg.embed_ln:
                x = layer_norm(x, pp["embed_ln_w"], pp.get("embed_ln_b"),
                               cfg.norm_eps)
            return x

        def crown(pp, x, ids, mask):
            x = model._norm(x, pp["final_norm"], pp.get("final_norm_b"))
            head = (pp["embed"].T if cfg.tie_embeddings else pp["lm_head"])
            m = (mask[:, 1:].astype(jnp.float32) if mask is not None
                 else jnp.ones(ids[:, 1:].shape, jnp.float32))
            total, count = _chunked_ce_loss(x[:, :-1], ids[:, 1:], m, head,
                                            cfg.loss_chunk)
            return (total / jnp.maximum(count, 1.0)).astype(jnp.float32)

        def layer_fwd(lp, x, cos, sin):
            return model._layer(x, lp, cos, sin)[0]

        def layer_bwd(lp, h_in, cos, sin, dh):
            _, pull = jax.vjp(
                lambda lp_, h_: layer_fwd(lp_, h_, cos, sin), lp, h_in)
            dlp, dh_in = pull(dh)
            return dh_in, dlp

        def crown_vjp(pp, x, ids, mask):
            (loss), (dpp, dx) = jax.value_and_grad(
                crown, argnums=(0, 1))(pp, x, ids, mask)
            return loss, dpp, dx

        def stem_vjp(pp, ids, dx):
            _, pull = jax.vjp(lambda pp_: stem(pp_, ids), pp)
            return pull(dx)[0]

        # NB: no donation on the forward hidden state — every layer input
        # is kept in `acts` for the backward sweep
        self._stem = jax.jit(stem)
        self._layer_fwd = jax.jit(layer_fwd)
        self._layer_bwd = jax.jit(layer_bwd, donate_argnums=(4,))
        self._crown_vjp = jax.jit(crown_vjp)
        self._crown_loss = jax.jit(crown)
        self._stem_vjp = jax.jit(stem_vjp)
        self._rope_tables = _rope_tables

    def _rope(self, S: int):
        if S not in self._rope_cache:
            cdt = self.compute_dtype
            if self.cfg.positional == "rope":
                cos, sin = self._rope_tables(self.cfg, S)
            else:  # unused by _layer; mirror forward_hidden's placeholders
                cos = sin = jnp.zeros((S, 1), cdt)
            self._rope_cache[S] = (jnp.asarray(cos, cdt),
                                   jnp.asarray(sin, cdt))
        return self._rope_cache[S]

    # ------------------------------------------------------------------
    # one full train batch (gas microbatches + optimizer sweep)
    # ------------------------------------------------------------------
    def train_batch(self, dev_batch, step: int, lr: float) -> Dict[str, Any]:
        L, gas = self.L, self.gas
        losses = []
        for g in self.grad_acc:
            g.fill(0.0)
        for g in self.persist_grad_acc:
            g.fill(0.0)

        for m in range(gas):
            micro = jax.tree.map(lambda x: x[m], dev_batch)
            ids = micro["input_ids"]
            mask = micro.get("loss_mask")
            cos, sin = self._rope(ids.shape[1])
            # ---- forward sweep (disk read i+1 overlaps layer i) ----
            h = self._stem(self.pp_dev, ids)
            acts = [h]
            for i in range(L):
                lp = self._fetch_layer(i, i + 1 if i + 1 < L else None)
                h = self._layer_fwd(lp, h, cos, sin)
                acts.append(h)
            loss, dpp_c, dh = self._crown_vjp(self.pp_dev, acts[-1],
                                              ids, mask)
            losses.append(loss)
            self._acc_persist(dpp_c)
            # ---- backward sweep (reverse stream; vjp recomputes fwd) ----
            for i in range(L - 1, -1, -1):
                lp = self._fetch_layer(i, i - 1 if i > 0 else None)
                dh, dlp = self._layer_bwd(lp, acts[i], cos, sin, dh)
                self._acc_layer_grads(i, dlp)
            acts.clear()
            self._acc_persist(self._stem_vjp(self.pp_dev, ids, dh))

        # ---- grad scale (1/gas), global norm, clip factor ----
        inv = 1.0 / gas
        sq = 0.0
        for g in self.grad_acc:
            g *= inv
            sq += float(np.dot(g, g))
        for g in self.persist_grad_acc:
            g *= inv
            sq += float(np.dot(g.ravel(), g.ravel()))
        gnorm = float(np.sqrt(sq))
        if self.clip and self.clip > 0 and gnorm > self.clip:
            factor = self.clip / (gnorm + 1e-6)
            for g in self.grad_acc:
                g *= factor
            for g in self.persist_grad_acc:
                g *= factor

        self._optimizer_sweep(step, lr)
        loss_mean = float(np.mean([float(l) for l in losses]))
        return {"loss": loss_mean, "grad_norm": gnorm,
                "skipped": 0}

    def _acc_layer_grads(self, i: int, dlp):
        leaves = jax.tree.leaves(dlp)
        buf, off = self.grad_acc[i], 0
        for leaf, sz in zip(leaves, self.layer_sizes):
            buf[off:off + sz] += np.asarray(leaf, np.float32).ravel()
            off += sz

    def _acc_persist(self, dpp):
        for acc, leaf in zip(self.persist_grad_acc, jax.tree.leaves(dpp)):
            acc += np.asarray(leaf, np.float32).reshape(acc.shape)

    # ------------------------------------------------------------------
    def _optimizer_sweep(self, step: int, lr: float):
        """Per-layer update with PipelinedOptimizerSwapper-style overlap:
        layer i+1's optim-state read and layer i-1's writeback ride the AIO
        threads while layer i runs the C++ CPU kernel."""
        L = self.L
        pbuf = np.zeros(self.layer_elems, self._np_cdtype)
        reads = [None, None]
        pending_write = None
        if self.optim_on_nvme:
            reads[0] = self.aio.pread(self.optim_files[0], self._obufs[0])
        for i in range(L):
            if self.optim_on_nvme:
                cur = self._obufs[i % 2]
                if i + 1 < L:
                    if pending_write is not None:
                        self.aio.wait(pending_write)
                        pending_write = None
                    reads[(i + 1) % 2] = self.aio.pread(
                        self.optim_files[i + 1], self._obufs[(i + 1) % 2])
                self.aio.wait(reads[i % 2])
            else:
                cur = self._optim_ram[i]
            grads, ooff, poff = self.grad_acc[i], 0, 0
            for sz in self.layer_sizes:
                master = cur[ooff:ooff + sz]
                moments = [cur[ooff + (1 + k) * sz:ooff + (2 + k) * sz]
                           for k in range(len(self.state_keys))]
                self.opt.step(step, master, grads[poff:poff + sz],
                              *moments, lr=lr)
                pbuf[poff:poff + sz] = master.astype(self._np_cdtype)
                ooff += sz * self._n_fields
                poff += sz
            if self.optim_on_nvme:
                pending_write = self.aio.pwrite(self.optim_files[i], cur)
            self.aio.sync_pwrite(self.param_files[i], pbuf)
        if pending_write is not None:
            self.aio.wait(pending_write)
        # any buffered layers predate the rewrite: drop them
        self._pstream.invalidate()
        # persistent (device-resident) params: plain host update
        for j, m in enumerate(self.persist_leaves):
            self.opt.step(step, m.ravel(), self.persist_grad_acc[j].ravel(),
                          *[s.ravel() for s in self.persist_state[j]], lr=lr)
        self._push_persist()

    # ------------------------------------------------------------------
    def eval_batch(self, dev_batch) -> float:
        losses = []
        for m in range(self.gas):
            micro = jax.tree.map(lambda x: x[m], dev_batch)
            ids = micro["input_ids"]
            cos, sin = self._rope(ids.shape[1])
            h = self._stem(self.pp_dev, ids)
            for i in range(self.L):
                lp = self._fetch_layer(i, i + 1 if i + 1 < self.L else None)
                # no donation: eval reuses the jitted fwd, fresh h each layer
                h = self._layer_fwd(lp, h, cos, sin)
            losses.append(float(self._crown_loss(
                self.pp_dev, h, ids, micro.get("loss_mask"))))
        return float(np.mean(losses))

    # ------------------------------------------------------------------
    # checkpoint interop (full-tree views, original init_params order)
    # ------------------------------------------------------------------
    def _read_optim(self, i: int) -> np.ndarray:
        if self.optim_on_nvme:
            buf = np.empty(self.layer_elems * self._n_fields, np.float32)
            self.aio.sync_pread(self.optim_files[i], buf)
            return buf
        return self._optim_ram[i]

    def full_master_and_state(self):
        """(master_tree fp32, {state_key: tree}) with 'layers' re-stacked."""
        stacked_m = [np.empty((self.L,) + s, np.float32)
                     for s in self.layer_shapes]
        stacked_s = {k: [np.empty((self.L,) + s, np.float32)
                         for s in self.layer_shapes]
                     for k in self.state_keys}
        for i in range(self.L):
            buf = self._read_optim(i)
            ooff = 0
            for j, (shape, sz) in enumerate(zip(self.layer_shapes,
                                                self.layer_sizes)):
                stacked_m[j][i] = buf[ooff:ooff + sz].reshape(shape)
                for k_idx, key in enumerate(self.state_keys):
                    stacked_s[key][j][i] = \
                        buf[ooff + (1 + k_idx) * sz:
                            ooff + (2 + k_idx) * sz].reshape(shape)
                ooff += sz * self._n_fields
        unflat_l = lambda ls: jax.tree_util.tree_unflatten(
            self.layer_treedef, ls)
        master = dict(jax.tree_util.tree_unflatten(
            self.persist_treedef, [m.copy() for m in self.persist_leaves]))
        master["layers"] = unflat_l(stacked_m)
        state = {}
        for k_idx, key in enumerate(self.state_keys):
            t = dict(jax.tree_util.tree_unflatten(
                self.persist_treedef,
                [s[k_idx].copy() for s in self.persist_state]))
            t["layers"] = unflat_l(stacked_s[key])
            state[key] = t
        return master, state

    def template_tree(self):
        master, state = None, None
        stacked = [np.empty((self.L,) + s, np.float32)
                   for s in self.layer_shapes]
        t = dict(jax.tree_util.tree_unflatten(
            self.persist_treedef,
            [np.empty(m.shape, np.float32) for m in self.persist_leaves]))
        t["layers"] = jax.tree_util.tree_unflatten(self.layer_treedef,
                                                   stacked)
        master = t
        state = {k: jax.tree.map(np.empty_like, t) for k in self.state_keys}
        return master, state

    def load_full(self, master_tree, state_trees: Optional[Dict[str, Any]]):
        """Restore master (and moments if given) into NVMe/RAM storage and
        refresh both the bf16 param files and the device persistents."""
        m = dict(master_tree)
        layers = m.pop("layers")
        for j, leaf in enumerate(jax.tree.leaves(m)):
            np.copyto(self.persist_leaves[j],
                      np.asarray(leaf, np.float32).reshape(
                          self.persist_leaves[j].shape))
        s_layers = None
        if state_trees is not None:
            s_layers = {}
            for key, tree in state_trees.items():
                tt = dict(tree)
                s_layers[key] = tt.pop("layers")
                for j, leaf in enumerate(jax.tree.leaves(tt)):
                    k_idx = self.state_keys.index(key)
                    np.copyto(self.persist_state[j][k_idx],
                              np.asarray(leaf, np.float32).reshape(
                                  self.persist_state[j][k_idx].shape))
        layer_leaves = jax.tree.leaves(layers)
        s_leaves = {k: jax.tree.leaves(v)
                    for k, v in (s_layers or {}).items()}
        pbuf = np.zeros(self.layer_elems, self._np_cdtype)
        for i in range(self.L):
            buf = self._read_optim(i) if state_trees is None else \
                np.zeros(self.layer_elems * self._n_fields, np.float32)
            ooff = poff = 0
            for j, sz in enumerate(self.layer_sizes):
                flat = np.asarray(layer_leaves[j][i], np.float32).ravel()
                buf[ooff:ooff + sz] = flat
                pbuf[poff:poff + sz] = flat.astype(self._np_cdtype)
                if state_trees is not None:
                    for k_idx, key in enumerate(self.state_keys):
                        buf[ooff + (1 + k_idx) * sz:
                            ooff + (2 + k_idx) * sz] = \
                            np.asarray(s_leaves[key][j][i],
                                       np.float32).ravel()
                ooff += sz * self._n_fields
                poff += sz
            if self.optim_on_nvme:
                self.aio.sync_pwrite(self.optim_files[i], buf)
            else:
                self._optim_ram[i] = buf
            self.aio.sync_pwrite(self.param_files[i], pbuf)
        self._pstream.invalidate()
        self._push_persist()

    # ------------------------------------------------------------------
    def device_param_bytes(self) -> int:
        """Bytes of parameters resident in device memory (persistents
        only — the layer stack lives on NVMe). For tests/telemetry."""
        return int(sum(np.prod(l.shape) * self._np_cdtype.itemsize
                       for l in self.persist_leaves))

    def close(self):
        if self.aio is not None:
            self.aio.close()
            self.aio = None
            import shutil
            shutil.rmtree(self.param_dir, ignore_errors=True)
            if self.optim_on_nvme:
                shutil.rmtree(self.optim_dir, ignore_errors=True)
        if getattr(self.opt, "destroy", None):
            self.opt.destroy()
