"""ZeRO-Offload / ZeRO-Infinity host-side optimizer state management.

TPU-native analogue of the reference's CPU/NVMe offload stack:
  * ZeRO-Offload — optimizer states in host RAM, update on host CPU
    (runtime/zero/stage_1_and_2.py cpu_offload path + csrc/adam/cpu_adam.cpp).
  * ZeRO-Infinity — optimizer states spilled to NVMe, swapped in per
    parameter group around the update
    (runtime/swap_tensor/partitioned_optimizer_swapper.py + csrc/aio/).

Here the device only computes (and reduces) gradients; this module owns the
fp32 master weights and moments as flat host numpy arrays, runs the native
OpenMP/SIMD update (ops/cpu_optimizers.py), and hands back bfloat16 parameter
leaves for the host->device transfer. In NVMe mode each leaf's fp32 state
lives in one file (master | moment0 | moment1 ...) under ``nvme_path`` and is
streamed through a double-buffered AIO pipeline: leaf i+1's read and leaf
i-1's writeback overlap with leaf i's CPU update (the same overlap the
reference gets from PipelinedOptimizerSwapper).
"""

import os
from typing import Any, Dict, List, Optional

import jax
import numpy as np

from ...ops.cpu_optimizers import build_host_optimizer
from ...utils.logging import logger


def _leaf_names(tree) -> List[str]:
    paths_and_leaves, _ = jax.tree_util.tree_flatten_with_path(tree)
    names = []
    for path, _leaf in paths_and_leaves:
        s = jax.tree_util.keystr(path)
        names.append("".join(c if c.isalnum() else "_" for c in s)
                     .strip("_") or "leaf")
    # de-duplicate defensively
    seen: Dict[str, int] = {}
    out = []
    for n in names:
        k = seen.get(n, 0)
        seen[n] = k + 1
        out.append(n if k == 0 else f"{n}__{k}")
    return out


class HostOffloadOptimizer:
    """Owns flat fp32 master + moments on host; steps via native C++ kernels.

    Parameters
    ----------
    opt_name / opt_params : optimizer selection (same registry keys as the
        device path, engine._configure_basic_optimizer analogue).
    master_leaves : list of fp32 numpy arrays (initial master weights), in
        tree_flatten order.
    device : "cpu" (RAM-resident) or "nvme" (file-resident, AIO-swapped).
    nvme_path : directory for swap files (nvme mode).
    aio : dict-ish with block_size / thread_count overrides.
    """

    _instance_counter = 0

    def __init__(self, opt_name: str, opt_params: Dict[str, Any],
                 master_leaves: List[np.ndarray], leaf_names: List[str],
                 device: str = "cpu", nvme_path: Optional[str] = None,
                 aio_block_size: int = 1 << 20, aio_threads: int = 8,
                 compute_dtype=None):
        import ml_dtypes

        self.opt = build_host_optimizer(opt_name, opt_params)
        self.state_keys = self.opt.state_keys()
        self.device = device
        self.names = leaf_names
        self.shapes = [m.shape for m in master_leaves]
        self.sizes = [m.size for m in master_leaves]
        self.out_dtype = np.dtype(
            ml_dtypes.bfloat16 if compute_dtype is None else compute_dtype)
        self._fused_bf16 = self.out_dtype == np.dtype(ml_dtypes.bfloat16)
        # preallocated compute-dtype output buffers for host->device transfer
        self.out_bf16 = [np.zeros(s, dtype=self.out_dtype) for s in self.shapes]
        self._step_count = 0

        if device == "cpu":
            # force owned, writable buffers (leaves may be read-only views of
            # jax arrays; the C++ kernel updates through the raw pointer)
            self.master = [np.array(m, np.float32, copy=True)
                           for m in master_leaves]
            self.state = [[np.zeros(m.shape, np.float32)
                           for _ in self.state_keys] for m in master_leaves]
            self._aio = None
        elif device == "nvme":
            from ...ops.aio import AsyncIOHandle

            assert nvme_path, "offload_optimizer.nvme_path required for nvme"
            HostOffloadOptimizer._instance_counter += 1
            self.swap_dir = os.path.join(
                nvme_path, "ds_tpu_swap",
                f"pid{os.getpid()}_{HostOffloadOptimizer._instance_counter}")
            os.makedirs(self.swap_dir, exist_ok=True)
            self._aio = AsyncIOHandle(aio_block_size, aio_threads)
            self._n_fields = 1 + len(self.state_keys)
            # two working buffers (current / prefetch), sized to largest leaf
            max_elems = max(self.sizes)
            self._bufs = [np.zeros(max_elems * self._n_fields, np.float32)
                          for _ in range(2)]
            # write initial state files (master followed by zero moments).
            # One leaf at a time: peak host RAM stays O(largest leaf), which
            # is the point of Infinity offload (caller can free master_leaves
            # incrementally since we never hold more than one copy).
            for i, m in enumerate(master_leaves):
                flat = np.zeros(self.sizes[i] * self._n_fields, np.float32)
                flat[:self.sizes[i]] = np.asarray(m, np.float32).ravel()
                self._aio.sync_pwrite(self._file(i), flat)
            logger.info(
                f"ZeRO-Infinity: optimizer state on NVMe at {self.swap_dir} "
                f"({sum(self.sizes) * 4 * self._n_fields / 1e9:.2f} GB)")
        else:
            raise ValueError(f"unknown offload device '{device}'")

    def _file(self, i: int) -> str:
        return os.path.join(self.swap_dir, f"{i:05d}_{self.names[i]}.bin")

    # ------------------------------------------------------------------
    def step(self, grad_leaves: List[np.ndarray], step: int,
             lr: Optional[float] = None) -> List[np.ndarray]:
        """Apply one optimizer step. grads may be fp32 or bfloat16 numpy.
        Returns the list of updated bf16 param leaves (preallocated buffers,
        valid until the next call)."""
        self._step_count = step
        if self.device == "cpu":
            for i, g in enumerate(grad_leaves):
                self._update_leaf(step, self.master[i].ravel(), g,
                                  [s.ravel() for s in self.state[i]],
                                  self.out_bf16[i], lr)
            return self.out_bf16
        return self._step_nvme(grad_leaves, step, lr)

    def _update_leaf(self, step, master_flat, grad, moments, out, lr):
        """Run the native update on one leaf; fill `out` (compute dtype).
        Uses the fused C++ bf16 copy-back when both the grads and the compute
        dtype are bfloat16; otherwise updates in fp32 and casts after."""
        g = np.ascontiguousarray(grad)
        if g.dtype != np.float32 and self._fused_bf16:
            self.opt.step(step, master_flat, g.ravel(), *moments, lr=lr,
                          params_out_bf16=out.ravel())
            return
        if g.dtype != np.float32:
            g = g.astype(np.float32)
        self.opt.step(step, master_flat, g.ravel(), *moments, lr=lr)
        np.copyto(out.ravel(), master_flat.astype(self.out_dtype))

    def _step_nvme(self, grad_leaves, step, lr):
        n = len(self.sizes)
        pending_write = None  # aio request id for previous leaf writeback
        # prime: read leaf 0 into buffer 0
        reads = [None, None]
        reads[0] = self._aio.pread(self._file(0),
                                   self._view(self._bufs[0], 0))
        for i in range(n):
            cur, nxt = self._bufs[i % 2], self._bufs[(i + 1) % 2]
            if i + 1 < n:  # prefetch next leaf while we update this one
                if pending_write is not None:
                    self._aio.wait(pending_write)  # buffer reuse barrier
                    pending_write = None
                reads[(i + 1) % 2] = self._aio.pread(
                    self._file(i + 1), self._view(nxt, i + 1))
            self._aio.wait(reads[i % 2])
            flat = self._view(cur, i)
            sz = self.sizes[i]
            master = flat[:sz]
            moments = [flat[(1 + k) * sz:(2 + k) * sz]
                       for k in range(len(self.state_keys))]
            self._update_leaf(step, master, grad_leaves[i], moments,
                              self.out_bf16[i], lr)
            pending_write = self._aio.pwrite(self._file(i), flat)
        if pending_write is not None:
            self._aio.wait(pending_write)
        return self.out_bf16

    def _view(self, buf: np.ndarray, i: int) -> np.ndarray:
        return buf[:self.sizes[i] * self._n_fields]

    # ------------------------------------------------------------------
    # Checkpoint interop: expose/load full fp32 state as leaf lists
    # ------------------------------------------------------------------
    def get_all_leaves(self):
        """One sweep over storage: (master_leaves, {state_key: leaves})."""
        if self.device == "cpu":
            master = [m.reshape(s) for m, s in zip(self.master, self.shapes)]
            state = {k: [st[j].reshape(s)
                         for st, s in zip(self.state, self.shapes)]
                     for j, k in enumerate(self.state_keys)}
            return master, state
        master: List[np.ndarray] = []
        state: Dict[str, List[np.ndarray]] = {k: [] for k in self.state_keys}
        for i in range(len(self.sizes)):
            flat = np.empty(self.sizes[i] * self._n_fields, np.float32)
            self._aio.sync_pread(self._file(i), flat)
            sz = self.sizes[i]
            master.append(flat[:sz].reshape(self.shapes[i]).copy())
            for j, k in enumerate(self.state_keys):
                state[k].append(flat[(1 + j) * sz:(2 + j) * sz]
                                .reshape(self.shapes[i]).copy())
        return master, state

    def get_master_leaves(self) -> List[np.ndarray]:
        return self.get_all_leaves()[0]

    def get_state_leaves(self) -> Dict[str, List[np.ndarray]]:
        return self.get_all_leaves()[1]

    def template_leaves(self):
        """Shape/dtype templates (np.empty: no file IO, no touched pages) for
        checkpoint loading."""
        master = [np.empty(s, np.float32) for s in self.shapes]
        state = {k: [np.empty(s, np.float32) for s in self.shapes]
                 for k in self.state_keys}
        return master, state

    def load_leaves(self, master: List[np.ndarray],
                    state: Optional[Dict[str, List[np.ndarray]]] = None):
        """Restore master (and, if given, moments) from checkpoint leaves.
        ``state=None`` keeps the existing moments
        (load_optimizer_states=False semantics, reference engine.py:2653)."""
        if self.device == "cpu":
            for i, m in enumerate(master):
                np.copyto(self.master[i], np.asarray(m, np.float32).reshape(
                    self.shapes[i]))
                if state is not None:
                    for j, k in enumerate(self.state_keys):
                        np.copyto(self.state[i][j],
                                  np.asarray(state[k][i], np.float32).reshape(
                                      self.shapes[i]))
            return
        for i in range(len(self.sizes)):
            sz = self.sizes[i]
            flat = np.empty(sz * self._n_fields, np.float32)
            if state is None:  # keep current moments: read-modify-write
                self._aio.sync_pread(self._file(i), flat)
            flat[:sz] = np.asarray(master[i], np.float32).ravel()
            if state is not None:
                for j, k in enumerate(self.state_keys):
                    flat[(1 + j) * sz:(2 + j) * sz] = np.asarray(
                        state[k][i], np.float32).ravel()
            self._aio.sync_pwrite(self._file(i), flat)

    def current_bf16_leaves(self) -> List[np.ndarray]:
        """Compute-dtype view of current master (for initial device params)."""
        masters = self.get_master_leaves()
        for i, m in enumerate(masters):
            np.copyto(self.out_bf16[i], m.astype(self.out_dtype))
        return self.out_bf16

    def close(self):
        if self._aio is not None:
            self._aio.close()
            self._aio = None
            import shutil
            shutil.rmtree(self.swap_dir, ignore_errors=True)
        if getattr(self.opt, "destroy", None):
            self.opt.destroy()
