"""Hybrid Engine: train↔serve colocation with zero-recompile hot-swap.

TPU-native analogue of the reference's DeepSpeedHybridEngine
(runtime/hybrid_engine.py:32 — inference v1 + RLHF): one process owns
BOTH halves of an RLHF actor — the ZeRO-sharded bucketed train step
(runtime/engine.py) and the paged serving engine (inference/v2) — and
the seam between them is explicit:

  * :class:`WeightPublisher` snapshots the training engine's live
    params (ZeRO-gathered bucket-by-bucket through
    ``engine.consolidated_param_buckets`` — the same fetch machinery
    the consolidated checkpoint uses, read-only, so the train step's
    executable is untouched) into a **versioned, chunked, CRC-checked
    payload** (serve/weights.py — the KV handoff's frame discipline).
  * The colocated serving engine ingests each publication by **donated
    buffer replacement** between scheduler steps: every new leaf lands
    on the old leaf's sharding/dtype, so the recompile watchdog stays
    at zero steady-state recompiles across a swap *by construction* —
    and post-publish streams are bit-identical to a fresh engine built
    from the published payload (pinned by the hot-swap parity tests).
  * :meth:`DeepSpeedHybridEngine.rollout` runs generation through the
    serving engine's ``put()`` + the existing host sampling path
    (``sampling.host_sample`` — the SplitFuse scheduler's exact draw
    discipline, so rollout streams are bit-identical to served
    streams) and feeds ``(prompt, tokens, per-token logprobs)`` into a
    **bounded** :class:`RolloutQueue` — the actor loop is
    train_batch → publish → rollout, one process, no recompiles.
  * The same payload pushes to a remote fleet:
    ``router.push_weights(engine.publish())`` runs the blue/green
    rollout (serve/router.py) — replicas advertise ``weight_version``
    in ``/healthz``, stale replicas drain as updated ones go live.

LoRA (reference _fuse_lora/_unfuse_lora :118-160): any subtree shaped
``{"w": [in, out], "lora_a": [in, r], "lora_b": [r, out]}`` fuses to
``w' = w + scale * (a @ b)`` for generation. ``fuse_lora`` carries the
pre-fuse base alongside the fused weight so ``unfuse_lora`` restores it
BIT-EXACTLY (recomputing ``w' - scale*(a@b)`` in floating point does
not round-trip); publication fuses adapters on the gathered host
leaves, so the published payload is inference-ready dense weights and
the live training params are never touched.
"""

import time
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from ..utils.logging import log_dist
from .engine import DeepSpeedTpuEngine

# ---------------------------------------------------------------------------
# LoRA fuse/unfuse (reference hybrid_engine.py _fuse_lora/_unfuse_lora)
# ---------------------------------------------------------------------------
# pre-fuse base stashed inside a fused group: what makes unfuse a
# bit-exact restore instead of a lossy float subtraction
_PRE_FUSE_KEY = "lora_w_prefuse"


def _is_lora_group(node) -> bool:
    return (isinstance(node, dict) and "w" in node and "lora_a" in node
            and "lora_b" in node)


def _fused_w(w, a, b, scale: float) -> np.ndarray:
    """THE fused-weight definition (host fp32 math): every fuse path —
    the tree transform and the publisher's flat-leaf fusion — goes
    through this one function, so fused-vs-unfused generate parity is
    bit-exact by construction."""
    w32 = np.asarray(w, np.float32)
    delta = float(scale) * (np.asarray(a, np.float32)
                            @ np.asarray(b, np.float32))
    return (w32 + delta).astype(np.asarray(w).dtype, copy=False)


def fuse_lora(params, scale: float = 1.0):
    """Fuse every LoRA group's adapters into its base weight (pure tree
    transform). The fused group keeps the pre-fuse base under a private
    key so :func:`unfuse_lora` restores it bit-exactly; fusing an
    already-fused group is a no-op."""
    import jax.numpy as jnp

    def walk(node):
        if _is_lora_group(node):
            if _PRE_FUSE_KEY in node:
                return dict(node)
            new = dict(node)
            new[_PRE_FUSE_KEY] = node["w"]
            new["w"] = jnp.asarray(
                _fused_w(node["w"], node["lora_a"], node["lora_b"],
                         scale),
                jnp.asarray(node["w"]).dtype)
            return new
        if isinstance(node, dict):
            return {k: walk(v) for k, v in node.items()}
        return node

    return walk(params)


def unfuse_lora(params, scale: float = 1.0):
    """Restore every fused group's base weight. Fused groups carry
    their pre-fuse base (bit-exact restore); a group fused by older
    code without the stash falls back to the reference's float
    subtraction."""
    import jax.numpy as jnp

    def walk(node):
        if _is_lora_group(node):
            new = dict(node)
            if _PRE_FUSE_KEY in new:
                new["w"] = new.pop(_PRE_FUSE_KEY)
            else:
                new["w"] = jnp.asarray(
                    _fused_w(node["w"], node["lora_a"], node["lora_b"],
                             -scale),
                    jnp.asarray(node["w"]).dtype)
            return new
        if isinstance(node, dict):
            return {k: walk(v) for k, v in node.items()}
        return node

    return walk(params)


def _lora_groups_flat(names: Iterable[str]) -> Dict[str, Dict[str, str]]:
    """Detect LoRA groups in FLAT leaf-path space: ``{prefix: {"w":
    path, "a": path, "b": path}}`` for every complete
    ``prefix/{w,lora_a,lora_b}`` triple."""
    groups: Dict[str, Dict[str, str]] = {}
    for n in names:
        head, _, tail = n.rpartition("/")
        key = {"w": "w", "lora_a": "a", "lora_b": "b"}.get(tail)
        if key is not None:
            groups.setdefault(head, {})[key] = n
    return {p: g for p, g in groups.items()
            if set(g) == {"w", "a", "b"}}


def fuse_flat_leaves(flat: Dict[str, np.ndarray], scale: float = 1.0,
                     adapters: Optional[Dict[str, Tuple[np.ndarray,
                                                        np.ndarray]]]
                     = None) -> Dict[str, np.ndarray]:
    """Host-side fusion over published flat leaves: every in-tree LoRA
    group's ``w`` is replaced by its fused form (adapter leaves stay —
    the serving tree structurally matches the training tree), and every
    EXTERNAL adapter (``{leaf_path: (a, b)}`` — hybrid-level adapters
    that are not part of the param tree) fuses into its named leaf."""
    out = dict(flat)
    for prefix, g in _lora_groups_flat(flat).items():
        out[g["w"]] = _fused_w(flat[g["w"]], flat[g["a"]],
                               flat[g["b"]], scale)
    for name, (a, b) in (adapters or {}).items():
        if name not in out:
            raise ValueError(
                f"external LoRA adapter targets unknown leaf {name!r}")
        out[name] = _fused_w(out[name], a, b, scale)
    return out


# ---------------------------------------------------------------------------
# Weight publication (training side of serve/weights.py)
# ---------------------------------------------------------------------------
class WeightPublication:
    """One publication's payloads: ``full`` is the fp32 chunked payload
    every ingest path accepts; ``delta`` (when the publisher tracked a
    base) is the block-quantized int8 delta vs ``base_version`` — ~4x
    fewer wire bytes for the same version. ``router.push_weights``
    accepts this object directly and negotiates delta-vs-full per
    replica."""

    __slots__ = ("full", "delta", "version", "base_version")

    def __init__(self, full: List[bytes], delta: Optional[List[bytes]],
                 version: int, base_version: Optional[int]):
        self.full = full
        self.delta = delta
        self.version = int(version)
        self.base_version = (None if base_version is None
                             else int(base_version))

    @property
    def full_bytes(self) -> int:
        return sum(len(p) for p in self.full)

    @property
    def delta_bytes(self) -> Optional[int]:
        return (None if self.delta is None
                else sum(len(p) for p in self.delta))

    @property
    def wire_ratio(self) -> Optional[float]:
        """full fp32 bytes / delta bytes — the delta's wire win."""
        if self.delta is None:
            return None
        return self.full_bytes / max(self.delta_bytes, 1)


class WeightPublisher:
    """Versioned snapshots of a training engine's live params.

    ``source`` is a :class:`~.engine.DeepSpeedTpuEngine` (gathered
    bucket-by-bucket through ``consolidated_param_buckets``) or any
    params pytree (tests, externally-held weights). Each
    :meth:`snapshot` bumps the version and returns the chunked payload
    (``[header, chunk...]`` — serve/weights.py) that
    ``ServingEngine.apply_weights`` / ``router.push_weights`` ingest.
    """

    def __init__(self, source, bucket_bytes: int = 16 << 20,
                 lora_scale: float = 1.0, track_deltas: bool = True,
                 delta_quant: str = "int8", delta_block: int = 2048):
        self.source = source
        self.bucket_bytes = max(int(bucket_bytes), 1)
        self.lora_scale = float(lora_scale)
        self.version = 0
        # error-feedback reference (EQuARX across-push discipline):
        # the RECEIVERS' bit-exact reconstruction of the last tracked
        # publication, so delta_{k+1} = current - ref_k folds the
        # residual the k-th quantization introduced back onto the
        # wire. Costs one fp32 host copy of the model while tracking.
        self.track_deltas = bool(track_deltas)
        self.delta_quant = str(delta_quant)
        self.delta_block = int(delta_block)
        self._delta_ref: Optional[Dict[str, np.ndarray]] = None
        self._delta_ref_version: Optional[int] = None
        from ..telemetry import get_registry
        reg = get_registry()
        self._m_publishes = reg.counter(
            "training_weight_publishes_total",
            "weight snapshots published by the training engine")
        self._m_publish_time = reg.histogram(
            "training_weight_publish_seconds",
            "gather + serialize time of one weight publication",
            unit="s", buckets=(1e-2, 0.1, 1.0, 10.0, 60.0, 600.0))
        self._m_publish_bytes = reg.counter(
            "training_weight_publish_bytes_total",
            "serialized weight-payload bytes published", unit="bytes")
        self._m_version = reg.gauge(
            "training_weight_version",
            "version of the newest published weight snapshot")
        self._m_delta_publishes = reg.counter(
            "weight_delta_publishes_total",
            "publications that emitted a quantized delta payload")
        self._m_delta_bytes = reg.counter(
            "weight_delta_bytes_total",
            "serialized delta-payload bytes published", unit="bytes")
        self._m_delta_ratio = reg.gauge(
            "weight_delta_wire_ratio",
            "full fp32 payload bytes / delta payload bytes of the "
            "newest delta publication (the wire win)")
        self._m_delta_residual = reg.gauge(
            "weight_delta_residual_norm",
            "l2 norm of the publisher-side error-feedback residual "
            "(live params minus the receivers' reconstruction) after "
            "the newest delta publication")

    @property
    def delta_ref_version(self) -> Optional[int]:
        """Version of the error-feedback reference — the
        ``delta_base`` the next :meth:`publish` can delta against
        (None until a tracked publication)."""
        return self._delta_ref_version

    def _iter_buckets(self) -> Iterable[Dict[str, np.ndarray]]:
        src = self.source
        if hasattr(src, "consolidated_param_buckets"):
            yield from src.consolidated_param_buckets(self.bucket_bytes)
            return
        from ..inference.v2.serve import weights as serve_weights
        items, _ = serve_weights.flatten_params(src)
        for names in serve_weights.plan_buckets(items,
                                                self.bucket_bytes):
            leaves = dict(items)
            yield {n: serve_weights.fetch_leaf(leaves[n]) for n in names}

    def snapshot(self, fuse_lora: bool = False,
                 lora_scale: Optional[float] = None,
                 adapters: Optional[Dict[str, Tuple[np.ndarray,
                                                    np.ndarray]]] = None
                 ) -> List[bytes]:
        """Gather + serialize one publication; returns the payload.

        ``fuse_lora=True`` (or external ``adapters``) fuses adapters
        into their base weights on the gathered HOST leaves — the live
        training params are never modified, so there is nothing to
        unfuse and the training executable cannot respecialize.

        Streams bucket-by-bucket without materializing the whole
        model, so it cannot maintain the delta error-feedback
        reference — snapshotting INVALIDATES it (the next
        :meth:`publish` re-anchors with a full-tracking publication).
        """
        from ..inference.v2.serve import weights as serve_weights
        from ..telemetry import recorder as flight
        t0 = time.perf_counter()
        self.version += 1
        self._delta_ref = None
        self._delta_ref_version = None
        scale = self.lora_scale if lora_scale is None else float(
            lora_scale)
        if fuse_lora or adapters:
            # fusion needs whole groups (and external adapters their
            # target leaf), so the fused publication stages the full
            # flat map before chunking
            flat: Dict[str, np.ndarray] = {}
            for group in self._iter_buckets():
                flat.update(group)
            fused = fuse_flat_leaves(flat, scale, adapters)
            items = list(fused.items())
            buckets = serve_weights.plan_buckets(items,
                                                 self.bucket_bytes)
            groups = ({n: fused[n] for n in names} for names in buckets)
            payloads = serve_weights.chunk_weight_leaves(
                groups, self.version)
        else:
            payloads = serve_weights.chunk_weight_leaves(
                self._iter_buckets(), self.version)
        dt = time.perf_counter() - t0
        nbytes = serve_weights.payload_bytes(payloads)
        self._m_publishes.inc()
        self._m_publish_time.observe(dt)
        self._m_publish_bytes.inc(nbytes)
        self._m_version.set(self.version)
        flight.record("weight_publish", version=self.version,
                      bytes=nbytes, chunks=len(payloads) - 1,
                      fused=bool(fuse_lora or adapters),
                      dur_s=round(dt, 4))
        return payloads

    def publish(self, delta_base: Optional[int] = None,
                quant: Optional[str] = None,
                block: Optional[int] = None, fuse_lora: bool = False,
                lora_scale: Optional[float] = None,
                adapters: Optional[Dict[str, Tuple[np.ndarray,
                                                   np.ndarray]]] = None
                ) -> WeightPublication:
        """Delta-aware publication: one gather produces the full fp32
        payload AND (when ``delta_base`` names the error-feedback
        reference version) the block-quantized int8 delta against it.

        The reference tracks the RECEIVERS' bit-exact reconstruction,
        so the residual each quantization introduces is folded into
        the next delta (EQuARX error feedback) — successive deltas
        cannot drift. ``delta_base`` mismatching the reference fails
        typed (the caller should publish full — ``delta_base=None`` —
        to re-anchor). With ``track_deltas`` off this is a plain full
        publication returning ``delta=None``."""
        from ..inference.v2.serve import weights as serve_weights
        from ..telemetry import recorder as flight
        t0 = time.perf_counter()
        scale = self.lora_scale if lora_scale is None else float(
            lora_scale)
        quant = self.delta_quant if quant is None else str(quant)
        block = self.delta_block if block is None else int(block)
        if delta_base is not None:
            if not self.track_deltas:
                raise ValueError(
                    "delta_base given but this publisher has "
                    "track_deltas disabled")
            if self._delta_ref is None \
                    or int(delta_base) != self._delta_ref_version:
                raise ValueError(
                    f"delta_base={int(delta_base)} does not match the "
                    f"publisher's error-feedback reference version "
                    f"{self._delta_ref_version}; publish full "
                    f"(delta_base=None) to re-anchor")
        flat: Dict[str, np.ndarray] = {}
        for group in self._iter_buckets():
            flat.update(group)
        if fuse_lora or adapters:
            flat = fuse_flat_leaves(flat, scale, adapters)
        flat = {n: np.ascontiguousarray(np.asarray(v, np.float32))
                for n, v in flat.items()}
        self.version += 1
        items = list(flat.items())
        buckets = serve_weights.plan_buckets(items, self.bucket_bytes)
        full = serve_weights.chunk_weight_leaves(
            ({n: flat[n] for n in names} for names in buckets),
            self.version)
        delta = None
        residual = None
        if delta_base is not None:
            delta, recon = serve_weights.chunk_weight_deltas(
                flat, self._delta_ref, self.version, int(delta_base),
                quant=quant, block=block,
                bucket_bytes=self.bucket_bytes)
            self._delta_ref = recon
            self._delta_ref_version = self.version
            residual = float(np.sqrt(sum(
                float(np.sum((flat[n] - recon[n]).astype(np.float64)
                             ** 2)) for n in flat)))
            self._m_delta_publishes.inc()
        elif self.track_deltas:
            # full-tracking publish: receivers applying this payload
            # hold exactly these bits — the next delta's base. The ref
            # must OWN its bytes: gathered leaves can alias live host
            # params, and a ref that drifts with them would diff to
            # zero forever
            self._delta_ref = {n: np.array(v, np.float32)
                               for n, v in flat.items()}
            self._delta_ref_version = self.version
        pub = WeightPublication(full, delta, self.version,
                                None if delta is None
                                else int(delta_base))
        dt = time.perf_counter() - t0
        self._m_publishes.inc()
        self._m_publish_time.observe(dt)
        self._m_publish_bytes.inc(pub.full_bytes)
        self._m_version.set(self.version)
        if delta is not None:
            self._m_delta_bytes.inc(pub.delta_bytes)
            self._m_delta_ratio.set(pub.wire_ratio)
            self._m_delta_residual.set(residual)
        flight.record("weight_publish", version=self.version,
                      bytes=pub.full_bytes, chunks=len(full) - 1,
                      fused=bool(fuse_lora or adapters),
                      delta_bytes=pub.delta_bytes,
                      delta_base=pub.base_version,
                      dur_s=round(dt, 4))
        return pub


# ---------------------------------------------------------------------------
# Rollouts (serving -> training direction of the seam)
# ---------------------------------------------------------------------------
class RolloutSample:
    """One generated rollout: the RLHF actor-loop unit.

    ``reward`` is filled by the actor loop's reward hook AFTER
    generation (a scalar sequence reward, or a per-generated-token
    list); ``done`` marks the episode finished at the sequence end
    (GAE bootstraps a zero value past a done step). Queue and loop
    share the same object, so a hook's mutation is visible to the
    learner that pops it."""

    __slots__ = ("prompt", "tokens", "logprobs", "weight_version",
                 "seed", "reward", "done")

    def __init__(self, prompt: List[int], tokens: List[int],
                 logprobs: List[float], weight_version: int,
                 seed: Optional[int], reward=None, done: bool = True):
        self.prompt = prompt
        self.tokens = tokens
        self.logprobs = logprobs
        self.weight_version = weight_version
        self.seed = seed
        self.reward = reward
        self.done = bool(done)


class RolloutQueue:
    """Bounded rollout->training queue: oldest samples drop (counted)
    when the learner falls behind — host memory never grows unboundedly
    behind a slow train step."""

    def __init__(self, maxlen: int = 64):
        import collections
        import threading
        self.maxlen = max(int(maxlen), 1)
        self._q: "collections.deque" = collections.deque()
        self._lock = threading.Lock()
        self._depth = 0
        from ..telemetry import get_registry
        reg = get_registry()
        self._m_depth = reg.gauge(
            "hybrid_rollout_queue_depth",
            "rollouts waiting in the bounded training queue")
        self._m_dropped = reg.counter(
            "hybrid_rollout_queue_dropped_total",
            "rollouts dropped oldest-first because the bounded queue "
            "was full (the learner fell behind the actor)")

    def _set_depth(self, n: int) -> None:
        # the gauge path: every mutation already publishes the depth
        # here, so `depth` below reads it lock-free
        self._depth = n
        self._m_depth.set(n)

    @property
    def depth(self) -> int:
        """Lock-free depth (the last value the gauge path published).
        The learner's backpressure check polls this from the train
        thread without contending the push/pop lock; ``len(queue)``
        remains the locked exact read."""
        return self._depth

    def push(self, sample: RolloutSample) -> None:
        with self._lock:
            self._q.append(sample)
            while len(self._q) > self.maxlen:
                self._q.popleft()
                self._m_dropped.inc()
            self._set_depth(len(self._q))

    def pop(self, n: int = 1) -> List[RolloutSample]:
        """Up to ``n`` oldest samples (the next training micro-batch)."""
        out: List[RolloutSample] = []
        with self._lock:
            while self._q and len(out) < n:
                out.append(self._q.popleft())
            self._set_depth(len(self._q))
        return out

    def __len__(self) -> int:
        with self._lock:
            return len(self._q)


def _host_logprob(logits: np.ndarray, token: int) -> float:
    """log softmax(logits)[token], numerically stable host math — the
    per-token policy logprob the rollout records."""
    x = np.asarray(logits, np.float32)
    m = float(x.max())
    return float(x[token] - (m + np.log(np.exp(x - m).sum())))


# ---------------------------------------------------------------------------
# The hybrid engine
# ---------------------------------------------------------------------------
class DeepSpeedHybridEngine(DeepSpeedTpuEngine):
    """Training engine + colocated paged serving engine on published
    weights (module docstring). Built by ``deepspeed_tpu.initialize``
    when the config has ``hybrid_engine.enabled``."""

    def __init__(self, *args, lora_scale: float = 1.0,
                 serving_model=None, **kwargs):
        super().__init__(*args, **kwargs)
        hy = self.config.hybrid_engine
        self.lora_scale = float(lora_scale)
        # external adapters ({flat leaf path: (lora_a, lora_b)} host
        # arrays): fused into the named leaves at publish time
        self.lora_adapters: Dict[str, Tuple[np.ndarray, np.ndarray]] = {}
        self.publisher = WeightPublisher(
            self, bucket_bytes=hy.publish_bucket_bytes,
            lora_scale=lora_scale,
            track_deltas=hy.delta_publish,
            delta_quant=hy.delta_quant,
            delta_block=hy.delta_block)
        self.rollout_queue = RolloutQueue(hy.rollout_queue_size)
        self._serving_model = serving_model
        self._serving = None
        self._published_at: Optional[Tuple[int, int]] = None
        self._rollout_uid = 1 << 20   # clear of serving-runtime uids
        self.latency_stats = {"generate_calls": 0,
                              "generate_seconds": 0.0,
                              "generated_tokens": 0}
        from ..telemetry import get_registry
        reg = get_registry()
        self._m_rollouts = reg.counter(
            "hybrid_rollouts_total",
            "rollouts generated by the hybrid engine's serving half")
        self._m_rollout_tokens = reg.counter(
            "hybrid_rollout_tokens_total",
            "tokens generated across hybrid rollouts")
        log_dist("hybrid engine ready (train step + paged serving on "
                 "published weights)", ranks=[0])

    # -- the colocated serving engine -----------------------------------
    @property
    def weight_version(self) -> int:
        return self.publisher.version

    @property
    def serving_engine(self):
        """The colocated :class:`InferenceEngineV2` (built on first
        use, always serving the newest publication)."""
        self._ensure_current()
        return self._serving

    def _serving_spec(self) -> Dict[str, Dict[str, Any]]:
        cfg = getattr(self.model, "cfg", None)
        assert cfg is not None, \
            "hybrid engine needs an inference/v2-capable model (a " \
            "TransformerLM-style .cfg); pass serving_model= for " \
            "custom models"
        overrides = dict(self.config.hybrid_engine.serving or {})
        sm = {"max_tracked_sequences": 8,
              "max_seq_len": int(cfg.max_seq_len), "block_size": 16}
        sm["num_blocks"] = (sm["max_tracked_sequences"]
                            * -(-sm["max_seq_len"] // sm["block_size"])
                            + 1)
        sm.update(overrides.get("state_manager", {}))
        eng = {"dtype": self.ds_config.precision_dtype,
               "prefill_bucket": 16}
        eng.update(overrides.get("engine", {}))
        return {"state_manager": sm, "engine": eng}

    def _build_serving(self, payloads: List[bytes]):
        import jax

        from ..inference.v2 import (InferenceEngineV2,
                                    RaggedInferenceEngineConfig)
        from ..inference.v2.config_v2 import DSStateManagerConfig
        from ..inference.v2.serve import weights as serve_weights
        spec = self._serving_spec()
        model = self._serving_model
        if model is None:
            # a FRESH model instance: the serving engine binds its own
            # (tp=1, ep=1) topology — sharing the training model object
            # would clobber the train mesh topology it carries
            model = type(self.model)(self.model.cfg)
        stager = serve_weights.stage_payload(payloads)
        shapes = jax.eval_shape(model.init_params,
                                jax.random.PRNGKey(0))
        host_tree = serve_weights.flat_to_tree(shapes, stager.leaves)
        engine = InferenceEngineV2(
            model, RaggedInferenceEngineConfig(
                state_manager=DSStateManagerConfig(
                    **spec["state_manager"]),
                **spec["engine"]),
            params=host_tree)
        engine.weight_version = stager.version
        # the freshly-built colocated engine retains its payload's fp32
        # leaves as the delta base, same as a swap would
        serve_weights.set_delta_base(engine, stager.leaves)
        return engine

    def _ensure_current(self) -> None:
        """Publish-on-demand: the serving engine always generates with
        the CURRENT training weights (the reference generate()
        contract) — stale publications re-publish, missing serving
        engines build from the newest payload."""
        stamp = (self.global_steps, self.micro_steps)
        if self._published_at != stamp or self._serving is None:
            self.publish()

    # -- publication -----------------------------------------------------
    def has_lora(self) -> bool:
        found: List[bool] = []

        def walk(node):
            if _is_lora_group(node):
                found.append(True)
            elif isinstance(node, dict):
                for v in node.values():
                    walk(v)

        walk(self.params if isinstance(self.params, dict) else {})
        return bool(found) or bool(self.lora_adapters)

    def publish(self, fuse_lora: Optional[bool] = None) -> List[bytes]:
        """Snapshot the live training params into a versioned payload,
        install it on the colocated serving engine (atomic swap — zero
        recompiles), and return it for fleet distribution
        (``router.push_weights``). ``fuse_lora`` defaults to auto:
        fused whenever the params carry LoRA groups or external
        adapters are attached."""
        from ..inference.v2.serve import weights as serve_weights
        if fuse_lora is None:
            fuse_lora = self.has_lora()
        payloads = self.publisher.snapshot(
            fuse_lora=fuse_lora,
            adapters=(self.lora_adapters or None) if fuse_lora
            else None)
        self._published_at = (self.global_steps, self.micro_steps)
        # the payload is NOT retained here (a fp32 serialized copy of
        # the whole model would double host footprint): the serving
        # engine holds the installed weights, the caller holds the
        # returned payload for fleet distribution, and the router
        # caches its own copy for scale-up sync
        if self._serving is None:
            self._serving = self._build_serving(payloads)
        else:
            serve_weights.apply_payload(self._serving, payloads)
        return payloads

    def publish_delta(self, fuse_lora: Optional[bool] = None,
                      quant: Optional[str] = None,
                      block: Optional[int] = None
                      ) -> WeightPublication:
        """Delta-aware publication (the RLHF publish-every-N path):
        one gather emits the full payload AND — once a tracked base
        exists — the block-quantized int8 delta against it. The
        colocated serving engine ingests the DELTA when available, so
        its weights stay bit-identical to every fleet replica
        following the delta chain; the returned
        :class:`WeightPublication` goes to ``router.push_weights``
        which negotiates delta-vs-full per replica."""
        from ..inference.v2.serve import weights as serve_weights
        if fuse_lora is None:
            fuse_lora = self.has_lora()
        pub = self.publisher.publish(
            delta_base=self.publisher.delta_ref_version,
            quant=quant, block=block, fuse_lora=fuse_lora,
            adapters=(self.lora_adapters or None) if fuse_lora
            else None)
        self._published_at = (self.global_steps, self.micro_steps)
        if self._serving is None:
            self._serving = self._build_serving(pub.full)
        else:
            serve_weights.apply_payload(
                self._serving,
                pub.delta if pub.delta is not None else pub.full)
        return pub

    def publish_adapter(self, name: str, adapters=None,
                        scale: Optional[float] = None) -> List[bytes]:
        """Package a freshly trained LoRA adapter as a versioned
        ADAPTER payload (serve/weights.chunk_adapter_payload) riding
        the same publish path as full weights: install it into the
        colocated serving engine's bank (when one is enabled) and
        return the payload for fleet distribution —
        ``router.push_weights`` routes it to ``push_adapter``
        automatically. ``adapters`` defaults to the attached external
        adapters (``self.lora_adapters``, the
        ``{path: (lora_a, lora_b)}`` convention ``fuse_flat_leaves``
        and ``engine.load_adapter`` share); ``scale`` defaults to the
        engine's ``lora_scale``. Unlike ``publish()`` this does NOT
        fuse into the base weights — the adapter stays a bank-slot
        delta, servable next to other tenants' adapters."""
        from ..inference.v2.serve import weights as serve_weights
        if adapters is None:
            adapters = dict(self.lora_adapters)
        if not adapters:
            raise ValueError(
                "no adapter leaves to publish: pass adapters= or "
                "attach external adapters first")
        if scale is None:
            scale = self.lora_scale
        self.publisher.version += 1
        payloads = serve_weights.chunk_adapter_payload(
            name, adapters, self.publisher.version,
            scale=float(scale))
        if (self._serving is not None
                and getattr(self._serving, "lora_bank", None)
                is not None):
            serve_weights.apply_payload(self._serving, payloads)
        return payloads

    # -- generation (reference hybrid_engine.generate :174) -------------
    def generate(self, input_ids, max_new_tokens: int = 32,
                 temperature: float = 0.0, top_k: int = 0,
                 top_p: float = 0.0,
                 eos_token_id: Optional[int] = None, seed: int = 0,
                 **_kw) -> np.ndarray:
        """Generate with the CURRENT training weights through the paged
        serving engine (engine_v2 — fused decode windows, paged KV,
        device-side sampling), returning ``[B, prompt+new]`` ids.
        Re-publishes automatically after training steps; repeated calls
        at one version never recompile (the swap preserves every
        executable signature)."""
        self._ensure_current()
        ids = np.asarray(input_ids)
        if ids.ndim == 1:
            ids = ids[None]
        t0 = time.perf_counter()
        outs = self._serving.generate(
            [list(map(int, row)) for row in ids],
            max_new_tokens=int(max_new_tokens),
            eos_token_id=eos_token_id,
            temperature=float(temperature),
            top_p=float(top_p) if top_p > 0 else 1.0,
            top_k=int(top_k), seed=int(seed))
        dt = time.perf_counter() - t0
        width = ids.shape[1] + int(max_new_tokens)
        pad = eos_token_id if eos_token_id is not None else 0
        full = np.full((len(outs), width), pad, ids.dtype)
        generated = 0
        for i, row in enumerate(outs):
            row = np.asarray(row)[:width]
            full[i, :len(row)] = row
            generated += len(row) - ids.shape[1]
        self.latency_stats["generate_calls"] += 1
        self.latency_stats["generate_seconds"] += dt
        self.latency_stats["generated_tokens"] += int(generated)
        return full

    # -- rollouts (serving -> training) ----------------------------------
    def rollout(self, prompts: Sequence[Sequence[int]],
                max_new_tokens: int = 32, temperature: float = 0.0,
                top_p: float = 1.0, top_k: int = 0,
                seed: Optional[int] = 0,
                eos_token_id: Optional[int] = None,
                enqueue: bool = True,
                allow_stale: bool = False) -> List[RolloutSample]:
        """Generate rollouts and feed the bounded training queue.

        Tokens come from the serving engine's ``put()`` logits sampled
        with ``sampling.host_sample`` under a per-prompt
        ``np.random.default_rng`` — EXACTLY the SplitFuse scheduler's
        draw discipline, so a rollout's stream is bit-identical to the
        same request served through the async runtime (parity-pinned).
        Per-token logprobs are the policy log-softmax of each sampled
        token, computed from the same logits that sampled it.

        ``allow_stale=True`` skips the publish-on-demand republish and
        acts on the last PUBLISHED weights even if train steps ran
        since — the actor-learner loop's publish-every-N cadence
        (samples carry ``weight_version`` so the learner's staleness
        telemetry measures the gap)."""
        from ..inference.v2.sampling import host_sample
        if not (allow_stale and self._serving is not None):
            self._ensure_current()
        eng = self._serving
        samples: List[RolloutSample] = []
        for row_i, prompt in enumerate(prompts):
            prompt = list(map(int, prompt))
            row_seed = None if seed is None else int(seed) + row_i
            rng = np.random.default_rng(row_seed)
            uid = self._rollout_uid
            self._rollout_uid += 1
            toks: List[int] = []
            lps: List[float] = []
            logits = np.asarray(
                eng.put([uid], [np.asarray(prompt, np.int64)])[0],
                np.float32)
            try:
                for i in range(int(max_new_tokens)):
                    tok = int(host_sample(logits, rng, temperature,
                                          top_p, top_k))
                    toks.append(tok)
                    lps.append(_host_logprob(logits, tok))
                    if eos_token_id is not None and tok == eos_token_id:
                        break
                    if i + 1 < int(max_new_tokens):
                        logits = np.asarray(
                            eng.put([uid], [[tok]])[0], np.float32)
            finally:
                eng.flush(uid)
            sample = RolloutSample(prompt, toks, lps,
                                   self.weight_version, row_seed)
            samples.append(sample)
            if enqueue:
                self.rollout_queue.push(sample)
            self._m_rollouts.inc()
            self._m_rollout_tokens.inc(len(toks))
        return samples

    # -- misc ------------------------------------------------------------
    def attach_lora_adapter(self, leaf_path: str, lora_a, lora_b) -> None:
        """Register an external adapter for ``leaf_path`` (a flat param
        path — see serve/weights.py ``flatten_params``): publication
        fuses it into that leaf (``publish(fuse_lora=True)`` or auto)."""
        self.lora_adapters[str(leaf_path)] = (
            np.asarray(lora_a, np.float32),
            np.asarray(lora_b, np.float32))
        # adapters change the published weights: the next generate()
        # must republish even though no train step ran
        self._published_at = None

    def eval(self):
        return self

    def train(self, mode: bool = True):
        return self
