"""Hybrid engine: one set of weights for RLHF training AND fast generation.

TPU-native analogue of the reference's DeepSpeedHybridEngine
(runtime/hybrid_engine.py:32; generate :174, _zero3_forward :363, LoRA
fuse/unfuse :118-160). The reference swaps module containers and gathers
ZeRO-3 params into inference kernels before each generate; in JAX the same
arrays back both paths for free — ``generate`` jits the KV-cache decode loop
directly over the TRAINING params with their live shardings (XLA inserts the
ZeRO-3 gathers where needed), and the actor's train_batch/step is inherited
unchanged. LoRA adapters fuse into the base weights for generation and
unfuse afterwards (pure tree transforms, no copies kept).
"""

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..inference.engine import generate_tokens
from ..utils.logging import log_dist
from ..utils.timer import SynchronizedWallClockTimer
from .engine import DeepSpeedTpuEngine


# ---------------------------------------------------------------------------
# LoRA fuse/unfuse (reference hybrid_engine.py _fuse_lora/_unfuse_lora):
# any subtree shaped {"w": [in, out], "lora_a": [in, r], "lora_b": [r, out]}
# fuses to w' = w + scale * (a @ b).
# ---------------------------------------------------------------------------
def _is_lora_group(node) -> bool:
    return (isinstance(node, dict) and "w" in node and "lora_a" in node
            and "lora_b" in node)


def fuse_lora(params, scale: float = 1.0):
    def walk(node):
        if _is_lora_group(node):
            new = dict(node)
            new["w"] = node["w"] + scale * (
                node["lora_a"] @ node["lora_b"]).astype(node["w"].dtype)
            return new
        if isinstance(node, dict):
            return {k: walk(v) for k, v in node.items()}
        return node

    return walk(params)


def unfuse_lora(params, scale: float = 1.0):
    return fuse_lora(params, -scale)


class DeepSpeedHybridEngine(DeepSpeedTpuEngine):
    """Training engine + inference-speed generate on shared weights."""

    def __init__(self, *args, lora_scale: float = 1.0, **kwargs):
        super().__init__(*args, **kwargs)
        assert hasattr(self.model, "forward_cached") and \
            hasattr(self.model, "init_kv_cache"), \
            "hybrid engine needs a model with a KV-cache decode path " \
            "(forward_cached/init_kv_cache)"
        self.lora_scale = lora_scale
        self._gen_jit_cache: Dict[Any, Any] = {}
        self._gen_timer = SynchronizedWallClockTimer()
        self.latency_stats = {"generate_calls": 0, "generate_seconds": 0.0,
                              "generated_tokens": 0}
        log_dist("hybrid engine ready (shared train/generate weights)",
                 ranks=[0])

    def _has_lora(self) -> bool:
        found = []

        def walk(node):
            if _is_lora_group(node):
                found.append(True)
            elif isinstance(node, dict):
                for v in node.values():
                    walk(v)

        walk(self.params)
        return bool(found)

    def generate(self, input_ids, max_new_tokens: int = 32,
                 temperature: float = 0.0, top_k: int = 0, top_p: float = 0.0,
                 eos_token_id: Optional[int] = None, seed: int = 0,
                 **_kw) -> np.ndarray:
        """Reference hybrid_engine.generate (:174): runs generation with the
        CURRENT training weights (post-update actor), returning
        [B, prompt+new] ids."""
        ids = np.asarray(input_ids)
        if ids.ndim == 1:
            ids = ids[None]
        eos = -1 if eos_token_id is None else int(eos_token_id)
        key = (ids.shape, int(max_new_tokens), float(temperature),
               int(top_k), float(top_p), eos, self._has_lora())
        if key not in self._gen_jit_cache:
            fuse = self._has_lora()
            scale = self.lora_scale
            model, dtype = self.model, self.compute_dtype

            def gen(params, ids, rng):
                if fuse:  # fuse adapters for the decode loop only
                    params = fuse_lora(params, scale)
                return generate_tokens(
                    model, params, ids, rng, dtype,
                    max_new_tokens=int(max_new_tokens),
                    temperature=float(temperature), top_k=int(top_k),
                    top_p=float(top_p), eos=eos)

            self._gen_jit_cache[key] = jax.jit(gen)
        self._gen_timer("generate").start()
        toks = self._gen_jit_cache[key](
            self.params, jnp.asarray(ids), jax.random.PRNGKey(seed))
        toks = np.asarray(jax.block_until_ready(toks))
        self._gen_timer("generate").stop()
        self.latency_stats["generate_calls"] += 1
        self.latency_stats["generate_seconds"] += \
            self._gen_timer("generate").elapsed(reset=True)
        self.latency_stats["generated_tokens"] += int(toks.size)
        return np.concatenate([ids, toks], axis=1)

    def eval(self):
        return self

    def train(self, mode: bool = True):
        return self
