"""Activation checkpointing (rematerialization).

TPU-native analogue of the reference's Megatron-compatible activation
checkpointing (runtime/activation_checkpointing/checkpointing.py:
CheckpointFunction :477, partition_activations :369, non_reentrant_checkpoint
:711, configure :1057, CudaRNGStatesTracker :122).

The torch implementation re-runs the forward in backward by saving/restoring
RNG states and manually partitioning/offloading saved tensors. Under XLA all
of that collapses into ``jax.checkpoint``:

  * recompute-in-backward  -> jax.checkpoint(fn, policy)
  * partition_activations  -> free: a saved residual keeps whatever sharding
    it has; activations computed under sequence/tensor sharding are saved as
    shards, which is what the reference's scatter-to-mp-group does by hand
  * cpu_checkpointing      -> offload policy ("device" -> "pinned_host"
    memory space), the reference's copy_to_main_memory path
  * RNG tracking           -> functional jax PRNG keys; the tracker below is
    an API shim for Megatron-style callers

``configure`` accepts the same config block as the reference (engine wires
``activation_checkpointing`` from the JSON config), plus a TPU-native
``policy`` knob naming any jax.checkpoint_policies entry for selective
checkpointing (e.g. "dots_saveable" to keep matmul outputs).
"""

from typing import Any, Callable, Dict, Optional

import jax

_config: Dict[str, Any] = {
    "partition_activations": False,
    "cpu_checkpointing": False,
    "contiguous_memory_optimization": False,
    "number_checkpoints": None,
    "synchronize_checkpoint_boundary": False,
    "profile": False,
    "policy": "nothing_saveable",
}
_configured = False


def _resolve_policy(name: str, cpu_checkpointing: bool = False):
    if cpu_checkpointing:
        # save matmul outputs to host memory instead of recomputing or
        # keeping them in HBM (reference checkpoint_in_cpu / copy_to_main_memory)
        return jax.checkpoint_policies.offload_dot_with_no_batch_dims(
            "device", "pinned_host")
    if name == "save_attn":
        # keep attention outputs (tagged checkpoint_name("attn_out") in the
        # model): dots_with_no_batch_dims skips them (attention einsums have
        # batch dims, and the Pallas flash call is opaque to dot policies),
        # so without the tag the whole attention fwd re-runs in backward
        return jax.checkpoint_policies.save_only_these_names("attn_out")
    if name == "save_dots_and_attn":
        return jax.checkpoint_policies.save_from_both_policies(
            jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
            jax.checkpoint_policies.save_only_these_names("attn_out"))
    policy = getattr(jax.checkpoint_policies, name, None)
    if policy is None:
        raise ValueError(
            f"unknown activation-checkpointing policy '{name}'; options: "
            f"save_attn, save_dots_and_attn, "
            f"{[p for p in dir(jax.checkpoint_policies) if not p.startswith('_')]}")
    return policy


def configure(mpu_=None, deepspeed_config=None, partition_activations=None,
              contiguous_checkpointing=None, num_checkpoints=None,
              checkpoint_in_cpu=None, synchronize=None, profile=None,
              policy=None):
    """Reference configure() signature (checkpointing.py:1057); also accepts
    the ActivationCheckpointingConfig dataclass via deepspeed_config."""
    global _configured
    if deepspeed_config is not None:
        ac = getattr(deepspeed_config, "activation_checkpointing",
                     deepspeed_config)
        _config.update(
            partition_activations=ac.partition_activations,
            cpu_checkpointing=ac.cpu_checkpointing,
            contiguous_memory_optimization=ac.contiguous_memory_optimization,
            number_checkpoints=ac.number_checkpoints,
            synchronize_checkpoint_boundary=ac.synchronize_checkpoint_boundary,
            profile=ac.profile,
            policy=ac.policy,
        )
    overrides = {
        "partition_activations": partition_activations,
        "contiguous_memory_optimization": contiguous_checkpointing,
        "number_checkpoints": num_checkpoints,
        "cpu_checkpointing": checkpoint_in_cpu,
        "synchronize_checkpoint_boundary": synchronize,
        "profile": profile,
        "policy": policy,
    }
    _config.update({k: v for k, v in overrides.items() if v is not None})
    _configured = True


def is_configured() -> bool:
    return _configured


def get_config() -> Dict[str, Any]:
    return dict(_config)


def active_policy():
    return _resolve_policy(_config["policy"], _config["cpu_checkpointing"])


def checkpoint(function: Callable, *args, policy_name: Optional[str] = None):
    """Megatron-compatible entry (reference CheckpointFunction.apply,
    checkpointing.py:477): checkpoint `function(*args)`, recomputing its
    activations in backward according to the configured policy."""
    pol = (_resolve_policy(policy_name) if policy_name is not None
           else active_policy())
    return jax.checkpoint(function, policy=pol)(*args)


def checkpoint_wrapper(function: Callable,
                       policy_name: Optional[str] = None) -> Callable:
    """Wrap once, call many times (what models use around a layer body)."""
    pol = (_resolve_policy(policy_name) if policy_name is not None
           else active_policy())
    return jax.checkpoint(function, policy=pol)


# the non-reentrant path is the only path under XLA (no autograd reentry)
non_reentrant_checkpoint = checkpoint


class RNGStatesTracker:
    """API shim for Megatron's CudaRNGStatesTracker (checkpointing.py:122).

    jax PRNG is functional, so "tracking states" is holding named keys and
    splitting deterministically; fork() returns a fresh key and advances the
    stored one, which is what the torch tracker's fork/restore achieves for
    reproducible dropout inside checkpointed regions.
    """

    def __init__(self):
        self._states: Dict[str, jax.Array] = {}

    def reset(self):
        self._states.clear()

    def get_states(self):
        return dict(self._states)

    def set_states(self, states):
        self._states = dict(states)

    def add(self, name: str, seed: int):
        if name in self._states:
            raise ValueError(f"rng state {name} already present")
        self._states[name] = jax.random.PRNGKey(seed)

    def fork(self, name: str = "model-parallel-rng"):
        if name not in self._states:
            raise KeyError(f"rng state {name} not added")
        self._states[name], sub = jax.random.split(self._states[name])
        return sub


_RNG_TRACKER = RNGStatesTracker()


def get_cuda_rng_tracker() -> RNGStatesTracker:  # reference-compat name
    return _RNG_TRACKER


def model_parallel_reconfigure_tp_seed(seed: int):
    """Reference model_parallel_reconfigure_tp_seed (checkpointing.py)."""
    _RNG_TRACKER.reset()
    _RNG_TRACKER.add("model-parallel-rng", seed)


def reset():
    """Testing hook: restore defaults."""
    global _configured
    _config.update(partition_activations=False, cpu_checkpointing=False,
                   contiguous_memory_optimization=False,
                   number_checkpoints=None,
                   synchronize_checkpoint_boundary=False, profile=False,
                   policy="nothing_saveable")
    _configured = False
    _RNG_TRACKER.reset()
