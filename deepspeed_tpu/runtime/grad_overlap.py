"""Overlapped bucketed gradient reduction.

The seed engine let GSPMD insert the data-parallel gradient reduction
wherever it liked — in practice one monolithic all-reduce/reduce-scatter
AFTER the full backward, fully exposed (BENCH_r05:
``exposed_collective_fraction: 1.0`` while the ZeRO-3 param gathers are 97%
overlapped). DeepCompile (arXiv:2504.09983) shows compiler-scheduled overlap
of exactly this collective is the dominant lever for distributed training
step time; the reference runtime buys the same overlap by hand with
bucketed reducers on a side stream (stage_1_and_2.py ``reduce_bucket_size``
ipg buckets, stage3.py:1135 ``__reduce_and_partition_ipg_grads``).

Here the training step instead *issues the reduction itself*, per bucket,
inside a ``shard_map`` over the data-parallel axes:

  * the gradient pytree is partitioned into size-capped **buckets**
    (``zero_optimization.reduce_bucket_size`` / ``allgather_bucket_size``,
    counted in elements like the reference), layer-ordered REVERSED so the
    buckets holding the last-produced grads (the loss-head end — backward
    emits those first) are ready, and reduce, first;
  * each bucket is ONE fused collective over a flat concatenation of its
    leaves — ``psum`` (grads that stay replicated: ZeRO-0/1) or a tiled
    ``reduce-scatter`` (ZeRO-2/3 dim-sharded grads), int8 all-to-all under
    ZeRO++ qgZ;
  * the last gradient-accumulation microbatch runs INLINE after the
    ``lax.scan`` over the first gas-1, so its per-layer backward is visible
    to XLA's latency-hiding scheduler alongside the bucket collectives —
    async collective fusion floats bucket k's reduce into the remaining
    backward and into bucket j's optimizer math instead of serializing the
    whole tree behind one fused reduce.

Numerics are bit-identical to a monolithic reduction by construction: a
bucket's collective computes exactly the same per-element cross-device sums
as one tree-wide collective (concatenation never mixes elements), and the
microbatch accumulation order is unchanged (scan over gas-1 then one inline
add is the same add sequence the full scan performs). Bucketing changes
*scheduling*, not math.

ZeRO-3 dim-sharded parameters are handled by ``make_zero3_gather``'s VJP
(the cotangent leaves the backward already reduce-scattered, per leaf, at
the exact point the reference's grad hooks would fire) — those leaves are
recorded on the plan as ``vjp`` and excluded from bucketing; only their
hpZ cross-group means and the replicated remainder ride buckets.
"""

from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ..comm.quantized import (all_to_all_quant_reduce, make_zero3_gather,
                              quant_wire_bytes, ring_all_gather_hier,
                              ring_all_gather_quant,
                              ring_reduce_scatter_hier,
                              ring_reduce_scatter_quant, shard_map_unchecked)

# leaf reduction categories
VJP = "vjp"                      # reduced by the stage-3 gather's VJP
REDUCE_SCATTER = "reduce_scatter"  # dim-sharded grad: bucketed reduce-scatter
ALL_REDUCE = "all_reduce"        # replicated grad: bucketed psum (mean)
CROSS_GROUP = "cross_group"      # hpZ: cross-group mean of a VJP-reduced leaf


@dataclass(frozen=True)
class GradUnit:
    """One reducible unit: a whole grad leaf, or one layer-slice of a
    stacked layer leaf (``layer >= 0`` — scanned models store layer params
    as ONE [L, ...] leaf; slicing restores per-layer granularity so a
    layer's bucket can reduce while earlier layers are still in backward).
    """

    leaf: int          # flat leaf index in the grad pytree
    layer: int         # -1 = whole leaf; else slice index along dim 0
    numel: int
    name: str
    kind: str


@dataclass(frozen=True)
class GradBucket:
    """One fused collective: the units (by position in plan.units) it
    carries."""

    kind: str
    indices: Tuple[int, ...]
    numel: int
    nbytes: int


@dataclass
class GradBucketPlan:
    """Static partition of the gradient pytree into collective buckets.

    The plan is pure Python config baked into the traced program: one
    program per layout (changing ``reduce_bucket_size`` retraces; repeated
    steps with the same layout reuse ONE executable).
    """

    buckets: Tuple[GradBucket, ...]
    units: Tuple[GradUnit, ...]
    vjp_leaves: Tuple[str, ...]
    reduce_bucket_numel: int
    allreduce_bucket_numel: int

    @property
    def num_buckets(self) -> int:
        return len(self.buckets)

    @property
    def max_bucket_bytes(self) -> int:
        return max((b.nbytes for b in self.buckets), default=0)

    @property
    def total_bucket_bytes(self) -> int:
        return sum(b.nbytes for b in self.buckets)

    def layout_key(self) -> Tuple:
        """Hashable identity of the traced collective layout."""
        return tuple(
            (b.kind, tuple((self.units[u].leaf, self.units[u].layer)
                           for u in b.indices))
            for b in self.buckets)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "reduce_bucket_size": self.reduce_bucket_numel,
            "allgather_bucket_size": self.allreduce_bucket_numel,
            "num_buckets": self.num_buckets,
            "max_bucket_bytes": self.max_bucket_bytes,
            "total_bucket_bytes": self.total_bucket_bytes,
            "vjp_leaves": list(self.vjp_leaves),
            "buckets": [{
                "kind": b.kind,
                "numel": b.numel,
                "bytes": b.nbytes,
                "leaves": [self.units[u].name for u in b.indices],
            } for b in self.buckets],
        }

    def summary(self) -> str:
        lines = [f"grad buckets: {self.num_buckets} "
                 f"(cap {self.reduce_bucket_numel} elems, "
                 f"largest {self.max_bucket_bytes / 2 ** 20:.1f} MiB)"]
        for b in self.buckets:
            lines.append(f"  [{b.kind:<14}] {b.numel:>10} elems x "
                         f"{len(b.indices)} units")
        if self.vjp_leaves:
            lines.append(f"  [vjp (stage-3) ] {len(self.vjp_leaves)} leaves "
                         f"reduced inside backward")
        return "\n".join(lines)


def order_units(names: Sequence[str], numels: Sequence[int],
                kinds: Sequence[str], layers: Sequence[int],
                stacked: Sequence[bool]) -> List[GradUnit]:
    """Production-ordered reducible units: reversed tree order (backward
    emits the loss-head end of the tree first), with the stacked layer
    block expanded LAYER-major in reversed layer order — layer L-1's
    backward completes first, so its units bucket together and their
    collective becomes issuable while layers L-2..0 are still computing
    (the reference reduces "last produced first" the same way).
    ``layers[i]`` is the slice count for leaf i (0 = not sliceable)."""
    units: List[GradUnit] = []
    n = len(names)
    stack_leaves = [i for i in range(n) if stacked[i]]
    emitted_stack = False
    for i in reversed(range(n)):
        if stacked[i]:
            if emitted_stack:
                continue
            emitted_stack = True
            depth = max(layers[j] for j in stack_leaves)
            for layer in reversed(range(depth)):
                for j in reversed(stack_leaves):
                    if layer < layers[j]:
                        units.append(GradUnit(
                            j, layer, numels[j] // layers[j],
                            f"{names[j]}[{layer}]", kinds[j]))
        else:
            units.append(GradUnit(i, -1, numels[i], names[i], kinds[i]))
    return units


def build_bucket_plan(units: Sequence[GradUnit],
                      reduce_bucket_size: int,
                      allgather_bucket_size: int,
                      grad_itemsize: int = 4) -> GradBucketPlan:
    """Greedy size-capped packing in the given (production) order.

    ``reduce_bucket_size`` caps reduce-scatter buckets;
    ``min(reduce_bucket_size, allgather_bucket_size)`` caps all-reduce
    buckets (an all-reduce is a reduce + the implicit allgather of the
    result, so BOTH knobs bound it — this is where the config keys the
    seed parsed but never consumed become live). Caps are element counts,
    matching the reference's ``reduce_bucket_size`` semantics. A single
    unit larger than its cap gets a bucket of its own (the reference
    overflows its ipg bucket the same way).
    """
    if reduce_bucket_size <= 0 or allgather_bucket_size <= 0:
        raise ValueError(
            f"bucket sizes must be > 0 (reduce_bucket_size="
            f"{reduce_bucket_size}, allgather_bucket_size="
            f"{allgather_bucket_size})")
    caps = {REDUCE_SCATTER: int(reduce_bucket_size),
            ALL_REDUCE: min(int(reduce_bucket_size),
                            int(allgather_bucket_size)),
            CROSS_GROUP: int(reduce_bucket_size)}
    open_buckets: Dict[str, List[int]] = {}
    buckets: List[GradBucket] = []
    vjp: List[str] = []

    def close(kind):
        idxs = open_buckets.pop(kind, None)
        if idxs:
            numel = sum(units[u].numel for u in idxs)
            buckets.append(GradBucket(kind, tuple(idxs), numel,
                                      numel * grad_itemsize))

    for u, unit in enumerate(units):
        if unit.kind == VJP:
            vjp.append(unit.name)
            continue
        cur = open_buckets.setdefault(unit.kind, [])
        cur_numel = sum(units[j].numel for j in cur)
        if cur and cur_numel + unit.numel > caps[unit.kind]:
            close(unit.kind)
            open_buckets[unit.kind] = [u]
        else:
            cur.append(u)
    for kind in list(open_buckets):
        close(kind)
    return GradBucketPlan(tuple(buckets), tuple(units), tuple(vjp),
                          int(reduce_bucket_size),
                          min(int(reduce_bucket_size),
                              int(allgather_bucket_size)))


def _leaf_paths(tree) -> List[str]:
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    return [jax.tree_util.keystr(path) for path, _ in flat]


# Why a hand-spelled ring and not jax.lax.psum/psum_scatter: on the TPU
# backend those lower to SYNCHRONOUS all-reduce/reduce-scatter HLO — the
# all-reduce combiner re-merges every bucket into one monolithic op, async
# collective fusion never chains reduce-type collectives (measured on v5e
# AOT compiles, even with the fuse_reduce_scatter flag), and a sync
# collective blocks the TensorCore. ``collective-permute``, by contrast,
# ALWAYS lowers to async start/done pairs the latency-hiding scheduler can
# pull compute between. So each bucket's reduction is the classic NCCL
# ring, spelled in ppermute hops with a local add per hop — the same
# primitive structure ring_attention uses to hide its KV exchange.


def _ring_reduce_rows(buf, axis: str, world: int):
    """[world, M] local partials -> flat [M]: device r ends with row r
    fully summed. world-1 async ppermute hops, one add per hop; the
    summation order per element is the fixed ring order (device r+1, r+2,
    ..., r), identical for every bucket layout — bucketed and monolithic
    reduction stay bit-identical."""
    if world == 1:
        return buf[0]
    perm = [(i, (i + 1) % world) for i in range(world)]
    idx = jax.lax.axis_index(axis)

    def take(b):
        return jax.lax.dynamic_index_in_dim(buf, b % world, 0,
                                            keepdims=False)

    acc = take(idx - 1)
    for s in range(world - 1):
        acc = jax.lax.ppermute(acc, axis, perm)
        acc = acc + take(idx - s - 2)
    return acc


def _ring_all_gather_rows(block, axis: str, world: int):
    """Per-device [M] block -> [world, M] full tensor (row r = device r's
    block) via world-1 async ppermute hops."""
    if world == 1:
        return block[None]
    perm = [(i, (i + 1) % world) for i in range(world)]
    idx = jax.lax.axis_index(axis)
    out = jnp.zeros((world,) + block.shape, block.dtype)
    out = jax.lax.dynamic_update_index_in_dim(out, block, idx, 0)
    cur = block
    for s in range(world - 1):
        cur = jax.lax.ppermute(cur, axis, perm)
        out = jax.lax.dynamic_update_index_in_dim(
            out, cur, (idx - s - 1) % world, 0)
    return out


def _unit_rows(flat, world: int):
    """Unit-flat [n] -> [world, ceil(n/world)] ring rows. The element->row
    assignment depends only on the UNIT (zero-padded to a world multiple),
    never on the bucket it rides in — the per-element ring summation order
    is therefore identical for every bucket layout, which is what makes
    bucketed and monolithic reduction bit-identical."""
    n = flat.shape[0]
    m = -(-n // world)
    if m * world != n:
        flat = jnp.pad(flat, (0, m * world - n))
    return flat.reshape(world, m)


def _rows_unit(rows_flat, numel: int):
    """Inverse of ``_unit_rows`` after the all-gather: [world * m] -> [n]."""
    return rows_flat[:numel]


def _reduce_axes(buf_2d, axes: Tuple[str, ...], sizes: Dict[str, int],
                 ring: bool = True):
    """Bucket reduce-scatter over possibly-multiple mesh axes. Single axis
    takes the async ring; multi-axis (MiCS/hpZ shard groups) and
    partial-manual programs (``ring=False`` — the SPMD partitioner rejects
    ppermute + dynamic indexing when auto axes remain) fall back to
    sequential fused scatters like ``reduce_scatter_leaf``."""
    live = [a for a in axes if sizes[a] > 1]
    if len(live) == 1 and ring:
        return _ring_reduce_rows(buf_2d, live[0], buf_2d.shape[0])
    out = buf_2d
    for a in live:
        out = jax.lax.psum_scatter(out, a, scatter_dimension=0, tiled=True)
    return out.reshape(-1)


def quant_reduce_layout(plan: GradBucketPlan, axes: Tuple[str, ...],
                        world: int, axis_sizes: Dict[str, int],
                        ring: bool = True,
                        a2a_quantized: bool = False) -> Dict[str, Dict]:
    """Which buckets the quantized ring transport carries, and the row
    shapes of their error-feedback residuals.

    Returns ``{"b<i>": {"rs": (world, M)[, "ag": (M,)]}}`` for every
    bucket that rides the single-axis ppermute ring: ALL_REDUCE buckets
    carry both phases' residuals (quantized reduce-scatter + quantized
    all-gather of the result), REDUCE_SCATTER buckets the reduce phase
    only. CROSS_GROUP (hpZ) and ZeRO++-a2a (``a2a_quantized``) buckets
    keep their existing transports. Empty when the mesh has no single
    live data-parallel axis (the ring precondition).
    """
    live = [a for a in axes if axis_sizes.get(a, 2) > 1]
    if len(live) != 1 or not ring or world <= 1:
        return {}
    out: Dict[str, Dict] = {}
    for i, b in enumerate(plan.buckets):
        if b.kind == ALL_REDUCE:
            M = sum(-(-plan.units[u].numel // world) for u in b.indices)
            out[f"b{i}"] = {"rs": (world, M), "ag": (M,)}
        elif b.kind == REDUCE_SCATTER and not a2a_quantized:
            out[f"b{i}"] = {"rs": (world, b.numel // world)}
    return out


def ring_wire_bytes(plan: GradBucketPlan, world: int,
                    quantized: bool = False,
                    quant_block: int = 2048) -> int:
    """Per-device bytes the bucket ring transports ship per step
    (world-1 hops per phase; ALL_REDUCE buckets pay reduce-scatter AND
    all-gather phases; vjp/CROSS_GROUP leaves are excluded — they do not
    ride the ring). The fp32/quantized ratio of this number is the
    perf-gate's wire-compression pin."""
    if world <= 1:
        return 0
    hops = world - 1
    total = 0
    for b in plan.buckets:
        if b.kind == REDUCE_SCATTER:
            M, phases = b.numel // world, 1
        elif b.kind == ALL_REDUCE:
            M = sum(-(-plan.units[u].numel // world) for u in b.indices)
            phases = 2
        else:
            continue
        per_hop = quant_wire_bytes(M, quant_block) if quantized else M * 4
        total += phases * hops * per_hop
    return total


def apply_bucketed_reduction(grads_flat: List[Any],
                             plan: GradBucketPlan,
                             grad_dims: Sequence[int],
                             axes: Tuple[str, ...],
                             cross_axes: Tuple[str, ...],
                             world: int,
                             cross_world: int,
                             axis_sizes: Optional[Dict[str, int]] = None,
                             quantized: bool = False,
                             quant_block: int = 2048,
                             quant_bits: int = 8,
                             ring: bool = True,
                             quant_reduce: Optional[str] = None,
                             quant_reduce_block: int = 2048,
                             quant_reduce_groups: int = 0,
                             qstate: Optional[Dict[str, Dict]] = None,
                             loss_scale=None):
    """Issue one fused collective per bucket over the flat leaf list.

    Must run inside shard_map over ``axes``. Every bucket is independent in
    the dataflow graph, so XLA's scheduler is free to start a bucket's
    collective the moment its leaves' cotangents exist and to run other
    buckets' compute (optimizer math, remaining backward) under it.
    Per-element sums are identical to per-leaf (and to monolithic)
    reduction: the bucket layout only changes how elements are packed into
    messages, never which values are summed.

    ``quant_reduce`` ("int8"|"fp8") reroutes the ring buckets through the
    block-quantized wire (comm/quantized.ring_*_quant) with per-bucket
    error feedback: ``qstate`` holds last step's residuals (the layout of
    :func:`quant_reduce_layout`), which are injected into the partials
    before transport; the call then returns ``(out, new_qstate)`` with
    this step's residuals. Residuals are stored UNSCALED (divided by
    ``loss_scale``) so fp16 dynamic-scale changes cannot stretch a stale
    residual. ``quant_reduce_groups`` > 1 routes the ring buckets
    through the two-level hierarchical rings instead (intra-host fp32 /
    inter-host quantized — ``zero_optimization.
    quantized_reduce_hierarchy``); the EF state layout is unchanged.
    """
    axis_sizes = axis_sizes or {}
    hier = int(quant_reduce_groups or 0) > 1

    def _ring_rs_quant(buf_q, ax, denom_q):
        if hier:
            return ring_reduce_scatter_hier(
                buf_q, ax, denom_q, quant_reduce_groups,
                block=quant_reduce_block, mode=quant_reduce)
        return ring_reduce_scatter_quant(
            buf_q, ax, denom_q, block=quant_reduce_block,
            mode=quant_reduce)

    def _ring_ag_quant(row_q, ax, denom_q):
        if hier:
            return ring_all_gather_hier(
                row_q, ax, denom_q, quant_reduce_groups,
                block=quant_reduce_block, mode=quant_reduce)
        return ring_all_gather_quant(
            row_q, ax, denom_q, block=quant_reduce_block,
            mode=quant_reduce)
    # accept the config-domain literal "off" (truthy) as disabled, so the
    # return arity matches what a caller forwarding the raw knob expects
    if quant_reduce == "off":
        quant_reduce = None
    out: List[Any] = list(grads_flat)
    slices: Dict[int, Dict[int, Any]] = {}  # leaf -> layer -> reduced slice
    qlayout = (quant_reduce_layout(plan, axes, world, axis_sizes,
                                   ring=ring, a2a_quantized=quantized)
               if quant_reduce else {})
    new_qstate: Dict[str, Dict] = {}
    ls = jnp.asarray(1.0, jnp.float32) if loss_scale is None else loss_scale

    def unit_value(u: GradUnit):
        g = grads_flat[u.leaf]
        return g if u.layer < 0 else g[u.layer]

    def unit_dim(u: GradUnit) -> int:
        d = grad_dims[u.leaf]
        return d if u.layer < 0 else d - 1

    def deliver(u: GradUnit, val):
        if u.layer < 0:
            out[u.leaf] = val
        else:
            slices.setdefault(u.leaf, {})[u.layer] = val

    for bi, b in enumerate(plan.buckets):
        us = [plan.units[i] for i in b.indices]
        key = f"b{bi}"
        if b.kind in (ALL_REDUCE, CROSS_GROUP):
            red_axes = axes if b.kind == ALL_REDUCE else cross_axes
            denom = world if b.kind == ALL_REDUCE else cross_world
            live = [a for a in red_axes if axis_sizes.get(a, 2) > 1]
            if denom > 1 and len(live) == 1 and ring:
                # ring all-reduce = ring reduce-scatter + ring all-gather
                # over per-UNIT row blocks (layout-invariant element order)
                parts = [_unit_rows(unit_value(u).reshape(-1), denom)
                         for u in us]
                buf = parts[0] if len(parts) == 1 else \
                    jnp.concatenate(parts, axis=1)
                if key in qlayout:
                    res = qstate[key]
                    buf = buf + res["rs"] * ls
                    red_sum, rs_err = _ring_rs_quant(buf, live[0],
                                                     denom)
                    red = red_sum / denom + res["ag"] * ls
                    full, ag_err = _ring_ag_quant(red, live[0], denom)
                    new_qstate[key] = {"rs": rs_err / ls, "ag": ag_err / ls}
                else:
                    red = _ring_reduce_rows(buf, live[0], denom) / denom
                    full = _ring_all_gather_rows(red, live[0], denom)
                off = 0
                for u, part in zip(us, parts):
                    m = part.shape[1]
                    piece = full[:, off:off + m].reshape(-1)
                    off += m
                    deliver(u, _rows_unit(piece, u.numel).reshape(
                        unit_value(u).shape))
                continue
            parts = [unit_value(u).reshape(-1) for u in us]
            buf = parts[0] if len(parts) == 1 else jnp.concatenate(parts)
            if denom > 1:
                buf = jax.lax.psum(buf, red_axes) / denom
            off = 0
            for u in us:
                deliver(u, buf[off:off + u.numel].reshape(
                    unit_value(u).shape))
                off += u.numel
        else:  # REDUCE_SCATTER
            parts, metas = [], []
            for u in us:
                g, d = unit_value(u), unit_dim(u)
                moved = jnp.moveaxis(g, d, 0)
                parts.append(moved.reshape(world, -1))
                metas.append((u, d, moved.shape))
            buf = parts[0] if len(parts) == 1 else \
                jnp.concatenate(parts, axis=1)
            if key in qlayout:
                live = [a for a in axes if axis_sizes.get(a, 2) > 1]
                res = qstate[key]
                buf = buf + res["rs"] * ls
                row, rs_err = _ring_rs_quant(buf, live[0], world)
                buf = row / world
                new_qstate[key] = {"rs": rs_err / ls}
            elif quantized:
                buf = all_to_all_quant_reduce(buf, 0, axes, block=quant_block,
                                              bits=quant_bits,
                                              mean=True).reshape(-1)
            elif world > 1:
                buf = _reduce_axes(buf, axes, axis_sizes, ring=ring) / world
            else:
                buf = buf.reshape(-1)
            off = 0
            for u, d, mshape in metas:
                cols = u.numel // world
                piece = buf[off:off + cols]
                off += cols
                shard = piece.reshape((mshape[0] // world,) + mshape[1:])
                deliver(u, jnp.moveaxis(shard, 0, d))
    # restack layer-sliced leaves (slice-of-stack and stack-of-slice cancel
    # in XLA; only the collectives' granularity actually changes)
    for leaf, per_layer in slices.items():
        out[leaf] = jnp.stack([per_layer[l]
                               for l in range(len(per_layer))], axis=0)
    if quant_reduce:
        return out, new_qstate
    return out


# ----------------------------------------------------------------------
# Engine integration
# ----------------------------------------------------------------------
# compositions the manual shard_map program cannot express (or that the
# quantized-collective predecessor already rejected): these raise under
# overlap_grad_reduce="bucketed" and silently keep the legacy GSPMD path
# under "auto".
_HARD = "hard"
_SOFT = "soft"


def overlap_blockers(engine, forced: bool) -> List[Tuple[str, str]]:
    """(severity, reason) list; empty means the manual path can run."""
    topo = engine.topology
    out: List[Tuple[str, str]] = []
    for ax in ("expert", "pipe"):
        if topo.axis_size(ax) > 1:
            out.append((_HARD, f"'{ax}' mesh axis > 1 (needs a manual "
                               f"program for that axis inside shard_map)"))
    if engine.param_offload:
        out.append((_HARD, "offload_param streams the layer stack from "
                           "host memory"))
    if engine.compression_spec is not None:
        out.append((_HARD, "compression_training rewrites params per step "
                           "inside the auto-SPMD loss"))
    if not forced:
        # conservative auto gate: anything beyond a pure data-parallel
        # mesh keeps the legacy GSPMD reduction unless explicitly forced
        if not engine.config.zero_optimization.overlap_comm:
            out.append((_SOFT, "overlap_comm is disabled"))
        if engine.zero_stage == 3:
            # stage-3's dominant exchange is the param gathers, which the
            # GSPMD path already hides almost completely (AOT dp8:
            # param_gather_exposed_fraction 0.027 with 145 async chains);
            # the manual program's explicit per-leaf gathers forfeit that
            # scheduling and regress peak memory. Manual stage 3 stays
            # opt-in ('bucketed') / ZeRO++-only.
            out.append((_SOFT, "stage-3 gathers ride GSPMD's async "
                               "collective fusion"))
        for ax in ("model", "seq", "shard"):
            if topo.axis_size(ax) > 1:
                out.append((_SOFT, f"'{ax}' mesh axis > 1"))
        dp = int(np.prod([topo.sizes[a] for a in topo.dp_axes]))
        if dp <= 1:
            out.append((_SOFT, "data-parallel world is 1 (nothing to "
                               "reduce)"))
        mcfg = getattr(engine.model, "cfg", None)
        if getattr(mcfg, "moe_num_experts", 0) or engine.config.moe.enabled:
            out.append((_SOFT, "MoE capacity routing depends on the global "
                               "batch view"))
    return out


def partial_manual_supported() -> bool:
    """Partial-manual shard_map (manual dp axes, auto tp/sp axes) needs the
    jax>=0.5 shard_map: the legacy experimental fallback's ``auto=`` path
    makes this jaxlib's SPMD partitioner hard-CHECK-fail (process abort,
    ``IsManualSubgroup``) on any collective under remaining auto axes —
    reject BEFORE compile, a Python error beats a SIGABRT."""
    try:
        from jax import shard_map  # noqa: F401
        return True
    except ImportError:
        return False


def resolve_overlap_mode(engine, use_zeropp: bool) -> str:
    """'bucketed' | 'off' for this engine build.

    ``zero_optimization.overlap_grad_reduce``: 'auto' engages the bucketed
    program on pure-dp meshes with dp > 1; 'bucketed' forces it (hard
    blockers raise); 'off' keeps the legacy GSPMD reduction. ZeRO++
    (qwZ/qgZ) always runs the manual program — its quantized collectives
    cannot be compiler-inserted — and gains the bucketing.
    """
    from .config import ConfigError
    mode = engine.config.zero_optimization.overlap_grad_reduce
    if use_zeropp:
        return "bucketed"
    if mode == "off":
        return "off"
    if engine.topology.axis_size("pipe") > 1 and mode != "bucketed":
        # the 1F1B program owns its own gradient computation; forced mode
        # falls through to the hard-blocker ConfigError below
        return "off"
    blockers = overlap_blockers(engine, forced=(mode == "bucketed"))
    if mode == "bucketed":
        hard = [r for s, r in blockers if s == _HARD]
        if hard:
            raise ConfigError(
                "zero_optimization.overlap_grad_reduce='bucketed' is not "
                "supported here: " + "; ".join(hard))
        return "bucketed"
    return "off" if blockers else "bucketed"


def make_overlapped_grad_fn(engine, zpp_w: bool, zpp_g: bool):
    """The manual gradient program: shard_map over the DP axes, per-micro
    autodiff with explicit stage-3 gathers, local accumulation across
    gradient-accumulation microbatches (scan over the first gas-1, last one
    inline so its backward overlaps the reduction), then per-bucket
    collectives. Returns ``(grad_fn, plan, qtemplate)``:
    ``grad_fn(params, rng, batch, scale) -> (grads, loss)`` (plus a
    threaded error-feedback state when ``zero_optimization.
    quantized_reduce`` is on: ``grad_fn(params, rng, batch, scale,
    qstate) -> (grads, loss, new_qstate)``); grads are summed over
    microbatches and MEANED over the DP world (the engine divides by gas
    only, like the legacy manual path). ``qtemplate`` describes the
    error-feedback state the engine must allocate —
    ``{"b<i>": {"rs"|"ag": (global_shape, PartitionSpec)}}`` — or None
    when quantized_reduce is off.

    Generalizes the ZeRO++ qwZ/qgZ program the seed shipped: with both
    quant flags off this is the plain bucketed-overlap path; with them on,
    gathers ride int8 transport (qwZ) and bucket reduces ride the int8
    all-to-all (qgZ) — now fused per bucket instead of per leaf. The
    ``quantized_reduce`` knob instead quantizes the ring transport itself
    (per-hop int8/fp8 wire with per-bucket error feedback) — the
    EQuARX-style path for stages 0-2.
    """
    mesh = engine.mesh
    topo = engine.topology
    axes = topo.dp_axes
    axis_sizes = topo.sizes
    plan_z = engine.zero_plan
    stage3 = engine.zero_stage == 3
    model = engine.model
    gas = engine.gas
    zc = engine.config.zero_optimization
    hpz = stage3 and topo.hpz_enabled
    gather_axes = topo.secondary_axes if hpz else axes
    cross_group_axes = tuple(a for a in axes if a not in gather_axes)
    world = int(np.prod([axis_sizes[a] for a in axes]))
    cross_world = int(np.prod([axis_sizes[a] for a in cross_group_axes])) \
        if cross_group_axes else 1

    param_specs = jax.tree.map(lambda ns: ns.spec, plan_z.param_sharding)
    grad_specs = jax.tree.map(lambda ns: ns.spec, plan_z.grad_sharding)

    def dim_of(spec):
        # -1 sentinel (None collapses pytree structure)
        for i, e in enumerate(spec):
            entries = e if isinstance(e, tuple) else (e,)
            if any(a in axes for a in entries if a is not None):
                return i
        return -1

    param_dims = jax.tree.map(dim_of, param_specs)
    grad_dims = jax.tree.map(dim_of, grad_specs)
    identity = lambda x: x  # noqa: E731
    gather_fns = jax.tree.map(
        lambda d: (make_zero3_gather(d, gather_axes, fwd_quantized=zpp_w,
                                     bwd_quantized=zpp_g)
                   if stage3 and d >= 0 else identity),
        param_dims)

    # --- bucket plan over the flat grad leaves ------------------------
    shapes = engine._param_shapes
    names = _leaf_paths(shapes)
    leaf_shapes = [tuple(l.shape) for l in jax.tree.leaves(shapes)]
    numels = [int(np.prod(s)) if s else 1 for s in leaf_shapes]
    pd_flat = jax.tree.leaves(param_dims)
    gd_flat = jax.tree.leaves(grad_dims)

    def kind_of(pd, gd):
        # pd >= 0 MUST be checked before gd < 0: under hpZ a dim can divide
        # the small group but not the full world (pd >= 0, gd < 0), and its
        # cotangent was already reduce-scattered over the shard axis by the
        # gather's VJP — a psum over that axis would average different
        # shard halves into corrupt gradients
        if stage3 and pd >= 0:
            return CROSS_GROUP if (hpz and cross_group_axes) else VJP
        if gd < 0:
            return ALL_REDUCE
        return REDUCE_SCATTER

    kinds = [kind_of(pd, gd) for pd, gd in zip(pd_flat, gd_flat)]
    # hpZ cross-group leaves live secondary-SHARDED inside the program
    # (the gather's VJP already reduce-scattered them over the group), so
    # their bucket units carry the shard numel, not the full-leaf numel
    gather_world = int(np.prod([axis_sizes[a] for a in gather_axes]))
    numels = [n // gather_world if k == CROSS_GROUP else n
              for n, k in zip(numels, kinds)]

    # Layer slicing: scanned models hold layer params as ONE stacked
    # [L, ...] leaf, which would force every layer's gradient into the
    # same post-backward bucket. When the layer loop is fully unrolled
    # (the grads of layer l exist before the stack is assembled), slice
    # stacked leaves per layer so a deep layer's bucket can reduce WHILE
    # shallower layers are still in backward — DeepCompile's
    # reduction-interleaving, recovered at the bucket-plan level.
    stack_keys = tuple(getattr(model, "param_offload_keys", ()) or ())
    unroll = max(int(getattr(getattr(model, "cfg", None), "scan_unroll", 1)
                     or 1),
                 int(getattr(model, "scan_unroll_hint", 1) or 1))

    def sliceable(i):
        if kinds[i] in (VJP, CROSS_GROUP):
            return False
        sh = leaf_shapes[i]
        if len(sh) < 2 or sh[0] < 2 or unroll < sh[0]:
            return False
        if not any(f"['{k}']" in names[i] for k in stack_keys):
            return False
        # slicing removes dim 0; a leaf sharded ON dim 0 cannot slice
        if kinds[i] == REDUCE_SCATTER and gd_flat[i] == 0:
            return False
        return True

    stacked = [sliceable(i) for i in range(len(names))]
    layer_counts = [leaf_shapes[i][0] if stacked[i] else 0
                    for i in range(len(names))]
    units = order_units(names, numels, kinds, layer_counts, stacked)
    plan = build_bucket_plan(units, zc.reduce_bucket_size,
                             zc.allgather_bucket_size)

    def linear_index():
        idx = jnp.asarray(0, jnp.int32)
        for a in axes:
            idx = idx * axis_sizes[a] + jax.lax.axis_index(a)
        return idx

    def _split_loss_aux(out):
        if isinstance(out, tuple) and len(out) == 2:
            return out[0], out[1]
        return out, {}

    def body(params_l, rng, batch_l, scale, qstate):
        def apply_model(pshards, micro, sub):
            pf = (jax.tree.map(lambda f, p: f(p), gather_fns, pshards)
                  if stage3 else pshards)
            out = model.apply(pf, micro, train=True, rng=sub)
            loss, _aux = _split_loss_aux(out)
            loss = loss.astype(jnp.float32)
            return loss * scale, loss

        def micro_step(grads_acc, rng, micro):
            rng, sub = jax.random.split(rng)
            sub = jax.random.fold_in(sub, linear_index())
            (_, loss), g = jax.value_and_grad(
                apply_model, has_aux=True)(params_l, micro, sub)
            grads_acc = jax.tree.map(
                lambda a, x: a + x.astype(jnp.float32), grads_acc, g)
            return grads_acc, rng, loss

        grads0 = jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32),
                              params_l)

        def scan_fn(carry, micro):
            acc, rng = carry
            acc, rng, loss = micro_step(acc, rng, micro)
            return (acc, rng), loss

        if inline_last:
            # last microbatch INLINE: its per-layer backward shares the
            # scheduling window with the bucket collectives below (inside
            # a scan the whole gradient only exists when the loop op
            # completes). The accumulation order is the same add sequence
            # the full scan performs — numerics unchanged.
            if gas > 1:
                head = jax.tree.map(lambda x: x[:-1], batch_l)
                (acc, rng), head_losses = jax.lax.scan(
                    scan_fn, (grads0, rng), head)
            else:
                acc, head_losses = grads0, None
            last = jax.tree.map(lambda x: x[-1], batch_l)
            acc, rng, last_loss = micro_step(acc, rng, last)
            losses = (last_loss[None] if head_losses is None
                      else jnp.concatenate([head_losses, last_loss[None]]))
        else:
            # partial-manual programs (auto tp/sp axes): the SPMD
            # partitioner rejects the scan-free inline backward
            # (IsManualSubgroup check), so every microbatch stays in the
            # scan as the ZeRO++ predecessor did
            (acc, rng), losses = jax.lax.scan(scan_fn, (grads0, rng),
                                              batch_l)

        flat, treedef = jax.tree_util.tree_flatten(acc)
        if use_qr:
            # local residual rows ride shard_map with a leading sharded
            # dim of 1 (global dim0 = world); squeeze in, unsqueeze out
            qin = {k: {kk: a[0] for kk, a in v.items()}
                   for k, v in qstate.items()}
            flat, qerr = apply_bucketed_reduction(
                flat, plan, gd_flat, axes, cross_group_axes, world,
                cross_world, axis_sizes=axis_sizes, quantized=zpp_g,
                ring=not tp, quant_reduce=qr_mode,
                quant_reduce_block=qr_block,
                quant_reduce_groups=qr_groups, qstate=qin,
                loss_scale=scale)
            qout = {k: {kk: a[None] for kk, a in v.items()}
                    for k, v in qerr.items()}
        else:
            flat = apply_bucketed_reduction(
                flat, plan, gd_flat, axes, cross_group_axes, world,
                cross_world, axis_sizes=axis_sizes, quantized=zpp_g,
                ring=not tp)
            qout = qstate
        grads = jax.tree_util.tree_unflatten(treedef, flat)
        loss = jax.lax.pmean(jnp.mean(losses), axes)
        return grads, loss, qout

    # grads of hpZ-sharded params leave the program secondary-sharded
    out_grad_specs = grad_specs
    if hpz:
        out_grad_specs = jax.tree.map(
            lambda gs, ps, pd: ps if pd >= 0 else gs,
            grad_specs, param_specs, param_dims)

    # tensor/sequence parallelism ride the AUTO axes: the program is
    # manual over the DP axes only, and specs mention only those (GSPMD
    # keeps the "model"/"seq"-axis collectives inside model.apply)
    tp = (topo.axis_size("model") > 1 or topo.axis_size("seq") > 1)
    if tp and not partial_manual_supported():
        raise NotImplementedError(
            "tensor/sequence parallelism x the manual gradient program "
            "(qwZ/qgZ/bucketed reduction) needs partial-manual shard_map "
            "(jax >= 0.5); this jax's fallback aborts the process in the "
            "SPMD partitioner. Disable zero_quantized_weights/gradients "
            "and overlap_grad_reduce for tp/sp runs on this jax.")
    inline_last = not tp
    manual = tuple(axes)

    def strip_auto(spec):
        if not tp:
            return spec
        out = []
        for e in spec:
            ents = e if isinstance(e, tuple) else (e,)
            kept = tuple(a for a in ents if a in manual)
            out.append(kept if len(kept) > 1 else
                       (kept[0] if kept else None))
        return P(*out)

    if tp:
        param_specs_in = jax.tree.map(strip_auto, param_specs)
        out_grad_specs = jax.tree.map(strip_auto, out_grad_specs)
    else:
        param_specs_in = param_specs

    # --- quantized ring transport (zero_optimization.quantized_reduce):
    # per-hop int8/fp8 wire over the same ppermute ring, with per-bucket
    # error-feedback residuals threaded through the program
    qr_mode = getattr(zc, "quantized_reduce", "off")
    qr_block = int(getattr(zc, "quant_block", 2048))
    qr_groups = int(getattr(zc, "quantized_reduce_hierarchy", 0) or 0)
    # inert without a ring to quantize (the engine logs and drops the
    # knob at dp=1; this guard keeps direct callers consistent)
    use_qr = qr_mode not in (None, "off") and world > 1
    qtemplate = None
    if use_qr:
        from .config import ConfigError
        if tp:
            raise ConfigError(
                "zero_optimization.quantized_reduce does not compose with "
                "tensor/sequence parallelism: the quantized ring needs the "
                "fully-manual data-parallel program")
        live = [a for a in axes if axis_sizes[a] > 1]
        if len(live) > 1:
            raise ConfigError(
                "zero_optimization.quantized_reduce needs a single live "
                f"data-parallel mesh axis for the ring transport (got "
                f"{live})")
        if qr_groups > 1 and world % qr_groups != 0:
            raise ConfigError(
                f"zero_optimization.quantized_reduce_hierarchy="
                f"{qr_groups} must divide the data-parallel world "
                f"({world}): the two-level ring lays the ring out as "
                f"hosts x devices-per-host")
        qlayout = quant_reduce_layout(plan, axes, world, axis_sizes,
                                      ring=True, a2a_quantized=zpp_g)
        qdim0 = manual if len(manual) > 1 else manual[0]
        qtemplate = {
            key: {kk: ((world,) + shape,
                       P(*((qdim0,) + (None,) * len(shape))))
                  for kk, shape in shapes.items()}
            for key, shapes in qlayout.items()}

    bt = topo.batch_axes
    if use_qr:
        qspecs = {k: {kk: spec for kk, (_, spec) in v.items()}
                  for k, v in qtemplate.items()}
        fn = shard_map_unchecked(
            body, mesh=mesh,
            in_specs=(param_specs_in, P(), P(None, bt), P(), qspecs),
            out_specs=(out_grad_specs, P(), qspecs),
            axis_names=None)
        return fn, plan, qtemplate

    def body4(params_l, rng, batch_l, scale):
        return body(params_l, rng, batch_l, scale, {})[:2]

    fn = shard_map_unchecked(
        body4, mesh=mesh,
        in_specs=(param_specs_in, P(), P(None, bt), P()),
        out_specs=(out_grad_specs, P()),
        axis_names=manual if tp else None)
    return fn, plan, None
