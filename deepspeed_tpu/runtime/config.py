"""JSON config system.

TPU-native analogue of the reference's ``runtime/config.py``
(``DeepSpeedConfig``, reference runtime/config.py:686) and per-feature config
models (e.g. ``runtime/zero/config.py:81``). The JSON surface keeps the
reference's key names (train_batch_size / zero_optimization / fp16 / bf16 /
optimizer / scheduler / pipeline / ...) so configs are drop-in recognizable,
while the semantics target a JAX device mesh: the data-parallel degree is
``total_devices // (tp * pp * sp)`` rather than a torch.distributed world size.
"""

import json
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Union

from .config_utils import AUTO, ConfigError, as_dict, hydrate, subconfig

TRAIN_BATCH_SIZE = "train_batch_size"
TRAIN_MICRO_BATCH_SIZE_PER_GPU = "train_micro_batch_size_per_gpu"
GRADIENT_ACCUMULATION_STEPS = "gradient_accumulation_steps"


@dataclass
class FP16Config:
    """Reference: runtime/fp16 loss-scaling config block."""

    enabled: bool = False
    auto_cast: bool = False
    loss_scale: float = 0.0  # 0 => dynamic
    initial_scale_power: int = 16
    loss_scale_window: int = 1000
    hysteresis: int = 2
    consecutive_hysteresis: bool = False
    min_loss_scale: float = 1.0


@dataclass
class BF16Config:
    enabled: bool = False


@dataclass
class OffloadConfig:
    """Reference: runtime/zero/offload_config.py (device: cpu|nvme).

    ``pin_memory`` on ``offload_optimizer`` with ``device: cpu`` selects
    the TIERED offload path (runtime/offload.py): optimizer state in
    host memory (``pinned_host`` where the runtime supports it), update
    streamed bucket-by-bucket at ``stage3_prefetch_bucket_size``
    granularity with ``buffer_count`` fetches in flight. Without it,
    ``device: cpu`` keeps the legacy host C++ optimizer
    (runtime/zero/offload.py)."""

    device: str = "none"
    nvme_path: Optional[str] = None
    pin_memory: bool = False
    buffer_count: int = 4
    buffer_size: int = 100_000_000
    pipeline_read: bool = False
    pipeline_write: bool = False
    fast_init: bool = False
    ratio: float = 1.0

    def __post_init__(self):
        if self.device not in ("none", "cpu", "nvme"):
            # the engine used to reject unknown devices only at init —
            # a config load is the cheapest place to fail
            raise ConfigError(
                f"offload device must be 'cpu' or 'nvme' (or 'none'), "
                f"got {self.device!r}")
        if self.device == "nvme" and not self.nvme_path:
            raise ConfigError(
                "offload device 'nvme' requires nvme_path")
        # buffer-count style knobs are CONSUMED (tiered prefetch depth,
        # AIO buffer sizing) — nonsense must fail at load, like the
        # bucket-size checks below (a buffer_count of 0 would silently
        # serialize every fetch; a negative size would wrap a malloc)
        if self.buffer_count < 1:
            raise ConfigError(
                f"offload buffer_count must be >= 1, got "
                f"{self.buffer_count}")
        if self.buffer_size <= 0:
            raise ConfigError(
                f"offload buffer_size must be > 0, got "
                f"{self.buffer_size}")
        if not 0.0 < self.ratio <= 1.0:
            raise ConfigError(
                f"offload ratio must be in (0, 1], got {self.ratio}")


@dataclass
class ZeroConfig:
    """Reference: runtime/zero/config.py:81 DeepSpeedZeroConfig."""

    stage: int = 0
    contiguous_gradients: bool = True
    reduce_scatter: bool = True
    # bucket caps are ELEMENT counts (reference zero/config.py semantics),
    # consumed by runtime/grad_overlap.py: reduce_bucket_size caps
    # reduce-scatter buckets; min(reduce_bucket_size, allgather_bucket_size)
    # caps all-reduce buckets (reduce + implicit allgather of the result)
    reduce_bucket_size: int = 500_000_000
    allgather_partitions: bool = True
    allgather_bucket_size: int = 500_000_000
    overlap_comm: bool = True
    # bucketed grad-reduction program (runtime/grad_overlap.py):
    #   "auto"     engage on pure data-parallel meshes with dp > 1
    #   "bucketed" force it (unsupported compositions raise)
    #   "off"      legacy GSPMD-inserted monolithic reduction
    overlap_grad_reduce: str = "auto"
    offload_optimizer: OffloadConfig = subconfig(OffloadConfig)
    offload_param: OffloadConfig = subconfig(OffloadConfig)
    sub_group_size: int = 1_000_000_000
    stage3_max_live_parameters: int = 1_000_000_000
    stage3_max_reuse_distance: int = 1_000_000_000
    stage3_prefetch_bucket_size: int = 50_000_000
    stage3_param_persistence_threshold: int = 100_000
    stage3_gather_16bit_weights_on_model_save: bool = False
    ignore_unused_parameters: bool = True
    round_robin_gradients: bool = False
    # ZeRO++ knobs (reference zero/config.py:256-272)
    zero_hpz_partition_size: int = 1
    zero_quantized_weights: bool = False
    zero_quantized_gradients: bool = False
    # block-quantized ring gradient reduction (EQuARX, arXiv:2506.17615;
    # runtime/grad_overlap.py): every hop of the bucketed ppermute-ring
    # reduce ships int8/fp8 + per-block fp32 scales instead of fp32
    # (~4x fewer collective bytes), with per-bucket ERROR FEEDBACK
    # residuals carried across steps so transport error does not bias
    # convergence. Stages 0-2 (stage-3 grads reduce inside the gather
    # VJP); forces the bucketed overlap program; mutually exclusive with
    # zero_quantized_gradients (qgZ already quantizes those buckets).
    quantized_reduce: str = "off"   # off | int8 | fp8
    quant_block: int = 2048         # elements per wire-quantization block
    # two-level (EQuARX multi-pod) shape for quantized_reduce: the
    # number of HOSTS the dp ring spans — intra-host legs stay fp32,
    # only inter-host legs ride the quantized wire
    # (comm/quantized.ring_*_hier). 0/1 = flat single-level ring; must
    # divide the dp world (validated where the mesh is known).
    quantized_reduce_hierarchy: int = 0
    # MiCS-style shard group (reference runtime/zero/mics.py)
    mics_shard_size: int = -1
    mics_hierarchical_params_gather: bool = False

    def __post_init__(self):
        if self.stage not in (0, 1, 2, 3):
            raise ConfigError(f"zero_optimization.stage must be 0-3, got {self.stage}")
        # bucket knobs are CONSUMED (grad_overlap.py / stage-3 plan) and
        # REGISTERED tunables (runtime/tunables.py): a nonsensical value
        # fails at config load naming the registry entry and its
        # documented range, and the effective value lands in /statusz
        # with its provenance
        from . import tunables
        for key in ("reduce_bucket_size", "allgather_bucket_size",
                    "stage3_prefetch_bucket_size"):
            tunables.check(f"zero_optimization.{key}",
                           getattr(self, key), exc=ConfigError)
            tunables.observe(f"zero_optimization.{key}",
                             getattr(self, key), "config")
        if self.overlap_grad_reduce not in ("auto", "bucketed", "off"):
            raise ConfigError(
                "zero_optimization.overlap_grad_reduce must be one of "
                f"'auto'|'bucketed'|'off', got {self.overlap_grad_reduce!r}")
        if self.quantized_reduce not in ("off", "int8", "fp8"):
            raise ConfigError(
                "zero_optimization.quantized_reduce must be one of "
                f"'off'|'int8'|'fp8', got {self.quantized_reduce!r}")
        tunables.check("zero_optimization.quant_block", self.quant_block,
                       exc=ConfigError)
        tunables.observe("zero_optimization.quant_block",
                         self.quant_block, "config")
        if self.quantized_reduce_hierarchy < 0:
            raise ConfigError(
                "zero_optimization.quantized_reduce_hierarchy must be "
                f">= 0 (a host count, 0/1 = flat), got "
                f"{self.quantized_reduce_hierarchy}")
        if (self.quantized_reduce_hierarchy > 1
                and self.quantized_reduce == "off"):
            raise ConfigError(
                "zero_optimization.quantized_reduce_hierarchy shapes "
                "the quantized ring — set quantized_reduce to "
                "'int8'|'fp8' (or drop the hierarchy knob)")
        if self.quantized_reduce != "off":
            if self.stage == 3:
                raise ConfigError(
                    "zero_optimization.quantized_reduce targets stages 0-2 "
                    "(stage-3 gradients reduce inside the parameter "
                    "gather's VJP; use zero_quantized_gradients for the "
                    "qgZ int8 all-to-all there)")
            if self.zero_quantized_gradients:
                raise ConfigError(
                    "quantized_reduce and zero_quantized_gradients both "
                    "quantize the gradient exchange — pick one transport")
        offloaded = (self.offload_optimizer.device != "none"
                     or self.offload_param.device != "none")
        if self.quantized_reduce != "off" and offloaded:
            # the offload paths (host C++ optimizer, tiered stream,
            # Infinity per-layer executor) build their own gradient
            # programs that never consult the knob — running fp32 wire
            # while the config claims int8 would be a silent no-op
            # (previously rejected at engine init, after the expensive
            # state build)
            raise ConfigError(
                "zero_optimization.quantized_reduce requires the "
                "standard jitted step: ZeRO-Offload / ZeRO-Infinity "
                "keep their own gradient transports")
        if self.offload_optimizer.pin_memory:
            # pin_memory selects the TIERED path (runtime/offload.py)
            if self.offload_optimizer.device == "nvme":
                raise ConfigError(
                    "offload_optimizer.pin_memory selects the tiered "
                    "HOST-RAM tier and composes with device 'cpu' only; "
                    "'nvme' runs the AIO-swapped host optimizer "
                    "(drop pin_memory or set device: cpu)")
            if (self.offload_optimizer.device == "cpu"
                    and self.stage not in (1, 2)):
                raise ConfigError(
                    "tiered optimizer offload (offload_optimizer "
                    "{device: cpu, pin_memory: true}) targets ZeRO "
                    f"stages 1/2 (got stage {self.stage}); stage-3 "
                    "state already shards via the parameter plan, "
                    "stage 0 has no sharded optimizer tier")
            if (self.offload_optimizer.device == "cpu"
                    and (self.zero_quantized_gradients
                         or self.zero_quantized_weights)):
                raise ConfigError(
                    "tiered optimizer offload does not compose with "
                    "ZeRO++ quantized gradients/weights (the streamed "
                    "update rides the plain bucketed grad program)")
        if self.zero_hpz_partition_size > 1 and self.stage != 3:
            # hpZ is a stage-3 feature (secondary partition of the COMPUTE
            # params; reference zero/config.py:256-272) — rejecting loudly
            # beats silently no-op'ing the key
            raise ConfigError(
                f"zero_hpz_partition_size={self.zero_hpz_partition_size} "
                f"requires zero stage 3 (got stage {self.stage})")
        if self.zero_hpz_partition_size > 1 and self.mics_shard_size > 1:
            raise ConfigError(
                "zero_hpz_partition_size and mics_shard_size cannot be "
                "combined: both partition over the shard sub-axis with "
                "opposite replication semantics")


@dataclass
class OptimizerConfig:
    type: str = "adamw"
    params: Dict[str, Any] = field(default_factory=dict)


@dataclass
class SchedulerConfig:
    type: Optional[str] = None
    params: Dict[str, Any] = field(default_factory=dict)


@dataclass
class PipelineConfig:
    """Pipeline-parallel block (reference: PipelineModule kwargs, pipe/module.py:86)."""

    stages: int = 1
    partition_method: str = "parameters"
    seed_layers: bool = False
    activation_checkpoint_interval: int = 0
    pipe_partitioned: bool = True
    grad_partitioned: bool = True
    num_microbatches: Optional[int] = None  # defaults to gradient_accumulation_steps


@dataclass
class ActivationCheckpointingConfig:
    """Reference: runtime/activation_checkpointing/checkpointing.py:1057 configure()."""

    partition_activations: bool = False
    cpu_checkpointing: bool = False
    contiguous_memory_optimization: bool = False
    number_checkpoints: Optional[int] = None
    synchronize_checkpoint_boundary: bool = False
    profile: bool = False
    # TPU-native: remat policy name passed to jax.checkpoint
    policy: str = "nothing_saveable"


@dataclass
class CommsLoggerConfig:
    enabled: bool = False
    verbose: bool = False
    prof_all: bool = True
    debug: bool = False
    prof_ops: List[str] = field(default_factory=list)


@dataclass
class FlopsProfilerConfig:
    enabled: bool = False
    profile_step: int = 1
    module_depth: int = -1
    top_modules: int = 1
    detailed: bool = True
    output_file: Optional[str] = None


@dataclass
class TensorboardConfig:
    enabled: bool = False
    output_path: str = ""
    job_name: str = "DeepSpeedTpuJobName"


@dataclass
class WandbConfig:
    enabled: bool = False
    group: Optional[str] = None
    team: Optional[str] = None
    project: str = "deepspeed_tpu"


@dataclass
class CSVConfig:
    enabled: bool = False
    output_path: str = ""
    job_name: str = "DeepSpeedTpuJobName"


# the diagnostics block (flight recorder, anomaly detectors, post-mortem
# bundles) is shared with the serving runtime's ServingConfig — one
# schema for both stacks (telemetry/anomaly.py)
from ..telemetry.anomaly import DiagnosticsConfig  # noqa: E402


@dataclass
class TelemetryConfig:
    """Unified telemetry layer (telemetry/registry.py + bridge.py).
    ``enabled`` gates the TRAINING engine's registry series, the bridge
    that flushes registry scalars into the monitor backends, and the
    span->XLA-annotation mirroring; inference/serving instrumentation
    records unconditionally (allocation-free hot path)."""

    enabled: bool = True
    flush_interval: int = 10        # flush registry scalars every N steps
    xla_annotations: bool = False   # mirror spans into jax.profiler


@dataclass
class DataTypesConfig:
    grad_accum_dtype: Optional[str] = None


@dataclass
class CheckpointConfig:
    tag_validation: str = "Warn"  # Ignore | Warn | Fail
    load_universal: bool = False
    use_node_local_storage: bool = False
    parallel_write: Dict[str, Any] = field(default_factory=dict)
    async_save: bool = False


@dataclass
class AioConfig:
    block_size: int = 1_048_576
    queue_depth: int = 8
    thread_count: int = 1
    single_submit: bool = False
    overlap_events: bool = True


@dataclass
class MoEConfig:
    """Expert-parallel block. Reference keeps this on the MoE layer args; we also
    accept it in config for engine-level group setup (reference moe/layer.py:16)."""

    enabled: bool = False
    num_experts: int = 1
    expert_parallel_size: int = 1
    capacity_factor: float = 1.0
    eval_capacity_factor: float = 1.0
    min_capacity: int = 4
    top_k: int = 1
    noisy_gate_policy: Optional[str] = None
    drop_tokens: bool = True
    use_residual: bool = False


@dataclass
class EigenvalueConfig:
    enabled: bool = False
    verbose: bool = False
    max_iter: int = 100
    tol: float = 1e-2
    stability: float = 1e-6
    gas_boundary_resolution: int = 1
    layer_name: str = "bert.encoder.layer"
    layer_num: int = 0


@dataclass
class PLDConfig:
    enabled: bool = False
    theta: float = 1.0
    gamma: float = 0.001


@dataclass
class ElasticityConfig:
    enabled: bool = False
    max_train_batch_size: int = 2000
    micro_batch_sizes: List[int] = field(default_factory=lambda: [2, 4, 6])
    min_gpus: int = 1
    max_gpus: int = 10000
    min_time: int = 0
    prefer_larger_batch: bool = True
    ignore_non_elastic_batch_info: bool = False
    version: float = 0.1


@dataclass
class HybridEngineConfig:
    """Reference: deepspeed/inference/config.py HybridEngineConfig (consumed
    by runtime/hybrid_engine.py)."""

    enabled: bool = False
    max_out_tokens: int = 512
    inference_tp_size: int = 1
    release_inference_cache: bool = False
    pin_parameters: bool = True
    tp_gather_partition_size: int = 8
    # train->serve seam (docs/TRAINING.md § Hybrid engine): publication
    # bucket size (host bytes gathered per payload chunk — the ZeRO
    # gather granularity and the remote push's per-frame wire unit)
    publish_bucket_bytes: int = 16 << 20
    # bounded rollout->training queue (oldest rollouts drop when full,
    # counted — an RLHF actor loop must never grow host memory
    # unboundedly behind a slow learner)
    rollout_queue_size: int = 64
    # quantized weight-DELTA publication (serve/weights.py § delta
    # payloads; docs/SERVING.md § Delta weight push): publish-every-N
    # RLHF cadence ships current-base block-quantized int8 + fp32
    # block scales (~4x fewer push bytes) with publisher-side error
    # feedback across pushes. delta_publish=False disables base
    # tracking (and its fp32 host copy of the model); delta_quant is
    # "int8" or "off" (changed leaves at full fp32 — bitwise-exact
    # reconstruction)
    delta_publish: bool = True
    delta_quant: str = "int8"
    delta_block: int = 2048
    # overrides for the colocated serving engine the hybrid engine
    # builds (keys: "state_manager", "engine", "serving" — the worker
    # --spec layout); empty = geometry derived from the model config
    serving: Dict[str, Any] = field(default_factory=dict)


@dataclass
class DeepSpeedTpuConfig:
    """Top-level typed view of the JSON config.

    Field names match the reference JSON schema (runtime/config.py:686).
    """

    train_batch_size: Optional[Union[int, str]] = None
    train_micro_batch_size_per_gpu: Optional[Union[int, str]] = None
    gradient_accumulation_steps: Optional[Union[int, str]] = None
    steps_per_print: int = 10
    wall_clock_breakdown: bool = False
    dump_state: bool = False
    prescale_gradients: bool = False
    gradient_predivide_factor: float = 1.0
    gradient_clipping: float = 0.0
    sparse_gradients: bool = False
    memory_breakdown: bool = False
    disable_allgather: bool = False

    optimizer: Optional[OptimizerConfig] = None
    scheduler: Optional[SchedulerConfig] = None
    fp16: FP16Config = subconfig(FP16Config)
    bf16: BF16Config = subconfig(BF16Config)
    zero_optimization: ZeroConfig = subconfig(ZeroConfig)
    pipeline: PipelineConfig = subconfig(PipelineConfig)
    activation_checkpointing: ActivationCheckpointingConfig = subconfig(ActivationCheckpointingConfig)
    comms_logger: CommsLoggerConfig = subconfig(CommsLoggerConfig)
    flops_profiler: FlopsProfilerConfig = subconfig(FlopsProfilerConfig)
    tensorboard: TensorboardConfig = subconfig(TensorboardConfig)
    wandb: WandbConfig = subconfig(WandbConfig)
    csv_monitor: CSVConfig = subconfig(CSVConfig)
    telemetry: TelemetryConfig = subconfig(TelemetryConfig)
    diagnostics: DiagnosticsConfig = subconfig(DiagnosticsConfig)
    data_types: DataTypesConfig = subconfig(DataTypesConfig)
    checkpoint: CheckpointConfig = subconfig(CheckpointConfig)
    aio: AioConfig = subconfig(AioConfig)
    moe: MoEConfig = subconfig(MoEConfig)
    eigenvalue: EigenvalueConfig = subconfig(EigenvalueConfig)
    progressive_layer_drop: PLDConfig = subconfig(PLDConfig)
    elasticity: ElasticityConfig = subconfig(ElasticityConfig)
    hybrid_engine: HybridEngineConfig = subconfig(HybridEngineConfig)

    # Parallel topology (TPU mesh axes; tp/sp are first-class here rather than
    # via an external mpu object as in the reference engine.py:94)
    tensor_parallel_size: int = 1
    sequence_parallel_size: int = 1

    # Misc reference keys accepted for compatibility
    zero_allow_untested_optimizer: bool = True
    zero_force_ds_cpu_optimizer: bool = False
    communication_data_type: Optional[str] = None
    seq_parallel_communication_data_type: str = "fp32"
    curriculum_learning: Dict[str, Any] = field(default_factory=dict)
    data_efficiency: Dict[str, Any] = field(default_factory=dict)
    compression_training: Dict[str, Any] = field(default_factory=dict)
    autotuning: Dict[str, Any] = field(default_factory=dict)
    train_steps: Optional[int] = None


def _contains_auto(node) -> bool:
    if isinstance(node, str):
        return node == AUTO
    if isinstance(node, (list, tuple)):
        return any(_contains_auto(v) for v in node)
    return False


def _scrub_auto(node):
    """Drop every ``"auto"`` value recursively: HF-style configs ship
    ``"auto"`` for fields the integration layer would fill (reference
    __init__.py add_config_arguments / HF Trainer contract); here a
    dropped key falls back to the field's default, which is the same
    resolution standalone DeepSpeed applies. A list-valued field with an
    ``"auto"`` element (e.g. ``betas: ["auto", "auto"]``) is auto as a
    whole: the key is dropped."""
    if isinstance(node, dict):
        return {k: _scrub_auto(v) for k, v in node.items()
                if not (isinstance(v, str) and v == AUTO)
                and not (isinstance(v, (list, tuple)) and _contains_auto(v))}
    if isinstance(node, (list, tuple)):
        return type(node)(_scrub_auto(v) for v in node)
    return node


def _coerce_optional_blocks(raw: Dict[str, Any]) -> Dict[str, Any]:
    raw = _scrub_auto(raw)
    for key, cls in (("optimizer", OptimizerConfig), ("scheduler", SchedulerConfig)):
        if isinstance(raw.get(key), dict):
            raw[key] = hydrate(cls, raw[key], path=f"{key}.")
    return raw


class DeepSpeedConfig:
    """Parse + validate a config (path or dict) and resolve batch-size math.

    Reference: runtime/config.py:686 DeepSpeedConfig; the batch triple
    resolution (train_batch = micro * gas * dp_world) mirrors
    runtime/config.py's _configure_train_batch_size.
    """

    def __init__(self, config: Union[str, Dict[str, Any]], world_size: Optional[int] = None):
        if isinstance(config, str):
            with open(config, "r") as fh:
                raw: Dict[str, Any] = json.load(fh)
        elif isinstance(config, dict):
            raw = config
        else:
            raise ConfigError(f"config must be a path or dict, got {type(config)}")
        self.raw = raw
        self.cfg = hydrate(DeepSpeedTpuConfig, _coerce_optional_blocks(raw))
        # tuned-config provenance: scripts/autotune.py stamps the knobs
        # it moved under autotuning.tuned; /statusz then reports them
        # as provenance "tuned" rather than "config"
        from . import tunables
        tuned = (self.cfg.autotuning or {}).get("tuned", {})
        if isinstance(tuned, dict):
            for name, value in tuned.items():
                if name in tunables.REGISTRY:
                    tunables.observe(name, value, "tuned")
        if world_size is None:
            import jax

            world_size = jax.device_count()
        self.world_size = world_size
        mp = self.cfg.tensor_parallel_size * self.cfg.pipeline.stages * self.cfg.sequence_parallel_size
        if world_size % mp != 0:
            raise ConfigError(
                f"device count {world_size} not divisible by tp*pp*sp={mp}")
        self.dp_world_size = world_size // mp
        self._resolve_batch_sizes()
        # cross-block reject (optimizer type x zero offload): 1-bit
        # optimizers own their communication AND their own state layout —
        # neither host-offload backend can stream it. Fails at load
        # instead of deep inside the engine's state init.
        if self.cfg.zero_optimization.offload_optimizer.device != "none" \
                and self.cfg.optimizer is not None:
            from .fp16.onebit import is_onebit_optimizer
            if is_onebit_optimizer(self.cfg.optimizer.type):
                raise ConfigError(
                    "offload_optimizer does not compose with 1-bit "
                    "optimizers (they own their error-feedback state "
                    "and communication); use the standard optimizer "
                    "registry or drop the offload block")

    def _resolve_batch_sizes(self):
        c = self.cfg
        # "auto" was scrubbed to the field default (None) at ingestion
        tb = None if c.train_batch_size is None else int(c.train_batch_size)
        mb = (None if c.train_micro_batch_size_per_gpu is None
              else int(c.train_micro_batch_size_per_gpu))
        gas = (None if c.gradient_accumulation_steps is None
               else int(c.gradient_accumulation_steps))
        dp = self.dp_world_size
        if tb is not None and mb is not None and gas is None:
            gas, rem = divmod(tb, mb * dp)
            if rem:
                raise ConfigError(
                    f"train_batch_size {tb} not divisible by micro_batch*dp = {mb}*{dp}")
        elif tb is not None and gas is not None and mb is None:
            mb, rem = divmod(tb, gas * dp)
            if rem:
                raise ConfigError(
                    f"train_batch_size {tb} not divisible by gas*dp = {gas}*{dp}")
        elif mb is not None and tb is None:
            gas = gas or 1
            tb = mb * gas * dp
        elif tb is not None and mb is None and gas is None:
            gas = 1
            mb, rem = divmod(tb, dp)
            if rem:
                raise ConfigError(f"train_batch_size {tb} not divisible by dp {dp}")
        elif tb is None and mb is None:
            raise ConfigError(
                "must provide train_batch_size or train_micro_batch_size_per_gpu")
        if tb != mb * gas * dp:
            raise ConfigError(
                f"inconsistent batch config: train_batch_size {tb} != "
                f"micro {mb} * gas {gas} * dp {dp}")
        self.train_batch_size = tb
        self.train_micro_batch_size_per_gpu = mb
        self.gradient_accumulation_steps = gas

    # -- convenience accessors -------------------------------------------------
    @property
    def zero_enabled(self) -> bool:
        return self.cfg.zero_optimization.stage > 0

    @property
    def zero_stage(self) -> int:
        return self.cfg.zero_optimization.stage

    @property
    def precision_dtype(self) -> str:
        if self.cfg.fp16.enabled and self.cfg.bf16.enabled:
            raise ConfigError("fp16 and bf16 cannot both be enabled")
        if self.cfg.fp16.enabled:
            return "float16"
        if self.cfg.bf16.enabled:
            return "bfloat16"
        return "float32"

    def to_dict(self) -> Dict[str, Any]:
        return as_dict(self.cfg)

    def print_config(self):
        from ..utils.logging import logger

        logger.info(json.dumps(self.to_dict(), indent=2, default=str))
