"""Pipeline parallelism (reference deepspeed/pipe facade + runtime/pipe)."""

from .module import (LayerSpec, PipelineModule,  # noqa: F401
                     TiedLayerSpec, partition_balanced)
from .pipeline import (broadcast_from_last, pipeline_1f1b,  # noqa: F401
                       pipeline_scan)
