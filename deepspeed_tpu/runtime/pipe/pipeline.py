"""Compiled pipeline parallelism over the "pipe" mesh axis.

TPU-native re-design of the reference pipeline engine
(runtime/pipe/engine.py:55 PipelineEngine, schedule.py:189 TrainSchedule,
p2p.py:50 send/recv): instead of an interpreted instruction stream with eager
p2p sends, the whole pipeline is ONE compiled program:

  * layer parameters are stacked [L, ...] and sharded over the "pipe" axis
    (each stage owns L/pp contiguous layers — the reference's uniform
    partition_method, pipe/module.py:370),
  * a lax.scan over num_micro + pp - 1 ticks moves activations between
    adjacent stages with lax.ppermute (ICI collective-permute — the compiled
    equivalent of p2p.send/recv),
  * jax.grad through the scan produces the reverse schedule automatically:
    the VJP of ppermute is the opposite-direction ppermute, so the backward
    pass streams gradients stage-to-stage just like _exec_send_grads
    (pipe/engine.py:980) — no hand-written backward schedule needed,
  * per-tick stage bodies are rematerialized (jax.checkpoint), bounding the
    activation stash the same way the reference's activation-checkpointed
    pipeline does.

The bubble fraction matches 1F1B/GPipe: (pp-1)/(num_micro+pp-1) of ticks are
idle per stage.

Embedding/head strategy: computed on every stage replica (they are replicated
across "pipe"), with the loss taken from the last stage; this trades a little
duplicated flop for zero special-case stages — on TPU the duplicated embed
gather is negligible and XLA dead-code-eliminates unused head math on
non-final stages where possible.
"""

from functools import partial
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax

from ...parallel.topology import MeshTopology, PIPE_AXIS


def pipeline_scan(stage_fn: Callable, x_microbatches, num_stages: int,
                  remat: bool = True, stage_aux: bool = False):
    """Run `stage_fn(x) -> y` as a pipeline over the pipe axis, inside
    shard_map.

    x_microbatches: [M, ...] microbatch activations entering stage 0.
    Returns [M, ...] outputs of the LAST stage (garbage on other stages —
    callers mask with stage == num_stages-1).

    stage_aux: stage_fn returns (y, aux_scalar) — a stage-LOCAL auxiliary
    loss (MoE load balancing; reference sharded_moe.py l_aux). The return
    becomes (ys, aux_sum) where aux_sum is this stage's aux summed over its
    REAL microbatch ticks (bubble ticks run on garbage activations whose
    gating aux is nonzero, so they must be masked out); callers psum over
    the pipe axis and divide by M.
    """
    pp = num_stages
    stage = lax.axis_index(PIPE_AXIS)
    M = x_microbatches.shape[0]
    T = M + pp - 1

    body = stage_fn
    if remat:
        body = jax.checkpoint(stage_fn,
                              policy=jax.checkpoint_policies.nothing_saveable)

    fwd_perm = [(i, i + 1) for i in range(pp - 1)]

    def tick(carry, t):
        buf, aux_acc = carry              # activation entering my stage
        m = t - stage                     # my microbatch index this tick
        m_in = jnp.clip(t, 0, M - 1)
        inp = jnp.where(stage == 0, x_microbatches[m_in], buf)
        if stage_aux:
            out, aux = body(inp)
            active = (m >= 0) & (m < M)
            aux_acc = aux_acc + jnp.where(active, aux.astype(jnp.float32),
                                          0.0)
        else:
            out = body(inp)
        nxt = lax.ppermute(out, PIPE_AXIS, perm=fwd_perm)
        # last stage's finished microbatch this tick
        y = jnp.where(stage == pp - 1, out, jnp.zeros_like(out))
        return (nxt, aux_acc), y

    buf0 = jnp.zeros_like(x_microbatches[0])
    (_, aux_sum), ys = lax.scan(tick, (buf0, jnp.zeros((), jnp.float32)),
                                jnp.arange(T))
    # tick t finishes microbatch t-(pp-1) on the last stage
    if stage_aux:
        return ys[pp - 1:], aux_sum
    return ys[pp - 1:]


def last_stage_mask(num_stages: int):
    return lax.axis_index(PIPE_AXIS) == num_stages - 1


def stage_index():
    return lax.axis_index(PIPE_AXIS)


def broadcast_from_last(x, num_stages: int):
    """psum trick: zero everywhere but the last stage, then sum over pipe."""
    masked = jnp.where(last_stage_mask(num_stages), x, jnp.zeros_like(x))
    return lax.psum(masked, PIPE_AXIS)


def pipeline_1f1b(stage_fn, loss_fn, params, x_microbatches, num_stages: int,
                  h_spec=None, loss_args=(), dp_axes=(),
                  pipe_reduce_mask=None, stage_aux: bool = False):
    """True 1F1B pipeline with BOUNDED activation memory, inside shard_map.

    The compiled equivalent of the reference's TrainSchedule
    (runtime/pipe/schedule.py:189) with its ``num_pipe_buffers``
    bound (schedule.py:247): per stage, at most 2*pp-1 microbatch inputs are
    live at any tick — independent of the number of microbatches M — versus
    the GPipe-shaped forward scan that stashed every tick's output.

    Mechanics (one scan over T = M + 2*(pp-1) ticks; every tick has one
    forward slot and one backward slot, all under SPMD masks):

      * forward slot: stage s runs microbatch m = t - s, stashes its INPUT
        in a circular [2*pp-1, ...] buffer, and ppermutes the output to
        stage s+1 (p2p.send -> ICI collective-permute).
      * backward slot: stage s re-runs its forward from the stashed input
        under jax.vjp (rematerialization — the reference's activation-
        checkpointed pipeline recomputes the same way) for microbatch
        m = t - 2*(pp-1) + s, consuming the output-gradient arriving from
        stage s+1, accumulating its parameter gradients, and ppermuting the
        input-gradient to stage s-1 (_exec_send_grads, pipe/engine.py:980).
      * the LAST stage folds the loss into its backward slot (cotangent
        1.0), so its backward of microbatch m runs in the same tick as its
        forward — the 1F1B steady state.

    Parameters
    ----------
    stage_fn : (stage_params, x_raw_microbatch, h) -> h_out. Branches on
        nothing itself: it receives the per-stage params and must return the
        UNIFORM inter-stage activation. It may be a single callable (all
        stages structurally identical, e.g. stacked transformer layers) or a
        list of pp callables (heterogeneous stages, dispatched by
        lax.switch on the stage index).
    loss_fn : (params, h_last, *loss_args_mb) -> scalar loss for ONE
        microbatch. It receives params so loss-side weights (final norm,
        LM head, tied embeddings) get gradients.
    params : the (replicated-over-pipe) parameter pytree handed to every
        stage function.
    x_microbatches : [M, b, ...] raw input microbatches (consumed by stage
        0's branch).
    loss_args : tuple of [M, ...] arrays sliced per-microbatch for the loss
        (labels, masks).
    dp_axes : data-parallel axis names to pmean the gradients over.

    pipe_reduce_mask : optional pytree of bool aligned with params. True
        (default for every leaf) = the param is REPLICATED over pipe, so its
        gradient is psum'd over the pipe axis — which is also what sums
        tied-weight contributions from different stages (the reference's
        _exec_reduce_tied_grads, pipe/engine.py:249). False = the param is
        pipe-SHARDED (e.g. stacked layer weights, one slice per stage): the
        local gradient is already complete and must not be reduced.

    stage_aux : stage_fn returns (h_out, aux_scalar) — a stage-LOCAL,
        pre-scaled auxiliary loss term (MoE load balancing; reference
        sharded_moe.py l_aux). Each stage differentiates its own aux with
        cotangent 1.0 inside its backward slot — no cross-stage gradient
        flow is needed because aux depends only on that stage's activations
        and params — and the reported loss is the psum of every stage's
        (ce + aux) contributions over the pipe axis.

    Returns (mean_loss, grads): loss replicated across stages; grads are the
    full parameter gradient on every device.
    """
    pp = num_stages
    stage = lax.axis_index(PIPE_AXIS)
    M = x_microbatches.shape[0]
    T = M + 2 * (pp - 1)
    K = 2 * pp - 1          # circular stash depth: max in-flight for stage 0

    branches = stage_fn if isinstance(stage_fn, (list, tuple)) else None

    def run_stage(p, x_raw, h):
        if branches is None:
            out = stage_fn(p, x_raw, h)
        else:
            out = lax.switch(stage, list(branches), p, x_raw, h)
        if stage_aux:
            return out                       # (h_out, aux)
        return out, jnp.zeros((), jnp.float32)

    def run_last_with_loss(p, x_raw, h, largs):
        out, aux = run_stage(p, x_raw, h)
        return loss_fn(p, out, *largs) + aux

    fwd_perm = [(i, i + 1) for i in range(pp - 1)]
    bwd_perm = [(i + 1, i) for i in range(pp - 1)]

    if h_spec is None:
        # probe the inter-stage activation shape from stage 0's branch
        # (stage 0 must ignore its h argument, so None is safe there)
        raw0 = stage_fn[0] if branches is not None else stage_fn
        h_spec = jax.eval_shape(lambda p, x: raw0(p, x, None),
                                params, x_microbatches[0])
        if stage_aux:
            h_spec = h_spec[0]
    zeros_h = jnp.zeros(h_spec.shape, h_spec.dtype)

    grads0 = jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), params)

    def tick(carry, t):
        fwd_buf, bwd_buf, stash, grads_acc, loss_acc = carry

        # ---------------- forward slot ----------------
        m_f = t - stage
        f_active = (m_f >= 0) & (m_f < M)
        m_f_c = jnp.clip(m_f, 0, M - 1)
        x_raw = x_microbatches[m_f_c]
        h_in = jnp.where(stage == 0, zeros_h, fwd_buf)
        out, _aux_f = run_stage(params, x_raw, h_in)  # aux counted in bwd slot
        # stash this microbatch's INPUT activation for the backward recompute
        stash = lax.dynamic_update_index_in_dim(
            stash, jnp.where(f_active, h_in, stash[m_f_c % K]),
            m_f_c % K, axis=0)
        new_fwd = lax.ppermute(jnp.where(f_active, out, jnp.zeros_like(out)),
                               PIPE_AXIS, perm=fwd_perm)

        # ---------------- backward slot ----------------
        m_b = t - 2 * (pp - 1) + stage
        b_active = (m_b >= 0) & (m_b < M)
        m_b_c = jnp.clip(m_b, 0, M - 1)
        x_raw_b = x_microbatches[m_b_c]
        h_in_b = stash[m_b_c % K]
        largs = tuple(a[m_b_c] for a in loss_args)

        def bwd_last(p):
            lval, vjp = jax.vjp(
                lambda pp_, h_: run_last_with_loss(pp_, x_raw_b, h_, largs),
                p, h_in_b)
            gp, gh = vjp(jnp.ones_like(lval))
            return lval.astype(jnp.float32), gp, gh

        def bwd_mid(p):
            (out_b, aux_b), vjp = jax.vjp(
                lambda pp_, h_: run_stage(pp_, x_raw_b, h_), p, h_in_b)
            # the stage's own aux loss differentiates locally: cotangent 1.0
            # alongside the activation cotangent arriving from stage s+1
            gp, gh = vjp((bwd_buf, jnp.ones((), aux_b.dtype)))
            return aux_b.astype(jnp.float32), gp, gh

        loss_m, gp, gh = lax.cond(stage == pp - 1, bwd_last, bwd_mid, params)
        gp = jax.tree.map(
            lambda a, g: a + jnp.where(b_active, g.astype(jnp.float32), 0.0),
            grads_acc, gp)
        loss_acc = loss_acc + jnp.where(b_active, loss_m, 0.0)
        new_bwd = lax.ppermute(
            jnp.where(b_active, gh, jnp.zeros_like(gh)), PIPE_AXIS,
            perm=bwd_perm)
        return (new_fwd, new_bwd, stash, gp, loss_acc), None

    stash0 = jnp.zeros((K,) + tuple(h_spec.shape), h_spec.dtype)
    # gradient cotangents travel between stages in the activation dtype
    # (the reference ships fp16 grads through p2p the same way)
    carry0 = (zeros_h, jnp.zeros(h_spec.shape, h_spec.dtype), stash0, grads0,
              jnp.zeros((), jnp.float32))
    carry, _ = lax.scan(tick, carry0, jnp.arange(T))
    _fwd, _bwd, _stash, grads, loss_sum = carry
    # psum over pipe: the last stage holds ce(+aux); with stage_aux the mid
    # stages contribute their own aux terms too (zero otherwise, making this
    # identical to the old broadcast_from_last)
    loss = lax.psum(loss_sum, PIPE_AXIS) / M
    # the scan accumulated per-microbatch gradients; the loss is the MEAN
    # over microbatches, so the gradient is too
    grads = jax.tree.map(lambda g: g / M, grads)
    if pipe_reduce_mask is None:
        grads = jax.tree.map(lambda g: lax.psum(g, PIPE_AXIS), grads)
    else:
        grads = jax.tree.map(
            lambda g, m: lax.psum(g, PIPE_AXIS) if m else g,
            grads, pipe_reduce_mask)
    if dp_axes:
        loss = lax.pmean(loss, dp_axes)
        grads = jax.tree.map(lambda g: lax.pmean(g, dp_axes), grads)
    return loss, grads
