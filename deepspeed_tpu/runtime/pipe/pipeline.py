"""Compiled pipeline parallelism over the "pipe" mesh axis.

TPU-native re-design of the reference pipeline engine
(runtime/pipe/engine.py:55 PipelineEngine, schedule.py:189 TrainSchedule,
p2p.py:50 send/recv): instead of an interpreted instruction stream with eager
p2p sends, the whole pipeline is ONE compiled program:

  * layer parameters are stacked [L, ...] and sharded over the "pipe" axis
    (each stage owns L/pp contiguous layers — the reference's uniform
    partition_method, pipe/module.py:370),
  * a lax.scan over num_micro + pp - 1 ticks moves activations between
    adjacent stages with lax.ppermute (ICI collective-permute — the compiled
    equivalent of p2p.send/recv),
  * jax.grad through the scan produces the reverse schedule automatically:
    the VJP of ppermute is the opposite-direction ppermute, so the backward
    pass streams gradients stage-to-stage just like _exec_send_grads
    (pipe/engine.py:980) — no hand-written backward schedule needed,
  * per-tick stage bodies are rematerialized (jax.checkpoint), bounding the
    activation stash the same way the reference's activation-checkpointed
    pipeline does.

The bubble fraction matches 1F1B/GPipe: (pp-1)/(num_micro+pp-1) of ticks are
idle per stage.

Embedding/head strategy: computed on every stage replica (they are replicated
across "pipe"), with the loss taken from the last stage; this trades a little
duplicated flop for zero special-case stages — on TPU the duplicated embed
gather is negligible and XLA dead-code-eliminates unused head math on
non-final stages where possible.
"""

from functools import partial
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax

from ...parallel.topology import MeshTopology, PIPE_AXIS


def pipeline_scan(stage_fn: Callable, x_microbatches, num_stages: int,
                  remat: bool = True):
    """Run `stage_fn(x) -> y` as a pipeline over the pipe axis, inside
    shard_map.

    x_microbatches: [M, ...] microbatch activations entering stage 0.
    Returns [M, ...] outputs of the LAST stage (garbage on other stages —
    callers mask with stage == num_stages-1).
    """
    pp = num_stages
    stage = lax.axis_index(PIPE_AXIS)
    M = x_microbatches.shape[0]
    T = M + pp - 1

    body = stage_fn
    if remat:
        body = jax.checkpoint(stage_fn,
                              policy=jax.checkpoint_policies.nothing_saveable)

    fwd_perm = [(i, i + 1) for i in range(pp - 1)]

    def tick(carry, t):
        buf = carry                                   # activation entering my stage
        m_in = jnp.clip(t, 0, M - 1)
        inp = jnp.where(stage == 0, x_microbatches[m_in], buf)
        out = body(inp)
        nxt = lax.ppermute(out, PIPE_AXIS, perm=fwd_perm)
        # last stage's finished microbatch this tick
        y = jnp.where(stage == pp - 1, out, jnp.zeros_like(out))
        return nxt, y

    buf0 = jnp.zeros_like(x_microbatches[0])
    _, ys = lax.scan(tick, buf0, jnp.arange(T))
    # tick t finishes microbatch t-(pp-1) on the last stage
    return ys[pp - 1:]


def last_stage_mask(num_stages: int):
    return lax.axis_index(PIPE_AXIS) == num_stages - 1


def stage_index():
    return lax.axis_index(PIPE_AXIS)


def broadcast_from_last(x, num_stages: int):
    """psum trick: zero everywhere but the last stage, then sum over pipe."""
    masked = jnp.where(last_stage_mask(num_stages), x, jnp.zeros_like(x))
    return lax.psum(masked, PIPE_AXIS)
