"""Generic layer-list pipeline API.

TPU-native equivalent of the reference's PipelineModule family
(runtime/pipe/module.py: LayerSpec :30, TiedLayerSpec :77, PipelineModule
:86, _partition_layers :370 with ``parameters|uniform|type:regex``
methods). A user describes their model as an ordered list of layers; the
module partitions them into pp contiguous stages and trains them through
the compiled 1F1B pipeline (pipeline.py pipeline_1f1b) over the "pipe"
mesh axis.

Layer protocol (functional, matching the engine's model protocol):
  layer.init(rng) -> params pytree
  layer.apply(params, x) -> x            # may use mesh collectives (TP)
  layer.partition_spec(topo) -> spec pytree   [optional: TP sharding]

Design departures from the reference, driven by XLA/SPMD:
  * One compiled program runs on every device; each stage executes its own
    contiguous layer slice via lax.switch on the pipe-axis index (the
    reference builds a different torch module per rank).
  * Parameter STORAGE: maximal runs of structurally identical LayerSpecs
    whose balanced partition gives every stage an equal count are STACKED
    into one [pp*k, ...] tree sharded over the pipe axis — each stage
    stores only its own k layers, giving the per-stage parameter-memory
    scaling of the reference's per-stage modules
    (runtime/pipe/module.py:370) without heterogeneous SPMD structure.
    Heterogeneous and tied layers stay replicated over pipe (SPMD cannot
    express per-device structure); their memory scaling comes from ZeRO
    sharding over the data axes, which composes orthogonally. Compute is
    always stage-local: only the owning stage's branch touches a layer.
  * Inter-stage activations must share ONE shape/dtype (the reference
    pre-allocates fixed p2p buffers per num_pipe_buffers the same way,
    schedule.py:247). Stage 0 consumes the raw microbatch input directly.
  * Tied layers (TiedLayerSpec, e.g. embedding+head) share one parameter
    tree under params["tied"][key]; the gradient psum over the pipe axis
    inside pipeline_1f1b sums every stage's contribution — the reference's
    _exec_reduce_tied_grads (pipe/engine.py:249) done by the compiler.
"""

import re
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ...parallel.topology import PIPE_AXIS
from .pipeline import pipeline_1f1b

__all__ = ["LayerSpec", "TiedLayerSpec", "PipelineModule",
           "partition_balanced"]


class LayerSpec:
    """Deferred layer construction (reference pipe/module.py:30): holds the
    layer class and ctor args so the module can build, count and partition
    layers before any parameters exist."""

    def __init__(self, typename, *args, **kwargs):
        self.typename = typename
        self.args = args
        self.kwargs = kwargs

    def build(self):
        return self.typename(*self.args, **self.kwargs)

    @property
    def type_name(self) -> str:
        return getattr(self.typename, "__name__", str(self.typename))


class TiedLayerSpec(LayerSpec):
    """LayerSpec whose parameters are shared with every other TiedLayerSpec
    of the same ``key`` (reference pipe/module.py:77): the canonical tied
    embedding/LM-head pattern."""

    def __init__(self, key, typename, *args, forward_fn=None, **kwargs):
        super().__init__(typename, *args, **kwargs)
        self.key = key
        self.forward_fn = forward_fn


def partition_balanced(weights: Sequence[float], parts: int) -> List[int]:
    """Optimal contiguous partition minimizing the max part weight
    (reference deepspeed/runtime/utils.py partition_balanced used by
    _partition_layers). Returns part boundaries of length parts+1."""
    n = len(weights)
    if n and not any(w > 0 for w in weights):
        raise ValueError(
            "partition weights are all zero (e.g. a type:regex that matches "
            "no layer) — cannot balance stages")
    prefix = np.concatenate([[0.0], np.cumsum(weights)])

    def max_part(bounds):
        return max(prefix[b] - prefix[a] for a, b in zip(bounds, bounds[1:]))

    # binary search on capacity + greedy packing (optimal for contiguous)
    lo = max(weights) if weights else 0.0
    hi = float(prefix[-1])
    best = None
    for _ in range(64):
        cap = (lo + hi) / 2.0
        bounds, start, used = [0], 0, 1
        ok = True
        for i in range(n):
            if prefix[i + 1] - prefix[start] > cap + 1e-9:
                if i == start:  # single item exceeds cap
                    ok = False
                    break
                bounds.append(i)
                start = i
                used += 1
                if used > parts:
                    ok = False
                    break
        if ok and used <= parts:
            bounds = bounds + [n]
            while len(bounds) < parts + 1:  # pad empty TAIL parts (never
                bounds.append(n)            # an empty stage 0)
            best = bounds
            hi = cap
        else:
            lo = cap
    if best is None:
        best = list(np.linspace(0, n, parts + 1).astype(int))
    return [int(b) for b in best]


class PipelineModule:
    """Layer-list model trained through the compiled 1F1B pipeline.

    Parameters
    ----------
    layers : list of LayerSpec/TiedLayerSpec or already-built layer objects.
    loss_fn : (last_stage_output, batch_without_x) -> scalar microbatch loss.
    partition_method : "parameters" (balance by param count, the reference
        default), "uniform" (equal layer counts), or "type:REGEX" (balance
        the count of layers whose class name matches REGEX).
    activation_spec : jax.ShapeDtypeStruct of the inter-stage activation
        for ONE microbatch. If omitted it is probed from stage 0's output.
    """

    supports_pp_tp = True  # engine may compose pipe with the model axis
    # axes the engine may compose with pipe because layers own their
    # collectives there (user layers must actually use the axis — a layer
    # list with no seq-axis ops under sp>1 just replicates work)
    pp_manual_axes = ("model", "seq")

    def __init__(self, layers, loss_fn: Callable,
                 partition_method: str = "parameters",
                 activation_spec=None, input_ndim: Optional[int] = None):
        # input_ndim: rank of ONE microbatch's "x" (e.g. 2 for [b, D]);
        # lets apply() accept both [M, b, ...] and single-micro [b, ...]
        self.input_ndim = input_ndim
        self.specs = list(layers)
        self.layers = [s.build() if isinstance(s, LayerSpec) else s
                       for s in self.specs]
        self.loss_fn = loss_fn
        self.partition_method = partition_method
        self.activation_spec = activation_spec
        self.topology = None
        self._bounds = None
        # tied-parameter wiring: layer index -> tied key
        self.tied_keys: Dict[int, str] = {
            i: s.key for i, s in enumerate(self.specs)
            if isinstance(s, TiedLayerSpec)}

    # -- engine protocol ---------------------------------------------------
    def set_topology(self, topo):
        self.topology = topo
        self._bounds = None

    def _param_key(self, i: int) -> str:
        return f"layer_{i:03d}"

    def _stack_key(self, a: int) -> str:
        return f"stack_{a:03d}"

    def _pp(self) -> int:
        if self.topology is None:
            return 1
        return self.topology.axis_size(PIPE_AXIS)

    def _spec_identity(self, i: int):
        """Comparable identity of layer i for stacking, or None if the
        layer can never stack (tied, or an already-built object whose
        construction we cannot verify)."""
        s = self.specs[i]
        if not isinstance(s, LayerSpec) or isinstance(s, TiedLayerSpec):
            return None
        return (s.typename, s.args, s.kwargs)

    def _stack_plan(self, pp: int) -> Dict[int, tuple]:
        """{run_start a: (a, b, k)} for every maximal run of identical
        LayerSpecs [a, b) that the balanced partition splits into an EQUAL
        count k per stage — those runs are stored stacked [pp*k, ...] and
        sharded over the pipe axis (per-stage parameter-memory scaling,
        reference pipe/module.py:370 per-stage modules)."""
        if pp <= 1:
            return {}
        bounds = self.stage_bounds(pp)
        n = len(self.specs)
        plan: Dict[int, tuple] = {}
        i = 0
        while i < n:
            ident = self._spec_identity(i)
            if ident is None:
                i += 1
                continue
            j = i + 1
            while j < n:
                try:
                    same = self._spec_identity(j) == ident
                except Exception:
                    same = False
                if not same:
                    break
                j += 1
            counts = [max(0, min(j, bounds[s + 1]) - max(i, bounds[s]))
                      for s in range(pp)]
            k = counts[0]
            if k > 0 and all(c == k for c in counts):
                plan[i] = (i, j, k)
            i = j
        return plan

    def _run_of(self, plan: Dict[int, tuple], i: int):
        for a, (a0, b, k) in plan.items():
            if a0 <= i < b:
                return (a0, k)
        return None

    def init_params(self, rng):
        plan = self._stack_plan(self._pp())
        params: Dict[str, Any] = {}
        tied: Dict[str, Any] = {}
        members: Dict[int, list] = {a: [] for a in plan}
        for i, layer in enumerate(self.layers):
            rng, sub = jax.random.split(rng)
            if i in self.tied_keys:
                key = self.tied_keys[i]
                if key not in tied:  # first occurrence owns the params
                    tied[key] = layer.init(sub)
                continue
            run = self._run_of(plan, i)
            if run is not None:
                members[run[0]].append(layer.init(sub))
            else:
                params[self._param_key(i)] = layer.init(sub)
        for a, ms in members.items():
            params[self._stack_key(a)] = jax.tree.map(
                lambda *xs: jnp.stack(xs), *ms)
        if tied:
            params["tied"] = tied
        return params

    def _layer_spec_for(self, i: int, topo):
        layer = self.layers[i]
        if hasattr(layer, "partition_spec"):
            return layer.partition_spec(topo)
        tpl = jax.eval_shape(layer.init, jax.random.PRNGKey(0))
        return jax.tree.map(lambda _: P(), tpl)

    def param_partition_specs(self, topo):
        """Per-layer TP specs if a layer provides them; otherwise
        replicated. Stacked runs get the pipe axis on their leading
        (layer) dim; everything else is replicated over pipe."""
        plan = self._stack_plan(self._pp())
        specs: Dict[str, Any] = {}
        tied: Dict[str, Any] = {}
        for i, layer in enumerate(self.layers):
            if i in self.tied_keys:
                key = self.tied_keys[i]
                if key not in tied:
                    tied[key] = self._layer_spec_for(i, topo)
                continue
            run = self._run_of(plan, i)
            if run is not None:
                if i == run[0]:  # representative member carries the spec
                    specs[self._stack_key(i)] = jax.tree.map(
                        lambda s: P(PIPE_AXIS, *s),
                        self._layer_spec_for(i, topo))
            else:
                specs[self._param_key(i)] = self._layer_spec_for(i, topo)
        if tied:
            specs["tied"] = tied
        return specs

    def pipe_grad_reduce_mask(self, params):
        """False for pipe-sharded (stacked) leaves — their local gradient
        is already complete — True (psum over pipe) for replicated/tied
        leaves (pipeline_1f1b pipe_reduce_mask)."""
        return {k: jax.tree.map(lambda _: not k.startswith("stack_"), v)
                for k, v in params.items()}

    # -- partitioning (reference _partition_layers, pipe/module.py:370) ----
    def _layer_weights(self) -> List[float]:
        method = self.partition_method.lower()
        if method == "uniform":
            return [1.0] * len(self.layers)
        if method == "parameters":
            weights = []
            for layer in self.layers:
                tpl = jax.eval_shape(layer.init, jax.random.PRNGKey(0))
                weights.append(float(sum(np.prod(l.shape)
                                         for l in jax.tree.leaves(tpl))))
            return weights
        if method.startswith("type:"):
            pat = re.compile(self.partition_method[len("type:"):],
                             re.IGNORECASE)
            return [1.0 if pat.search(
                        s.type_name if isinstance(s, LayerSpec)
                        else type(s).__name__) else 0.0
                    for s in self.specs]
        raise ValueError(
            f"unknown partition_method {self.partition_method!r} "
            f"(expected parameters|uniform|type:regex)")

    def stage_bounds(self, pp: int) -> List[int]:
        if self._bounds is None or len(self._bounds) != pp + 1:
            self._bounds = partition_balanced(self._layer_weights(), pp)
        return self._bounds

    def _layer_params(self, params, i, plan=None, local_base=None):
        """Params of layer i. For a stacked run member, index the stacked
        leaf: with ``local_base`` (inside the pipeline, where the leaf is
        this stage's local [k, ...] shard) the index is i - local_base;
        otherwise the leaf is global [pp*k, ...] and the index is i - a."""
        if i in self.tied_keys:
            return params["tied"][self.tied_keys[i]]
        run = self._run_of(plan, i) if plan else None
        if run is not None:
            a, _k = run
            j = i - (local_base if local_base is not None else a)
            return jax.tree.map(lambda t: t[j],
                                params[self._stack_key(a)])
        return params[self._param_key(i)]

    def _apply_layer(self, params, i, x, plan=None, local_base=None):
        spec = self.specs[i]
        p = self._layer_params(params, i, plan, local_base)
        if isinstance(spec, TiedLayerSpec) and spec.forward_fn is not None:
            return spec.forward_fn(p, x)
        return self.layers[i].apply(p, x)

    def _stage_branches(self, pp: int):
        bounds = self.stage_bounds(pp)
        # the plan follows STORAGE, which follows the topology at
        # init_params time — not the pp argument (a caller building
        # branches without a topology gets replicated-storage branches)
        plan = self._stack_plan(self._pp())

        def make_branch(s, lo, hi, is_first):
            def branch(params, x_raw, h):
                x = x_raw if is_first else h
                for i in range(lo, hi):
                    run = self._run_of(plan, i)
                    # local shard of run (a,b,k) holds members
                    # [a + s*k, a + (s+1)*k) — index relative to a + s*k
                    base = (run[0] + s * run[1]) if run is not None else None
                    x = self._apply_layer(params, i, x, plan, base)
                return x
            return branch

        return [make_branch(s, bounds[s], bounds[s + 1], s == 0)
                for s in range(pp)]

    # -- execution ---------------------------------------------------------
    def _split_batch(self, batch):
        x = batch["x"]
        rest_keys = sorted(k for k in batch if k != "x")
        return x, rest_keys, tuple(batch[k] for k in rest_keys)

    def loss_and_grads(self, params, batch, rng=None, scale=None):
        """(loss, grads) through the 1F1B pipeline; called by the engine in
        pipeline mode instead of value_and_grad (the pipeline IS the
        gradient computation). batch leaves: [M, global_micro, ...]."""
        topo = self.topology
        pp = topo.axis_size(PIPE_AXIS)
        branches = self._stage_branches(pp)
        x, rest_keys, rest = self._split_batch(batch)
        dp_axes = topo.dp_axes
        bt = topo.batch_axes
        batch_spec = P(None, bt)
        param_specs = self.param_partition_specs(topo)

        def loss_fn(_p, out, *largs):
            # user loss needs no params: loss-side weights (e.g. a tied
            # head) are ordinary layers in the list
            return self.loss_fn(out, dict(zip(rest_keys, largs)))

        reduce_mask = self.pipe_grad_reduce_mask(params)

        def body(p, x_l, *rest_l):
            return pipeline_1f1b(branches, loss_fn, p, x_l, pp,
                                 h_spec=self.activation_spec,
                                 loss_args=rest_l, dp_axes=dp_axes,
                                 pipe_reduce_mask=reduce_mask)

        from ...comm.quantized import shard_map_unchecked
        sm = shard_map_unchecked(
            body, mesh=topo.mesh,
            in_specs=(param_specs, batch_spec) + (batch_spec,) * len(rest),
            out_specs=(P(), param_specs))
        return sm(params, x, *rest)

    def apply(self, params, batch, train: bool = True, rng=None):
        """Loss without the pipeline schedule (eval / non-pp fallback):
        every device runs the full layer stack with TP collectives intact.
        Pipe-sharded (stacked) runs are all-gathered over the pipe axis
        first — eval is not the memory-critical path."""
        topo = self.topology
        x, rest_keys, rest = self._split_batch(batch)
        if self.input_ndim is not None and x.ndim == self.input_ndim:
            # single microbatch (engine's non-pipeline gas scan): add M=1
            x = x[None]
            rest = tuple(r[None] for r in rest)
        if topo is None:
            def run(x_m, *rest_m):
                h = x_m
                for i in range(len(self.layers)):
                    h = self._apply_layer(params, i, h)
                return self.loss_fn(h, dict(zip(rest_keys, rest_m)))
            losses = [run(x[m], *(r[m] for r in rest))
                      for m in range(x.shape[0])]
            return jnp.mean(jnp.stack(losses))

        bt = topo.batch_axes
        batch_spec = P(None, bt)
        param_specs = self.param_partition_specs(topo)
        dp_axes = topo.dp_axes
        plan = self._stack_plan(self._pp())

        def body(p, x_l, *rest_l):
            if plan:
                p = {k: (jax.tree.map(
                        lambda t: jax.lax.all_gather(t, PIPE_AXIS, axis=0,
                                                     tiled=True), v)
                         if k.startswith("stack_") else v)
                     for k, v in p.items()}

            def one(m):
                h = x_l[m]
                for i in range(len(self.layers)):
                    h = self._apply_layer(p, i, h, plan)
                return self.loss_fn(h, dict(zip(rest_keys,
                                                (r[m] for r in rest_l))))
            M = x_l.shape[0]
            loss = jnp.mean(jnp.stack([one(m) for m in range(M)]))
            return jax.lax.pmean(loss, dp_axes)

        from ...comm.quantized import shard_map_unchecked
        sm = shard_map_unchecked(
            body, mesh=topo.mesh,
            in_specs=(param_specs, batch_spec) + (batch_spec,) * len(rest),
            out_specs=P())
        return sm(params, x, *rest)
