"""Random layer token dropping (random-LTD).

Reference: runtime/data_pipeline/data_routing/basic_layer.py:14
RandomLayerTokenDrop + scheduler.py RandomLTDScheduler + the csrc/random_ltd
token_sort/gather_scatter CUDA kernels. Each wrapped layer processes only a
random subset of tokens; the skipped tokens bypass the layer and are
scattered back in order. The kept-token count follows a linear schedule from
`start_ratio` of the sequence up to the full sequence.

TPU-native: the gather/scatter is jnp.take_along_axis / scatter on a static
keep-count (static shapes under jit — the schedule changes keep_count only
between compiled steps, mirroring the reference's per-step reconfiguration).
The random permutation comes from jax PRNG, so dropping is identical across
data-parallel replicas given the same key (the reference broadcasts its
sorted indices the same way).
"""

from typing import Any, Callable, Dict, Tuple

import jax
import jax.numpy as jnp


def sample_token_subset(rng, seq_len: int, keep: int) -> Tuple[jnp.ndarray,
                                                               jnp.ndarray]:
    """Random kept-token indices (sorted, order-preserving like the
    reference's token_sort.cu) + their inverse scatter positions."""
    perm = jax.random.permutation(rng, seq_len)
    kept = jnp.sort(perm[:keep])
    return kept, perm


def gather_tokens(x: jnp.ndarray, kept: jnp.ndarray) -> jnp.ndarray:
    """[B, S, H] -> [B, keep, H] (reference gather_scatter.cu gather)."""
    return jnp.take(x, kept, axis=1)


def scatter_tokens(full: jnp.ndarray, processed: jnp.ndarray,
                   kept: jnp.ndarray) -> jnp.ndarray:
    """Write processed kept tokens back into the full sequence; dropped
    tokens keep their input values (layer bypass)."""
    return full.at[:, kept, :].set(processed)


def random_ltd_layer(layer_fn: Callable[[jnp.ndarray], jnp.ndarray],
                     x: jnp.ndarray, rng, keep: int) -> jnp.ndarray:
    """Apply `layer_fn` to a random `keep`-token subset of x [B, S, H]
    (reference RandomLayerTokenDrop.forward)."""
    S = x.shape[1]
    if keep >= S:
        return layer_fn(x)
    kept, _ = sample_token_subset(rng, S, keep)
    sub = gather_tokens(x, kept)
    out = layer_fn(sub)
    return scatter_tokens(x, out, kept)


class RandomLTDScheduler:
    """Kept-token schedule (reference data_routing/scheduler.py):
    linear ramp from min_value to max_value (full seq) over schedule steps."""

    def __init__(self, config: Dict[str, Any]):
        sched = config.get("random_ltd_schedule", {})
        self.min_value = sched.get("min_value",
                                   config.get("random_ltd_layer_num", 128))
        self.max_value = sched["max_value"]
        self.total_steps = sched.get("schedule_config", {}).get(
            "total_layer_token_drop_step",
            sched.get("total_layer_token_drop_step", 1000))
        self.step_size = sched.get("schedule_config", {}).get(
            "seq_per_step", sched.get("seq_per_step", 16))
        self.current_seq = self.min_value

    def get_value(self, global_step: int) -> int:
        frac = min(1.0, global_step / max(self.total_steps, 1))
        val = self.min_value + frac * (self.max_value - self.min_value)
        val = int(val // self.step_size) * self.step_size
        return int(min(max(val, self.min_value), self.max_value))

    def update_seq(self, global_step: int) -> int:
        self.current_seq = self.get_value(global_step)
        return self.current_seq

    def state_dict(self):
        return {"current_seq": self.current_seq}

    def load_state_dict(self, sd):
        self.current_seq = sd["current_seq"]
