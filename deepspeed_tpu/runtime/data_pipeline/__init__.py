from .curriculum_scheduler import CurriculumScheduler  # noqa: F401
from .data_sampler import DeepSpeedDataSampler, truncate_seqlen  # noqa: F401
from .indexed_dataset import (MMapIndexedDataset,  # noqa: F401
                              MMapIndexedDatasetBuilder, make_dataset)
from .random_ltd import RandomLTDScheduler, random_ltd_layer  # noqa: F401
