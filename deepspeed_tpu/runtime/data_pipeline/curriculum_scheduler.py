"""Curriculum learning difficulty scheduler.

Reference: runtime/data_pipeline/data_sampling/curriculum_scheduler.py (also
the legacy runtime/curriculum_scheduler.py) — maps global step -> current
difficulty, with the same schedule types: fixed_linear, fixed_root,
fixed_discrete, custom.
"""

import math
from typing import Any, Callable, Dict, Optional

FIXED_LINEAR = "fixed_linear"
FIXED_ROOT = "fixed_root"
FIXED_DISCRETE = "fixed_discrete"
CUSTOM = "custom"


class CurriculumScheduler:
    """config keys (reference schema):
      curriculum_type: fixed_linear | fixed_root | fixed_discrete | custom
      min_difficulty, max_difficulty
      schedule_type-specific block `schedule_config`:
        fixed_linear:  {total_curriculum_step, difficulty_step}
        fixed_root:    {total_curriculum_step, difficulty_step, root_degree}
        fixed_discrete:{difficulty: [...], max_step: [...]}
    """

    def __init__(self, config: Dict[str, Any]):
        self.state = dict(config)
        self.curriculum_type = config.get("curriculum_type", FIXED_LINEAR)
        self.min_difficulty = config["min_difficulty"]
        self.max_difficulty = config["max_difficulty"]
        self.schedule = config.get("schedule_config", {})
        self.custom_fn: Optional[Callable[[int], int]] = None
        self.current_difficulty = self.min_difficulty
        if self.curriculum_type in (FIXED_LINEAR, FIXED_ROOT):
            assert "total_curriculum_step" in self.schedule, \
                "schedule_config.total_curriculum_step required"
        if self.curriculum_type == FIXED_DISCRETE:
            d, s = self.schedule["difficulty"], self.schedule["max_step"]
            assert len(d) == len(s) + 1, \
                "fixed_discrete: len(difficulty) must be len(max_step)+1"

    def set_custom_get_difficulty(self, fn: Callable[[int], int]):
        self.custom_fn = fn

    def _root_difficulty(self, step: int, degree: float) -> int:
        total = self.schedule["total_curriculum_step"]
        if step >= total:  # schedule complete: exactly max, no unit flooring
            return self.max_difficulty
        frac = min(1.0, step / total) ** (1.0 / degree)
        diff = self.min_difficulty + frac * (self.max_difficulty
                                             - self.min_difficulty)
        unit = self.schedule.get("difficulty_step", 1)
        diff = int(diff / unit) * unit
        return min(max(diff, self.min_difficulty), self.max_difficulty)

    def get_difficulty(self, global_step: int) -> int:
        if self.curriculum_type == CUSTOM:
            assert self.custom_fn is not None, \
                "custom curriculum requires set_custom_get_difficulty"
            return self.custom_fn(global_step)
        if self.curriculum_type == FIXED_LINEAR:
            return self._root_difficulty(global_step, 1.0)
        if self.curriculum_type == FIXED_ROOT:
            return self._root_difficulty(
                global_step, self.schedule.get("root_degree", 2))
        if self.curriculum_type == FIXED_DISCRETE:
            for diff, max_step in zip(self.schedule["difficulty"],
                                      self.schedule["max_step"]):
                if global_step < max_step:
                    return diff
            return self.schedule["difficulty"][-1]
        raise ValueError(f"unknown curriculum_type {self.curriculum_type}")

    def update_difficulty(self, global_step: int) -> int:
        self.current_difficulty = self.get_difficulty(global_step)
        return self.current_difficulty

    def state_dict(self):
        return {"current_difficulty": self.current_difficulty}

    def load_state_dict(self, sd):
        self.current_difficulty = sd["current_difficulty"]
