"""Curriculum-aware data sampling.

Reference: runtime/data_pipeline/data_sampling/data_sampler.py:36
DeepSpeedDataSampler — samples batches whose difficulty metric is within the
current curriculum difficulty, from pre-computed per-sample metric values.
Also the seqlen-truncation helpers used by the legacy curriculum
(engine truncates the batch to the scheduled sequence length).
"""

from typing import Dict, Iterator, List, Optional, Sequence

import numpy as np

from .curriculum_scheduler import CurriculumScheduler


class DeepSpeedDataSampler:
    """Difficulty-filtered batch index sampler.

    metric_values: per-sample difficulty (e.g. sequence length or loss-based
    score, the reference reads these from an offline analysis run). Each
    call to set_step(step) advances the curriculum; iterating yields batches
    drawn only from samples with metric <= current difficulty.
    """

    def __init__(self, curriculum_config: Dict, metric_values: Sequence[float],
                 batch_size: int, drop_last: bool = True, seed: int = 0,
                 replacement_when_short: bool = True):
        self.scheduler = CurriculumScheduler(curriculum_config)
        self.metric = np.asarray(metric_values)
        self.order = np.argsort(self.metric, kind="stable")
        self.sorted_metric = self.metric[self.order]
        self.batch_size = batch_size
        self.drop_last = drop_last
        self.rng = np.random.default_rng(seed)
        self.replacement_when_short = replacement_when_short
        self.global_step = 0

    def set_step(self, global_step: int):
        self.global_step = global_step
        self.scheduler.update_difficulty(global_step)

    @property
    def current_difficulty(self):
        return self.scheduler.current_difficulty

    def eligible_indices(self) -> np.ndarray:
        cutoff = np.searchsorted(self.sorted_metric,
                                 self.scheduler.current_difficulty,
                                 side="right")
        return self.order[:cutoff]

    def sample_batch(self) -> np.ndarray:
        pool = self.eligible_indices()
        if len(pool) == 0:
            raise RuntimeError(
                f"no samples at difficulty {self.scheduler.current_difficulty}")
        if len(pool) < self.batch_size:
            if not self.replacement_when_short:
                raise RuntimeError(
                    f"only {len(pool)} samples at difficulty "
                    f"{self.scheduler.current_difficulty} < batch "
                    f"{self.batch_size}")
            return self.rng.choice(pool, self.batch_size, replace=True)
        return self.rng.choice(pool, self.batch_size, replace=False)

    def __iter__(self) -> Iterator[np.ndarray]:
        while True:
            yield self.sample_batch()

    def state_dict(self):
        return {"global_step": self.global_step,
                "scheduler": self.scheduler.state_dict(),
                "rng": self.rng.bit_generator.state}

    def load_state_dict(self, sd):
        self.global_step = sd["global_step"]
        self.scheduler.load_state_dict(sd["scheduler"])
        self.rng.bit_generator.state = sd["rng"]


def truncate_seqlen(batch: Dict[str, np.ndarray], seqlen: int,
                    seq_axis: int = -1,
                    keys: Optional[List[str]] = None) -> Dict[str, np.ndarray]:
    """Legacy seqlen curriculum (reference engine curriculum_seqlen path):
    truncate token-like fields to the scheduled length. Static-shape caveat:
    on TPU each new seqlen triggers one recompile, so schedules should use a
    coarse difficulty_step (e.g. 64) — same guidance as the reference's
    `difficulty_step` for tensor-core alignment."""
    out = {}
    for k, v in batch.items():
        if keys is not None and k not in keys:
            out[k] = v
            continue
        v = np.asarray(v)
        axis = seq_axis if seq_axis >= 0 else v.ndim + seq_axis
        if v.ndim > axis and v.shape[axis] > seqlen:
            sl = [slice(None)] * v.ndim
            sl[axis] = slice(0, seqlen)
            v = v[tuple(sl)]
        out[k] = v
    return out
